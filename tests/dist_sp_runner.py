"""Runnable multi-process SEQUENCE-PARALLEL trainer: the ring-attention
ring spanning a process boundary — the long-context multi-host shape
(cross-host ring SP over DCN, reference's multi-node NCCL2 analog for
the sequence dimension).

    python dist_sp_runner.py <proc_id> <nprocs> <port> <steps>

Each process owns 4 virtual devices; the mesh is one {"sp": nprocs*4}
axis, so zigzag ring attention's permute hops cross the process
boundary. Every process feeds the identical global batch (seq is the
sharded dim; the runtime slices each process's addressable shards).
With nprocs=1 and a single device the same script is the dense
baseline. Prints `LOSS <step> <value>` per step.
"""

import os
import sys

pid, nprocs, port, steps = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                            int(sys.argv[4]))
local_devices = 4 if nprocs > 1 else 1
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append(f"--xla_force_host_platform_device_count={local_devices}")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax

jax.config.update("jax_platforms", "cpu")

if nprocs > 1:
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nprocs, process_id=pid)

import numpy as np

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.models import gpt
from paddle_tpu.parallel import DistStrategy
from paddle_tpu.parallel.sharding import ShardingRules

VOCAB, SEQ = 64, 32


def batch(step, bs=8):
    rng = np.random.RandomState(500 + step)
    ids = rng.randint(3, VOCAB, (bs, SEQ)).astype(np.int32)
    labels = np.concatenate([ids[:, 1:], np.full((bs, 1), 2)],
                            axis=1).astype(np.int32)
    return {"ids": ids, "labels": labels}


def main():
    cfg = gpt.base_config(vocab_size=VOCAB, max_len=SEQ, d_model=32,
                          d_inner=64, num_heads=4, num_layers=2,
                          use_flash=False, fused_ce=False)
    prog = pt.build(gpt.make_model(cfg))
    if nprocs > 1:
        mesh = pt.make_mesh({"sp": jax.device_count()})
        trainer = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss",
                             mesh=mesh,
                             sharding_rules=ShardingRules(seq_axis="sp"),
                             strategy=DistStrategy(sequence_parallel=True))
    else:
        trainer = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss")
    trainer.startup(rng=jax.random.PRNGKey(7), sample_feed=batch(0))
    for s in range(steps):
        out = trainer.step(batch(s), rng=jax.random.PRNGKey(100 + s))
        print(f"LOSS {s} {float(out['loss']):.6f}", flush=True)


if __name__ == "__main__":
    main()
