"""CTC family: loss, greedy decoding, edit distance.

Analog of the reference's warpctc_op (operators/warpctc_op.cc, dynload of
libwarpctc), ctc_align_op (ctc_greedy_decoder, layers/nn.py) and
edit_distance_op (operators/edit_distance_op.cc). The reference handles
variable length via LoD; here sequences are padded + explicit lengths
(the framework's static-shape LoD design, layers/sequence.py), and the
whole computation is a log-space forward algorithm under ``lax.scan`` —
differentiable by jax autodiff, so no hand-written backward like
warp-ctc's.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _extend_labels(labels, blank):
    """[B, L] labels -> [B, 2L+1] blank-interleaved extended labels."""
    b, l = labels.shape
    ext = jnp.full((b, 2 * l + 1), blank, labels.dtype)
    return ext.at[:, 1::2].set(labels)


def warpctc(
    logits,
    labels,
    logit_lengths,
    label_lengths,
    blank: int = 0,
    norm_by_times: bool = False,
):
    """CTC negative log-likelihood (warpctc_op analog).

    Args:
      logits: [B, T, C] unnormalized activations (the reference feeds
        pre-softmax activations to warp-ctc; same here).
      labels: [B, L] padded label ids (no blanks).
      logit_lengths: [B] valid timesteps per sample.
      label_lengths: [B] valid labels per sample.
      blank: blank label id.
      norm_by_times: divide each loss by its input length.

    Returns [B, 1] per-sample loss, matching the reference's summed-time
    output shape.
    """
    logits = jnp.asarray(logits)
    labels = jnp.asarray(labels).astype(jnp.int32)
    logit_lengths = jnp.asarray(logit_lengths).astype(jnp.int32).reshape(-1)
    label_lengths = jnp.asarray(label_lengths).astype(jnp.int32).reshape(-1)
    b, t, _ = logits.shape
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    ext = _extend_labels(labels, blank)          # [B, S], S = 2L+1
    s = ext.shape[1]
    pos = jnp.arange(s)[None, :]                 # [1, S]

    # transition mask: alpha[s] may also come from alpha[s-2] when
    # ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :s]
    allow_skip = (ext != blank) & (ext != ext_m2)        # [B, S]

    # initial alpha: positions 0 (blank) and 1 (first label)
    alpha0 = jnp.where(pos == 0, 0.0, NEG_INF)
    first = jnp.where((pos == 1) & (label_lengths[:, None] > 0), 0.0, NEG_INF)
    emit0 = jnp.take_along_axis(log_probs[:, 0, :], ext, axis=1)
    alpha0 = jnp.maximum(alpha0, first) + emit0          # log(a or b) where disjoint

    def step(alpha, lp_t):
        lp, tt = lp_t
        prev1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=NEG_INF)[:, :s]
        prev2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=NEG_INF)[:, :s]
        prev2 = jnp.where(allow_skip, prev2, NEG_INF)
        stacked = jnp.stack([alpha, prev1, prev2], axis=0)
        merged = jax.scipy.special.logsumexp(stacked, axis=0)
        emit = jnp.take_along_axis(lp, ext, axis=1)
        new = merged + emit
        # freeze alpha once past this sample's input length
        active = (tt < logit_lengths)[:, None]
        return jnp.where(active, new, alpha), None

    lps = jnp.moveaxis(log_probs, 1, 0)                  # [T, B, C]
    alpha, _ = jax.lax.scan(step, alpha0, (lps[1:], jnp.arange(1, t)))

    send = 2 * label_lengths                             # index of final blank
    last_blank = jnp.take_along_axis(alpha, send[:, None], axis=1)[:, 0]
    last_label = jnp.take_along_axis(
        alpha, jnp.maximum(send - 1, 0)[:, None], axis=1)[:, 0]
    last_label = jnp.where(label_lengths > 0, last_label, NEG_INF)
    ll = jax.scipy.special.logsumexp(jnp.stack([last_blank, last_label]), axis=0)
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(logit_lengths, 1).astype(loss.dtype)
    return loss[:, None]


def ctc_greedy_decoder(input, blank: int, input_length=None, padding_value: int = -1):
    """Greedy (best-path) CTC decoding (layers/nn.py ctc_greedy_decoder;
    ctc_align_op): argmax per step, merge repeats, drop blanks.

    Args:
      input: [B, T, C] probabilities or logits.
      blank: blank id.
      input_length: optional [B] valid timesteps.
      padding_value: fill for the padded decoded output.

    Returns (decoded [B, T] padded with ``padding_value``, lengths [B]).
    """
    x = jnp.asarray(input)
    b, t, _ = x.shape
    tok = jnp.argmax(x, axis=-1).astype(jnp.int32)       # [B, T]
    prev = jnp.pad(tok, ((0, 0), (1, 0)), constant_values=-1)[:, :t]
    keep = (tok != blank) & (tok != prev)
    if input_length is not None:
        il = jnp.asarray(input_length).astype(jnp.int32).reshape(-1)
        keep = keep & (jnp.arange(t)[None, :] < il[:, None])
    dest = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1  # write position
    lengths = jnp.max(dest, axis=1) + 1
    dest = jnp.where(keep, dest, t)                       # dropped -> OOB (ignored)
    out = jnp.full((b, t + 1), padding_value, jnp.int32)
    out = jax.vmap(lambda o, d, v: o.at[d].set(v, mode="drop"))(out, dest, tok)
    return out[:, :t], lengths


def edit_distance(
    input,
    label,
    input_length=None,
    label_length=None,
    normalized: bool = True,
):
    """Levenshtein distance between token sequences (edit_distance_op.cc).

    Args:
      input/label: [B, Th] / [B, Tr] padded int sequences (hypothesis, ref).
      input_length/label_length: [B] valid lengths (default: full width).
      normalized: divide by reference length.

    Returns (distance [B, 1] float32, sequence_num scalar) like the
    reference (the op also outputs SequenceNum).
    """
    hyp = jnp.asarray(input).astype(jnp.int32)
    ref = jnp.asarray(label).astype(jnp.int32)
    b, th = hyp.shape
    tr = ref.shape[1]
    hl = (jnp.full((b,), th, jnp.int32) if input_length is None
          else jnp.asarray(input_length).astype(jnp.int32).reshape(-1))
    rl = (jnp.full((b,), tr, jnp.int32) if label_length is None
          else jnp.asarray(label_length).astype(jnp.int32).reshape(-1))

    # DP over hyp rows; each row is itself a left-to-right scan over ref.
    row0 = jnp.broadcast_to(jnp.arange(tr + 1, dtype=jnp.int32), (b, tr + 1))

    def outer(prev_row, i):
        htok = hyp[:, i]                                  # [B]

        def inner(left, j):
            up = prev_row[:, j + 1]
            diag = prev_row[:, j]
            cost = (htok != ref[:, j]).astype(jnp.int32)
            val = jnp.minimum(jnp.minimum(up + 1, left + 1), diag + cost)
            return val, val

        first = prev_row[:, 0] + 1
        _, rest = jax.lax.scan(inner, first, jnp.arange(tr))
        row = jnp.concatenate([first[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)
        return row, row

    _, rows = jax.lax.scan(outer, row0, jnp.arange(th))
    table = jnp.concatenate([row0[None], rows], axis=0)   # [Th+1, B, Tr+1]
    dist = table[hl, jnp.arange(b), rl].astype(jnp.float32)
    if normalized:
        dist = dist / jnp.maximum(rl, 1).astype(jnp.float32)
    return dist[:, None], jnp.asarray(b, jnp.int32)
