"""The bench suite's driver contract (bench.py): priority ordering,
config registry consistency, result assembly, and quick-mode overrides
— pure-Python, no device. The driver records BENCH_r{N}.json from this
machinery; a silent drift here loses the round's record."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import bench
import pytest


@pytest.fixture(autouse=True)
def _no_ambient_filter(monkeypatch):
    # a leaked BENCH_ONLY debug setting must not skew the contract tests
    monkeypatch.delenv("BENCH_ONLY", raising=False)


def test_priority_order_leads_with_baseline_configs():
    names = bench._suite_names()
    assert names[:5] == ["mnist_mlp", "resnet50", "transformer", "bert",
                         "deepfm"]
    assert names[5:8] == ["resnet50_infer_bf16", "resnet50_infer_int8",
                          "resnet50_infer_fp32"]
    assert names[8] == "gpt"
    # every registered config appears exactly once
    expect = (set(bench.TRAIN_CONFIGS) | set(bench.INFER_CONFIGS)
              | {"gpt_decode", "dispatch_overhead", "guard_overhead",
                 "quantized_allreduce", "zero_sharding", "input_pipeline",
                 "device_cache", "serving", "serving_fleet", "autoscale",
                 "fusion_profile", "elastic_reshard"})
    assert set(names) == expect and len(names) == len(expect)


def test_bench_only_filter(monkeypatch):
    monkeypatch.setenv("BENCH_ONLY", "bert, gpt_decode")
    assert bench._suite_names() == ["bert", "gpt_decode"]


def test_result_key_mapping():
    assert bench._result_key("bert") == "bert_train"
    assert bench._result_key("resnet50_infer_int8") == "resnet50_infer_int8"
    assert bench._result_key("gpt_decode") == "gpt_decode"


def test_run_one_rejects_unknown_and_applies_quick_overrides(monkeypatch):
    with pytest.raises(ValueError, match="unknown config"):
        bench._run_one("nope", 1.0)
    seen = {}
    monkeypatch.setitem(bench.TRAIN_CONFIGS, "gpt_32k",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("gpt_32k", 1.0, quick=True)
    assert seen == {"iters": 2, "seq": 2048}  # QUICK_OVERRIDES applied


def test_steps_per_dispatch_knob_recorded(monkeypatch):
    """--steps_per_dispatch / BENCH_STEPS_PER_DISPATCH rides the env so
    suite children inherit it, and every train row records the K it was
    measured under (a K=16 row must never be read as a K=1 row)."""
    monkeypatch.setitem(bench.TRAIN_CONFIGS, "mnist_mlp",
                        lambda peak, **kw: {"value": 1.0})
    monkeypatch.setenv("BENCH_STEPS_PER_DISPATCH", "16")
    assert bench._run_one("mnist_mlp", 1.0)["steps_per_dispatch"] == 16
    monkeypatch.delenv("BENCH_STEPS_PER_DISPATCH")
    assert bench._run_one("mnist_mlp", 1.0)["steps_per_dispatch"] == 1
    # infer configs have no step loop: no knob recorded
    monkeypatch.setitem(bench.INFER_CONFIGS, "googlenet_infer",
                        lambda peak, **kw: {"value": 1.0})
    assert "steps_per_dispatch" not in bench._run_one("googlenet_infer", 1.0)


def test_dispatch_overhead_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_dispatch_overhead",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("dispatch_overhead", 1.0, quick=True)
    assert seen == {"iters": 8, "k": 4}


def test_guard_overhead_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_guard_overhead",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("guard_overhead", 1.0, quick=True)
    assert seen == {"iters": 8, "k": 4}


def test_quantized_allreduce_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_quantized_allreduce",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("quantized_allreduce", 1.0, quick=True)
    assert seen == {"iters": 8, "k": 4}
    assert bench._result_key("quantized_allreduce") == "quantized_allreduce"


def test_zero_sharding_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_zero_sharding",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("zero_sharding", 1.0, quick=True)
    assert seen == {"iters": 8, "k": 4}
    assert bench._result_key("zero_sharding") == "zero_sharding"


def test_input_pipeline_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_input_pipeline",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("input_pipeline", 1.0, quick=True)
    assert seen == {"iters": 8, "k": 4}
    assert bench._result_key("input_pipeline") == "input_pipeline"


def test_device_cache_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_device_cache",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("device_cache", 1.0, quick=True)
    assert seen == {"iters": 8, "k": 4, "link_delay_ms": 20.0}
    assert bench._result_key("device_cache") == "device_cache"


def test_device_cache_row_schema():
    """The device_cache row (HBM-cached vs streamed vs compute-only +
    the slow-link overlap A/B) pins its schema: the round records are
    read for the ROADMAP gate (delivered >= 0.9x compute-only when the
    dataset fits residual HBM) and the overlap delta, so the keys and
    the zero-wire-bytes pin must not drift. Runs the real row at a
    tiny config — the cells are the contract, not the magnitudes."""
    row = bench.bench_device_cache(1e12, batch_size=8, iters=4, k=2,
                                   link_delay_ms=15.0)
    for key in ("value", "unit", "step_time_ms", "cached_vs_streamed_x",
                "h2d_bytes_epoch1", "h2d_bytes_epoch2",
                "overlap_vs_blocking", "cache", "steps_per_dispatch"):
        assert key in row, key
    assert set(row["step_time_ms"]) == {"streamed", "cached",
                                        "compute_only"}
    ob = row["overlap_vs_blocking"]
    assert set(ob) == {"blocking_step_ms", "overlap_step_ms",
                       "speedup_x", "link_delay_ms"}
    # the cache really served epoch 2: zero wire bytes moved
    assert row["h2d_bytes_epoch1"] > 0
    assert row["h2d_bytes_epoch2"] == 0
    assert row["cache"]["state"] == "full"
    assert row["cache"]["hits"] > 0
    assert row["steps_per_dispatch"] == 2


def test_serving_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_serving",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("serving", 1.0, quick=True)
    assert seen == {"requests": 40}
    assert bench._result_key("serving") == "serving"


def test_fusion_profile_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_fusion_profile",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("fusion_profile", 1.0, quick=True)
    assert seen == {"iters": 2, "batch_size": 4, "seq": 64}
    assert bench._result_key("fusion_profile") == "fusion_profile"


def test_elastic_reshard_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_elastic_reshard",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("elastic_reshard", 1.0, quick=True)
    assert seen == {"iters": 1}
    assert bench._result_key("elastic_reshard") == "elastic_reshard"


def test_train_rows_carry_top_fusions(monkeypatch):
    """Every train row records its top-k fusion table (the regression-
    attribution contract: two BENCH records diff via
    tools/profile_diff.py by these rows' stable keys), and a fusion
    failure degrades to an error field, never a lost row."""
    table = [{"key": "dot|dense/matmul|f32[8,8]", "name": "dot.1",
              "op": "dot", "kind": "dot", "computation": "main",
              "in_loop": False, "flops": 1024.0, "bytes": 768,
              "out_bytes": 256, "source_ops": ["dense/matmul"],
              "cost_frac": 0.9}]

    class _T:
        feed_wire = None

        def fusion_report(self, feed, top_k=8):
            return {"top_fusions": table, "n_units": 12,
                    "coverage_top_k": 0.97, "temp_mb": 1.5}

    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_T(), feed={"x": 1})
    assert row["top_fusions"] == table
    assert row["fusion_n_units"] == 12
    assert row["fusion_coverage_top_k"] == 0.97
    assert row["temp_mb"] == 1.5

    class _Broken(_T):
        def fusion_report(self, feed, top_k=8):
            raise RuntimeError("no HLO text on this backend")

    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_Broken(), feed={"x": 1})
    assert "top_fusions" not in row
    assert "no HLO text" in row["top_fusions_error"]
    assert row["value"] > 0  # the row itself survived

    # BENCH_FUSIONS=0 opt-out: no fusion work attempted
    monkeypatch.setenv("BENCH_FUSIONS", "0")
    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_Broken(), feed={"x": 1})
    assert "top_fusions" not in row and "top_fusions_error" not in row


def test_train_rows_carry_telemetry_snapshot():
    """Every train row records the measured window's registry counter
    deltas per step under `telemetry` (what _time_trainer snapshots
    around the pipelined loop); a trainer without a measured window
    (stubbed/infer paths) records none — never a crash."""

    class _T:
        feed_wire = None
        _bench_telemetry = {
            'paddle_tpu_trainer_steps_total{inst="0"}': 1.0,
            'paddle_tpu_feeder_h2d_bytes_total{inst="0"}': 25088.0,
        }

    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_T())
    assert row["telemetry"] == _T._bench_telemetry

    class _Bare:
        feed_wire = None

    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_Bare())
    assert "telemetry" not in row and row["value"] > 0


def test_rows_carry_shipper_deltas_when_collector_attached(monkeypatch):
    """With a telemetry collector attached (PDTPU_TELEMETRY_ADDR /
    ship_to), train and serving rows additionally record the measured
    window's SHIPPER counter deltas (events shipped/dropped, flush
    seconds) under `shipper`; without one the key is absent — never a
    crash."""

    # train row: _time_trainer snapshots into trainer._bench_shipper
    class _T:
        feed_wire = None
        _bench_telemetry = {'paddle_tpu_trainer_steps_total{inst="0"}': 1.0}
        _bench_shipper = {"events_shipped": 1.0, "events_dropped": 0.0,
                          "flush_seconds": 0.0002}

    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_T())
    assert row["shipper"] == _T._bench_shipper

    class _Bare:
        feed_wire = None

    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_Bare())
    assert "shipper" not in row

    # serving row: per-variant deltas, keyed like `telemetry`
    class _FakeShipper:
        def __init__(self):
            self.n = 0

        def counters(self):
            self.n += 1
            return {"events_shipped": 40.0 * self.n,
                    "events_dropped": 0.0,
                    "flush_seconds": 0.002 * self.n}

    class _Server:
        def close(self, drain=True, timeout=None):
            pass

    fake = _FakeShipper()
    monkeypatch.setattr(bench, "_shipper_snapshot",
                        lambda: (fake, fake.counters()))
    monkeypatch.setattr(bench, "_serving_predictors",
                        lambda bs: {"fp32": ("P32", {"x": 1}),
                                    "int8": ("P8", {"x": 1})})
    monkeypatch.setattr(bench, "_make_server",
                        lambda pred, workers, queue_size: _Server())
    monkeypatch.setattr(bench, "_calibrate_serving",
                        lambda server, feed, iters=8: 0.002)
    monkeypatch.setattr(bench, "_drive_serving",
                        lambda server, feed, n, rate: ([0.004] * n, 0))
    row = bench.bench_serving(1.0, batch_size=8, requests=20, workers=2,
                              queue_size=4)
    assert set(row["shipper"]) == {"fp32", "int8"}
    for ship in row["shipper"].values():
        assert isinstance(ship, dict)
        assert all(isinstance(v, float) for v in ship.values())
        assert ship["events_shipped"] == 40.0 / 20   # delta per request

    # no shipper active: the serving row omits the key
    monkeypatch.setattr(bench, "_shipper_snapshot", lambda: (None, None))
    row = bench.bench_serving(1.0, batch_size=8, requests=20, workers=2,
                              queue_size=4)
    assert "shipper" not in row


def test_rows_carry_collector_store_deltas_when_persistence_on(monkeypatch):
    """With a PERSISTING collector attached (store_dir), train and
    serving rows additionally record the store's ingest-write cost
    over the measured window (appends/bytes/append_seconds per step or
    request) under `collector_store`; a collector without persistence
    — or a shipper without a reachable collector — omits the key."""

    # train row: _time_trainer snapshots into trainer._bench_store
    class _T:
        feed_wire = None
        _bench_telemetry = {'paddle_tpu_trainer_steps_total{inst="0"}': 1.0}
        _bench_shipper = {"events_shipped": 1.0}
        _bench_store = {"appends": 0.5, "bytes": 120.0,
                        "append_seconds": 1e-5}

    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_T())
    assert row["collector_store"] == _T._bench_store

    class _NoStore:
        feed_wire = None
        _bench_shipper = {"events_shipped": 1.0}

    row = bench._result(8, "samples/sec", 1e-3, 1e-3, 1e6, 1e12,
                        trainer=_NoStore())
    assert "collector_store" not in row and row["shipper"]

    # the snapshot source: persistence off (or stats unreachable) -> None
    class _FakeShipper:
        def __init__(self, stats):
            self._stats = stats
            self.n = 0

        def counters(self):
            self.n += 1
            return {"events_shipped": 10.0 * self.n}

        def collector_stats(self):
            if self._stats is not None:
                self._stats = dict(self._stats)
                store = self._stats.get("store")
                if store:
                    self._stats["store"] = {
                        k: v * 2 for k, v in store.items()}
            return self._stats

    assert bench._store_snapshot(None) is None
    assert bench._store_snapshot(_FakeShipper(None)) is None
    assert bench._store_snapshot(
        _FakeShipper({"persistence": False})) is None
    snap = bench._store_snapshot(_FakeShipper(
        {"persistence": True,
         "store": {"appends": 4, "bytes": 100, "append_seconds": 0.001,
                   "segments": 2}}))
    assert snap == {"appends": 8.0, "bytes": 200.0,
                    "append_seconds": 0.002}

    # serving row: per-variant deltas keyed like `shipper`
    class _Server:
        def close(self, drain=True, timeout=None):
            pass

    fake = _FakeShipper({"persistence": True,
                         "store": {"appends": 4.0, "bytes": 100.0,
                                   "append_seconds": 0.001}})
    monkeypatch.setattr(bench, "_shipper_snapshot",
                        lambda: (fake, fake.counters()))
    monkeypatch.setattr(bench, "_serving_predictors",
                        lambda bs: {"fp32": ("P32", {"x": 1}),
                                    "int8": ("P8", {"x": 1})})
    monkeypatch.setattr(bench, "_make_server",
                        lambda pred, workers, queue_size: _Server())
    monkeypatch.setattr(bench, "_calibrate_serving",
                        lambda server, feed, iters=8: 0.002)
    monkeypatch.setattr(bench, "_drive_serving",
                        lambda server, feed, n, rate: ([0.004] * n, 0))
    row = bench.bench_serving(1.0, batch_size=8, requests=20, workers=2,
                              queue_size=4)
    assert set(row["collector_store"]) == {"fp32", "int8"}
    for store in row["collector_store"].values():
        assert set(store) == {"appends", "bytes", "append_seconds"}
        assert all(isinstance(v, float) for v in store.values())


def test_telemetry_counter_deltas_math():
    """counter_deltas is the snapshot's whole math: only moved series,
    normalized by the measured step/request count."""
    from paddle_tpu.telemetry import counter_deltas

    before = {"a": 10.0, "b": 5.0}
    after = {"a": 26.0, "b": 5.0, "c": 4.0}
    assert counter_deltas(before, after, per=8) == {"a": 2.0, "c": 0.5}
    assert counter_deltas(before, after) == {"a": 16.0, "c": 4.0}


def test_serving_row_schema(monkeypatch):
    """The serving row (PredictorServer steady p50/p99 + saturated
    reject rate, fp32 vs int8) pins its schema: downstream readers
    compare rounds by these exact keys. Export/server/driver are
    stubbed — the assembly math is pure python."""

    class _Server:
        def close(self, drain=True, timeout=None):
            pass

    monkeypatch.setattr(bench, "_serving_predictors",
                        lambda bs: {"fp32": ("P32", {"x": 1}),
                                    "int8": ("P8", {"x": 1})})
    monkeypatch.setattr(bench, "_make_server",
                        lambda pred, workers, queue_size: _Server())
    monkeypatch.setattr(bench, "_calibrate_serving",
                        lambda server, feed, iters=8: 0.002)
    monkeypatch.setattr(
        bench, "_drive_serving",
        # saturated phase (rate > capacity) rejects half the offered load
        lambda server, feed, n, rate: ([0.004] * n,
                                       n // 2 if rate > 1000.0 else 0))
    row = bench.bench_serving(1.0, batch_size=8, requests=20, workers=2,
                              queue_size=4)
    for key in ("value", "unit", "latency_ms", "reject_rate_saturated",
                "offered_rps", "telemetry", "requests", "workers",
                "queue_size", "batch_size"):
        assert key in row, key
    # the telemetry snapshot is per-variant: steady-phase registry
    # counter deltas per offered request (dict of series -> delta)
    assert set(row["telemetry"]) == {"fp32", "int8"}
    for tel in row["telemetry"].values():
        assert isinstance(tel, dict)
        assert all(isinstance(v, float) for v in tel.values())
    assert set(row["latency_ms"]) == {"fp32", "int8"}
    for v in row["latency_ms"].values():
        assert set(v) == {"p50", "p99"}
    assert row["value"] == row["latency_ms"]["fp32"]["p99"] == 4.0
    # capacity = 2 workers / 2ms = 1000 rps: steady at 600 keeps 0
    # rejects, saturated at 3000 sheds half
    assert row["reject_rate_saturated"] == {"fp32": 0.5, "int8": 0.5}
    assert row["offered_rps"]["fp32"]["steady_rps"] == 600.0
    assert row["offered_rps"]["fp32"]["saturated_rps"] == 3000.0


def test_serving_fleet_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_serving_fleet",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("serving_fleet", 1.0, quick=True)
    assert seen == {"requests": 60, "replicas": 2}
    assert bench._result_key("serving_fleet") == "serving_fleet"


def test_autoscale_quick_overrides(monkeypatch):
    seen = {}
    monkeypatch.setattr(bench, "bench_autoscale",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("autoscale", 1.0, quick=True)
    assert seen == {"low_s": 0.8, "burst_s": 1.5, "max_replicas": 2}
    assert bench._result_key("autoscale") == "autoscale"


def test_autoscale_row_schema(monkeypatch):
    """The autoscale row (closed-loop autoscaler vs statically
    peak-provisioned fleet over the same diurnal curve) pins its
    schema: rounds are compared by the p99 + worker-seconds-per-1k +
    SLO-attainment cells, so the keys must not drift. Artifact/front/
    driver/variant-runner are stubbed — the assembly math is pure
    python."""

    class _Front:
        def close(self, drain=True, timeout=None):
            pass

    monkeypatch.setattr(bench, "_fleet_artifact",
                        lambda bs: ("DIR", {"x": 1}))
    monkeypatch.setattr(
        bench, "_make_fleet_front",
        lambda dirname, variant, replicas, workers, queue_size,
        max_wait_ms: _Front())
    # one replica's measured coalesced capacity: 500 rps
    monkeypatch.setattr(bench, "_saturation_probe",
                        lambda front, feed, n=128, inflight=16: 500.0)
    info_by_variant = {
        "fixed": {"provisioned": 3, "peak_replicas": 3},
        "autoscaled": {"provisioned": 1, "peak_replicas": 3,
                       "scale_ups": 2, "scale_downs": 2},
    }
    # fixed burns 3 workers the whole elapsed 10s; autoscaled 16 ws
    ws_by_variant = {"fixed": 30.0, "autoscaled": 16.0}
    lat_by_variant = {"fixed": 0.004, "autoscaled": 0.006}

    def run_variant(dirname, variant, max_replicas, workers, queue_size,
                    max_wait_ms, feed, phases):
        n = sum(k for k, _ in phases)
        return ([lat_by_variant[variant]] * n, 0, 10.0,
                ws_by_variant[variant], info_by_variant[variant])

    monkeypatch.setattr(bench, "_run_autoscale_variant", run_variant)
    row = bench.bench_autoscale(1.0, batch_size=8, low_s=2.0, burst_s=4.0,
                                max_replicas=3, workers=1, queue_size=4,
                                max_wait_ms=2.0, slo_ms=50.0)
    for key in ("value", "unit", "latency_ms", "worker_seconds_per_1k",
                "slo_attainment", "slo_ms", "reject_rate", "scale",
                "offered_rps", "phases", "requests", "max_replicas",
                "workers", "queue_size", "batch_size", "max_wait_ms"):
        assert key in row, key
    variants = {"fixed", "autoscaled"}
    for per_variant in ("latency_ms", "worker_seconds_per_1k",
                        "slo_attainment", "reject_rate", "scale"):
        assert set(row[per_variant]) == variants, per_variant
    for v in row["latency_ms"].values():
        assert set(v) == {"p50", "p99"}
    assert row["value"] == row["latency_ms"]["autoscaled"]["p99"] == 6.0
    # curve: low = 0.4 * 500 = 200 rps for 2s (400 reqs) twice, burst
    # = 2.5 * 500 = 1250 rps for 4s (5000 reqs)
    assert row["offered_rps"] == {"low": 200.0, "burst": 1250.0}
    assert row["requests"] == 400 + 5000 + 400
    # worker-seconds per 1k completed: ws / n * 1000
    assert row["worker_seconds_per_1k"]["fixed"] == round(
        30.0 / 5800 * 1000, 2)
    assert row["worker_seconds_per_1k"]["autoscaled"] == round(
        16.0 / 5800 * 1000, 2)
    # 4/6ms latencies both inside the 50ms SLO
    assert row["slo_attainment"] == {"fixed": 1.0, "autoscaled": 1.0}
    assert row["scale"]["autoscaled"]["scale_ups"] == 2
    assert row["scale"]["fixed"]["provisioned"] == 3


def test_serving_fleet_row_schema(monkeypatch):
    """The serving_fleet row (p99 + throughput/worker at 3x saturation
    for single-process vs fleet vs coalesced-fleet, with the
    fleet-vs-single and coalesced-vs-pad-alone deltas) pins its schema:
    downstream readers compare rounds by these exact keys. Artifact/
    front/driver are stubbed — the assembly math is pure python."""

    class _Front:
        def close(self, drain=True, timeout=None):
            pass

    monkeypatch.setattr(bench, "_fleet_artifact",
                        lambda bs: ("DIR", {"x": 1}))
    monkeypatch.setattr(
        bench, "_make_fleet_front",
        lambda dirname, variant, replicas, workers, queue_size,
        max_wait_ms: _Front())
    monkeypatch.setattr(bench, "_calibrate_serving",
                        lambda front, feed, iters=8: 0.002)
    lat_by_variant = {"single": 0.004, "fleet": 0.003,
                      "fleet_coalesced": 0.002}
    calls = []

    def drive(front, feed, n, rate):
        variant = ("single", "fleet", "fleet_coalesced")[len(calls)]
        calls.append(rate)
        # every variant completes all n in n/100 s, at its own latency
        return [lat_by_variant[variant]] * n, 0, n / 100.0

    monkeypatch.setattr(bench, "_drive_fleet", drive)
    row = bench.bench_serving_fleet(1.0, batch_size=8, requests=20,
                                    replicas=2, workers=1, queue_size=4,
                                    max_wait_ms=2.0)
    for key in ("value", "unit", "latency_ms", "throughput_per_worker_rps",
                "reject_rate", "deltas", "telemetry", "offered_rps",
                "requests", "replicas", "workers", "queue_size",
                "batch_size", "max_wait_ms"):
        assert key in row, key
    variants = {"single", "fleet", "fleet_coalesced"}
    assert set(row["latency_ms"]) == variants
    assert set(row["telemetry"]) == variants
    for v in row["latency_ms"].values():
        assert set(v) == {"p50", "p99"}
    # calibrated ONCE on the single front: 3x * 2 workers / 2ms = 3000
    # rps offered to every variant
    assert calls == [3000.0] * 3
    assert row["offered_rps"] == 3000.0
    # completed 20 in 0.2s over 2 workers = 50 rps/worker everywhere
    assert row["throughput_per_worker_rps"] == {
        "single": 50.0, "fleet": 50.0, "fleet_coalesced": 50.0}
    assert row["value"] == row["latency_ms"]["fleet_coalesced"]["p99"] == 2.0
    d = row["deltas"]
    assert set(d) == {"fleet_vs_single", "coalesced_vs_pad_alone"}
    assert d["fleet_vs_single"]["p99_ms"] == 3.0 - 4.0
    assert d["coalesced_vs_pad_alone"]["p99_ms"] == 2.0 - 3.0
    assert d["fleet_vs_single"]["throughput_per_worker_ratio"] == 1.0


def test_input_pipeline_row_schema(monkeypatch):
    """The input_pipeline row (fp32 vs bf16 vs uint8 wire at K=1/K=16)
    pins its schema here: the driver's round records are read by byte
    math downstream, so the wire/logical byte fields and the per-cell
    step-time keys must not silently drift. Timing and Trainer are
    stubbed — the byte math is pure python."""
    monkeypatch.setattr(bench, "_time_trainer",
                        lambda tr, feeds, **kw: (1e-3, 1e-3))

    class _T:
        feed_wire = None

        def startup(self, **kw):
            pass

    import paddle_tpu as pt
    monkeypatch.setattr(pt, "Trainer", lambda *a, **kw: _T())
    row = bench.bench_input_pipeline(1.0, batch_size=8, iters=2, k=2)
    for key in ("value", "unit", "step_time_ms", "feed_wire_bytes_per_step",
                "feed_logical_bytes_per_step", "steps_per_dispatch",
                "speedup_uint8_vs_fp32_k1", "speedup_uint8_vs_fp32_fused",
                "speedup_bf16_vs_fp32_fused"):
        assert key in row, key
    assert row["steps_per_dispatch"] == 2  # names the K "fused" measured
    # the acceptance lever: uint8 wire cuts >= 3.5x off the fp32 bytes
    assert row["value"] >= 3.5
    b = row["feed_wire_bytes_per_step"]
    assert b["fp32"] > b["bf16"] > b["uint8"]
    assert set(row["step_time_ms"]) == {f"{v}_k{kk}" for v in
                                        ("fp32", "bf16", "uint8")
                                        for kk in (1, 2)}


def test_assemble_headline_and_partial_shape():
    configs = {
        "mnist_mlp_train": {"mfu": 0.4, "value": 1.0},
        "bert_train": {"mfu": 0.55, "value": 2.0},
        "resnet50_train": {"mfu": 0.5, "value": 3.0, "vs_baseline": 24.0},
        "resnet50_infer_bf16": {"mfu": 0.9, "value": 4.0},  # infer: no headline
        "broken_train": {"error": "Timeout"},
    }
    res = bench._assemble(configs, "TPU v5 lite", 197e12, "table", "bfloat16")
    assert res["metric"] == "suite"
    assert res["value"] == 0.55          # max TRAIN mfu only
    assert res["vs_baseline"] == 24.0    # resnet50 ratio carried up
    assert res["device"] == "TPU v5 lite"
    assert res["configs"] is configs


def test_assemble_degraded_link_uses_compute_only():
    """Below LINK_DEGRADED_MBPS the pipelined numbers measure the dev
    tunnel, not the framework: the headline must switch to the
    compute-only variant, say so in the unit, and flag the record."""
    configs = {
        "bert_train": {"mfu": 0.01, "mfu_compute_only": 0.55, "value": 2.0},
        "resnet50_train": {"mfu": 0.002, "mfu_compute_only": 0.3, "value": 3.0,
                           "compute_only": 2000.0, "vs_baseline": 0.2},
    }
    res = bench._assemble(configs, "TPU v5 lite", 197e12, "table", "bfloat16",
                          h2d_mbps=12.0)
    assert res["link_degraded"] is True
    assert res["value"] == 0.55
    assert "compute-only" in res["unit"]
    assert res["vs_baseline"] == round(2000.0 / bench.BASELINES["resnet50"], 2)
    # healthy link: pipelined headline, no flag
    res2 = bench._assemble(configs, "TPU v5 lite", 197e12, "table", "bfloat16",
                           h2d_mbps=8000.0)
    assert "link_degraded" not in res2 and res2["value"] == 0.01
    assert res2["unit"] == "MFU"


def test_baselines_match_baseline_md_rows():
    # the ratios the suite reports are anchored to these exact numbers
    assert bench.BASELINES["resnet50"] == 81.69
    assert bench.BASELINES["resnet50_infer_fp32"] == 217.69
    assert bench.BASELINES["googlenet_infer"] == 600.94
    assert abs(bench.BASELINES["lstm_big"] - 256 / 1.655) < 1e-9


def test_load_mid_round_picks_latest_valid(tmp_path):
    import json
    (tmp_path / "BENCH_mid_r03.json").write_text(json.dumps(
        {"configs": {"a_train": {"mfu": 0.1, "value": 1.0}}}))
    (tmp_path / "BENCH_mid_r04.json").write_text(json.dumps(
        {"configs": {"b_train": {"mfu": 0.2, "value": 2.0}}}))
    rec = bench._load_mid_round(root=str(tmp_path))
    assert "b_train" in rec["configs"]
    assert rec["_source"] == "BENCH_mid_r04.json"
    # a corrupt latest file falls through to the previous one
    (tmp_path / "BENCH_mid_r05.json").write_text("{not json")
    rec = bench._load_mid_round(root=str(tmp_path))
    assert rec["_source"] == "BENCH_mid_r04.json"
    assert bench._load_mid_round(root=str(tmp_path / "empty")) is None


def test_backfill_fills_only_holes(monkeypatch):
    """A live row (even a slow one) beats a carried row; errored and
    missing rows are backfilled from the mid-round record with the
    provenance marker so the judge can tell which is which."""
    mid = {"configs": {
        "resnet50_train": {"mfu": 0.3, "value": 2000.0},
        "bert_train": {"mfu": 0.4, "value": 5.0},
        "gpt_train": {"error": "timeout 600s"},   # errored mid rows never carry
    }}
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: mid)
    configs = {
        "resnet50_train": {"mfu": 0.1, "value": 900.0},  # live wins
        "bert_train": {"error": "Timeout: config exceeded 600s"},
    }
    bench._backfill_from_mid_round(configs)
    assert configs["resnet50_train"]["value"] == 900.0
    assert "carried_from_mid_round" not in configs["resnet50_train"]
    assert configs["bert_train"]["value"] == 5.0
    assert configs["bert_train"]["carried_from_mid_round"] is True
    assert "exceeded 600s" in configs["bert_train"]["live_error"]
    assert "gpt_train" not in configs
    # mid record untouched (backfill must copy, not alias)
    assert "carried_from_mid_round" not in mid["configs"]["bert_train"]


def test_probe_fail_falls_back_to_mid_round(monkeypatch):
    # h2d None (the mid-round probe died before the bandwidth read) must
    # still force the compute-only headline: a failed probe IS a dead link
    mid = {"configs": {"bert_train": {"mfu": 0.01, "mfu_compute_only": 0.5,
                                      "value": 5.0}},
           "device": "TPU v5 lite", "peak_flops": 197e12,
           "peak_source": "table", "host_to_device_mbps": None,
           "compute_dtype": "bfloat16", "_source": "BENCH_mid_r04.json"}
    monkeypatch.setattr(bench, "_probe_device", lambda *a, **k: (None, None))
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: mid)
    res = bench.run_suite()
    assert res["link_down_at_suite_time"] is True
    assert res["value"] == 0.5            # dead link -> compute-only
    assert "compute-only" in res["unit"]
    assert res["host_to_device_mbps"] is None
    assert res["configs"]["bert_train"]["carried_from_mid_round"] is True
    assert "mid-round" in res["note"]
    # no mid record at all: the old explicit-error record
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: None)
    res = bench.run_suite()
    assert "device probe failed" in res["error"]


def test_backfill_respects_scheduled_scope(monkeypatch):
    """BENCH_ONLY debug runs must not sprout rows they never attempted."""
    mid = {"configs": {"resnet50_train": {"mfu": 0.3, "value": 2000.0},
                       "bert_train": {"mfu": 0.4, "value": 5.0}}}
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: mid)
    configs = {"mnist_mlp_train": {"mfu": 0.0, "value": 8000.0}}
    bench._backfill_from_mid_round(configs, scheduled={"mnist_mlp_train"})
    assert set(configs) == {"mnist_mlp_train"}


def test_backfill_never_carries_ab_variant_rows(monkeypatch):
    """chip_queue's A/B rows (key@variant) live in the mid record for
    the judge but must not leak into suite records: the suite never
    measures variant keys, so a carried one would persist forever."""
    mid = {"configs": {
        "transformer_train": {"mfu": 0.3, "value": 2000.0},
        "transformer_train@no_flash": {"mfu": 0.2, "value": 1500.0},
    }}
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: mid)
    configs = {}
    bench._backfill_from_mid_round(configs,
                                   scheduled={"transformer_train"})
    assert set(configs) == {"transformer_train"}
    # unscoped (signal-handler) path skips variants too
    configs = {}
    bench._backfill_from_mid_round(configs)
    assert set(configs) == {"transformer_train"}


def test_assemble_carried_rows_never_drive_headline():
    """The one-line headline reflects the code under test: carried
    (prior-capture) rows are excluded from the max unless NO live train
    row was measured at all — and then the unit discloses it."""
    configs = {
        "bert_train": {"mfu": 0.9, "value": 5.0,
                       "carried_from_mid_round": True},
        "transformer_train": {"mfu": 0.2, "value": 2.0},
    }
    res = bench._assemble(configs, "TPU v5 lite", 197e12, "table", "bfloat16")
    assert res["value"] == 0.2                     # live row wins
    assert res["unit"] == "MFU"
    assert res["carried_configs"] == ["bert_train"]
    # all rows carried: headline falls back to them, unit says so
    res2 = bench._assemble(
        {"bert_train": configs["bert_train"]},
        "TPU v5 lite", 197e12, "table", "bfloat16")
    assert res2["value"] == 0.9
    assert "carried from mid-round" in res2["unit"]


def test_all_error_mid_record_yields_explicit_error(monkeypatch):
    """A mid record whose rows are ALL errors must not produce a
    success-shaped empty record on probe failure."""
    mid = {"configs": {"bert_train": {"error": "timeout"}},
           "compute_dtype": "bfloat16", "_source": "BENCH_mid_r04.json"}
    monkeypatch.setattr(bench, "_probe_device", lambda *a, **k: (None, None))
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: mid)
    res = bench.run_suite()
    assert "error" in res and res["value"] == 0.0


def test_mid_record_dtype_and_quick_gating(monkeypatch):
    """Carried rows only make sense under the same measurement settings:
    quick mode and a different compute_dtype both disable the fallback."""
    mid = {"configs": {"bert_train": {"mfu": 0.5, "mfu_compute_only": 0.5,
                                      "value": 5.0}},
           "compute_dtype": "bfloat16", "_source": "BENCH_mid_r04.json"}
    monkeypatch.setattr(bench, "_probe_device", lambda *a, **k: (None, None))
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: mid)
    assert "error" in bench.run_suite(compute_dtype="float32")
    assert "error" in bench.run_suite(quick=True)
    assert "error" not in bench.run_suite()   # matching settings: fallback


def test_assemble_live_headline_drops_carried_vs_baseline():
    configs = {
        "resnet50_train": {"mfu": 0.3, "value": 2000.0, "vs_baseline": 24.0,
                           "carried_from_mid_round": True},
        "transformer_train": {"mfu": 0.2, "value": 2.0},
    }
    res = bench._assemble(configs, "TPU v5 lite", 197e12, "table", "bfloat16")
    assert res["value"] == 0.2 and res["vs_baseline"] is None
    # fully-carried record: the ratio is allowed (unit already discloses)
    res2 = bench._assemble(
        {"resnet50_train": configs["resnet50_train"]},
        "TPU v5 lite", 197e12, "table", "bfloat16")
    assert res2["vs_baseline"] == 24.0


def test_unstamped_mid_record_rejected(monkeypatch):
    """A mid record with no compute_dtype field is a mismatch: rows of
    unknown dtype must not be presented as this run's compute_dtype."""
    mid = {"configs": {"bert_train": {"mfu": 0.5, "mfu_compute_only": 0.5,
                                      "value": 5.0}},
           "_source": "BENCH_mid_r04.json"}
    monkeypatch.setattr(bench, "_probe_device", lambda *a, **k: (None, None))
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: mid)
    assert "error" in bench.run_suite()


def test_load_mid_round_normalizes_envelope_rows(tmp_path):
    import json
    (tmp_path / "BENCH_mid_r04.json").write_text(json.dumps(
        {"configs": {"bert_train": {"result": {"mfu": 0.4, "value": 7.0},
                                    "device": "TPU v5 lite"}}}))
    rec = bench._load_mid_round(root=str(tmp_path))
    assert rec["configs"]["bert_train"] == {"mfu": 0.4, "value": 7.0}


def test_timed_out_configs_get_one_retry(monkeypatch):
    """The persistent compile cache makes attempt 1's compile reusable,
    so the suite retries each timed-out config once; a successful retry
    replaces the timeout row."""
    import subprocess as sp

    monkeypatch.setenv("BENCH_ONLY", "mnist_mlp")
    monkeypatch.setattr(bench, "_probe_device",
                        lambda *a, **k: ("TPU v5 lite", 9000.0))
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: None)
    calls = []

    class FakeChild:
        def __init__(self, attempt):
            self.attempt = attempt
            self.returncode = 0

        def communicate(self, timeout=None):
            if self.attempt == 0 and timeout is not None:
                # the post-kill reap calls communicate() with no timeout
                raise sp.TimeoutExpired("cmd", timeout)
            if self.attempt == 0:
                return ("", "")
            import json
            return (json.dumps({"result": {"value": 1.0, "unit": "u",
                                           "mfu": 0.5},
                                "device": "TPU v5 lite",
                                "peak_flops": 197e12,
                                "peak_source": "table"}) + "\n", "")

        def poll(self):
            return self.returncode

        def kill(self):
            pass

    def fake_popen(cmd, **kw):
        child = FakeChild(len(calls))
        calls.append(cmd)
        return child

    monkeypatch.setattr(sp, "Popen", fake_popen)
    res = bench.run_suite()
    assert len(calls) == 2                     # attempt + one retry
    assert res["configs"]["mnist_mlp_train"]["mfu"] == 0.5
    assert "timed_out" not in res["configs"]["mnist_mlp_train"]
    assert res["value"] == 0.5


def test_assemble_strips_retry_marker():
    configs = {"bert_train": {"error": "Timeout: ...", "timed_out": True}}
    res = bench._assemble(configs, "TPU", 197e12, "table", "bfloat16")
    assert "timed_out" not in res["configs"]["bert_train"]


def test_child_deadline_timeouts_also_retry(monkeypatch):
    """A child-side _ConfigTimeout (SIGALRM deadline inside the config
    subprocess) is the same rescue case as a parent-level kill: the
    retry pass must pick it up."""
    import json as _json
    import subprocess as sp

    monkeypatch.setenv("BENCH_ONLY", "mnist_mlp")
    monkeypatch.setattr(bench, "_probe_device",
                        lambda *a, **k: ("TPU v5 lite", 9000.0))
    monkeypatch.setattr(bench, "_load_mid_round", lambda root=None: None)
    calls = []

    class FakeChild:
        def __init__(self, attempt):
            self.attempt = attempt
            self.returncode = 0

        def communicate(self, timeout=None):
            if self.attempt == 0:
                return (_json.dumps(
                    {"error": "_ConfigTimeout: config exceeded 1200s"}), "")
            return (_json.dumps({"result": {"value": 2.0, "unit": "u",
                                            "mfu": 0.4},
                                 "device": "TPU", "peak_flops": 197e12,
                                 "peak_source": "table"}), "")

        def poll(self):
            return 0

        def kill(self):
            pass

    monkeypatch.setattr(sp, "Popen",
                        lambda cmd, **kw: (calls.append(cmd),
                                           FakeChild(len(calls) - 1))[1])
    res = bench.run_suite()
    assert len(calls) == 2
    assert res["configs"]["mnist_mlp_train"]["mfu"] == 0.4
