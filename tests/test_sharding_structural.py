"""Transpiler-style structural sharding tests (SURVEY §4 implication 2,
test_dist_transpiler.py pattern): assert the EXACT PartitionSpec each
preset rule table produces for zoo-model parameters, and that dropped
axes warn loudly (multi_devices_check_pass analog)."""

import warnings

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.models import transformer
from paddle_tpu.parallel import sharding


@pytest.fixture
def tp_mesh():
    return pt.make_mesh({"dp": 2, "fsdp": 2, "tp": 2})


def _transformer_params():
    cfg = transformer.base_config(src_vocab=64, trg_vocab=64, d_model=16,
                                  d_inner=32, num_heads=2, num_encoder_layers=1,
                                  num_decoder_layers=1, dropout=0.0)
    prog = pt.build(transformer.make_model(cfg))
    rng = np.random.RandomState(0)
    src = rng.randint(3, 64, (2, 8)).astype(np.int64)
    feed = {"src_ids": src, "trg_ids": src, "labels": src}
    params, _ = prog.init(jax.random.PRNGKey(0), **feed)
    return params


EXPECTED_TP_SPECS = {
    "encoder/mha_0/q_proj/w": P("fsdp", "tp"),
    "encoder/mha_0/k_proj/w": P("fsdp", "tp"),
    "encoder/mha_0/v_proj/w": P("fsdp", "tp"),
    "encoder/mha_0/q_proj/b": P("tp"),
    "encoder/mha_0/out_proj/w": P("tp", "fsdp"),
    "encoder/mha_0/out_proj/b": P(),
    "encoder/ffn_0/ffn_in/w": P("fsdp", "tp"),
    "encoder/ffn_0/ffn_in/b": P("tp"),
    "encoder/ffn_0/ffn_out/w": P("tp", "fsdp"),
    "encoder/layer_norm_0/scale": P(),
    "decoder/mha_1/v_proj/w": P("fsdp", "tp"),
    "decoder/ffn_1/ffn_out/w": P("tp", "fsdp"),
    "src/embedding_0/w": P("tp", None),
    "trg/embedding_1/w": P("tp", None),
    "logits_proj_0/w": P(None, "fsdp"),
}


def test_transformer_tp_rules_exact_specs(tp_mesh):
    params = _transformer_params()
    rules = pt.parallel.transformer_tp_rules()
    for name, expected in EXPECTED_TP_SPECS.items():
        assert name in params, f"model no longer has param {name}"
        got = rules.spec_for(name, params[name].shape, tp_mesh)
        assert got == expected, f"{name}: got {got}, want {expected}"


def test_transformer_tp_rules_every_param_resolves(tp_mesh):
    """Every zoo param resolves to a spec whose axes divide its dims —
    i.e. the preset never relies on the permissive drop path."""
    params = _transformer_params()
    rules = pt.parallel.transformer_tp_rules()
    sharding.reset_drop_warnings()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for name, v in params.items():
            rules.spec_for(name, v.shape, tp_mesh)
    drops = [w for w in rec if "sharding rule" in str(w.message)]
    assert not drops, [str(w.message) for w in drops]


def test_fsdp_preset_shards_largest_dim():
    mesh = pt.make_mesh({"fsdp": 8})
    rules = pt.parallel.fsdp(min_size_to_shard=64)
    assert rules.spec_for("x/w", (128, 64), mesh) == P("fsdp", None)
    assert rules.spec_for("x/w", (64, 128), mesh) == P(None, "fsdp")
    # too small -> replicated
    assert rules.spec_for("x/b", (7,), mesh) == P()
    # no dim divisible -> replicated
    assert rules.spec_for("x/w", (65, 67), mesh) == P()


def test_dropped_axis_warns_once(tp_mesh):
    sharding.reset_drop_warnings()
    rules = pt.parallel.ShardingRules([(r".*typo.*", P("tpp"))], default=P())
    with warnings.catch_warnings(record=True) as rec:
        # "default" action exercises the warnings-module registry dedup
        # (once per rule key); "always" would re-warn every call
        warnings.simplefilter("default")
        rules.spec_for("a/typo/w", (16, 16), tp_mesh)
        rules.spec_for("b/typo/w", (16, 16), tp_mesh)
    msgs = [str(w.message) for w in rec if "not in the mesh" in str(w.message)]
    assert len(msgs) == 1 and "'tpp'" in msgs[0], msgs


def test_non_divisible_dim_warns(tp_mesh):
    sharding.reset_drop_warnings()
    rules = pt.parallel.ShardingRules([(r".*odd.*", P("tp"))], default=P())
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        spec = rules.spec_for("x/odd/w", (15, 16), tp_mesh)
    assert spec == P(None)  # degraded to replicated...
    msgs = [str(w.message) for w in rec if "not divisible" in str(w.message)]
    assert len(msgs) == 1, msgs  # ...but loudly


DRYRUN_MESHES = [
    {"dp": 2, "fsdp": 2, "tp": 2},   # _dryrun_trainer
    {"dp": 2, "tp": 2, "pp": 2},     # _dryrun_pipeline (the r3 warning mesh)
    {"dp": 2, "sp": 4},              # _dryrun_sp
    {"dp": 2, "ep": 4},              # _dryrun_moe
    {"dp": 8},                       # degenerate single-axis
]


def _dryrun_rule_sets():
    yield "tp", pt.parallel.transformer_tp_rules()
    yield "tp+moe", pt.parallel.transformer_tp_rules(
        extra=list(pt.parallel.moe_ep_rules()))
    yield "moe", pt.parallel.ShardingRules(
        list(pt.parallel.moe_ep_rules()), default=None)
    yield "sp", pt.parallel.ShardingRules(seq_axis="sp")
    yield "fsdp", pt.parallel.fsdp(min_size_to_shard=64)


@pytest.mark.parametrize("axes", DRYRUN_MESHES,
                         ids=lambda a: "x".join(a))
def test_adapted_rules_warning_free_on_dryrun_meshes(axes):
    """MULTICHIP r3 regression: preset rule tables adapted to each
    driver-dryrun mesh must resolve every zoo param without tripping the
    _validate replication warning (VERDICT r3 next-round #4)."""
    mesh = pt.make_mesh(axes)
    params = _transformer_params()
    sharding.reset_drop_warnings()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for _, rules in _dryrun_rule_sets():
            adapted = rules.adapted_to(mesh)
            for name, v in params.items():
                adapted.spec_for(name, v.shape, mesh)
            adapted.batch_spec(mesh, 2, shape=(16, 16))
    drops = [w for w in rec if "sharding rule" in str(w.message)]
    assert not drops, [str(w.message) for w in drops]


def test_adapted_to_drops_foreign_axes_only():
    mesh = pt.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    rules = pt.parallel.transformer_tp_rules()
    adapted = rules.adapted_to(mesh)
    # fsdp dropped, tp kept, on the exact rule the r3 dryrun warned about
    assert adapted.spec_for("logits_proj_0/w", (16, 64), mesh) == P(None, None)
    assert adapted.spec_for("enc/mha_0/q_proj/w", (16, 16), mesh) == P(None, "tp")
    # original table untouched (adapted_to returns a copy)
    full = pt.make_mesh({"fsdp": 4, "tp": 2})
    assert rules.spec_for("enc/mha_0/q_proj/w", (16, 16), full) == P("fsdp", "tp")


def test_adapted_to_preserves_fsdp_subclass_and_seq_axis():
    mesh_nofsdp = pt.make_mesh({"dp": 8})
    f = pt.parallel.fsdp(min_size_to_shard=64).adapted_to(mesh_nofsdp)
    assert f.spec_for("x/w", (128, 64), mesh_nofsdp) == P()  # subclass logic intact
    mesh_fsdp = pt.make_mesh({"fsdp": 8})
    f2 = pt.parallel.fsdp(min_size_to_shard=64).adapted_to(mesh_fsdp)
    assert f2.spec_for("x/w", (128, 64), mesh_fsdp) == P("fsdp", None)
    sp = pt.parallel.ShardingRules(seq_axis="sp")
    assert sp.adapted_to(mesh_nofsdp).seq_axis is None
    assert sp.adapted_to(pt.make_mesh({"sp": 8})).seq_axis == "sp"


def test_adapted_to_warns_on_noncanonical_axis_typo():
    """adapted_to silently sheds canonical preset vocabulary, but a
    hand-written rule with a typo'd axis must still warn at adapt time."""
    mesh = pt.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    sharding.reset_drop_warnings()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        pt.parallel.ShardingRules([(r".*/w", P("fdsp", "tp"))]).adapted_to(mesh)
    msgs = [str(w.message) for w in rec if "likely a typo" in str(w.message)]
    assert len(msgs) == 1 and "'fdsp'" in msgs[0], msgs
    # canonical axes stay silent
    sharding.reset_drop_warnings()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        pt.parallel.transformer_tp_rules().adapted_to(mesh)
    assert not [w for w in rec if "sharding" in str(w.message).lower()]


def test_adapted_to_memoized_and_idempotent():
    mesh = pt.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    rules = pt.parallel.transformer_tp_rules()
    a1 = rules.adapted_to(mesh)
    assert rules.adapted_to(mesh) is a1          # memoized on the source
    assert a1.adapted_to(mesh) is a1             # already-adapted short-circuits
    other = pt.make_mesh({"dp": 4, "fsdp": 2})
    assert a1.adapted_to(other) is not a1        # different axis set re-adapts


def test_trainer_adapts_rules_at_construction():
    """Trainer(mesh=..., sharding_rules=preset) must not rely on the
    warning fallback: its stored rules are pre-adapted to the mesh."""
    from paddle_tpu import optimizer as opt
    cfg = transformer.base_config(src_vocab=64, trg_vocab=64, d_model=16,
                                  d_inner=32, num_heads=2,
                                  num_encoder_layers=1, num_decoder_layers=1,
                                  dropout=0.0)
    prog = pt.build(transformer.make_model(cfg))
    mesh = pt.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    sharding_rules=pt.parallel.transformer_tp_rules())
    sharding.reset_drop_warnings()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        params = _transformer_params()
        for name, v in params.items():
            tr.sharding_rules.spec_for(name, v.shape, mesh)
    drops = [w for w in rec if "sharding rule" in str(w.message)]
    assert not drops, [str(w.message) for w in drops]


def test_executor_jit_cache_keyed_on_program_object():
    """A dead Program's id must not alias a new Program's cache entry."""
    import gc

    exe = pt.Executor()

    def make(mult):
        def f(x):
            return {"y": x * mult}
        return pt.build(f)

    x = np.ones((2,), np.float32)
    outs = []
    for mult in (2.0, 3.0, 4.0):
        prog = make(mult)
        outs.append(float(exe.run(prog, feed={"x": x}, fetch_list=["y"])[0][0]))
        del prog
        gc.collect()
    assert outs == [2.0, 3.0, 4.0]
