"""Pipeline parallelism over the ``pp`` mesh axis.

Gap-fill component (SURVEY §2.2: PP is absent in the reference).
TPU-native design: for repeated-structure models (transformer blocks),
per-layer parameters are STACKED on a leading [num_layers, ...] axis and
sharded over ``pp``. A schedule runs M microbatches through the ranks
inside one ``shard_map``: each tick, every rank applies its local
layer-chunk to the activation it holds, then ``ppermute``s the result to
the next rank (neighbor ICI hop). Activations enter at rank 0 and exit
at rank P-1, which all-gathers the finished microbatches.

Two schedules, selected by ``interleave`` (= V, virtual stages/rank):

- V=1 (GPipe): rank r owns one contiguous span of L/P layers; the loop
  runs M + P - 1 ticks, of which P-1 are fill/drain bubble.
- V>1 (Megatron interleaved / virtual pipeline): rank r owns V
  NON-adjacent chunks of L/(P·V) layers (global chunk q lives on rank
  q mod P), and chunk q of microbatch j runs at tick
  (j÷P)·VP + (q÷P)·P + (q mod P) + (j mod P). Under this assignment
  every activation produced at tick t is consumed at tick t+1 by the
  next ring rank, so the PER-TICK communication structure is identical
  to GPipe (one ppermute per tick, single holding buffer); the loop
  runs M·V + P - 1 ticks of 1/V the work each, shrinking the bubble
  time by V× (see ``bubble_fraction`` for the exact P ∤ M case) at the
  cost of V× more (pipelined, neighbor-hop) activation traffic. With
  ``param_layout="stacked"`` (logical layer order at rest) the schedule
  additionally pays a once-per-step re-layout of (V-1)/V of the stacked
  parameter bytes into chunk-interleaved order (an all-to-all over pp;
  gradients take the inverse path in backward); the Trainer avoids it
  by storing stacked rows chunk-interleaved at startup — the Megatron
  layout, :func:`interleave_perm` — and passing
  ``param_layout="interleaved"``, under which the re-chunk is a local
  reshape and the step's only collectives are the activation ppermutes
  (pinned by tests/test_pipeline.py's HLO structural test). This is
  the schedule half of 1F1B: the memory half (depth-bounded live
  activations) is expressed through per-microbatch rematerialization
  (``DistStrategy.remat``) instead, because reverse-mode over the scan
  already frees what remat drops.

Dropout: the schedule threads an explicit rng key (``rng_key``), folded
per (global layer, microbatch, data-shard position) inside the body, so
masks decorrelate across layers/microbatches/data shards and every
(layer, microbatch) application — computed on exactly one rank at one
tick — is deterministic given the step key. The tp axis is deliberately
NOT folded: post-psum residual masks must agree across tp ranks (they
apply to replicated activations); the pre-psum sites (attention probs,
ffn inner) then reuse one mask pattern across a layer's tp-local head/
hidden blocks — still valid per-element Bernoulli, just block-
correlated, matching what the masks' shared key implies.

Composable with dp/tp: batch stays sharded on dp; stacked layer params
can additionally shard their weight dims on tp.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.errors import enforce
from .mesh import pvary


def stack_layer_params(per_layer_params: list) -> Any:
    """Stack a list of per-layer param pytrees into [L, ...] leaves."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params)


def interleave_perm(L: int, pp: int, v: int):
    """Row permutation taking logical layer order to the rank-major
    chunk-interleaved rest layout (Megatron virtual-stage storage).

    Row j of the interleaved layout holds logical layer ``perm[j]``:
    rank r's V local chunks live contiguously at rows [r·V·Lc,
    (r+1)·V·Lc), local chunk c being GLOBAL chunk c·P + r (layers
    [(c·P+r)·Lc, (c·P+r+1)·Lc)). Sharding the leading dim over pp then
    hands each rank exactly its chunks with no data movement; the
    inverse layout is ``np.argsort(interleave_perm(...))``."""
    import numpy as np

    enforce(L % (pp * v) == 0,
            f"{L} layers not divisible by pp·interleave={pp}·{v}")
    Lc = L // (pp * v)
    perm = np.empty(L, dtype=np.int64)
    j = 0
    for r in range(pp):
        for c in range(v):
            g = c * pp + r
            for i in range(Lc):
                perm[j] = g * Lc + i
                j += 1
    return perm


def _schedule_ticks(m: int, p: int, v: int) -> int:
    """Total ticks: the last microbatch's last chunk runs at
    ((m-1)÷p)·vp + (v-1)·p + (p-1) + ((m-1) mod p); +1 for the count.
    Reduces to m + p - 1 when v=1 or p | m: m·v + p - 1."""
    return ((m - 1) // p) * v * p + (v - 1) * p + (p - 1) + ((m - 1) % p) + 1


def _pp_body(x, stacked, extras, rng_key, layer_fn, axis_name: str,
             microbatches: int, interleave: int,
             varying_axes: Tuple[str, ...],
             data_axes: Tuple[str, ...] = ()):
    """Per-rank body. x: local microbatch stack [M, ...mb shape...] on
    rank 0's slot (all ranks receive the same x spec; only rank 0's
    content is used). stacked: this rank's [V, layers_per_chunk, ...]
    params — chunk c here is GLOBAL chunk c·P + rank. extras: pytree of
    [M, ...] per-microbatch side inputs (masks, encoder outputs) — each
    rank indexes the extras for the microbatch it is processing that
    tick rather than forwarding them. rng_key: replicated per-step key,
    or None when the blocks draw no randomness; folded per (global
    layer, microbatch, data-shard) before each layer_fn call so dropout
    masks decorrelate (tp deliberately excluded — see module doc)."""
    from ..framework import rng_scope

    p = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    m, v = microbatches, interleave
    Lc = jax.tree.leaves(stacked)[0].shape[1]
    if rng_key is not None:
        for a in data_axes:
            rng_key = jax.random.fold_in(rng_key, jax.lax.axis_index(a))

    def apply_chunk(act, chunk_idx, extra, mb_idx):
        chunk = jax.tree.map(
            lambda leaf: jax.lax.dynamic_index_in_dim(leaf, chunk_idx, 0,
                                                      keepdims=False),
            stacked)
        chunk_base = (chunk_idx * p + rank) * Lc  # first global layer

        def one_layer(a, xs):
            layer_params, li = xs
            key = None if rng_key is None else jax.random.fold_in(
                jax.random.fold_in(rng_key, chunk_base + li), mb_idx)
            with rng_scope(key):
                if extra is None:
                    return layer_fn(a, layer_params), None
                return layer_fn(a, layer_params, extra), None
        out, _ = jax.lax.scan(one_layer, act, (chunk, jnp.arange(Lc)))
        return out

    mb_shape = x.shape[1:]
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        holding, outputs = carry
        # this rank's position in the interleaved schedule at tick t:
        # u = t - rank counts its chunk-computations; within a group of
        # P microbatches it cycles chunk c for mb (g·P + u mod P).
        groups = -(-m // p)
        u_glob = jnp.clip(t - rank, 0, groups * v * p - 1)
        g = u_glob // (v * p)
        u = u_glob % (v * p)
        c_local = u // p                       # which of this rank's V chunks
        mb_idx = jnp.clip(g * p + u % p, 0, m - 1)
        # rank 0 starting a chunk-0 pass ingests a fresh microbatch;
        # everything else continues from what arrived on the ring
        fresh = x[mb_idx]
        cur = jnp.where((rank == 0) & (c_local == 0), fresh, holding)
        extra = (None if extras is None
                 else jax.tree.map(lambda e: e[mb_idx], extras))
        done = apply_chunk(cur, c_local, extra, mb_idx)
        # last rank finishing its last chunk completes microbatch mb_idx
        record = (rank == p - 1) & (c_local == v - 1) & (t - rank >= 0) \
            & (g * p + u % p < m)
        outputs = jnp.where(
            record,
            jax.lax.dynamic_update_index_in_dim(outputs, done, mb_idx, axis=0),
            outputs)
        nxt = jax.lax.ppermute(done, axis_name, perm)
        return (nxt, outputs), None

    holding0 = pvary(jnp.zeros(mb_shape, x.dtype), varying_axes)
    outputs0 = pvary(jnp.zeros((m,) + mb_shape, x.dtype), varying_axes)
    (_, outputs), _ = jax.lax.scan(tick, (holding0, outputs0),
                                   jnp.arange(_schedule_ticks(m, p, v)))
    # broadcast final outputs from last rank to all (so out spec can be
    # replicated over pp)
    outputs = jnp.where(rank == p - 1, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis_name)


def bubble_fraction(pp: int, microbatches: int, interleave: int = 1) -> float:
    """Exact wasted-tick fraction of the schedule: every rank executes
    its chunk each tick (SPMD programs cannot skip compute), M·V of the
    ``_schedule_ticks`` are useful per rank, the rest are fill/drain.
    (P-1)/(M·V+P-1) when P | M or V=1 — pp=4, m=16: 15.8% (V=1) → 4.5%
    (V=4) — and LARGER when P ∤ M with V>1 (the last group still spans
    a full V·P-tick window; e.g. pp=2, m=3, V=2: 25%, not 14%). Raise
    ``microbatches`` (ideally a multiple of pp) or ``interleave`` to
    amortize; interleave costs V× more neighbor-hop activation traffic."""
    t = _schedule_ticks(microbatches, pp, interleave)
    return (t - microbatches * interleave) / t


def pipeline_apply(
    x,
    stacked_params,
    layer_fn: Callable,
    mesh: Mesh,
    axis_name: str = "pp",
    microbatches: int = 4,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    param_specs=None,
    extras=None,
    interleave: int = 1,
    param_layout: str = "stacked",
    rng_key=None,
):
    """Run ``layer_fn`` over stacked layers pipelined across ``axis_name``.

    - x: activations [B, ...]; B divisible by ``microbatches``.
    - stacked_params: pytree with leading [L, ...] axis per leaf, L
      divisible by pp·interleave. interleave=1: rank k owns the
      contiguous span [k·L/P, (k+1)·L/P) (GPipe). interleave=V>1: the
      layers split into V·P chunks and rank k owns chunks {c·P+k}
      (Megatron virtual stages) — bubble shrinks V×, neighbor-hop
      activation traffic grows V×.
    - layer_fn(activation, layer_params[, extra]) -> activation.
    - param_specs: optional pytree of PartitionSpecs for each leaf's
      NON-layer dims (tensor parallelism inside a stage): e.g.
      ``{"w1": P("tp"), "w2": P(None, "tp")}`` — composed after the
      leading pp dim; layer_fn must then psum its tp partial sums
      (Megatron pattern), making dp×tp×pp 3D parallelism one call.
    - extras: optional pytree of [B, ...] side inputs constant across
      layers (attention masks, encoder outputs for cross-attention);
      microbatched like ``x`` and delivered to whichever rank is working
      on that microbatch each tick.
    - param_layout: "stacked" (leaves in logical layer order; V>1 pays
      a per-step all-to-all re-layout) or "interleaved" (leaves already
      row-permuted by :func:`interleave_perm`, as Trainer.startup
      stores them; the re-chunk is then a free local reshape).
    - rng_key: per-step PRNG key threaded into the schedule when the
      blocks use dropout in training; folded per (layer, microbatch,
      data-shard) inside the body. None for deterministic blocks.
    """
    if extras is not None and jax.tree.leaves(extras):
        enforce(all(e.shape[0] == x.shape[0] for e in jax.tree.leaves(extras)),
                "extras leaves must share x's batch dim")
    else:
        extras = None

    from ..framework import rng_fold, rng_scope

    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        enforce(param_layout == "stacked",
                "interleaved param storage requires a pp axis (size>1) in "
                "the mesh — the Trainer only permutes rows when one exists")
        bspec = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)

        def _seq(xv, sp, ex, key):
            def one(a, xs):
                lp, li = xs
                # per-layer rng: the scan body is traced once, so without
                # the fold every layer would reuse one dropout key
                k = None if key is None else jax.random.fold_in(key, li)
                with rng_scope(k) if k is not None else rng_fold(li):
                    out = layer_fn(a, lp) if ex is None else layer_fn(a, lp, ex)
                return out, None
            L_ = jax.tree.leaves(sp)[0].shape[0]
            out, _ = jax.lax.scan(one, xv, (sp, jnp.arange(L_)))
            return out
        if param_specs is None:
            # plain GSPMD trace: the threaded per-step key (when the
            # blocks use dropout) drives per-layer masks exactly as the
            # schedule paths do — GSPMD shards the masks globally, so no
            # per-shard fold is needed (or possible: no axis binding)
            return _seq(x, stacked_params, extras, rng_key)

        # degenerate pipeline but tp-parallel stages: layer_fn uses mesh
        # collectives, so it still needs to run under shard_map; rng (if
        # any) is folded per data-shard position before the shared body
        def _seq_sharded(xv, sp, ex, key):
            if key is not None:
                for a in bspec:
                    key = jax.random.fold_in(key, jax.lax.axis_index(a))
            return _seq(xv, sp, ex, key)

        bshard = bspec if len(bspec) > 1 else (bspec[0] if bspec else None)
        x_spec = P(bshard, *([None] * (x.ndim - 1)))
        param_spec = jax.tree.map(
            lambda leaf, extra: P(None, *(tuple(extra) + (None,) * (leaf.ndim - 1 - len(extra)))),
            stacked_params, param_specs)
        ex_spec = None if extras is None else jax.tree.map(
            lambda e: P(bshard, *([None] * (e.ndim - 1))), extras)
        key_spec = None if rng_key is None else P()
        return jax.shard_map(_seq_sharded, mesh=mesh,
                             in_specs=(x_spec, param_spec, ex_spec, key_spec),
                             out_specs=x_spec, check_vma=False)(
                                 x, stacked_params, extras, rng_key)

    p = mesh.shape[axis_name]
    v = max(1, int(interleave))
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    enforce(L % (p * v) == 0,
            f"{L} layers not divisible by pp·interleave={p}·{v}")
    b = x.shape[0]
    enforce(b % microbatches == 0,
            f"batch {b} not divisible by microbatches={microbatches}")
    mb = b // microbatches
    dshard = 1
    for a in batch_axes:
        if a in mesh.axis_names:
            dshard *= mesh.shape[a]
    enforce(mb % dshard == 0,
            f"microbatch size {mb} (batch {b} / microbatches {microbatches}) "
            f"must be divisible by the data-shard product {dshard} of axes "
            f"{tuple(a for a in batch_axes if a in mesh.axis_names)}; lower "
            f"microbatches or raise the batch")
    xm = x.reshape((microbatches, mb) + x.shape[1:])
    exm = None if extras is None else jax.tree.map(
        lambda e: e.reshape((microbatches, mb) + e.shape[1:]), extras)

    # chunk layout: rank r must hold rows [r·V, (r+1)·V) of a [P·V, Lc]
    # view, row r·V + c being global chunk c·P + r. "interleaved" rest
    # layout (Trainer startup, interleave_perm) already has rows in that
    # order, so the re-chunk is a free local reshape; "stacked" (logical
    # order) needs [L] → [V, P, Lc] → [P, V, Lc] — a real re-layout that
    # GSPMD lowers to a per-step all-to-all over pp when V > 1
    Lc = L // (p * v)
    if param_layout == "interleaved":
        chunked = jax.tree.map(
            lambda leaf: leaf.reshape((p * v, Lc) + leaf.shape[1:]),
            stacked_params)
    else:
        enforce(param_layout == "stacked",
                f"unknown param_layout {param_layout!r} "
                "('stacked'|'interleaved')")
        chunked = jax.tree.map(
            lambda leaf: jnp.moveaxis(
                leaf.reshape((v, p, Lc) + leaf.shape[1:]), 0, 1
            ).reshape((p * v, Lc) + leaf.shape[1:]),
            stacked_params)

    bspec = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    bshard = bspec if len(bspec) > 1 else (bspec[0] if bspec else None)
    x_spec = P(None, bshard, *([None] * (x.ndim - 1)))
    ex_spec = None if exm is None else jax.tree.map(
        lambda e: P(None, bshard, *([None] * (e.ndim - 2))), exm)
    if param_specs is None:
        param_spec = jax.tree.map(
            lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), chunked)
    else:
        param_spec = jax.tree.map(
            lambda leaf, extra: P(axis_name, None,
                                  *(tuple(extra) + (None,) * (leaf.ndim - 2 - len(extra)))),
            chunked, param_specs)

    body = functools.partial(
        _pp_body, layer_fn=layer_fn, axis_name=axis_name,
        microbatches=microbatches, interleave=v,
        varying_axes=tuple(mesh.axis_names),
        data_axes=tuple(a for a in batch_axes if a in mesh.axis_names
                        and mesh.shape[a] > 1))
    key_spec = None if rng_key is None else P()
    # with in-stage tensor parallelism the carried activation is
    # tp-invariant only because layer_fn psums — beyond the static
    # varying-axes analysis, so drop the VMA check in that case; the
    # threaded rng (device-varying after the data-axis folds) is also
    # outside what the static analysis can see
    out = jax.shard_map(body, mesh=mesh,
                        in_specs=(x_spec, param_spec, ex_spec, key_spec),
                        out_specs=x_spec,
                        check_vma=(param_specs is None and extras is None
                                   and rng_key is None))(
                            xm, chunked, exm, rng_key)
    return out.reshape((b,) + x.shape[1:])
