"""Per-host fleet agent::

    python -m paddle_tpu.fleet.agent [--root DIR] [--bind ADDR] [--port N]

One agent runs on each serving host and is the router's hands there:
it spawns, kills, and respawns replica processes on request over the
same framed wire the fleet speaks (:mod:`paddle_tpu.fleet.remote`),
and it fronts the HOST's artifact cache — the router ships a
``save_inference_model`` dir once per host over FETCH/ARTIFACT and
every replica the agent spawns serves (and reloads) from that shared,
CRC-validated cache.

Wire verbs (client → agent)::

    SPAWN <len> + json   {"dirname", "name", "server_kw"} → replica addr/pid
    STOP  <len> + json   {"pid"} → SIGKILL + reap (idempotent)
    PS                   → spawned children (bounded history), liveness
    FETCH / ARTIFACT     the artifact door (same protocol as a replica)
    QUIT

``PS`` is deliberately a *history*, not a process list: a child that
died stays in the table marked dead. That makes the agent a waitpid
oracle for :meth:`~paddle_tpu.fleet.remote.RemoteReplica.
_provably_dead` across proxied links — "tracked and exited" or "no
longer tracked" is proof of death where "connect refused" can no
longer be. The dead-entry history is bounded (``--max-dead``, default
256): the oldest dead children are evicted first and live pids are
never evicted, and since "no longer tracked" already reads as
dead, eviction preserves the oracle's verdicts.

Prints ``PORT <n>`` on stdout once the listener is up (the
``AgentProcess.wait_ready`` handshake, same as a replica's).
"""

from __future__ import annotations

import argparse
import base64
import io as _io
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, Optional, Tuple

from .replica_main import _reply_err, _reply_json


def _log():
    import logging
    return logging.getLogger("paddle_tpu.fleet.agent")


def decode_server_kw(kw: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`~paddle_tpu.fleet.remote.encode_server_kw`:
    rehydrate the base64-npz golden feed into arrays (policy dicts
    pass through — the replica entrypoint rebuilds the dataclasses)."""
    import numpy as np

    kw = dict(kw)
    npz = kw.pop("golden_feed_npz", None)
    if npz is not None:
        with np.load(_io.BytesIO(base64.b64decode(npz))) as z:
            kw["golden_feed"] = {k: z[k] for k in z.files}
    return kw


class AgentService:
    """The verb dispatcher around this host's replica children and
    artifact cache."""

    def __init__(self, root: str, child_bind: Optional[str] = None,
                 advertise: str = "127.0.0.1", max_dead: int = 256):
        from .remote import ArtifactStore

        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.artifacts = ArtifactStore(os.path.join(self.root, "artifacts"))
        self._child_bind = child_bind
        self._advertise = advertise
        self._lock = threading.Lock()
        # pid -> {"name", "proc", "addr"}, insertion-ordered (= spawn
        # order). PS is a death oracle, not a process list: a dead
        # child STAYS in the table, but the dead-entry history is
        # BOUNDED — once more than ``max_dead`` dead children
        # accumulate, the oldest dead ones are evicted (live pids are
        # never evicted). Eviction is oracle-compatible: remote.
        # _provably_dead reads "no longer tracked" as reaped-therefore-
        # dead, which is exactly what an evicted entry was — so an
        # autoscaling host churning replicas for weeks holds a bounded
        # table without weakening the at-most-once death proof.
        self._procs: Dict[int, Dict[str, Any]] = {}
        self.max_dead = int(max_dead)
        self.stopping = threading.Event()

    def _prune_dead_locked(self) -> None:
        """Evict the oldest dead children beyond ``max_dead``. Caller
        holds ``self._lock``."""  # guarded-by: self._lock
        dead = [pid for pid, info in self._procs.items()
                if info["proc"].poll() is not None]
        for pid in dead[:max(0, len(dead) - self.max_dead)]:
            del self._procs[pid]

    # -- verbs ---------------------------------------------------------------

    def handle_spawn(self, conn: socket.socket, parts) -> None:
        # retry: at-most-once — a replayed SPAWN launches a second
        # replica process (the orphan would be visible in PS, but the
        # client surfaces the lost reply instead of resending)
        from ..parallel.async_ps import read_exact
        from .remote import ReplicaProcess

        body = read_exact(conn, int(parts[1]))
        req = json.loads(body)
        dirname = req["dirname"]
        if not os.path.isabs(dirname):
            # relative names resolve against the host artifact cache
            dirname = os.path.join(self.artifacts.root, dirname)
        kw = decode_server_kw(dict(req.get("server_kw") or {}))
        try:
            proc = ReplicaProcess(dirname, server_kw=kw,
                                  artifact_root=self.artifacts.root,
                                  bind=self._child_bind)
            addr = proc.wait_ready()
        except BaseException as e:
            _reply_err(conn, e)
            return
        info = {"name": req.get("name"), "proc": proc, "addr": addr}
        with self._lock:
            self._procs[proc.pid] = info
            self._prune_dead_locked()
        _reply_json(conn, {"name": req.get("name"), "pid": proc.pid,
                           "addr": [self._advertise, addr[1]]})

    def handle_stop(self, conn: socket.socket, parts) -> None:
        from ..parallel.async_ps import read_exact

        body = read_exact(conn, int(parts[1]))
        pid = int(json.loads(body)["pid"])
        with self._lock:
            info = self._procs.get(pid)
        if info is None:
            _reply_json(conn, {"stopped": False, "known": False})
            return
        info["proc"].stop()
        _reply_json(conn, {"stopped": True, "known": True})

    def handle_ps(self, conn: socket.socket) -> None:
        with self._lock:
            self._prune_dead_locked()
            procs = [{"name": info["name"], "pid": pid,
                      "alive": info["proc"].poll() is None,
                      "addr": [self._advertise, info["addr"][1]]}
                     for pid, info in self._procs.items()]
        _reply_json(conn, {"procs": procs, "pid": os.getpid()})

    def handle_fetch(self, conn: socket.socket, parts) -> None:
        from ..parallel.async_ps import read_exact

        token = parts[1]
        body = read_exact(conn, int(parts[2]))
        _reply_json(conn, self.artifacts.handle_fetch(token, body))

    def handle_artifact(self, conn: socket.socket, parts) -> None:
        from ..parallel.async_ps import read_exact

        token, fname = parts[1], parts[2]
        off, nbytes = int(parts[3]), int(parts[4])
        crc = int(parts[5], 16)
        data = read_exact(conn, nbytes)
        self.artifacts.handle_chunk(token, fname, off, crc, data)

    # -- connection loop -----------------------------------------------------

    def serve_conn(self, conn: socket.socket) -> None:
        from ..parallel.async_ps import read_line

        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self.stopping.is_set():
                try:
                    line = read_line(conn)
                except (ConnectionError, OSError):
                    return
                parts = line.split()
                if not parts or parts[0] == "QUIT":
                    return
                verb = parts[0]
                try:
                    if verb == "SPAWN":
                        self.handle_spawn(conn, parts)
                    elif verb == "STOP":
                        self.handle_stop(conn, parts)
                    elif verb == "PS":
                        self.handle_ps(conn)
                    elif verb == "FETCH":
                        self.handle_fetch(conn, parts)
                    elif verb == "ARTIFACT":
                        self.handle_artifact(conn, parts)
                    else:
                        _reply_err(conn, RuntimeError(
                            f"unknown verb {verb!r}"))
                except (ConnectionError, OSError):
                    return
                except BaseException as e:
                    try:
                        _reply_err(conn, e)
                    except OSError:
                        return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self.stopping.set()
        with self._lock:
            procs = list(self._procs.values())
        for info in procs:
            try:
                info["proc"].stop()
            except Exception:
                pass


# -- spawning an agent from tests/drills --------------------------------------


class AgentProcess:
    """Spawn-and-own one agent process (the test/drill injector: a
    whole-"host" kill is SIGKILLing this plus every replica its PS
    lists). Same ``PORT <n>`` readiness handshake as a replica."""

    def __init__(self, root: str, bind: Optional[str] = None,
                 port: int = 0):
        from ..parallel.async_ps import child_python_env

        self.root = root
        argv = [sys.executable, "-m", "paddle_tpu.fleet.agent",
                "--root", root, "--port", str(int(port))]
        if bind:
            argv += ["--bind", bind]
        env = child_python_env(pop=("PDTPU_TELEMETRY_ORIGIN",))
        self._proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                      text=True, env=env)
        self.addr: Optional[Tuple[str, int]] = None
        self._host = bind if bind and bind != "0.0.0.0" else "127.0.0.1"

    @property
    def pid(self) -> int:
        return self._proc.pid

    def wait_ready(self, timeout: float = 60.0) -> Tuple[str, int]:
        import select

        if self.addr is not None:
            return self.addr
        deadline = time.monotonic() + timeout
        line = ""
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            ready, _, _ = select.select([self._proc.stdout], [], [],
                                        min(remaining, 1.0))
            if not ready:
                continue
            line = self._proc.stdout.readline()
            if not line:
                rc = self._proc.poll()
                raise RuntimeError(
                    f"agent process exited (rc={rc}) before reporting "
                    "its port — see its stderr above")
            line = line.strip()
            if line.startswith("PORT "):
                self.addr = (self._host, int(line.split()[1]))
                return self.addr
        raise TimeoutError(
            f"agent process did not report a port within {timeout}s "
            f"(last line: {line!r})")

    def poll(self) -> Optional[int]:
        return self._proc.poll()

    def kill(self) -> None:
        """SIGKILL the agent itself (NOT its replicas — a real host
        kill delivers those separately; tests kill each pid)."""
        if self._proc.poll() is None:
            self._proc.kill()

    def stop(self) -> None:
        self.kill()
        try:
            self._proc.wait(timeout=5.0)
        except Exception:
            pass

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.fleet.agent",
        description="per-host fleet agent: replica launcher + artifact "
                    "cache over the framed wire")
    p.add_argument("--root", default=None,
                   help="host base dir (artifact cache lives under it; "
                        "default: a fresh temp dir)")
    p.add_argument("--bind", default=None,
                   help="listener bind address (also PDTPU_BIND_ADDR; "
                        "default loopback). Spawned replicas bind it too.")
    p.add_argument("--advertise", default=None,
                   help="host address spawned replicas are advertised at "
                        "(default: the bind address, or loopback)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--max-dead", type=int, default=256,
                   help="dead-children history cap for PS (oldest dead "
                        "entries evicted beyond this; live pids never "
                        "evicted)")
    args = p.parse_args(argv)
    root = args.root or tempfile.mkdtemp(prefix="pdtpu_agent_")
    bind = args.bind or os.environ.get("PDTPU_BIND_ADDR") or "127.0.0.1"
    advertise = args.advertise or (bind if bind != "0.0.0.0"
                                   else "127.0.0.1")
    service = AgentService(root, child_bind=args.bind, advertise=advertise,
                           max_dead=args.max_dead)
    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    ls.bind((bind, int(args.port)))
    ls.listen(128)
    print(f"PORT {ls.getsockname()[1]}", flush=True)
    try:
        while not service.stopping.is_set():
            try:
                conn, _ = ls.accept()
            except OSError:
                break
            threading.Thread(target=service.serve_conn, args=(conn,),
                             daemon=True).start()
    finally:
        service.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
