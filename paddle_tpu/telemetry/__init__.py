"""paddle_tpu.telemetry — unified process telemetry.

Before this package every subsystem reported into its own ad-hoc dict
(``PipelineMetrics.report()``, ``ServingMetrics.report()``,
``trainer.profile_report()``, bare ``pushes_lost`` attributes) with no
common export format, no cross-component correlation, and nothing
captured at the moment of a crash. Telemetry is the one surface an
operator points Prometheus (and post-mortem tooling) at:

- :mod:`registry` — the process-wide **metrics registry** (counters,
  gauges, log-bucket histograms with labels; scrape-time collectors
  for zero hot-path cost) that Trainer/feeder/guard/checkpoint,
  async-PS client/server counters, and serving queue/latency/breaker
  state all publish into, under the
  ``paddle_tpu_<subsystem>_<name>{labels}`` naming convention, with
  Prometheus-text and JSON exporters.
- :mod:`journal` — the **structured run journal**: a JSONL event
  stream with a run id and monotonic per-event sequence; span ids
  minted at submit/dispatch time correlate feeder fill, fused-dispatch
  chunks, serving worker execution, and async-PS pushes end to end.
- :mod:`recorder` — the **flight recorder**: the journal's bounded
  ring flushed to disk (atomic, CRC-manifested like checkpoints) on
  guard escalation, watchdog ``WorkerHung``, breaker trips, SIGTERM
  preemption, ``ReshardError``, and unhandled ``fit`` exceptions;
  rendered by ``tools/flight_dump.py``.
- :mod:`http` — the opt-in stdlib-only ``GET /metrics`` +
  ``GET /healthz`` endpoint both ``Trainer.serve_metrics()`` and
  ``PredictorServer.serve_metrics()`` expose.
- :mod:`collector` — the **collector daemon**: a standalone (or
  in-process) sink ANY process pushes its journal + registry snapshots
  to over the framed wire, maintaining per-origin time series, a
  fleet-wide journal, and ``/metrics`` (merged under ``origin``),
  ``/alerts``, ``/timeline?trace=<span>``, ``/query`` read endpoints;
  alert rules hot-reload via SIGHUP / ``POST /rules``.
- :mod:`store` — the **durable series store**: a segmented, CRC-framed,
  retention-bounded (time AND bytes) append-only log the collector
  writes every ingest through; a restart — or a standby collector
  promoting over the shared log — replays it to rebuild the rings,
  dedupe high-water marks, fleet journal, and alert firing/pending
  state, and ``GET /query`` range reads serve from it.
- :mod:`alerts` — the **declarative alert engine** the collector
  evaluates: threshold / rate-over-window / absence / histogram-
  quantile rules with ``for_s`` durations and a firing→resolved state
  machine, plus the preset pack over the metric name table
  (``tools/alert_check.py`` lints rule files offline).
- :mod:`shipper` — the **push pipeline**: a background thread shipping
  journal-ring deltas + periodic snapshots to a collector, auto-
  started by ``PDTPU_TELEMETRY_ADDR`` (or ``ship_to(addr)``), bounded
  buffering, the hot path never blocks.

See MIGRATION.md "Telemetry" for the metric name table, journal event
schema, flight-recorder trigger/dump format, the collector wire verbs,
and the alert-rule grammar + preset table.
"""

from .journal import (RunJournal, get_journal, new_run_id, parse_sample,
                      set_journal)
from .recorder import (FlightRecorder, default_flight_dir, flight_dump,
                       get_recorder)
from .registry import (Counter, FamiliesView, Gauge, Histogram, MetricFamily,
                       MetricsRegistry, counter_deltas, counter_family,
                       families_from_snapshot, families_snapshot,
                       gauge_family, get_registry,
                       histogram_family, merge_exports,
                       render_families_prometheus, validate_families)
from .http import TelemetryServer, serve_metrics
from .alerts import (AlertEngine, AlertRule, PRESET_PACK, lint_rules,
                     load_rules, parse_rule, preset_rules)
from .collector import (CollectorProcess, SeriesStore, TelemetryCollector,
                        assemble_timeline, render_timeline_text)
from .store import SegmentStore, downsample
from .shipper import (Shipper, active_shipper, maybe_auto_ship, parse_addrs,
                      ship_to, stop_shipping)

__all__ = [
    "AlertEngine", "AlertRule", "CollectorProcess", "Counter",
    "FamiliesView", "FlightRecorder", "Gauge", "Histogram",
    "MetricFamily", "MetricsRegistry", "PRESET_PACK", "RunJournal",
    "SegmentStore", "SeriesStore", "Shipper", "TelemetryCollector",
    "TelemetryServer", "active_shipper", "assemble_timeline",
    "counter_deltas", "counter_family", "default_flight_dir",
    "downsample", "families_from_snapshot",
    "families_snapshot", "flight_dump", "gauge_family", "get_journal",
    "get_recorder", "get_registry", "histogram_family", "lint_rules",
    "load_rules", "maybe_auto_ship", "merge_exports", "new_run_id",
    "parse_addrs", "parse_rule", "parse_sample", "preset_rules",
    "render_families_prometheus", "render_timeline_text", "serve_metrics",
    "set_journal", "ship_to", "stop_shipping", "validate_families",
]
