"""Learning-rate schedules.

Analog of python/paddle/fluid/layers/learning_rate_scheduler.py, where
each decay is built as in-graph ops over a ``@LR_DECAY_COUNTER@`` var.
Here each schedule is a pure ``step -> lr`` function of the optimizer's
step counter (traceable, so it lives inside the jitted update).
"""

from __future__ import annotations

import math
from typing import Callable, List, Sequence

import jax.numpy as jnp

Schedule = Callable  # step (int array) -> float array


def _as_f32(step):
    return jnp.asarray(step, dtype=jnp.float32)


def noam_decay(d_model: int, warmup_steps: int, learning_rate: float = 1.0) -> Schedule:
    def sched(step):
        s = jnp.maximum(_as_f32(step), 1.0)
        return learning_rate * (d_model ** -0.5) * jnp.minimum(s ** -0.5, s * warmup_steps ** -1.5)
    return sched


def exponential_decay(learning_rate: float, decay_steps: int, decay_rate: float,
                      staircase: bool = False) -> Schedule:
    def sched(step):
        p = _as_f32(step) / decay_steps
        if staircase:
            p = jnp.floor(p)
        return learning_rate * jnp.power(decay_rate, p)
    return sched


def natural_exp_decay(learning_rate: float, decay_steps: int, decay_rate: float,
                      staircase: bool = False) -> Schedule:
    def sched(step):
        p = _as_f32(step) / decay_steps
        if staircase:
            p = jnp.floor(p)
        return learning_rate * jnp.exp(-decay_rate * p)
    return sched


def inverse_time_decay(learning_rate: float, decay_steps: int, decay_rate: float,
                       staircase: bool = False) -> Schedule:
    def sched(step):
        p = _as_f32(step) / decay_steps
        if staircase:
            p = jnp.floor(p)
        return learning_rate / (1.0 + decay_rate * p)
    return sched


def polynomial_decay(learning_rate: float, decay_steps: int, end_learning_rate: float = 1e-4,
                     power: float = 1.0, cycle: bool = False) -> Schedule:
    def sched(step):
        s = _as_f32(step)
        if cycle:
            div = jnp.maximum(1.0, jnp.ceil(s / decay_steps))
            ds = decay_steps * div
        else:
            ds = float(decay_steps)
            s = jnp.minimum(s, ds)
        return (learning_rate - end_learning_rate) * jnp.power(1 - s / ds, power) + end_learning_rate
    return sched


def piecewise_decay(boundaries: Sequence[int], values: Sequence[float]) -> Schedule:
    bs = jnp.asarray(boundaries, dtype=jnp.float32)
    vs = jnp.asarray(values, dtype=jnp.float32)

    def sched(step):
        idx = jnp.sum(_as_f32(step) >= bs)
        return vs[idx]
    return sched


def cosine_decay(learning_rate: float, step_each_epoch: int, epochs: int) -> Schedule:
    def sched(step):
        epoch = jnp.floor(_as_f32(step) / step_each_epoch)
        return learning_rate * 0.5 * (jnp.cos(epoch * math.pi / epochs) + 1.0)
    return sched


def cosine_decay_steps(learning_rate: float, total_steps: int, min_lr: float = 0.0) -> Schedule:
    def sched(step):
        frac = jnp.clip(_as_f32(step) / total_steps, 0.0, 1.0)
        return min_lr + (learning_rate - min_lr) * 0.5 * (1.0 + jnp.cos(math.pi * frac))
    return sched


def linear_lr_warmup(learning_rate, warmup_steps: int, start_lr: float, end_lr: float) -> Schedule:
    """Wraps a schedule (or constant) with linear warmup
    (learning_rate_scheduler.py linear_lr_warmup)."""
    base = learning_rate if callable(learning_rate) else (lambda step: jnp.asarray(learning_rate, jnp.float32))

    def sched(step):
        s = _as_f32(step)
        warm = start_lr + (end_lr - start_lr) * (s / max(warmup_steps, 1))
        return jnp.where(s < warmup_steps, warm, base(step))
    return sched


def append_LARS(params_grads, learning_rate, weight_decay: float = 0.0,
                epsilon: float = 1e-9):
    """Layer-wise Adaptive Rate Scaling helper
    (learning_rate_scheduler.py append_LARS): per-param lr =
    lr * ||param|| / (||grad|| + weight_decay*||param||). Returns the list
    of per-parameter scaled learning rates (the reference rewrites each
    optimizer op's LR input; functionally the LarsMomentum optimizer is the
    first-class path)."""
    import jax.numpy as jnp

    out = []
    for p, g in params_grads:
        pn = jnp.sqrt(jnp.sum(jnp.square(p)))
        gn = jnp.sqrt(jnp.sum(jnp.square(g)))
        out.append(learning_rate * pn / (gn + weight_decay * pn + epsilon))
    return out
