"""Property tests for the packed-segment sequence core against a plain
per-sequence numpy oracle (loop over ragged slices) — random lengths,
every pool type, softmax, and reverse. The segment-ids representation
underlies the whole LoD surface (layers/sequence.py module doc), so a
subtle indexing bug here corrupts ~30 ops at once.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu.layers as L

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402


@st.composite
def ragged(draw):
    lens = draw(st.lists(st.integers(1, 5), min_size=1, max_size=4))
    rows = sum(lens)
    rng = np.random.RandomState(draw(st.integers(0, 2 ** 16)))
    vals = rng.randn(rows, 3).astype(np.float32)
    seg = np.repeat(np.arange(len(lens)), lens).astype(np.int32)
    return lens, vals, seg


def _slices(lens, vals):
    out, pos = [], 0
    for n in lens:
        out.append(vals[pos:pos + n])
        pos += n
    return out


@settings(max_examples=30, deadline=None)
@given(ragged(), st.sampled_from(
    ["sum", "average", "sqrt", "max", "min", "first", "last"]))
def test_sequence_pool_matches_ragged_oracle(case, pool_type):
    lens, vals, seg = case
    got = np.asarray(L.sequence_pool(jnp.asarray(vals), jnp.asarray(seg),
                                     len(lens), pool_type))
    oracle = {
        "sum": lambda s: s.sum(0),
        "average": lambda s: s.mean(0),
        "sqrt": lambda s: s.sum(0) / np.sqrt(len(s)),
        "max": lambda s: s.max(0),
        "min": lambda s: s.min(0),
        "first": lambda s: s[0],
        "last": lambda s: s[-1],
    }[pool_type]
    want = np.stack([oracle(s) for s in _slices(lens, vals)])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@settings(max_examples=30, deadline=None)
@given(ragged())
def test_sequence_softmax_matches_ragged_oracle(case):
    lens, vals, seg = case
    x = vals[:, 0]  # sequence_softmax is over a vector per the reference
    got = np.asarray(L.sequence_softmax(jnp.asarray(x), jnp.asarray(seg),
                                        len(lens)))
    outs = []
    for s in _slices(lens, x):
        e = np.exp(s - s.max())
        outs.append(e / e.sum())
    np.testing.assert_allclose(got, np.concatenate(outs), rtol=1e-5,
                               atol=1e-6)
    # softmax sums to 1 within every sequence
    for i, n in enumerate(lens):
        np.testing.assert_allclose(got[seg == i].sum(), 1.0, rtol=1e-5)


@settings(max_examples=30, deadline=None)
@given(ragged())
def test_sequence_reverse_is_an_involution_and_matches_oracle(case):
    lens, vals, seg = case
    rev = L.sequence_reverse(jnp.asarray(vals), jnp.asarray(seg), len(lens))
    want = np.concatenate([s[::-1] for s in _slices(lens, vals)])
    np.testing.assert_allclose(np.asarray(rev), want, rtol=1e-6)
    back = L.sequence_reverse(rev, jnp.asarray(seg), len(lens))
    np.testing.assert_allclose(np.asarray(back), vals, rtol=1e-6)
