#!/usr/bin/env python
"""Offline alert-rule linter: validate a rule file against the known
metric name table BEFORE a collector ever loads it.

    python tools/alert_check.py rules.json          # lint a rule file
    python tools/alert_check.py --preset            # lint the preset pack
    python tools/alert_check.py rules.json --json   # machine-readable

A rule that names a metric this build does not export, a label its
publisher never stamps, or an expression the grammar rejects is a
named finding (``alert:unknown-metric`` / ``alert:unknown-label`` /
``alert:malformed-expr`` / ``alert:type-mismatch`` /
``alert:bad-duration`` / ``alert:duplicate-name``) — caught here in
CI, not at 3am when the collector silently evaluates a rule that can
never fire. The preset pack (``paddle_tpu.telemetry.alerts.
PRESET_PACK``) ships through this gate as a tier-1 test.

Exit status (same contract as ``lint_gate.py`` / ``python -m
paddle_tpu.analysis``):

- **0** — every rule parses and names only known metrics/labels;
- **1** — findings, each printed one per line;
- **3** — the linter itself crashed (never a lint verdict).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

EXIT_CLEAN, EXIT_FINDINGS, EXIT_INTERNAL = 0, 1, 3


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/alert_check.py",
        description="offline alert-rule linter vs the metric name table")
    ap.add_argument("rules", nargs="?", default="",
                    help="JSON rule file: [{name, expr, severity?, "
                         "annotations?}, ...] (or {'rules': [...]})")
    ap.add_argument("--preset", action="store_true",
                    help="lint the built-in preset pack instead of a file")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON list")
    args = ap.parse_args(argv)

    if bool(args.rules) == bool(args.preset):
        ap.error("pass exactly one of: a rules file, or --preset")

    try:
        from paddle_tpu.telemetry import alerts

        if args.preset:
            specs = alerts.PRESET_PACK
            source = "<preset pack>"
        else:
            source = args.rules
            with open(args.rules, "r", encoding="utf-8") as f:
                doc = json.load(f)
            specs = doc.get("rules", []) if isinstance(doc, dict) else doc
            if not isinstance(specs, list):
                print(f"alert_check: {source}: expected a JSON list of "
                      "rules (or {'rules': [...]})", file=sys.stderr)
                return EXIT_FINDINGS
        findings = alerts.lint_rules(specs)
        if args.json:
            print(json.dumps(findings, indent=1))
        elif findings:
            print(f"alert_check: {len(findings)} finding(s) in {source}:")
            for f in findings:
                print(f"  {f}")
        else:
            print(f"alert_check clean: {len(specs)} rule(s) in {source} "
                  f"({len(alerts.METRIC_TABLE)} known metrics)")
        return EXIT_FINDINGS if findings else EXIT_CLEAN
    except Exception:
        traceback.print_exc()
        print("alert_check: internal error (exit 3) — the linter crashed; "
              "this is NOT a lint verdict", file=sys.stderr)
        return EXIT_INTERNAL


if __name__ == "__main__":
    sys.exit(main())
