"""Sequence parallelism as a first-class training path: GPT (decoder-
only causal LM) trains through zigzag ring attention via Trainer +
DistStrategy(sequence_parallel), loss parity vs single device. The sp
sibling of test_pipeline_transformer_e2e (exists ≠ integrated)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.parallel import DistStrategy, transformer_tp_rules
from paddle_tpu.parallel.sharding import ShardingRules
from paddle_tpu.models import gpt


def _cfg(**kw):
    base = dict(vocab_size=128, max_len=64, d_model=32, d_inner=64,
                num_heads=4, num_layers=3, use_flash=False, fused_ce=False)
    base.update(kw)
    return gpt.base_config(**base)


def _feed(bs, seq=32, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, vocab, (bs, seq)).astype(np.int32)
    labels = np.concatenate([ids[:, 1:], np.full((bs, 1), 2)], axis=1).astype(np.int32)
    return {"ids": ids, "labels": labels}


def _run_steps(trainer, feeds):
    trainer.startup(sample_feed=feeds[0])
    return [float(trainer.step(f)["loss"]) for f in feeds]


def test_gpt_trains_single_device():
    prog = pt.build(gpt.make_model(_cfg()))
    feed = _feed(4)
    tr = pt.Trainer(prog, opt.Adam(1e-2), loss_name="loss")
    tr.startup(sample_feed=feed)
    first = float(tr.step(tr._put_feed(feed))["loss"])
    for _ in range(10):
        out = tr.step(tr._put_feed(feed))
    assert float(out["loss"]) < first


def test_sp_training_loss_parity():
    """dp2×sp4 ring-attention training == single-device training, step
    for step (zigzag permutation of ids/labels/positions is loss-
    invariant; attention numerics match dense)."""
    feeds = [_feed(8, seed=i) for i in range(3)]

    prog_ref = pt.build(gpt.make_model(_cfg()))
    ref = _run_steps(pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss"),
                     feeds)

    mesh = pt.make_mesh({"dp": 2, "sp": 4})
    prog_sp = pt.build(gpt.make_model(_cfg()))
    sp = _run_steps(
        pt.Trainer(prog_sp, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                   sharding_rules=ShardingRules(seq_axis="sp"),
                   strategy=DistStrategy(sequence_parallel=True)),
        feeds)

    np.testing.assert_allclose(sp, ref, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_sp_with_fused_ce_and_flash():
    """The production long-context config: flash attention inside the
    ring + chunked logits-free CE, still parity with the dense path."""
    feeds = [_feed(4, seed=7)]

    prog_ref = pt.build(gpt.make_model(_cfg(use_flash=True, fused_ce=True)))
    ref = _run_steps(pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss"),
                     feeds)

    mesh = pt.make_mesh({"sp": 8})
    prog_sp = pt.build(gpt.make_model(_cfg(use_flash=True, fused_ce=True)))
    sp = _run_steps(
        pt.Trainer(prog_sp, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                   sharding_rules=ShardingRules(seq_axis="sp"),
                   strategy=DistStrategy(sequence_parallel=True)),
        feeds)
    np.testing.assert_allclose(sp, ref, atol=2e-4, rtol=2e-4)


def test_sp_ulysses_loss_parity():
    """sp_impl='ulysses': all-to-all head-sharded attention trains to
    the same losses as single device (natural layout, no permutation)."""
    feeds = [_feed(8, seed=i) for i in range(2)]

    prog_ref = pt.build(gpt.make_model(_cfg()))
    ref = _run_steps(pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss"),
                     feeds)

    mesh = pt.make_mesh({"dp": 2, "sp": 4})  # num_heads=4 % sp=4 == 0
    prog_sp = pt.build(gpt.make_model(_cfg()))
    sp = _run_steps(
        pt.Trainer(prog_sp, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                   sharding_rules=ShardingRules(seq_axis="sp"),
                   strategy=DistStrategy(sequence_parallel=True,
                                         sp_impl="ulysses")),
        feeds)
    np.testing.assert_allclose(sp, ref, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_sp_ulysses_with_flash_parity():
    """ulysses + the pallas flash kernel as the full-sequence inner
    attention (the composition DESIGN.md advertises)."""
    feeds = [_feed(4, seed=11)]

    prog_ref = pt.build(gpt.make_model(_cfg(use_flash=True, fused_ce=True)))
    ref = _run_steps(pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss"),
                     feeds)

    mesh = pt.make_mesh({"dp": 2, "sp": 4})
    prog_sp = pt.build(gpt.make_model(_cfg(use_flash=True, fused_ce=True)))
    sp = _run_steps(
        pt.Trainer(prog_sp, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                   sharding_rules=ShardingRules(seq_axis="sp"),
                   strategy=DistStrategy(sequence_parallel=True,
                                         sp_impl="ulysses")),
        feeds)
    np.testing.assert_allclose(sp, ref, atol=2e-4, rtol=2e-4)


def test_sp_ulysses_seq_divisibility_enforced():
    from paddle_tpu.core.errors import EnforceError

    mesh = pt.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    prog = pt.build(gpt.make_model(_cfg()))
    feed = _feed(4, seq=30)  # 30 % 4 != 0
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    strategy=DistStrategy(sequence_parallel=True,
                                          sp_impl="ulysses"))
    tr.startup(sample_feed=feed)
    with pytest.raises(EnforceError):
        tr.step(tr._put_feed(feed))


def test_sp_bad_impl_rejected():
    from paddle_tpu.core.errors import EnforceError

    mesh = pt.make_mesh({"sp": 8})
    prog = pt.build(gpt.make_model(_cfg()))
    feed = _feed(8)
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    strategy=DistStrategy(sequence_parallel=True,
                                          sp_impl="rings"))
    tr.startup(sample_feed=feed)
    with pytest.raises(EnforceError):
        tr.step(tr._put_feed(feed))


def test_sp_unconsumed_warns():
    """sequence_parallel with a model that never reads the sp context
    must warn (silent no-sp training was the pipeline review finding)."""
    from paddle_tpu.models import mnist

    mesh = pt.make_mesh({"sp": 8})
    prog = pt.build(mnist.mlp)
    feed = {"image": np.random.randn(8, 784).astype(np.float32),
            "label": np.random.randint(0, 10, (8, 1)).astype(np.int64)}
    tr = pt.Trainer(prog, opt.SGD(0.1), loss_name="loss", mesh=mesh,
                    sharding_rules=ShardingRules(),
                    strategy=DistStrategy(sequence_parallel=True))
    tr.startup(sample_feed=feed)
    with pytest.warns(UserWarning, match="never consumed the context"):
        tr.step(tr._put_feed(feed))


def test_sp_seq_divisibility_enforced():
    from paddle_tpu.core.errors import EnforceError

    mesh = pt.make_mesh({"sp": 8})
    prog = pt.build(gpt.make_model(_cfg()))
    feed = _feed(8, seq=24)  # 24 % 16 != 0
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    strategy=DistStrategy(sequence_parallel=True))
    tr.startup(sample_feed=feed)
    with pytest.raises(EnforceError):
        tr.step(tr._put_feed(feed))


def test_gpt_generator_continues_overfit_pattern():
    """Train GPT on a periodic token stream, then the KV-cache
    incremental generator must continue the period from a prompt —
    proves cache indexing/positions and train↔generate param-name
    compatibility in one shot."""
    cfg = _cfg(vocab_size=16, max_len=48, num_layers=2)
    prog = pt.build(gpt.make_model(cfg))
    period = [3, 4, 5, 6]
    seq = np.array([period[i % 4] for i in range(32)], np.int32)
    ids = np.tile(seq, (4, 1))
    labels = np.concatenate([ids[:, 1:], ids[:, :1]], axis=1)
    feed = {"ids": ids, "labels": labels.astype(np.int32)}
    tr = pt.Trainer(prog, opt.Adam(1e-2), loss_name="loss")
    tr.startup(sample_feed=feed)
    for _ in range(60):
        out = tr.step(tr._put_feed(feed))
    assert float(out["loss"]) < 0.1, float(out["loss"])

    gen_prog = pt.build(gpt.make_generator(cfg, max_new_tokens=8))
    prompt = ids[:2, :8]  # ends with ...3,4,5,6 -> expect 3,4,5,6,3,4,5,6
    outs, _ = gen_prog.apply(dict(tr.scope.params), {},
                             jnp.asarray(prompt))
    got = np.asarray(outs["ids"])[0].tolist()
    expect = [period[i % 4] for i in range(8)]
    assert got == expect, (got, expect)

    # beam path: per-layer cache lists obey beam_search's [B*beam, ...]
    # state contract, so lane reordering reaches the KV caches — the top
    # beam of the overfit model must equal the greedy continuation
    beam_prog = pt.build(gpt.make_generator(cfg, max_new_tokens=8,
                                            beam_size=2))
    bouts, _ = beam_prog.apply(dict(tr.scope.params), {},
                               jnp.asarray(prompt))
    assert np.asarray(bouts["ids"]).shape == (2, 2, 8)
    assert np.asarray(bouts["ids"])[0, 0].tolist() == expect


def test_gpt_generator_exports_to_aot_predictor(tmp_path):
    """The generation program exports through save_inference_model
    (StableHLO) and serves via the AOT Predictor — the decoder-only
    serving story end-to-end (api_impl.cc Run analog for LMs)."""
    from paddle_tpu import io as pio

    cfg = _cfg(num_layers=2)
    prog = pt.build(gpt.make_generator(cfg, max_new_tokens=8))
    prompt = np.random.RandomState(0).randint(3, 128, (2, 8)).astype(np.int32)
    params, state = prog.init(jax.random.PRNGKey(0), prompt)
    direct, _ = prog.apply(params, state, jnp.asarray(prompt))

    pio.save_inference_model(str(tmp_path / "g"), prog, params, state,
                             {"prompt_ids": prompt})
    pred = pio.load_inference_model(str(tmp_path / "g"))
    assert type(pred._compiled).__name__ == "Compiled"  # AOT, no retrace
    served = pred.run({"prompt_ids": prompt})
    np.testing.assert_array_equal(np.asarray(served["ids"]),
                                  np.asarray(direct["ids"]))


def test_gpt_generator_param_names_subset_of_train():
    cfg = _cfg(num_layers=2)
    train_params, _ = pt.build(gpt.make_model(cfg)).init(
        jax.random.PRNGKey(0), **_feed(2))
    gen_params, _ = pt.build(gpt.make_generator(cfg, max_new_tokens=4)).init(
        jax.random.PRNGKey(0), np.zeros((2, 8), np.int32))
    assert set(gen_params) == set(train_params), (
        set(gen_params) ^ set(train_params))


def test_sp_and_pp_mutually_exclusive():
    from paddle_tpu.core.errors import EnforceError

    mesh = pt.make_mesh({"sp": 2, "pp": 4})
    prog = pt.build(gpt.make_model(_cfg(num_layers=4)))
    feed = _feed(8)
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    sharding_rules=transformer_tp_rules(),
                    strategy=DistStrategy(sequence_parallel=True,
                                          pp_microbatches=2))
    tr.startup(sample_feed=feed)
    with pytest.raises(EnforceError):
        tr.step(tr._put_feed(feed))
