"""Layer-op tests with numpy references + FD grad checks — the
test_*_op.py suite analog (SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L

from op_test import check_grad, check_output


def run_layer(fn, *inputs, training=False, rng_seed=None, **kwargs):
    """Build a one-layer program and run init+apply — the OpTest
    single-op-program pattern."""
    prog = pt.build(lambda *a: fn(*a, **kwargs))
    params, state = prog.init(jax.random.PRNGKey(0), *inputs)
    rng = jax.random.PRNGKey(rng_seed) if rng_seed is not None else None
    out, _ = prog.apply(params, state, *inputs, training=training, rng=rng)
    return out, params


# ---------------------------------------------------------------------------


def test_fc_output_and_grad():
    x = np.random.randn(4, 8).astype(np.float32)
    prog = pt.build(lambda a: L.fc(a, 16))
    params, state = prog.init(jax.random.PRNGKey(0), x)
    out, _ = prog.apply(params, state, x)
    w, b = np.asarray(params["fc_0/w"]), np.asarray(params["fc_0/b"])
    np.testing.assert_allclose(np.asarray(out), x @ w + b, rtol=1e-5, atol=1e-5)


def test_fc_num_flatten_dims():
    x = np.random.randn(2, 3, 4, 5).astype(np.float32)
    out, params = run_layer(L.fc, x, size=7, num_flatten_dims=2)
    assert out.shape == (2, 3, 7)


def test_fc_multiple_inputs_summed():
    x1 = np.random.randn(4, 8).astype(np.float32)
    x2 = np.random.randn(4, 6).astype(np.float32)
    prog = pt.build(lambda a, b: L.fc([a, b], 5))
    params, state = prog.init(jax.random.PRNGKey(0), x1, x2)
    out, _ = prog.apply(params, state, x1, x2)
    assert out.shape == (4, 5)
    assert "fc_0/w_0" in params and "fc_0/w_1" in params


def test_embedding_lookup_and_padding_idx():
    ids = np.array([[1], [0], [3]], dtype=np.int64)
    prog = pt.build(lambda i: L.embedding(i, size=[5, 4], padding_idx=0))
    params, state = prog.init(jax.random.PRNGKey(0), ids)
    out, _ = prog.apply(params, state, ids)
    table = np.asarray(params["embedding_0/w"])
    np.testing.assert_allclose(np.asarray(out[0]), table[1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out[1]), np.zeros(4), atol=1e-7)


def test_conv2d_matches_manual():
    # 1x1 conv == channelwise matmul
    x = np.random.randn(2, 3, 5, 5).astype(np.float32)
    prog = pt.build(lambda a: L.conv2d(a, num_filters=4, filter_size=1, bias_attr=False))
    params, state = prog.init(jax.random.PRNGKey(0), x)
    out, _ = prog.apply(params, state, x)
    w = np.asarray(params["conv2d_0/w"]).reshape(4, 3)
    want = np.einsum("nchw,oc->nohw", x, w)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-4)


def test_conv2d_shapes_padding_stride():
    x = np.random.randn(1, 3, 8, 8).astype(np.float32)
    out, _ = run_layer(L.conv2d, x, num_filters=6, filter_size=3, stride=2, padding=1)
    assert out.shape == (1, 6, 4, 4)


def test_conv2d_groups():
    x = np.random.randn(1, 4, 6, 6).astype(np.float32)
    out, _ = run_layer(L.conv2d, x, num_filters=4, filter_size=3, groups=2, padding=1)
    assert out.shape == (1, 4, 6, 6)


def test_pool2d_max_and_avg():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out, _ = run_layer(L.pool2d, x, pool_size=2, pool_type="max", pool_stride=2)
    np.testing.assert_allclose(np.asarray(out)[0, 0], [[5, 7], [13, 15]])
    out, _ = run_layer(L.pool2d, x, pool_size=2, pool_type="avg", pool_stride=2)
    np.testing.assert_allclose(np.asarray(out)[0, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_pool2d_global():
    x = np.random.randn(2, 3, 5, 5).astype(np.float32)
    out, _ = run_layer(L.pool2d, x, pool_type="avg", global_pooling=True)
    np.testing.assert_allclose(np.asarray(out)[:, :, 0, 0], x.mean(axis=(2, 3)), rtol=1e-5)


def test_batch_norm_train_and_infer():
    x = np.random.randn(8, 4, 3, 3).astype(np.float32) * 3 + 1

    def net(a):
        return L.batch_norm(a)

    prog = pt.build(net)
    params, state = prog.init(jax.random.PRNGKey(0), x)
    out, new_state = prog.apply(params, state, x, training=True)
    o = np.asarray(out)
    np.testing.assert_allclose(o.mean(axis=(0, 2, 3)), 0, atol=1e-4)
    np.testing.assert_allclose(o.std(axis=(0, 2, 3)), 1, atol=1e-2)
    # moving stats updated toward batch stats
    mm = np.asarray(new_state["batch_norm_0/moving_mean"])
    assert not np.allclose(mm, 0)
    # inference path uses moving stats (no batch dependence)
    out1, _ = prog.apply(params, new_state, x[:2], training=False)
    out2, _ = prog.apply(params, new_state, x[:4], training=False)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2)[:2], rtol=1e-5)


def test_layer_norm():
    x = np.random.randn(4, 10).astype(np.float32)
    out, _ = run_layer(L.layer_norm, x)
    o = np.asarray(out)
    np.testing.assert_allclose(o.mean(axis=1), 0, atol=1e-5)
    np.testing.assert_allclose(o.std(axis=1), 1, atol=1e-2)


def test_dropout_semantics():
    x = np.ones((1000,), dtype=np.float32)
    # downgrade_in_infer (reference default): infer scales by (1-p)
    out, _ = run_layer(L.dropout, x, dropout_prob=0.3, training=False)
    np.testing.assert_allclose(np.asarray(out), 0.7 * x, rtol=1e-6)
    out, _ = run_layer(L.dropout, x, dropout_prob=0.3, training=True, rng_seed=0)
    kept = np.asarray(out) > 0
    assert 0.6 < kept.mean() < 0.8
    # upscale_in_train: train scales kept by 1/(1-p)
    out, _ = run_layer(L.dropout, x, dropout_prob=0.5, training=True, rng_seed=0,
                       dropout_implementation="upscale_in_train")
    vals = np.unique(np.asarray(out))
    assert set(np.round(vals, 3)).issubset({0.0, 2.0})


def test_softmax_with_cross_entropy_vs_numpy():
    logits = np.random.randn(6, 10).astype(np.float32)
    label = np.random.randint(0, 10, (6, 1)).astype(np.int64)

    def np_ref(lg, lb):
        m = lg - lg.max(axis=1, keepdims=True)
        logp = m - np.log(np.exp(m).sum(axis=1, keepdims=True))
        return -logp[np.arange(6), lb[:, 0]][:, None]

    check_output(lambda lg, lb: L.softmax_with_cross_entropy(lg, lb),
                 np_ref, [logits, label], atol=1e-5)


def test_softmax_with_cross_entropy_soft_label():
    logits = np.random.randn(4, 5).astype(np.float32)
    soft = np.random.rand(4, 5).astype(np.float32)
    soft /= soft.sum(axis=1, keepdims=True)
    out = L.softmax_with_cross_entropy(jnp.asarray(logits), jnp.asarray(soft), soft_label=True)
    logp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    want = -jnp.sum(soft * logp, axis=-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-5)


def test_grad_checks_elementwise_ops():
    x = np.random.randn(3, 4).astype(np.float32)
    check_grad(lambda a: L.relu(a) * 1.0, [x + 0.1])  # avoid kink at 0
    check_grad(L.sigmoid, [x])
    check_grad(L.tanh, [x])
    check_grad(lambda a: L.softmax(a), [x])
    check_grad(lambda a: L.reduce_mean(a), [x])


def test_grad_check_fc():
    x = np.random.randn(3, 5).astype(np.float32)
    w = np.random.randn(5, 4).astype(np.float32)
    check_grad(lambda a, b: jnp.matmul(a, b), [x, w], wrt=0)
    check_grad(lambda a, b: jnp.matmul(a, b), [x, w], wrt=1)


def test_grad_check_conv2d():
    x = np.random.randn(1, 2, 5, 5).astype(np.float32)
    w = np.random.randn(3, 2, 3, 3).astype(np.float32)

    def conv(a, b):
        dn = jax.lax.conv_dimension_numbers(a.shape, b.shape, ("NCHW", "OIHW", "NCHW"))
        return jax.lax.conv_general_dilated(a, b, (1, 1), [(1, 1), (1, 1)],
                                            dimension_numbers=dn)

    check_grad(conv, [x, w], wrt=1, eps=1e-2, atol=5e-2, rtol=5e-2)


def test_elementwise_axis_broadcast():
    x = np.random.randn(2, 3, 4).astype(np.float32)
    y = np.random.randn(3).astype(np.float32)
    out = L.elementwise_add(jnp.asarray(x), jnp.asarray(y), axis=1)
    np.testing.assert_allclose(np.asarray(out), x + y[None, :, None], rtol=1e-6)


def test_topk():
    x = np.array([[1.0, 5.0, 3.0], [9.0, 2.0, 4.0]], dtype=np.float32)
    vals, idx = L.topk(jnp.asarray(x), 2)
    np.testing.assert_allclose(np.asarray(vals), [[5, 3], [9, 4]])
    np.testing.assert_array_equal(np.asarray(idx), [[1, 2], [0, 2]])


def test_one_hot_and_label_smooth():
    ids = np.array([[1], [3]], dtype=np.int64)
    oh = L.one_hot(jnp.asarray(ids), 4)
    np.testing.assert_allclose(np.asarray(oh), [[0, 1, 0, 0], [0, 0, 0, 1]])
    sm = L.label_smooth(oh, epsilon=0.1)
    np.testing.assert_allclose(np.asarray(sm)[0], [0.025, 0.925, 0.025, 0.025], rtol=1e-5)


def test_split_and_concat():
    x = np.random.randn(4, 6).astype(np.float32)
    parts = L.split(jnp.asarray(x), [2, -1, 1], dim=1)
    assert [p.shape[1] for p in parts] == [2, 3, 1]
    back = L.concat(parts, axis=1)
    np.testing.assert_allclose(np.asarray(back), x)


def test_reshape_zero_and_minus_one():
    x = np.zeros((2, 3, 4), dtype=np.float32)
    assert L.reshape(jnp.asarray(x), [0, -1]).shape == (2, 12)


def test_lrn_shape():
    x = np.random.randn(2, 8, 4, 4).astype(np.float32)
    out = L.lrn(jnp.asarray(x))
    assert out.shape == x.shape


def test_group_norm():
    x = np.random.randn(2, 6, 4, 4).astype(np.float32)
    out, _ = run_layer(L.group_norm, x, groups=3)
    assert out.shape == x.shape


def test_conv2d_transpose_shape():
    x = np.random.randn(1, 3, 4, 4).astype(np.float32)
    out, _ = run_layer(L.conv2d_transpose, x, num_filters=2, filter_size=2, stride=2)
    assert out.shape == (1, 2, 8, 8)


def test_sigmoid_cross_entropy_with_logits():
    x = np.random.randn(4, 3).astype(np.float32)
    lb = np.random.randint(0, 2, (4, 3)).astype(np.float32)

    def np_ref(a, b):
        return np.maximum(a, 0) - a * b + np.log1p(np.exp(-np.abs(a)))

    check_output(L.sigmoid_cross_entropy_with_logits, np_ref, [x, lb], atol=1e-5)


def test_image_resize():
    x = np.random.randn(1, 3, 4, 4).astype(np.float32)
    out = L.resize_bilinear(jnp.asarray(x), out_shape=(8, 8))
    assert out.shape == (1, 3, 8, 8)


def test_maxout():
    x = np.random.randn(2, 6, 3, 3).astype(np.float32)
    out = L.maxout(jnp.asarray(x), groups=3)
    assert out.shape == (2, 2, 3, 3)
    np.testing.assert_allclose(np.asarray(out), x.reshape(2, 2, 3, 3, 3).max(axis=2), rtol=1e-6)


def test_pixel_shuffle():
    x = np.random.randn(1, 8, 2, 2).astype(np.float32)
    assert L.pixel_shuffle(jnp.asarray(x), 2).shape == (1, 2, 4, 4)


def test_unfold_matches_conv():
    x = np.random.randn(1, 2, 5, 5).astype(np.float32)
    cols = L.unfold(jnp.asarray(x), 3, paddings=1)
    assert cols.shape == (1, 2 * 9, 25)
