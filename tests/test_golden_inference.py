"""Golden-model inference regression — analyzer_*_tester.cc analog.

The reference pins its inference stack by running frozen trained models
through every deployment configuration and comparing outputs
(inference/tests/api/analyzer_resnet50_tester.cc: fp32 vs quantized vs
engine-rewritten, with stated tolerances). Here: train a small
conv+BN+fc classifier to convergence ONCE, export it, then pin the
whole export→AOT-Predictor surface against the trained program:

  * fp32 Predictor == in-process program outputs (the golden),
  * bf16-cast export within bf16 tolerance + top-1 agreement,
  * real-int8-datapath export within quantization tolerance + top-1
    agreement,
  * BN-fold rewrite (quantize.fold_batch_norms) numerically equal to
    the unfolded inference graph,
  * Clone() serves the same outputs as the parent predictor.

Every comparison is against a REAL trained artifact, not random init —
wrong scale handling or a broken rewrite that random weights mask
(e.g. near-zero BN stats) shows up here.
"""

import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import layers as L
from paddle_tpu import optimizer as opt
from paddle_tpu import quantize

pytestmark = pytest.mark.slow


def _net(image, label):
    """Small conv+BN+fc classifier: the three surfaces the deployment
    rewrites touch (conv for int8, BN for folding, fc for both)."""
    x = L.reshape(image, [-1, 1, 12, 12])
    x = L.conv2d(x, num_filters=8, filter_size=3, padding=1,
                 bias_attr=False, name="c0")
    x = L.batch_norm(x, act="relu", name="bn0")
    x = L.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")
    x = L.conv2d(x, num_filters=16, filter_size=3, padding=1, act="relu",
                 name="c1")
    x = L.pool2d(x, pool_size=2, pool_stride=2, pool_type="avg")
    logits = L.fc(x, 4, name="head")
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    from paddle_tpu.metrics import accuracy
    return {"loss": loss, "acc": accuracy(logits, label), "logits": logits}


def _batch(rng, n=64):
    img = rng.randn(n, 144).astype(np.float32)
    # 4-way quadrant-marker rule with a clear margin: quickly learnable
    # to ~100% (this is a serving regression, not a learning benchmark —
    # it just needs a genuinely trained, non-random artifact)
    lbl = rng.randint(0, 4, n)
    q = img.reshape(n, 12, 12)
    for i in range(n):
        r0, c0 = [(0, 0), (0, 6), (6, 0), (6, 6)][lbl[i]]
        q[i, r0:r0 + 6, c0:c0 + 6] += 0.6
    return {"image": img, "label": lbl.reshape(n, 1).astype(np.int64)}


@pytest.fixture(scope="module")
def golden():
    """Train once per module; everything else pins against this."""
    rng = np.random.RandomState(0)
    prog = pt.build(_net)
    tr = pt.Trainer(prog, opt.Adam(3e-3), loss_name="loss",
                    fetch_list=["loss", "acc"])
    tr.startup(sample_feed=_batch(rng))
    acc = 0.0
    for step in range(300):
        out = tr.step(_batch(rng))
        acc = float(out["acc"])
        if step > 50 and acc >= 0.97:
            break
    assert acc >= 0.9, f"golden model failed to train (acc={acc})"
    holdout = _batch(np.random.RandomState(999), n=32)
    ref_out, _ = prog.apply(tr.scope.params, tr.scope.state,
                            training=False, **holdout)
    return {"prog": prog, "params": tr.scope.params, "state": tr.scope.state,
            "holdout": holdout, "ref_logits": np.asarray(ref_out["logits"]),
            "acc": acc}


def _export_and_run(golden, params=None, ctx=None, state=None):
    import contextlib
    d = tempfile.mkdtemp()
    params = golden["params"] if params is None else params
    state = golden["state"] if state is None else state
    with (ctx or contextlib.nullcontext()):
        pio.save_inference_model(d, golden["prog"], params, state,
                                 golden["holdout"])
    pred = pio.load_inference_model(d)
    out = pred.run(golden["holdout"])
    return pred, np.asarray(out["logits"]).astype(np.float32)


def test_fp32_predictor_matches_program(golden):
    pred, got = _export_and_run(golden)
    np.testing.assert_allclose(got, golden["ref_logits"], rtol=1e-5, atol=1e-5)
    # Clone serves identical outputs (PaddlePredictor::Clone contract)
    clone_out = pred.clone().run(golden["holdout"])
    np.testing.assert_allclose(np.asarray(clone_out["logits"]), got,
                               rtol=1e-6, atol=1e-6)


def test_bf16_export_within_tolerance(golden):
    bf16_params = quantize.cast_params_for_inference(
        golden["params"], jnp.bfloat16)
    _, got = _export_and_run(golden, params=bf16_params)
    ref = golden["ref_logits"]
    # bf16 has ~3 decimal digits; logits of a trained model are O(1-10)
    rel = np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-8)
    assert rel < 0.05, f"bf16 deviation {rel}"
    agree = (got.argmax(1) == ref.argmax(1)).mean()
    assert agree >= 0.95, f"bf16 top-1 agreement {agree}"


def test_int8_export_within_tolerance(golden):
    _, got = _export_and_run(golden, ctx=quantize.int8_serving())
    ref = golden["ref_logits"]
    rel = np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-8)
    assert rel < 0.2, f"int8 deviation {rel}"
    agree = (got.argmax(1) == ref.argmax(1)).mean()
    assert agree >= 0.9, f"int8 top-1 agreement {agree}"


def test_bn_fold_rewrite_matches_trained_graph(golden):
    """fold_batch_norms on the TRAINED artifact reproduces the inference
    graph's conv+BN numerically (inference_transpiler conv+BN fuse) —
    random-init BN stats (mean≈0, var≈1) would hide scale bugs that
    trained stats expose."""
    params, state = golden["params"], golden["state"]
    folded = quantize.fold_batch_norms(params, state, [("c0", "bn0")])
    x = jnp.asarray(golden["holdout"]["image"].reshape(-1, 1, 12, 12))
    w = params["c0/w"]
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))

    def conv(v, wgt):
        return jax.lax.conv_general_dilated(v, wgt, (1, 1), [(1, 1), (1, 1)],
                                            dimension_numbers=dn)

    # inference-mode BN on trained moving stats
    g, b = params["bn0/scale"], params["bn0/bias"]
    m, v = state["bn0/moving_mean"], state["bn0/moving_variance"]
    ref = (conv(x, w) - m.reshape(1, -1, 1, 1)) * \
        (g * jax.lax.rsqrt(v + 1e-5)).reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1)
    got = conv(x, folded["c0/w"]) + folded["c0/folded_bias"].reshape(1, -1, 1, 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
