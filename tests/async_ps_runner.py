"""Runnable async-PS trainer script, launched as subprocesses by
test_async_ps.py::test_multiprocess_async_trainers — genuinely
concurrent barrier-free trainers hammering one C++ pserver (the
listen_and_serv RunAsyncLoop deployment shape: N trainer processes,
no synchronization between them).

    python async_ps_runner.py <trainer_id> <ps_port> <steps>

Prints `LOSS <step> <value>` per step and `DONE` at the end.
"""

import os
import sys

pid, port, steps = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import mnist
from paddle_tpu.parallel import AsyncPSTrainer


def batch(rng, n=64):
    """Learnable synthetic task shared by every trainer: the label is a
    deterministic function of the image, so stale-gradient training must
    still reduce loss."""
    img = rng.randn(n, 784).astype(np.float32)
    lbl = img[:, :780].reshape(n, 10, 78)[:, :, :4].sum(-1).argmax(1)
    return {"image": img, "label": lbl.reshape(n, 1).astype(np.int64)}


def main():
    rng = np.random.RandomState(100 + pid)  # each trainer: its own shard
    feeds = [batch(rng) for _ in range(2)]
    prog = pt.build(mnist.mlp)
    t = AsyncPSTrainer(prog, ("127.0.0.1", port), trainer_id=pid,
                       pull_interval=2, fetch_list=["loss"])
    t.startup(sample_feed=feeds[0])
    for s in range(steps):
        out = t.step(feeds[s % 2])
        print(f"LOSS {s} {float(out['loss']):.6f}", flush=True)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
