"""Quantized ring collectives — block-scaled int8/int4 all-reduce.

EQuARX-inspired (PAPERS.md: "Efficient Quantized AllReduce in XLA"):
a ring all-reduce whose every hop carries int8 (or packed int4)
payloads with f32 abs-max scales instead of f32/bf16 — ~4× (int8) to
~8× (int4) less wire at ~1%-of-max per-hop quantization error. XLA's
native collectives (what GSPMD inserts for the rule-table shardings)
remain the default everywhere; this exists for custom ``shard_map``
training loops on bandwidth-limited axes — the DCN data axis of a
multi-host mesh, where the reference's gRPC pserver transport was the
analogous bottleneck (grpc_bytebuffer_stream.cc zero-copy serde solved
transport overhead; quantization attacks the byte count itself).

Scale granularity: ``block_size=None`` keeps the original per-chunk
scalar scale (one f32 per ring chunk); an integer ``block_size`` B
switches to BLOCK scaling — one f32 abs-max scale per B contiguous
elements — so a single outlier only flattens the resolution of its own
block instead of the whole tensor. Scales are zero/NaN-safe: an
all-zero block encodes exactly to zeros (scale pinned to 1.0, no
epsilon-floored division blowing tiny gradients away), and a block
containing non-finite values is POISONED via its wire scale (the whole
block dequantizes to NaN) so overflow detection downstream (loss
scaler / NaN guard) still fires, while every other block stays intact
— containment at block granularity instead of the historical
whole-tensor scale collapse.

``bits=4`` packs two codes per byte on the wire (bias-8 nibbles);
``rng`` enables stochastic rounding (floor(x + u), u~U[0,1)) on the
reduce-scatter-phase encodes — the all-gather phase always rounds
deterministically so every rank still ends bitwise identical.

Usage (inside shard_map, like lax.psum)::

    grads = quantized_psum(local_grads, "dp", bits=8, block_size=256)

The module also hosts the HOST-side numpy codec
(:func:`encode_wire_blocks` / :func:`decode_wire_blocks`) the async-PS
``PUSHQB`` wire verb shares with the jnp in-graph encoder — one block
format, whether the link crossing is an ICI/DCN collective hop or a
trainer→pserver TCP push — and :func:`ring_wire_bytes`, the
bytes-on-wire accounting ``profile_report()``'s collective line uses.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import enforce


def _qmax(bits: int) -> float:
    enforce(bits in (8, 4), f"quantized collectives carry int8 or int4 "
            f"payloads, not int{bits}")
    return float(2 ** (bits - 1) - 1)  # 127 / 7


def _align(bits: int, block_size: Optional[int]) -> int:
    """Element alignment an encoded vector needs: the block grid, and
    an even count for int4 (two codes share a byte)."""
    a = int(block_size) if block_size else 1
    if bits == 4 and a % 2:
        a *= 2
    return a


def _check_block(bits: int, block_size: Optional[int]) -> None:
    _qmax(bits)
    if block_size is not None:
        enforce(int(block_size) >= 1,
                f"quant block_size must be >= 1, got {block_size}")
        enforce(bits != 4 or int(block_size) % 2 == 0,
                f"int4 packs two codes per byte: block_size must be even, "
                f"got {block_size}")


def _pack4(q):
    """int8 codes in [-7, 7] (even count) → uint8, two bias-8 nibbles
    per byte: lo | hi<<4."""
    u = (q.astype(jnp.int32) + 8).astype(jnp.uint8)
    return u[0::2] | (u[1::2] << 4)


def _unpack4(payload):
    """Inverse of :func:`_pack4` (returns 2× the payload length)."""
    lo = (payload & 0xF).astype(jnp.int32) - 8
    hi = ((payload >> 4) & 0xF).astype(jnp.int32) - 8
    return jnp.stack([lo, hi], axis=1).reshape(-1).astype(jnp.int8)


def _safe_scales(v2):
    """Per-row (code_scale, wire_scale) for a (nblk, B) f32 grid.

    code_scale is always finite/positive (abs-max over the FINITE
    elements, 1.0 for all-zero blocks — zeros encode to exact zeros);
    wire_scale equals code_scale except for blocks containing any
    non-finite element, which get NaN so the whole block dequantizes
    to NaN — non-finiteness survives the wire without poisoning the
    neighbours."""
    finite = jnp.isfinite(v2)
    amax = jnp.max(jnp.where(finite, jnp.abs(v2), 0.0), axis=1)
    safe = jnp.where(amax > 0, amax, 1.0).astype(jnp.float32)
    wire = jnp.where(jnp.all(finite, axis=1), safe,
                     jnp.float32(jnp.nan))
    return safe, wire


def _encode(flat, bits, block_size, rng=None):
    """Aligned flat f32 vector → (wire payload, scales). Payload is
    int8 codes (bits=8) or packed uint8 nibble pairs (bits=4); scales
    are one f32 scalar (block_size=None) or f32[nblk]."""
    qmax = _qmax(bits)
    v2 = flat[None, :] if block_size is None else \
        flat.reshape(-1, int(block_size))
    safe, wire = _safe_scales(v2)
    x = jnp.where(jnp.isfinite(v2), v2, 0.0) / safe[:, None] * qmax
    q = jnp.round(x) if rng is None else \
        jnp.floor(x + jax.random.uniform(rng, x.shape))
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8).reshape(-1)
    scales = wire.reshape(()) if block_size is None else wire
    return (_pack4(q) if bits == 4 else q), scales


def _decode(payload, scales, bits, block_size):
    qmax = _qmax(bits)
    q = (_unpack4(payload) if bits == 4 else payload).astype(jnp.float32)
    if block_size is None:
        return q * (scales / qmax)
    return (q.reshape(-1, int(block_size))
            * (scales[:, None] / qmax)).reshape(-1)


def _ring_chunk(n: int, p: int, bits: int, block_size: Optional[int]) -> int:
    """Per-rank chunk length of the ring: ceil(n/p) rounded up to the
    encode alignment, so block boundaries never straddle chunks (the
    block grid of a whole-tensor roundtrip and of the ring encodes
    coincide — what makes error feedback compose with the ring)."""
    chunk = -(-n // p)
    a = _align(bits, block_size)
    return -(-chunk // a) * a


def block_roundtrip(x, *, bits: int = 8, block_size: Optional[int] = None,
                    rng=None):
    """Quantize-dequantize ``x`` through the wire grid WITHOUT an
    exchange: the value a rank's contribution becomes on the wire.
    ``x - block_roundtrip(x)`` is the local compression error — the
    error-feedback residual the Trainer carries in its scan carry.
    Alignment matches :func:`quantized_psum`'s chunk grid, so feeding
    the roundtripped value into the ring re-encodes to the same codes
    (abs-max quantization is idempotent per block)."""
    _check_block(bits, block_size)
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    a = _align(bits, block_size)
    flat = jnp.pad(flat, (0, -(-n // a) * a - n))
    out = _decode(*_encode(flat, bits, block_size, rng), bits, block_size)
    return out[:n].reshape(x.shape).astype(x.dtype)


def quantized_psum(x, axis_name: str, *, bits: int = 8,
                   block_size: Optional[int] = None, rng=None):
    """Ring all-reduce of ``x`` over ``axis_name`` with int8/int4-
    quantized hops. Drop-in for ``lax.psum`` inside ``shard_map`` when
    wire bytes matter more than exactness; accumulation stays f32,
    each of the 2(P-1) hops quantizes its payload (error per hop ≤
    max/qmax of the partial being carried, per scale block).

    Ring schedule (reduce-scatter then all-gather, one neighbor
    ppermute per step): rank r first forwards chunk (r+1)%P, adds its
    own contribution to the partial arriving at step k (chunk
    (r-k+1)%P), and after P-1 steps owns fully-reduced chunk (r+2)%P;
    the all-gather phase circulates the reduced chunks back around.

    ``rng`` (optional) applies stochastic rounding to the reduce-
    scatter-phase encodes only; the owner's roundtrip and the
    all-gather hops stay deterministic so the across-rank bitwise-
    identity contract holds regardless.
    """
    _check_block(bits, block_size)
    p = jax.lax.axis_size(axis_name)
    if p == 1:
        return x
    r = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p) for i in range(p)]

    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    chunk = _ring_chunk(n, p, bits, block_size)
    flat = jnp.pad(flat, (0, chunk * p - n))
    chunks = flat.reshape(p, chunk)

    def take(idx):
        return jax.lax.dynamic_index_in_dim(chunks, idx, 0, keepdims=False)

    def hop(v, key=None):
        q, s = _encode(v, bits, block_size, key)
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        return _decode(q, s, bits, block_size)

    # reduce-scatter: after the loop `carry` is chunk (r+2)%p summed
    # over every rank
    carry = take((r + 1) % p)
    for k in range(1, p):
        key = jax.random.fold_in(rng, k) if rng is not None else None
        carry = hop(carry, key) + take((r - k + 1) % p)

    # all-gather: circulate the reduced chunks; rank r receives chunk
    # owned by rank r-k, i.e. ((r-k)+2)%p, at step k. The OWNER also
    # stores the quantized roundtrip of its chunk, not the exact f32:
    # abs-max quantization is idempotent per scale block (the block max
    # maps to exactly ±qmax, so every further hop re-encodes to the
    # same codes), which makes the final result BITWISE IDENTICAL on
    # every rank — the all-reduce contract DP replicas rely on to not
    # drift.
    carry = _decode(*_encode(carry, bits, block_size), bits, block_size)
    out = jnp.zeros_like(chunks)
    out = jax.lax.dynamic_update_index_in_dim(out, carry, (r + 2) % p, 0)
    recv = carry
    for k in range(1, p):
        recv = hop(recv)
        out = jax.lax.dynamic_update_index_in_dim(out, recv, (r - k + 2) % p, 0)

    return out.reshape(-1)[:n].reshape(orig_shape).astype(orig_dtype)


def quantized_pmean(x, axis_name: str, *, bits: int = 8,
                    block_size: Optional[int] = None, rng=None):
    """Mean-reduction sibling of :func:`quantized_psum` (the gradient
    averaging form data-parallel training actually uses)."""
    return quantized_psum(x, axis_name, bits=bits, block_size=block_size,
                          rng=rng) / jax.lax.axis_size(axis_name)


# --------------------------------------------------------------------------
# host-side wire codec (the async-PS PUSHQB verb) + byte accounting
# --------------------------------------------------------------------------


def encode_wire_blocks(arr, *, bits: int = 8, block_size: int = 256
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Numpy twin of the in-graph encoder, for host wire crossings
    (``PSClient.push_quantized_blocks``): flat gradient → (payload,
    scales). Payload is int8 codes (bits=8) or packed bias-8 nibble
    pairs as uint8 (bits=4), input padded with zeros to the block
    grid; scales are f32[nblk] with the same zero/NaN-safe semantics
    as the collective's (:func:`_safe_scales`)."""
    enforce(block_size and int(block_size) >= 1,
            f"encode_wire_blocks needs a positive block_size, "
            f"got {block_size}")
    _check_block(bits, block_size)
    b = int(block_size)
    qmax = _qmax(bits)
    g = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
    n = g.size
    padded = -(-max(n, 1) // b) * b
    g = np.pad(g, (0, padded - n))
    v2 = g.reshape(-1, b)
    finite = np.isfinite(v2)
    amax = np.max(np.abs(np.where(finite, v2, 0.0)), axis=1)
    safe = np.where(amax > 0, amax, 1.0).astype(np.float32)
    wire = np.where(finite.all(axis=1), safe,
                    np.float32(np.nan)).astype(np.float32)
    q = np.clip(np.rint(np.where(finite, v2, 0.0) / safe[:, None] * qmax),
                -qmax, qmax).astype(np.int8).reshape(-1)
    if bits == 4:
        u = (q.astype(np.int32) + 8).astype(np.uint8)
        q = (u[0::2] | (u[1::2] << 4)).astype(np.uint8)
    return q, wire


def decode_wire_blocks(payload, scales, n: int, *, bits: int = 8,
                       block_size: int = 256) -> np.ndarray:
    """Inverse of :func:`encode_wire_blocks` (the pserver's dequant,
    in numpy — parity-tested against both the C++ server and the jnp
    in-graph decoder)."""
    _check_block(bits, block_size)
    b = int(block_size)
    qmax = _qmax(bits)
    q = np.asarray(payload)
    if bits == 4:
        u = q.view(np.uint8) if q.dtype != np.uint8 else q
        lo = (u & 0xF).astype(np.int32) - 8
        hi = ((u >> 4) & 0xF).astype(np.int32) - 8
        q = np.stack([lo, hi], axis=1).reshape(-1)
    s = np.asarray(scales, dtype=np.float32)
    out = (q.astype(np.float32).reshape(-1, b)
           * (s[:, None] / qmax)).reshape(-1)
    return out[:n]


def wire_block_bytes(n: int, *, bits: int = 8, block_size: int = 256
                     ) -> Tuple[int, int]:
    """(payload_bytes, scales_bytes) :func:`encode_wire_blocks` puts on
    the wire for ``n`` elements — what both the PUSHQB header contract
    and the C++ server's body-length computation derive from."""
    _check_block(bits, block_size)
    b = int(block_size)
    padded = -(-max(int(n), 1) // b) * b
    nblk = padded // b
    return (padded if bits == 8 else padded // 2), 4 * nblk


def ring_wire_bytes(n: int, p: int, *, bits: Optional[int] = None,
                    block_size: Optional[int] = None) -> int:
    """Per-device bytes-on-wire of ONE ring all-reduce of ``n``
    elements over a ``p``-ring: 2(p-1) hops, each carrying one chunk's
    payload (+ scales when quantized). ``bits=None`` is the f32
    baseline — the same ring schedule at 4 bytes/element, the apples-
    to-apples denominator of the collective-bytes attribution in
    ``profile_report()``."""
    n, p = int(n), int(p)
    if p <= 1 or n <= 0:
        return 0
    if bits is None:
        return 2 * (p - 1) * (-(-n // p)) * 4
    _check_block(bits, block_size)
    chunk = _ring_chunk(n, p, bits, block_size)
    codes = chunk if bits == 8 else chunk // 2
    scales = 4 * (chunk // int(block_size) if block_size else 1)
    return 2 * (p - 1) * (codes + scales)
