"""Serving-runtime fault-injection suite (paddle_tpu.serving +
io.Predictor validation/bucketing + atomic inference artifacts).

The acceptance contracts, all CPU + deterministic:

  * malformed requests raise typed InvalidRequest naming the field;
  * a saturated bounded queue rejects with ServerOverloaded (no
    deadlock, bounded memory);
  * after warmup, off-bucket request shapes cause ZERO new compiles
    (the AOT compile count is pinned) and in-bucket results are
    bit-identical to bare Predictor.run;
  * a hung dispatch trips the watchdog + circuit breaker, fails fast,
    and a half-open probe recovers the pool;
  * hot reload of a corrupt/canary-failing artifact rolls back with
    zero dropped in-flight requests;
  * save_inference_model commits atomically (crash points leave the
    previous artifact intact) and load_inference_model rejects
    torn/bit-flipped artifacts with CheckpointCorrupt.
"""

import os
import shutil
import threading
import time

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import serving
from paddle_tpu.resilience import CheckpointCorrupt
from paddle_tpu.serving import (BreakerPolicy, CircuitOpen, DeadlineExceeded,
                                InvalidRequest, PredictorServer, ReloadFailed,
                                ServerClosed, ServerOverloaded, WorkerHung)
from paddle_tpu.testing import faults


def _feed(n, seed=0):
    rng = np.random.RandomState(seed)
    return {"image": rng.randn(n, 784).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    """One exported classifier with bucket set {4, 8}; everything else
    clones/copies it."""
    from paddle_tpu.models import mnist

    d = str(tmp_path_factory.mktemp("serving") / "model")
    prog = pt.build(mnist.mlp)
    feed8 = _feed(8)
    params, state = prog.init(jax.random.PRNGKey(0), **feed8)
    pio.save_inference_model(d, prog, params, state, feed8,
                             batch_buckets=[4, 8])
    return {"dir": d, "prog": prog, "params": params, "state": state,
            "feed8": feed8}


@pytest.fixture(scope="module")
def pred(artifact):
    return pio.load_inference_model(artifact["dir"])


# -- request validation ------------------------------------------------------


def test_predictor_run_validates_standalone(pred):
    feed8 = _feed(8)
    out = pred.run(feed8)
    assert np.asarray(out["logits"]).shape == (8, 10)
    # bucket 4 dispatches to its own precompiled executable
    assert np.asarray(pred.run(_feed(4))["logits"]).shape == (4, 10)

    with pytest.raises(InvalidRequest, match="label.*missing") as ei:
        pred.run({"image": feed8["image"]})
    assert ei.value.field == "label"
    with pytest.raises(InvalidRequest, match="extra_key.*not a feed"):
        pred.run({**feed8, "extra_key": np.zeros(3)})
    with pytest.raises(InvalidRequest, match="image.*shape"):
        pred.run({**feed8, "image": feed8["image"][:, :700]})
    with pytest.raises(InvalidRequest, match="label.*dtype") as ei:
        pred.run({**feed8, "label": feed8["label"].astype(np.float32)})
    assert ei.value.field == "label"
    # off-bucket batch: run() is strict (padding is the server's job)
    with pytest.raises(InvalidRequest, match="not a precompiled bucket"):
        pred.run(_feed(5))
    with pytest.raises(InvalidRequest, match="batch dim.*disagrees"):
        pred.run({"image": feed8["image"], "label": _feed(4)["label"]})


def test_server_rejects_nonfinite_payload(pred):
    with PredictorServer(pred, workers=1, queue_size=4) as srv:
        bad = _feed(8)
        bad["image"][3, 17] = np.nan
        with pytest.raises(InvalidRequest, match="image.*non-finite") as ei:
            srv.submit(bad)
        assert ei.value.field == "image"
        assert srv.metrics.snapshot()["rejected_invalid"] == 1
        # int feeds are never finite-scanned
        srv.run(_feed(8), timeout=60)


# -- bucketing + compile pin -------------------------------------------------


def test_off_bucket_rejected_compiles_pinned_inbucket_bitexact(pred):
    """The acceptance pin: warmed up, mixed traffic (in-bucket, padded,
    off-bucket-rejected) causes zero new compiles, and in-bucket answers
    are bit-identical to bare Predictor.run."""
    feed8 = _feed(8, seed=3)
    golden = np.asarray(pred.run(feed8)["logits"])
    with PredictorServer(pred, workers=2, queue_size=16,
                         golden_feed=feed8) as srv:
        before = pio.aot_compile_count()
        for _ in range(3):
            got = np.asarray(srv.run(feed8, timeout=60)["logits"])
            assert got.tobytes() == golden.tobytes()  # bit-identical
            out5 = srv.run(_feed(5, seed=4), timeout=60)  # padded to 8
            assert np.asarray(out5["logits"]).shape == (5, 10)
            with pytest.raises(InvalidRequest,
                               match="exceeds the largest precompiled"):
                srv.submit(_feed(16))
            with pytest.raises(InvalidRequest):
                srv.submit(_feed(0))
        rep = srv.report()
        assert pio.aot_compile_count() == before
        assert rep["compiles_since_warmup"] == 0
        assert rep["batch_buckets"] == [4, 8]


def test_padded_rows_match_unpadded(pred):
    """Padding up to a bucket must not perturb the real rows (rows are
    independent through the MLP)."""
    f3 = _feed(3, seed=5)
    with PredictorServer(pred, workers=1, queue_size=4) as srv:
        served = np.asarray(srv.run(f3, timeout=60)["logits"])
    f4 = {k: np.concatenate([v, np.zeros((1,) + v.shape[1:], v.dtype)])
          for k, v in f3.items()}
    direct = np.asarray(pred.run(f4)["logits"])[:3]
    np.testing.assert_allclose(served, direct, rtol=1e-6, atol=1e-6)


# -- bounded queue + deadlines -----------------------------------------------


def test_saturated_queue_rejects_no_deadlock(pred):
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = PredictorServer(hang, workers=1, queue_size=2, warmup=False,
                          watchdog_timeout=30.0)
    try:
        f = _feed(8)
        first = srv.submit(f)          # occupies the lone worker
        for _ in range(40):            # wait for it to be dequeued
            if srv._queue.empty():
                break
            time.sleep(0.02)
        queued = [srv.submit(f), srv.submit(f)]   # fills the queue
        with pytest.raises(ServerOverloaded) as ei:
            srv.submit(f)
        assert ei.value.capacity == 2
        assert srv.health()["state"] == "overloaded"
        assert srv.metrics.snapshot()["rejected_overload"] == 1
        release.set()                  # unwedge: everything drains
        assert np.asarray(first.result(timeout=60)["logits"]).shape == (8, 10)
        for p in queued:
            p.result(timeout=60)
    finally:
        release.set()
        srv.close(drain=False, timeout=5)


def test_deadline_expired_in_queue_is_dropped(pred):
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = PredictorServer(hang, workers=1, queue_size=4, warmup=False,
                          watchdog_timeout=30.0)
    try:
        f = _feed(8)
        blocker = srv.submit(f)
        expiring = srv.submit(f, deadline=0.05)
        time.sleep(0.2)                # deadline passes while queued
        release.set()
        blocker.result(timeout=60)
        with pytest.raises(DeadlineExceeded):
            expiring.result(timeout=60)
        assert srv.metrics.snapshot()["timeouts"] == 1
    finally:
        release.set()
        srv.close(drain=False, timeout=5)


# -- circuit breaker + watchdog ----------------------------------------------


def test_breaker_trips_fails_fast_and_half_open_recovers(pred):
    flaky = faults.failing_predictor(pred, fail_calls=3)
    srv = PredictorServer(flaky, workers=1, queue_size=8, warmup=False,
                          breaker=BreakerPolicy(failure_threshold=3,
                                                cooldown=0.2))
    try:
        f = _feed(8)
        for _ in range(3):
            with pytest.raises(RuntimeError, match="injected executable"):
                srv.run(f, timeout=60)
        assert srv.breaker.state == "open"
        assert srv.health()["state"] == "breaker_open"
        assert not srv.health()["ready"]
        with pytest.raises(CircuitOpen):        # fail fast, no queueing
            srv.submit(f)
        time.sleep(0.25)                        # cooldown elapses
        out = srv.run(f, timeout=60)            # the half-open probe
        assert np.asarray(out["logits"]).shape == (8, 10)
        assert srv.breaker.state == "closed"
        assert srv.health()["ready"]
        rep = srv.report()
        assert rep["breaker"]["trips"] == 1
        assert rep["errors"] == 3 and rep["rejected_breaker"] == 1
    finally:
        srv.close(drain=False, timeout=5)


def test_probe_failure_reopens(pred):
    flaky = faults.failing_predictor(pred, fail_calls=5)
    srv = PredictorServer(flaky, workers=1, queue_size=8, warmup=False,
                          breaker=BreakerPolicy(failure_threshold=2,
                                                cooldown=0.15))
    try:
        f = _feed(8)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                srv.run(f, timeout=60)
        assert srv.breaker.state == "open"
        time.sleep(0.2)
        with pytest.raises(RuntimeError):       # probe fails (call #3)
            srv.run(f, timeout=60)
        assert srv.breaker.state == "open"      # re-opened
        with pytest.raises(CircuitOpen):
            srv.submit(f)
    finally:
        srv.close(drain=False, timeout=5)


def test_watchdog_hung_worker_trips_breaker_and_replaces(pred):
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = PredictorServer(hang, workers=1, queue_size=4, warmup=False,
                          watchdog_timeout=0.2,
                          breaker=BreakerPolicy(failure_threshold=5,
                                                cooldown=0.2))
    try:
        f = _feed(8)
        hung = srv.submit(f)
        with pytest.raises(WorkerHung, match="watchdog"):
            hung.result(timeout=60)             # failed FAST, not at join
        assert srv.breaker.state == "open"      # one hang is conclusive
        m = srv.metrics.snapshot()
        assert m["hangs"] == 1 and m["workers_replaced"] == 1
        release.set()                           # executable recovers
        time.sleep(0.25)                        # cooldown
        out = srv.run(f, timeout=60)            # probe on the REPLACEMENT
        assert np.asarray(out["logits"]).shape == (8, 10)
        assert srv.breaker.state == "closed"
        assert srv.health()["ready"] and srv.health()["live"]
    finally:
        release.set()
        srv.close(drain=False, timeout=5)


def test_breaker_stale_probe_success_cannot_bypass_fresh_trip():
    """A half-open probe that HANGS, gets abandoned, and finally returns
    success after the watchdog tripped the breaker again must not close
    it — the fresh trip's cooldown holds."""
    from paddle_tpu.serving import CircuitBreaker

    b = CircuitBreaker(BreakerPolicy(failure_threshold=1, cooldown=0.05))
    b.record("pass", success=False)          # trip
    assert b.state == "open"
    time.sleep(0.06)
    tok = b.acquire()
    assert tok == "probe"
    b.trip()                                 # watchdog fires mid-probe
    b.record(tok, success=True)              # the stale probe success
    assert b.state == "open"                 # cooldown NOT bypassed
    # and a stale "pass" success can't either
    b.record("pass", success=True)
    assert b.state == "open"


def test_expired_probe_returns_slot_breaker_recovers(pred):
    """A half-open PROBE whose deadline expires while queued must return
    its slot — otherwise the breaker wedges in half_open and rejects
    every request forever."""
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = PredictorServer(hang, workers=1, queue_size=4, warmup=False,
                          watchdog_timeout=30.0,
                          breaker=BreakerPolicy(failure_threshold=5,
                                                cooldown=0.05))
    try:
        f = _feed(8)
        blocker = srv.submit(f)              # worker busy (hangs)
        for _ in range(200):                 # wait until it is DEQUEUED
            if any(w.busy_since is not None for w in srv._workers):
                break
            time.sleep(0.01)
        srv.breaker.trip()                   # breaker opens meanwhile
        time.sleep(0.06)                     # cooldown elapses
        probe = srv.submit(f, deadline=0.01)  # THE half-open probe
        time.sleep(0.05)                     # its deadline passes queued
        release.set()                        # worker frees, dequeues probe
        with pytest.raises(DeadlineExceeded):
            probe.result(timeout=60)
        # slot returned: the NEXT request becomes the probe and recovers
        out = srv.run(f, timeout=60)
        assert np.asarray(out["logits"]).shape == (8, 10)
        assert srv.breaker.state == "closed"
        blocker.result(timeout=60)
    finally:
        release.set()
        srv.close(drain=False, timeout=5)


def test_raw_validation_error_returns_probe_slot(pred):
    """Validation can raise RAW numpy errors (ragged nested list) — the
    half-open probe slot must come back or the breaker wedges."""
    srv = PredictorServer(pred, workers=1, queue_size=4, warmup=False,
                          breaker=BreakerPolicy(failure_threshold=5,
                                                cooldown=0.05))
    try:
        srv.breaker.trip()
        time.sleep(0.06)                     # cooldown: next token = probe
        bad = dict(_feed(8))
        bad["image"] = [[1.0, 2.0], [3.0]]   # ragged: np.asarray raises
        with pytest.raises(Exception) as ei:
            srv.submit(bad)
        assert not isinstance(ei.value, (CircuitOpen, InvalidRequest))
        # the slot was returned: this request becomes the probe
        out = srv.run(_feed(8), timeout=60)
        assert np.asarray(out["logits"]).shape == (8, 10)
        assert srv.breaker.state == "closed"
    finally:
        srv.close(drain=False, timeout=5)


def test_drain_timeout_fails_stranded_queue(pred):
    """A drain that hits its timeout must fail still-queued requests
    with ServerClosed rather than stranding their clients forever."""
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = PredictorServer(hang, workers=1, queue_size=8, warmup=False,
                          watchdog_timeout=30.0)
    try:
        f = _feed(8)
        blocker = srv.submit(f)
        queued = [srv.submit(f) for _ in range(3)]
        srv.close(drain=True, timeout=0.2)   # worker still hung: timeout
        for p in queued:
            assert p.done()
            with pytest.raises(ServerClosed):
                p.result(timeout=0)
        blocker  # in-flight on the hung worker; typed outcome either way
    finally:
        release.set()


def test_failed_reload_does_not_poison_compile_pin(artifact, pred, tmp_path):
    """A rolled-back reload AOT-compiled its candidate off the request
    path; the compiles_since_warmup contract signal must re-pin, not
    read as a permanent (false) request-path recompile."""
    d_nan = _export_variant(
        artifact, tmp_path, "vnan_pin",
        lambda p: jax.tree.map(lambda v: np.full_like(v, np.nan), p))
    srv = PredictorServer(pred, workers=1, queue_size=8,
                          golden_feed=artifact["feed8"])
    try:
        with pytest.raises(ReloadFailed):
            srv.reload(d_nan, block=True)
        srv.run(artifact["feed8"], timeout=60)
        assert srv.report()["compiles_since_warmup"] == 0
    finally:
        srv.close(drain=True, timeout=10)


def test_drain_completes_despite_abandoned_hung_worker(pred):
    """close(drain=True) must not spin on a watchdog-abandoned worker
    whose dispatch never returns (the SIGTERM drain path)."""
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = PredictorServer(hang, workers=2, queue_size=8, warmup=False,
                          watchdog_timeout=0.2)
    try:
        hung = srv.submit(_feed(8))
        with pytest.raises(WorkerHung):
            hung.result(timeout=60)
        t0 = time.monotonic()
        srv.close(drain=True)                # no timeout: must still return
        assert time.monotonic() - t0 < 10.0
        assert srv.health()["state"] == "stopped"
    finally:
        release.set()


# -- hot reload ---------------------------------------------------------------


def _export_variant(artifact, tmp_path, name, mutate):
    """Re-export the module model with mutated params."""
    params = jax.tree.map(np.asarray, artifact["params"])
    params = mutate(params)
    d = str(tmp_path / name)
    pio.save_inference_model(d, artifact["prog"], params, artifact["state"],
                             artifact["feed8"], batch_buckets=[4, 8])
    return d


def test_hot_reload_swaps_with_zero_dropped_requests(artifact, pred, tmp_path):
    d2 = _export_variant(artifact, tmp_path, "v2",
                         lambda p: jax.tree.map(lambda v: v * 0.5, p))
    golden_new = np.asarray(pio.load_inference_model(d2).run(
        artifact["feed8"])["logits"])
    golden_old = np.asarray(pred.run(artifact["feed8"])["logits"])
    srv = PredictorServer(pred, workers=2, queue_size=16,
                          golden_feed=artifact["feed8"])
    results, errors = [], []
    stop_pump = threading.Event()

    def pump():
        while not stop_pump.is_set():
            try:
                out = srv.run(artifact["feed8"], timeout=60)
                results.append(np.asarray(out["logits"]))
            except BaseException as e:          # pragma: no cover
                errors.append(e)
                return

    t = threading.Thread(target=pump)
    t.start()
    try:
        time.sleep(0.05)                        # in-flight traffic exists
        srv.reload(d2, block=True)
        assert srv.generation == 2
        for _ in range(3):                      # post-swap traffic
            results_len = len(results)
            while len(results) == results_len and not errors:
                time.sleep(0.01)
        stop_pump.set()
        t.join(timeout=120)
        assert not errors                       # ZERO dropped in-flight
        assert len(results) >= 4
        # every answer is exactly old-model or new-model output — the
        # swap is atomic, no half-reloaded frankenmodel
        for r in results:
            assert (r.tobytes() == golden_old.tobytes()
                    or r.tobytes() == golden_new.tobytes())
        assert results[-1].tobytes() == golden_new.tobytes()
        assert srv.report()["compiles_since_warmup"] == 0  # re-pinned
        assert srv.metrics.snapshot()["reloads"] == 1
    finally:
        stop_pump.set()
        t.join(timeout=5)
        srv.close(drain=True, timeout=10)


def test_hot_reload_corrupt_artifact_rolls_back(artifact, pred, tmp_path):
    d2 = _export_variant(artifact, tmp_path, "v2c",
                         lambda p: jax.tree.map(lambda v: v * 0.5, p))
    faults.flip_byte(d2, "params.npz")          # silent bitrot
    srv = PredictorServer(pred, workers=1, queue_size=8,
                          golden_feed=artifact["feed8"])
    try:
        inflight = [srv.submit(artifact["feed8"]) for _ in range(3)]
        with pytest.raises(CheckpointCorrupt, match="checksum"):
            srv.reload(d2, block=True)
        assert srv.generation == 1              # rolled back
        for p in inflight:                      # zero dropped
            p.result(timeout=60)
        srv.run(artifact["feed8"], timeout=60)  # still serving gen 1
        m = srv.metrics.snapshot()
        assert m["reload_failures"] == 1 and m["reloads"] == 0
        assert isinstance(srv.last_reload_error, CheckpointCorrupt)
    finally:
        srv.close(drain=True, timeout=10)


def test_hot_reload_canary_failure_rolls_back(artifact, pred, tmp_path):
    d_nan = _export_variant(
        artifact, tmp_path, "vnan",
        lambda p: jax.tree.map(lambda v: np.full_like(v, np.nan), p))
    srv = PredictorServer(pred, workers=1, queue_size=8,
                          golden_feed=artifact["feed8"])
    try:
        with pytest.raises(ReloadFailed, match="non-finite"):
            srv.reload(d_nan, block=True)
        assert srv.generation == 1
        srv.run(artifact["feed8"], timeout=60)
    finally:
        srv.close(drain=True, timeout=10)


def test_hot_reload_custom_canary_check(artifact, pred, tmp_path):
    d2 = _export_variant(artifact, tmp_path, "v2k",
                         lambda p: jax.tree.map(lambda v: v * 0.5, p))
    srv = PredictorServer(pred, workers=1, queue_size=8,
                          golden_feed=artifact["feed8"],
                          canary_check=lambda out: False)
    try:
        with pytest.raises(ReloadFailed, match="canary_check"):
            srv.reload(d2, block=True)
        assert srv.generation == 1
    finally:
        srv.close(drain=True, timeout=10)


def test_reload_succeeds_with_off_bucket_golden_feed(artifact, pred, tmp_path):
    """A legal golden feed whose batch is not itself a bucket pads on
    submit and resizes in warmup — the canary must do the same, not
    fail every reload with an exact-bucket InvalidRequest."""
    d2 = _export_variant(artifact, tmp_path, "v2g",
                         lambda p: jax.tree.map(lambda v: v * 0.5, p))
    golden6 = {k: np.asarray(v)[:6] for k, v in artifact["feed8"].items()}
    srv = PredictorServer(pred, workers=1, queue_size=8, golden_feed=golden6)
    try:
        srv.reload(d2, block=True)
        assert srv.generation == 2
    finally:
        srv.close(drain=True, timeout=10)


def test_reload_rejects_feed_shape_drift(artifact, pred, tmp_path):
    """Same feed names + buckets but a drifted per-feed shape: queued
    in-flight requests validated against the old shapes would all fail
    on the new model — rejected before the swap."""
    feed700 = {"image": np.asarray(artifact["feed8"]["image"])[:, :700].copy(),
               "label": np.asarray(artifact["feed8"]["label"])}
    params700, state700 = artifact["prog"].init(jax.random.PRNGKey(1),
                                                **feed700)
    d_drift = str(tmp_path / "vdrift")
    pio.save_inference_model(d_drift, artifact["prog"],
                             jax.tree.map(np.asarray, params700), state700,
                             feed700, batch_buckets=[4, 8])
    srv = PredictorServer(pred, workers=1, queue_size=8,
                          golden_feed=artifact["feed8"])
    try:
        with pytest.raises(ReloadFailed, match="feed signature drifted"):
            srv.reload(d_drift, block=True)
        assert srv.generation == 1
        srv.run(artifact["feed8"], timeout=60)
    finally:
        srv.close(drain=True, timeout=10)


def test_reload_rejects_signature_drift(artifact, pred, tmp_path):
    """A candidate whose bucket set shrank would send in-flight bucket
    traffic off-bucket: rejected before the swap."""
    d_small = str(tmp_path / "vsmall")
    pio.save_inference_model(d_small, artifact["prog"],
                             jax.tree.map(np.asarray, artifact["params"]),
                             artifact["state"], artifact["feed8"])  # only {8}
    srv = PredictorServer(pred, workers=1, queue_size=8,
                          golden_feed=artifact["feed8"])
    try:
        with pytest.raises(ReloadFailed, match="bucket set shrank"):
            srv.reload(d_small, block=True)
        assert srv.generation == 1
    finally:
        srv.close(drain=True, timeout=10)


# -- drain + health -----------------------------------------------------------


def test_graceful_drain_completes_queued_work(pred):
    srv = PredictorServer(pred, workers=1, queue_size=16)
    pending = [srv.submit(_feed(8)) for _ in range(6)]
    srv.close(drain=True, timeout=60)
    assert all(p.done() for p in pending)
    for p in pending:
        assert np.asarray(p.result(timeout=0)["logits"]).shape == (8, 10)
    with pytest.raises(ServerClosed):
        srv.submit(_feed(8))
    h = srv.health()
    assert h["state"] == "stopped" and not h["live"] and not h["ready"]


def test_close_without_drain_fails_queued_fast(pred):
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = PredictorServer(hang, workers=1, queue_size=8, warmup=False,
                          watchdog_timeout=30.0)
    f = _feed(8)
    blocker = srv.submit(f)
    queued = [srv.submit(f) for _ in range(3)]
    release.set()
    srv.close(drain=False, timeout=10)
    for p in queued:
        if p.done():
            with pytest.raises((ServerClosed, Exception)):
                p.result(timeout=0)
    blocker  # the in-flight one may have completed either way


def test_health_state_machine(pred):
    srv = PredictorServer(pred, workers=1, queue_size=4, start=False)
    assert srv.health()["state"] == "starting"
    with pytest.raises(ServerClosed, match="not started"):
        srv.submit(_feed(8))
    srv.start()
    h = srv.health()
    assert h["state"] == "ready" and h["ready"] and h["live"]
    assert h["workers"] == 1 and h["queue_capacity"] == 4
    srv.close(drain=True, timeout=30)
    assert srv.health()["state"] == "stopped"


def test_metrics_report_schema(pred):
    with PredictorServer(pred, workers=1, queue_size=4) as srv:
        srv.run(_feed(8), timeout=60)
        rep = srv.report()
    for key in ("submitted", "completed", "rejected_invalid",
                "rejected_overload", "rejected_breaker", "timeouts", "errors",
                "hangs", "workers_replaced", "reloads", "reload_failures",
                "latency_ms", "health", "breaker", "batch_buckets",
                "compiles_since_warmup"):
        assert key in rep, key
    assert rep["completed"] == 1
    assert rep["latency_ms"]["p50"] is not None
    assert rep["latency_ms"]["p99"] >= rep["latency_ms"]["p50"]


def test_preemption_handler_drains_server(pred):
    """The SIGTERM path: PreemptionHandler.on_signal kicks the drain —
    queued work completes, then the server is stopped."""
    import signal

    from paddle_tpu.resilience import PreemptionHandler

    srv = PredictorServer(pred, workers=1, queue_size=16)
    drained = threading.Event()
    with PreemptionHandler() as ph:
        ph.on_signal(lambda: (srv.close(drain=True), drained.set()))
        pending = [srv.submit(_feed(8)) for _ in range(4)]
        os.kill(os.getpid(), signal.SIGTERM)
        assert drained.wait(timeout=60)
    assert ph.requested
    for p in pending:
        p.result(timeout=0)                     # all completed, none dropped
    assert srv.health()["state"] == "stopped"


# -- atomic inference artifacts ----------------------------------------------


def test_save_inference_model_atomic_crash_points(artifact, tmp_path):
    d = str(tmp_path / "m")
    params = jax.tree.map(np.asarray, artifact["params"])
    pio.save_inference_model(d, artifact["prog"], params, artifact["state"],
                             artifact["feed8"])
    golden = np.asarray(
        pio.load_inference_model(d).run(artifact["feed8"])["logits"])
    for tag in ("save_inference_model:files-written",
                "save_inference_model:manifest-written"):
        with faults.crashing(tag):
            with pytest.raises(faults.InjectedCrash):
                pio.save_inference_model(
                    d, artifact["prog"],
                    jax.tree.map(lambda v: v * 2.0, params),
                    artifact["state"], artifact["feed8"])
        # the committed artifact is untouched by the torn overwrite
        got = np.asarray(
            pio.load_inference_model(d).run(artifact["feed8"])["logits"])
        assert got.tobytes() == golden.tobytes()
    # the two-rename overwrite window: a crash between rename-aside and
    # commit leaves the OLD artifact preserved under the .tmp.*.old
    # marker (never silently torn), and the next save recovers
    with faults.crashing("save_inference_model:committing"):
        with pytest.raises(faults.InjectedCrash):
            pio.save_inference_model(
                d, artifact["prog"], jax.tree.map(lambda v: v * 2.0, params),
                artifact["state"], artifact["feed8"])
    olds = [n for n in os.listdir(str(tmp_path)) if n.endswith(".old")]
    assert len(olds) == 1 and not os.path.exists(d)
    kept = np.asarray(pio.load_inference_model(
        str(tmp_path / olds[0])).run(artifact["feed8"])["logits"])
    assert kept.tobytes() == golden.tobytes()
    # recovery save restores the .old BEFORE sweeping — if it crashes
    # pre-commit itself, the previous artifact is back at the committed
    # path, never deleted while it is the only copy
    with faults.crashing("save_inference_model:files-written"):
        with pytest.raises(faults.InjectedCrash):
            pio.save_inference_model(
                d, artifact["prog"], jax.tree.map(lambda v: v * 2.0, params),
                artifact["state"], artifact["feed8"])
    restored = np.asarray(
        pio.load_inference_model(d).run(artifact["feed8"])["logits"])
    assert restored.tobytes() == golden.tobytes()
    # the next successful save sweeps the stale tmp dirs and commits
    pio.save_inference_model(d, artifact["prog"],
                             jax.tree.map(lambda v: v * 2.0, params),
                             artifact["state"], artifact["feed8"])
    assert not [n for n in os.listdir(str(tmp_path)) if ".tmp." in n]
    got = np.asarray(
        pio.load_inference_model(d).run(artifact["feed8"])["logits"])
    assert got.tobytes() != golden.tobytes()


def test_load_inference_model_rejects_torn_and_bitflipped(artifact, tmp_path):
    for fault, match in ((faults.truncate_file, "truncated"),
                         (faults.flip_byte, "checksum")):
        d = str(tmp_path / f"m_{fault.__name__}")
        shutil.copytree(artifact["dir"], d)
        fault(d, "params.npz")
        with pytest.raises(CheckpointCorrupt, match=match):
            pio.load_inference_model(d)
    # a flipped executable is caught too (manifest covers EVERY file)
    d = str(tmp_path / "m_hlo")
    shutil.copytree(artifact["dir"], d)
    faults.flip_byte(d, "model.stablehlo")
    with pytest.raises(CheckpointCorrupt):
        pio.load_inference_model(d)


def test_legacy_artifact_without_manifest_still_loads(artifact, tmp_path):
    d = str(tmp_path / "legacy")
    shutil.copytree(artifact["dir"], d)
    os.remove(os.path.join(d, "manifest.json"))
    p = pio.load_inference_model(d)
    assert np.asarray(p.run(artifact["feed8"])["logits"]).shape == (8, 10)


def test_predictor_fallback_logs_reason(artifact, monkeypatch, caplog):
    """The old SILENT AOT→jit fallback is now loud: the degradation to
    trace-on-request names the exception that caused it."""
    import logging

    def boom(exported):
        raise RuntimeError("no PJRT executable for you")

    monkeypatch.setattr(pio, "_aot_compile", boom)
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.io"):
        p = pio.load_inference_model(artifact["dir"])
    assert any("AOT compile failed" in r.getMessage()
               for r in caplog.records)
    assert any("no PJRT executable for you" in r.getMessage()
               for r in caplog.records)
    # the fallback still serves (first call traces)
    assert np.asarray(p.run(artifact["feed8"])["logits"]).shape == (8, 10)
