"""Sequence-op (LoD-equivalent) tests vs per-sequence numpy references —
the test_sequence_*_op.py family analog."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.layers import sequence as S


def _ragged(lengths, dim=3, seed=0):
    """Build packed values + segment ids from python lengths."""
    rng = np.random.RandomState(seed)
    total = sum(lengths)
    packed = rng.randn(total, dim).astype(np.float32)
    seg = np.concatenate([[i] * l for i, l in enumerate(lengths)]).astype(np.int32)
    return packed, seg


def test_offsets_roundtrip():
    lengths = jnp.asarray([3, 1, 4])
    off = S.lengths_to_offsets(lengths)
    np.testing.assert_array_equal(np.asarray(off), [0, 3, 4, 8])
    np.testing.assert_array_equal(np.asarray(S.offsets_to_lengths(off)), [3, 1, 4])


def test_lengths_to_segment_ids_with_padding_tail():
    seg = S.lengths_to_segment_ids(jnp.asarray([2, 3]), total=8)
    np.testing.assert_array_equal(np.asarray(seg), [0, 0, 1, 1, 1, 2, 2, 2])


def test_sequence_pool_all_types():
    lengths = [2, 3, 1]
    packed, seg = _ragged(lengths)
    splits = np.split(packed, np.cumsum(lengths)[:-1])
    for ptype, ref in [
        ("sum", np.stack([s.sum(0) for s in splits])),
        ("average", np.stack([s.mean(0) for s in splits])),
        ("sqrt", np.stack([s.sum(0) / np.sqrt(len(s)) for s in splits])),
        ("max", np.stack([s.max(0) for s in splits])),
        ("min", np.stack([s.min(0) for s in splits])),
        ("first", np.stack([s[0] for s in splits])),
        ("last", np.stack([s[-1] for s in splits])),
    ]:
        out = S.sequence_pool(jnp.asarray(packed), jnp.asarray(seg), 3, ptype)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6,
                                   err_msg=f"pool_type={ptype}")


def test_sequence_pool_ignores_padding_segment():
    packed, seg = _ragged([2, 2])
    # append garbage with segment id == num_seqs (padding)
    packed2 = np.concatenate([packed, 100 * np.ones((3, 3), np.float32)])
    seg2 = np.concatenate([seg, [2, 2, 2]]).astype(np.int32)
    out = S.sequence_pool(jnp.asarray(packed2), jnp.asarray(seg2), 2, "sum")
    ref = S.sequence_pool(jnp.asarray(packed), jnp.asarray(seg), 2, "sum")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_sequence_softmax():
    lengths = [3, 2]
    packed = np.array([1.0, 2.0, 3.0, 0.5, 0.5], np.float32)
    seg = np.array([0, 0, 0, 1, 1], np.int32)
    out = np.asarray(S.sequence_softmax(jnp.asarray(packed), jnp.asarray(seg), 2))
    e = np.exp(np.array([1.0, 2.0, 3.0]) - 3.0)
    np.testing.assert_allclose(out[:3], e / e.sum(), rtol=1e-5)
    np.testing.assert_allclose(out[3:], [0.5, 0.5], rtol=1e-5)
    np.testing.assert_allclose(out[:3].sum(), 1.0, rtol=1e-6)


def test_sequence_pad_unpad_roundtrip():
    lengths = [2, 3, 1]
    packed, seg = _ragged(lengths)
    padded, lens = S.sequence_pad(jnp.asarray(packed), jnp.asarray(lengths), max_len=4,
                                  pad_value=0.0)
    assert padded.shape == (3, 4, 3)
    np.testing.assert_allclose(np.asarray(padded[0, :2]), packed[:2], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(padded[0, 2:]), 0.0)
    np.testing.assert_allclose(np.asarray(padded[1, :3]), packed[2:5], rtol=1e-6)
    flat, seg2 = S.sequence_unpad(padded, jnp.asarray(lengths))
    pooled_a = S.sequence_pool(flat, seg2, 3, "sum")
    pooled_b = S.sequence_pool(jnp.asarray(packed), jnp.asarray(seg), 3, "sum")
    np.testing.assert_allclose(np.asarray(pooled_a), np.asarray(pooled_b), rtol=1e-5)


def test_sequence_expand():
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    out = S.sequence_expand(jnp.asarray(x), jnp.asarray([2, 3]), axis_total=5)
    np.testing.assert_allclose(np.asarray(out),
                               [[1, 2], [1, 2], [3, 4], [3, 4], [3, 4]])


def test_sequence_reverse():
    lengths = [3, 2]
    packed = np.arange(5, dtype=np.float32)[:, None]
    seg = np.array([0, 0, 0, 1, 1], np.int32)
    out = S.sequence_reverse(jnp.asarray(packed), jnp.asarray(seg), 2)
    np.testing.assert_allclose(np.asarray(out)[:, 0], [2, 1, 0, 4, 3])


def test_sequence_concat():
    p1 = np.array([[1.0], [2.0], [3.0]], np.float32)
    s1 = np.array([0, 0, 1], np.int32)
    p2 = np.array([[10.0], [20.0]], np.float32)
    s2 = np.array([0, 1], np.int32)
    packed, seg = S.sequence_concat([jnp.asarray(p1), jnp.asarray(p2)],
                                    [jnp.asarray(s1), jnp.asarray(s2)], 2)
    np.testing.assert_array_equal(np.asarray(seg), [0, 0, 0, 1, 1])
    np.testing.assert_allclose(np.asarray(packed)[:, 0], [1, 2, 10, 3, 20])


def test_sequence_enumerate():
    ids = jnp.asarray([[1, 2, 3, 4]])
    out = S.sequence_enumerate(ids, win_size=2, pad_value=0)
    np.testing.assert_array_equal(np.asarray(out)[0],
                                  [[1, 2], [2, 3], [3, 4], [4, 0]])


def test_sequence_mask():
    m = S.sequence_mask(jnp.asarray([1, 3]), maxlen=4)
    np.testing.assert_allclose(np.asarray(m), [[1, 0, 0, 0], [1, 1, 1, 0]])


def test_sequence_erase():
    packed = jnp.asarray(np.array([1, 2, 1, 3], np.int32))
    seg = jnp.asarray(np.array([0, 0, 1, 1], np.int32))
    _, new_seg = S.sequence_erase(packed, seg, [1], 2)
    np.testing.assert_array_equal(np.asarray(new_seg), [2, 0, 2, 1])


def test_sequence_slice():
    lengths = [4, 3]
    packed = np.arange(7, dtype=np.float32)[:, None]
    seg = np.array([0, 0, 0, 0, 1, 1, 1], np.int32)
    out, out_seg = S.sequence_slice(jnp.asarray(packed), jnp.asarray(seg), 2,
                                    offset=jnp.asarray([1, 0]),
                                    length=jnp.asarray([2, 2]), total_out=4)
    np.testing.assert_allclose(np.asarray(out)[:, 0], [1, 2, 4, 5])
    np.testing.assert_array_equal(np.asarray(out_seg), [0, 0, 1, 1])


def test_jit_safety():
    """All shape params static: ops must jit without retrace surprises."""
    import jax

    @jax.jit
    def fn(packed, seg):
        return S.sequence_pool(packed, seg, 3, "average")

    packed, seg = _ragged([2, 2, 2])
    out = fn(jnp.asarray(packed), jnp.asarray(seg))
    assert out.shape == (3, 3)
