"""Forward-compat shims for older jax releases.

The codebase targets the modern spellings (``jax.shard_map`` with
``check_vma=``, ``jax.lax.axis_size``). On a jax that predates them
(<0.5: shard_map still lives in jax.experimental and the replication
check is spelled ``check_rep``), install equivalent aliases ON the jax
modules so every call site — ours and the test-suite's — works
unchanged. Imported first from ``paddle_tpu/__init__`` so the shims are
in place before any submodule (or user code that imported us) touches
them. No-ops entirely on a jax that already has the real thing.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map

if not hasattr(jax.lax, "axis_size"):
    def _axis_size(axis_name):
        # psum of a literal 1 is folded to the (static) axis size
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = _axis_size

if not hasattr(jax.lax, "pcast") and not hasattr(jax.lax, "pvary"):
    def _pvary(x, axis_names=None):
        # pre-vma jax has no device-varying bookkeeping to update:
        # replication consistency is handled by check_rep, so marking
        # a value varying is the identity
        return x

    jax.lax.pvary = _pvary
