"""Layer library — the ``fluid.layers`` surface (python/paddle/fluid/layers/)."""

from . import attention, beam_search, control_flow, crf, ctc, detection
from . import nn, ops, rnn, sequence, tensor
from .ctc import ctc_greedy_decoder, edit_distance, warpctc
from .attention import (
    ffn,
    multi_head_attention,
    padding_mask,
    positional_encoding,
    scaled_dot_product_attention,
)
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .rnn import dynamic_gru, dynamic_lstm, rnn as rnn_scan
from .tensor import *  # noqa: F401,F403
