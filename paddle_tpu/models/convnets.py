"""AlexNet, GoogLeNet(v1), SE-ResNeXt — the remaining benchmark model
families (benchmark/README.md rows; benchmark/fluid/models/se_resnext).
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import layers as L
from ..framework import current_layout, name_scope
from ..metrics import accuracy
from .resnet import conv_bn_layer


def make_alexnet(class_num=1000):
    """AlexNet (benchmark/README.md AlexNet rows)."""

    def alexnet(image, label):
        x = L.conv2d(image, 64, 11, stride=4, padding=2, act="relu")
        x = L.pool2d(x, 3, "max", 2)
        x = L.conv2d(x, 192, 5, padding=2, act="relu")
        x = L.pool2d(x, 3, "max", 2)
        x = L.conv2d(x, 384, 3, padding=1, act="relu")
        x = L.conv2d(x, 256, 3, padding=1, act="relu")
        x = L.conv2d(x, 256, 3, padding=1, act="relu")
        x = L.pool2d(x, 3, "max", 2)
        x = L.flatten(L.to_chw_order(x), axis=1)
        x = L.dropout(x, 0.5)
        x = L.fc(x, 4096, act="relu")
        x = L.dropout(x, 0.5)
        x = L.fc(x, 4096, act="relu")
        logits = L.fc(x, class_num)
        loss = L.mean(L.softmax_with_cross_entropy(logits, label))
        return {"loss": loss, "acc": accuracy(logits, label), "logits": logits}

    return alexnet


def _inception(x, c1, c3r, c3, c5r, c5, proj):
    b1 = L.conv2d(x, c1, 1, act="relu")
    b2 = L.conv2d(L.conv2d(x, c3r, 1, act="relu"), c3, 3, padding=1, act="relu")
    b3 = L.conv2d(L.conv2d(x, c5r, 1, act="relu"), c5, 5, padding=2, act="relu")
    b4 = L.conv2d(L.pool2d(x, 3, "max", 1, 1), proj, 1, act="relu")
    return L.concat([b1, b2, b3, b4],
                    axis=1 if current_layout() == "NCHW" else 3)


def make_googlenet(class_num=1000):
    """GoogLeNet v1 (benchmark/README.md GoogleNet rows)."""

    def googlenet(image, label):
        x = L.conv2d(image, 64, 7, stride=2, padding=3, act="relu")
        x = L.pool2d(x, 3, "max", 2, 1)
        x = L.conv2d(x, 64, 1, act="relu")
        x = L.conv2d(x, 192, 3, padding=1, act="relu")
        x = L.pool2d(x, 3, "max", 2, 1)
        x = _inception(x, 64, 96, 128, 16, 32, 32)
        x = _inception(x, 128, 128, 192, 32, 96, 64)
        x = L.pool2d(x, 3, "max", 2, 1)
        x = _inception(x, 192, 96, 208, 16, 48, 64)
        x = _inception(x, 160, 112, 224, 24, 64, 64)
        x = _inception(x, 128, 128, 256, 24, 64, 64)
        x = _inception(x, 112, 144, 288, 32, 64, 64)
        x = _inception(x, 256, 160, 320, 32, 128, 128)
        x = L.pool2d(x, 3, "max", 2, 1)
        x = _inception(x, 256, 160, 320, 32, 128, 128)
        x = _inception(x, 384, 192, 384, 48, 128, 128)
        x = L.pool2d(x, pool_type="avg", global_pooling=True)
        x = L.dropout(x, 0.4)
        logits = L.fc(L.flatten(x, axis=1), class_num)
        loss = L.mean(L.softmax_with_cross_entropy(logits, label))
        return {"loss": loss, "acc": accuracy(logits, label), "logits": logits}

    return googlenet


def _squeeze_excite(x, reduction=16):
    c_axis = 1 if current_layout() == "NCHW" else 3
    c = x.shape[c_axis]
    s = L.pool2d(x, pool_type="avg", global_pooling=True)
    s = L.fc(L.flatten(s, axis=1), max(c // reduction, 4), act="relu")
    s = L.fc(s, c, act="sigmoid")
    return x * (s[:, :, None, None] if c_axis == 1 else s[:, None, None, :])


def make_se_resnext(depth=50, class_num=1000, cardinality=32, reduction=16):
    """SE-ResNeXt-50 (benchmark/fluid/models/se_resnext.py analog)."""
    stages = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3)}[depth]

    def block(x, filters, stride):
        h = conv_bn_layer(x, filters, 1, act="relu")
        h = conv_bn_layer(h, filters, 3, stride=stride, act="relu",
                          groups=cardinality)
        h = conv_bn_layer(h, filters * 2, 1)
        h = _squeeze_excite(h, reduction)
        if x.shape[1 if current_layout() == "NCHW" else 3] != filters * 2 \
                or stride != 1:
            x = conv_bn_layer(x, filters * 2, 1, stride=stride)
        return L.relu(h + x)

    def se_resnext(image, label):
        x = conv_bn_layer(image, 64, 7, stride=2, act="relu")
        x = L.pool2d(x, 3, "max", 2, 1)
        for s, blocks in enumerate(stages):
            filters = 128 * (2 ** s)
            with name_scope(f"stage{s}"):
                for i in range(blocks):
                    x = block(x, filters, stride=2 if s > 0 and i == 0 else 1)
        x = L.pool2d(x, pool_type="avg", global_pooling=True)
        x = L.dropout(L.flatten(x, axis=1), 0.2)
        logits = L.fc(x, class_num)
        loss = L.mean(L.softmax_with_cross_entropy(logits, label))
        return {"loss": loss, "acc": accuracy(logits, label), "logits": logits}

    return se_resnext
