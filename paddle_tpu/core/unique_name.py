"""Unique name generation for layers/parameters.

Analog of python/paddle/fluid/unique_name.py: layer helpers ask for
"fc", "conv2d", ... and get "fc_0", "fc_1" — stable across a trace as
long as layer-call order is deterministic (the same requirement the
reference's Program construction has).

Unlike the reference's process-global generator, generators here are
usually scoped to a build context (paddle_tpu.framework.BuildContext) so
that ``init`` and ``apply`` traces of the same function produce the same
names. The module-level generator exists for eager/experimental use and
``guard()`` parity.
"""

from __future__ import annotations

import contextlib
from collections import defaultdict
from typing import Dict, Iterator, List, Optional


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids: Dict[str, int] = defaultdict(int)

    def __call__(self, key: str) -> str:
        i = self.ids[key]
        self.ids[key] += 1
        name = f"{key}_{i}"
        return f"{self.prefix}{name}" if self.prefix else name

    def reset(self) -> None:
        self.ids.clear()


_generator_stack: List[UniqueNameGenerator] = [UniqueNameGenerator()]


def generate(key: str) -> str:
    return _generator_stack[-1](key)


@contextlib.contextmanager
def guard(prefix: Optional[str] = None) -> Iterator[None]:
    """Fresh name namespace (unique_name.guard analog)."""
    _generator_stack.append(UniqueNameGenerator(prefix or ""))
    try:
        yield
    finally:
        _generator_stack.pop()
