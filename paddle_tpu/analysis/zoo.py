"""Model-zoo registry for the lint CLI: name → (Program, sample feed).

Mirrors the feed conventions the tests use for each zoo family, so
``python -m paddle_tpu.analysis --model mnist`` lints exactly the
program shape the e2e tests train."""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np

from ..core.errors import enforce
from ..framework import Program, build


def _mnist(variant: str, batch: int, seq: int):
    from ..models import mnist
    fn = {"mlp": mnist.mlp, "conv": mnist.conv_net}[variant or "mlp"]
    rng = np.random.RandomState(0)
    feed = {"image": rng.rand(batch, 784).astype(np.float32),
            "label": rng.randint(0, 10, (batch, 1)).astype(np.int64)}
    return build(fn), feed


def _lm_feed(batch: int, seq: int, vocab: int = 64, seed: int = 0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, vocab, (batch, seq)).astype(np.int32)
    labels = np.concatenate([ids[:, 1:], np.full((batch, 1), 2)],
                            axis=1).astype(np.int32)
    return ids, labels


def _moe_transformer(variant: str, batch: int, seq: int):
    from ..models import moe_transformer as m
    enforce(variant in ("", "tight"),
            f"moe_transformer variants: tight; got {variant!r}")
    # "tight": a deliberately under-capacitied router (capacity_factor
    # 0.5 drops ~half of all routed tokens under uniform routing) — the
    # moe:capacity golden-finding fixture; the default config stays
    # clean (cf 1.25 -> ~0.04% expected drop)
    cf = 0.5 if variant == "tight" else 1.25
    cfg = m.base_config(vocab_size=64, max_len=max(64, seq), d_model=32,
                        d_inner=64, d_expert=32, num_heads=4, num_layers=2,
                        num_experts=4, top_k=2, dropout=0.0, fused_ce=False,
                        capacity_factor=cf)
    ids, labels = _lm_feed(batch, seq)
    return build(m.make_model(cfg)), {"ids": ids, "labels": labels}


def _transformer(variant: str, batch: int, seq: int):
    from ..models import transformer as t
    cfg = t.base_config(src_vocab=64, trg_vocab=64, d_model=32, d_inner=64,
                        num_heads=4, num_encoder_layers=2,
                        num_decoder_layers=2, dropout=0.0)
    rng = np.random.RandomState(0)
    feed = {"src_ids": rng.randint(3, 64, (batch, seq)).astype(np.int32),
            "trg_ids": rng.randint(3, 64, (batch, seq)).astype(np.int32),
            "labels": rng.randint(3, 64, (batch, seq)).astype(np.int32)}
    return build(t.make_model(cfg)), feed


def _gpt(variant: str, batch: int, seq: int):
    from ..models import gpt as g
    cfg = g.base_config(vocab_size=64, max_len=max(64, seq), d_model=32,
                        d_inner=64, num_heads=4, num_layers=2,
                        use_flash=False, fused_ce=False, dropout=0.0)
    ids, labels = _lm_feed(batch, seq)
    return build(g.make_model(cfg)), {"ids": ids, "labels": labels}


ZOO: Dict[str, Callable[[str, int, int], Tuple[Program, dict]]] = {
    "mnist": _mnist,
    "moe_transformer": _moe_transformer,
    "transformer": _transformer,
    "gpt": _gpt,
}


def build_model(name: str, variant: str = "", batch: int = 8,
                seq: int = 16) -> Tuple[Program, dict]:
    enforce(name in ZOO,
            f"unknown zoo model {name!r}; options: {sorted(ZOO)}")
    return ZOO[name](variant, batch, seq)
