"""Program visualization & debugging.

Analog of python/paddle/fluid/debugger.py + graphviz.py (program → dot)
and the graph_viz_pass (ir/graph_viz_pass.cc): renders a Program's
jaxpr (the ProgramDesc analog) as graphviz dot, dumps HLO text, and
summarizes parameters (memory_usage_calc.py analog).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np


def program_to_dot(program, params, state, *args, max_nodes: int = 400, **kwargs) -> str:
    """Render the traced program as graphviz dot (draw_block_graphviz
    analog, debugger.py)."""
    jaxpr = program.desc(params, state, *args, **kwargs).jaxpr
    lines = ["digraph program {", '  rankdir="TB";',
             '  node [shape=box, fontsize=10];']
    var_ids: Dict[Any, str] = {}

    def vid(v):
        key = id(v)  # Literals are unhashable; identity is fine here
        if key not in var_ids:
            var_ids[key] = f"v{len(var_ids)}"
        return var_ids[key]

    for i, eqn in enumerate(jaxpr.eqns[:max_nodes]):
        op = f"op{i}"
        lines.append(f'  {op} [label="{eqn.primitive.name}", style=filled, fillcolor=lightblue];')
        for invar in eqn.invars:
            if hasattr(invar, "aval") and not hasattr(invar, "val"):
                v = vid(invar)
                lines.append(f'  {v} [label="{getattr(invar.aval, "shape", "")}", shape=ellipse];')
                lines.append(f"  {v} -> {op};")
        for outvar in eqn.outvars:
            v = vid(outvar)
            lines.append(f'  {v} [label="{getattr(outvar.aval, "shape", "")}", shape=ellipse];')
            lines.append(f"  {op} -> {v};")
    if len(jaxpr.eqns) > max_nodes:
        lines.append(f'  trunc [label="... {len(jaxpr.eqns) - max_nodes} more ops"];')
    lines.append("}")
    return "\n".join(lines)


def program_hlo(program, params, state, *args, optimized: bool = False, **kwargs) -> str:
    """Dump (optimized) HLO text — the debug_graphviz_path /
    inspection analog at the XLA level."""
    def f(p, s):
        return program.apply(p, s, *args, **kwargs)

    lowered = jax.jit(f).lower(params, state)
    if optimized:
        return lowered.compile().as_text()
    return lowered.as_text()


def summarize_params(params: Dict[str, jax.Array]) -> str:
    """Parameter/memory table (memory_usage_calc.py analog)."""
    rows = []
    total = 0
    for name in sorted(params):
        v = params[name]
        n = int(np.prod(v.shape))
        total += n * v.dtype.itemsize
        rows.append(f"{name:<50} {str(v.shape):<20} {str(v.dtype):<10} {n:>12,}")
    header = f"{'name':<50} {'shape':<20} {'dtype':<10} {'elements':>12}"
    rows.append(f"TOTAL {total / 1e6:.2f} MB")
    return "\n".join([header, "-" * len(header)] + rows)


def _walk_jaxprs(jx, visit):
    """Depth-first over a jaxpr and every nested jaxpr (scan/cond/pjit)."""
    visit(jx)
    for eqn in jx.eqns:
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                _walk_jaxprs(v.jaxpr, visit)
            elif isinstance(v, (list, tuple)):
                for u in v:
                    if hasattr(u, "jaxpr"):
                        _walk_jaxprs(u.jaxpr, visit)


def op_frequence(program, params, state, *args, **kwargs) -> Dict[str, int]:
    """tools/op_frequence.py analog: histogram of primitive ops in the
    traced program (jaxpr = ProgramDesc), including nested bodies."""
    from collections import Counter

    jaxpr = program.desc(params, state, *args, **kwargs)
    counts: Counter = Counter()

    def visit(jx):
        for eqn in jx.eqns:
            counts[eqn.primitive.name] += 1

    _walk_jaxprs(jaxpr.jaxpr, visit)
    return dict(counts.most_common())


def memory_usage(program, params, state, *args, **kwargs) -> Dict[str, float]:
    """contrib/memory_usage_calc.py analog: estimate a program's memory
    footprint in MB — parameters (×3 for grads+momentum-style optimizer
    state, the calc the reference does) plus the sum of traced
    intermediate sizes (including scan/cond bodies) as an activation
    upper bound (XLA buffer reuse brings the true peak far below the
    sum; this mirrors the reference's coarse DESC-walk estimate). The
    estimate is for the example args' shapes — re-trace to size a
    different batch."""
    param_bytes = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                      for v in jax.tree.leaves(params))
    jaxpr = program.desc(params, state, *args, **kwargs)
    act = [0]

    def visit(jx):
        for eqn in jx.eqns:
            for ov in eqn.outvars:
                aval = getattr(ov, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    act[0] += int(np.prod(aval.shape or (1,))) * aval.dtype.itemsize

    _walk_jaxprs(jaxpr.jaxpr, visit)
    return {
        "param_mb": param_bytes / 1e6,
        "param_with_optimizer_mb": 3 * param_bytes / 1e6,
        "activation_sum_mb": act[0] / 1e6,
    }


def compiled_memory_usage(trainer, feed) -> Dict[str, float]:
    """Buffer-assignment memory of the Trainer's compiled train step —
    the runtime-accurate sibling of :func:`memory_usage` (the reference's
    DESC-walk estimate, contrib/memory_usage_calc.py): lowers the jitted
    step for the current scope + feed shapes and reads XLA's
    ``memory_analysis()``. The ``temp_mb`` delta is how remat/donation
    knobs are verified (memory_optimization_transpiler.py:456 analog)."""
    import jax.random as jrandom

    from .core.errors import enforce

    enforce(trainer._step_fn is not None, "call startup() before compiled_memory_usage()")
    feed = trainer._put_feed(feed)
    ls = getattr(trainer.scope, "loss_scale_state", None) or {}
    lowered = trainer._step_fn.lower(trainer.scope.params, trainer.scope.opt_state,
                                     trainer.scope.state, jrandom.PRNGKey(0),
                                     feed, ls)
    ma = lowered.compile().memory_analysis()
    if ma is None:
        return {}
    return {
        "temp_mb": ma.temp_size_in_bytes / 1e6,
        "argument_mb": ma.argument_size_in_bytes / 1e6,
        "output_mb": ma.output_size_in_bytes / 1e6,
        "generated_code_mb": ma.generated_code_size_in_bytes / 1e6,
    }
