"""Pin the profiler capture path BEFORE a TPU link window needs it
(round-4 verdict: the watcher's pass 3 had never been proven to emit a
readable trace, risking trace-bug discovery during precious link
minutes). Reference analog: the device tracer -> timeline.py pipeline
(platform/device_tracer.h:49, tools/timeline.py:115) which ships
tested end-to-end.
"""

import glob
import gzip
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import trace_summary  # noqa: E402


def _fake_trace():
    # minimal perfetto shape jax.profiler writes: metadata (ph=M)
    # process names + complete (ph=X) duration events, dur in us
    return {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "name": "fusion.42", "dur": 3000, "ts": 0},
        {"ph": "X", "pid": 1, "name": "fusion.42", "dur": 1000, "ts": 9},
        {"ph": "X", "pid": 1, "name": "convolution.7", "dur": 2000, "ts": 5},
        {"ph": "X", "pid": 2, "name": "$py_frame_a", "dur": 500, "ts": 0},
        {"ph": "X", "pid": 2, "name": "$py_frame_b", "dur": 700, "ts": 1},
        {"ph": "B", "pid": 1, "name": "not_complete_event", "ts": 2},
    ]}


def test_summarize_ranks_ops_and_buckets_host_frames(capsys):
    trace_summary.summarize(_fake_trace(), top=10)
    out = capsys.readouterr().out
    # busiest lane first, ops ranked by total (fusion 4ms > conv 2ms),
    # $-frames aggregated into one bucket
    tpu_at = out.index("lane: /device:TPU:0")
    cpu_at = out.index("lane: /host:CPU")
    assert tpu_at < cpu_at
    assert out.index("fusion.42") < out.index("convolution.7")
    assert "4.00 ms" in out and "2.00 ms" in out
    assert "[python host frames]" in out
    assert "$py_frame_a" not in out
    assert "not_complete_event" not in out


def test_lane_filter_limits_output(capsys):
    trace_summary.summarize(_fake_trace(), top=10, lane_filter="tpu")
    out = capsys.readouterr().out
    assert "/device:TPU:0" in out and "/host:CPU" not in out


def test_load_trace_missing_dir_exits_with_hint(tmp_path):
    with pytest.raises(SystemExit, match="bench.py --profile"):
        trace_summary.load_trace(str(tmp_path))


def test_load_trace_reads_newest_gz(tmp_path):
    d = tmp_path / "plugins" / "profile" / "x"
    d.mkdir(parents=True)
    for name, tag in [("old.trace.json.gz", "old"),
                      ("new.trace.json.gz", "new")]:
        with gzip.open(d / name, "wt") as f:
            json.dump({"traceEvents": [], "tag": tag}, f)
        os.utime(d / name, (1, 1) if tag == "old" else None)
    assert trace_summary.load_trace(str(tmp_path))["tag"] == "new"


@pytest.mark.slow
def test_bench_profile_emits_readable_trace(tmp_path):
    """End-to-end: bench.py --profile materializes a *.trace.json.gz
    that trace_summary can parse — the exact flow link_watch pass 3
    runs on chip."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--model",
         "mnist_mlp", "--quick", "--profile", str(tmp_path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["value"] > 0
    gzs = glob.glob(str(tmp_path / "**" / "*.trace.json.gz"),
                    recursive=True)
    assert gzs, f"no trace under {tmp_path}"
    trace = trace_summary.load_trace(str(tmp_path))
    assert any(e.get("ph") == "X" and "dur" in e
               for e in trace["traceEvents"])
