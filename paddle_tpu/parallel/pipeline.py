"""Pipeline parallelism over the ``pp`` mesh axis.

Gap-fill component (SURVEY §2.2: PP is absent in the reference).
TPU-native design: for repeated-structure models (transformer blocks),
per-layer parameters are STACKED on a leading [num_layers, ...] axis and
sharded over ``pp``. A schedule runs M microbatches through the ranks
inside one ``shard_map``: each tick, every rank applies its local
layer-chunk to the activation it holds, then ``ppermute``s the result to
the next rank (neighbor ICI hop). Activations enter at rank 0 and exit
at rank P-1, which all-gathers the finished microbatches.

Two schedules, selected by ``interleave`` (= V, virtual stages/rank):

- V=1 (GPipe): rank r owns one contiguous span of L/P layers; the loop
  runs M + P - 1 ticks, of which P-1 are fill/drain bubble.
- V>1 (Megatron interleaved / virtual pipeline): rank r owns V
  NON-adjacent chunks of L/(P·V) layers (global chunk q lives on rank
  q mod P), and chunk q of microbatch j runs at tick
  (j÷P)·VP + (q÷P)·P + (q mod P) + (j mod P). Under this assignment
  every activation produced at tick t is consumed at tick t+1 by the
  next ring rank, so the PER-TICK communication structure is identical
  to GPipe (one ppermute per tick, single holding buffer); the loop
  runs M·V + P - 1 ticks of 1/V the work each, shrinking the bubble
  time by V× (see ``bubble_fraction`` for the exact P ∤ M case) at two
  costs: V× more (pipelined, neighbor-hop) activation traffic, and —
  because the Trainer stores stacked params contiguously pp-sharded —
  a once-per-step re-layout of (V-1)/V of the stacked parameter bytes
  into the chunk-interleaved order (an all-to-all over pp; gradients
  take the inverse path in backward). Storing params chunk-interleaved
  at startup (the Megatron layout) would remove that re-layout and is
  the known follow-up. This is the schedule half of 1F1B: the memory
  half (depth-bounded live activations) is expressed through
  per-microbatch rematerialization (``DistStrategy.remat``) instead,
  because reverse-mode over the scan already frees what remat drops.

Composable with dp/tp: batch stays sharded on dp; stacked layer params
can additionally shard their weight dims on tp.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.errors import enforce
from .mesh import pvary


def stack_layer_params(per_layer_params: list) -> Any:
    """Stack a list of per-layer param pytrees into [L, ...] leaves."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params)


def _schedule_ticks(m: int, p: int, v: int) -> int:
    """Total ticks: the last microbatch's last chunk runs at
    ((m-1)÷p)·vp + (v-1)·p + (p-1) + ((m-1) mod p); +1 for the count.
    Reduces to m + p - 1 when v=1 or p | m: m·v + p - 1."""
    return ((m - 1) // p) * v * p + (v - 1) * p + (p - 1) + ((m - 1) % p) + 1


def _pp_body(x, stacked, extras, layer_fn, axis_name: str, microbatches: int,
             interleave: int, varying_axes: Tuple[str, ...]):
    """Per-rank body. x: local microbatch stack [M, ...mb shape...] on
    rank 0's slot (all ranks receive the same x spec; only rank 0's
    content is used). stacked: this rank's [V, layers_per_chunk, ...]
    params — chunk c here is GLOBAL chunk c·P + rank. extras: pytree of
    [M, ...] per-microbatch side inputs (masks, encoder outputs) — each
    rank indexes the extras for the microbatch it is processing that
    tick rather than forwarding them."""
    p = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    m, v = microbatches, interleave

    def apply_chunk(act, chunk_idx, extra):
        chunk = jax.tree.map(
            lambda leaf: jax.lax.dynamic_index_in_dim(leaf, chunk_idx, 0,
                                                      keepdims=False),
            stacked)

        def one_layer(a, layer_params):
            if extra is None:
                return layer_fn(a, layer_params), None
            return layer_fn(a, layer_params, extra), None
        out, _ = jax.lax.scan(one_layer, act, chunk)
        return out

    mb_shape = x.shape[1:]
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        holding, outputs = carry
        # this rank's position in the interleaved schedule at tick t:
        # u = t - rank counts its chunk-computations; within a group of
        # P microbatches it cycles chunk c for mb (g·P + u mod P).
        groups = -(-m // p)
        u_glob = jnp.clip(t - rank, 0, groups * v * p - 1)
        g = u_glob // (v * p)
        u = u_glob % (v * p)
        c_local = u // p                       # which of this rank's V chunks
        mb_idx = jnp.clip(g * p + u % p, 0, m - 1)
        # rank 0 starting a chunk-0 pass ingests a fresh microbatch;
        # everything else continues from what arrived on the ring
        fresh = x[mb_idx]
        cur = jnp.where((rank == 0) & (c_local == 0), fresh, holding)
        extra = (None if extras is None
                 else jax.tree.map(lambda e: e[mb_idx], extras))
        done = apply_chunk(cur, c_local, extra)
        # last rank finishing its last chunk completes microbatch mb_idx
        record = (rank == p - 1) & (c_local == v - 1) & (t - rank >= 0) \
            & (g * p + u % p < m)
        outputs = jnp.where(
            record,
            jax.lax.dynamic_update_index_in_dim(outputs, done, mb_idx, axis=0),
            outputs)
        nxt = jax.lax.ppermute(done, axis_name, perm)
        return (nxt, outputs), None

    holding0 = pvary(jnp.zeros(mb_shape, x.dtype), varying_axes)
    outputs0 = pvary(jnp.zeros((m,) + mb_shape, x.dtype), varying_axes)
    (_, outputs), _ = jax.lax.scan(tick, (holding0, outputs0),
                                   jnp.arange(_schedule_ticks(m, p, v)))
    # broadcast final outputs from last rank to all (so out spec can be
    # replicated over pp)
    outputs = jnp.where(rank == p - 1, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis_name)


def bubble_fraction(pp: int, microbatches: int, interleave: int = 1) -> float:
    """Exact wasted-tick fraction of the schedule: every rank executes
    its chunk each tick (SPMD programs cannot skip compute), M·V of the
    ``_schedule_ticks`` are useful per rank, the rest are fill/drain.
    (P-1)/(M·V+P-1) when P | M or V=1 — pp=4, m=16: 15.8% (V=1) → 4.5%
    (V=4) — and LARGER when P ∤ M with V>1 (the last group still spans
    a full V·P-tick window; e.g. pp=2, m=3, V=2: 25%, not 14%). Raise
    ``microbatches`` (ideally a multiple of pp) or ``interleave`` to
    amortize; interleave costs V× more neighbor-hop activation traffic."""
    t = _schedule_ticks(microbatches, pp, interleave)
    return (t - microbatches * interleave) / t


def pipeline_apply(
    x,
    stacked_params,
    layer_fn: Callable,
    mesh: Mesh,
    axis_name: str = "pp",
    microbatches: int = 4,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    param_specs=None,
    extras=None,
    interleave: int = 1,
):
    """Run ``layer_fn`` over stacked layers pipelined across ``axis_name``.

    - x: activations [B, ...]; B divisible by ``microbatches``.
    - stacked_params: pytree with leading [L, ...] axis per leaf, L
      divisible by pp·interleave. interleave=1: rank k owns the
      contiguous span [k·L/P, (k+1)·L/P) (GPipe). interleave=V>1: the
      layers split into V·P chunks and rank k owns chunks {c·P+k}
      (Megatron virtual stages) — bubble shrinks V×, neighbor-hop
      activation traffic grows V×.
    - layer_fn(activation, layer_params[, extra]) -> activation.
    - param_specs: optional pytree of PartitionSpecs for each leaf's
      NON-layer dims (tensor parallelism inside a stage): e.g.
      ``{"w1": P("tp"), "w2": P(None, "tp")}`` — composed after the
      leading pp dim; layer_fn must then psum its tp partial sums
      (Megatron pattern), making dp×tp×pp 3D parallelism one call.
    - extras: optional pytree of [B, ...] side inputs constant across
      layers (attention masks, encoder outputs for cross-attention);
      microbatched like ``x`` and delivered to whichever rank is working
      on that microbatch each tick.
    """
    if extras is not None and jax.tree.leaves(extras):
        enforce(all(e.shape[0] == x.shape[0] for e in jax.tree.leaves(extras)),
                "extras leaves must share x's batch dim")
    else:
        extras = None

    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        def _seq(xv, sp, ex):
            def one(a, lp):
                out = layer_fn(a, lp) if ex is None else layer_fn(a, lp, ex)
                return out, None
            out, _ = jax.lax.scan(one, xv, sp)
            return out
        if param_specs is None:
            return _seq(x, stacked_params, extras)
        # degenerate pipeline but tp-parallel stages: layer_fn uses mesh
        # collectives, so it still needs to run under shard_map
        bspec = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)
        bshard = bspec if len(bspec) > 1 else (bspec[0] if bspec else None)
        x_spec = P(bshard, *([None] * (x.ndim - 1)))
        param_spec = jax.tree.map(
            lambda leaf, extra: P(None, *(tuple(extra) + (None,) * (leaf.ndim - 1 - len(extra)))),
            stacked_params, param_specs)
        ex_spec = None if extras is None else jax.tree.map(
            lambda e: P(bshard, *([None] * (e.ndim - 1))), extras)
        return jax.shard_map(_seq, mesh=mesh,
                             in_specs=(x_spec, param_spec, ex_spec),
                             out_specs=x_spec, check_vma=False)(
                                 x, stacked_params, extras)

    p = mesh.shape[axis_name]
    v = max(1, int(interleave))
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    enforce(L % (p * v) == 0,
            f"{L} layers not divisible by pp·interleave={p}·{v}")
    b = x.shape[0]
    enforce(b % microbatches == 0,
            f"batch {b} not divisible by microbatches={microbatches}")
    mb = b // microbatches
    dshard = 1
    for a in batch_axes:
        if a in mesh.axis_names:
            dshard *= mesh.shape[a]
    enforce(mb % dshard == 0,
            f"microbatch size {mb} (batch {b} / microbatches {microbatches}) "
            f"must be divisible by the data-shard product {dshard} of axes "
            f"{tuple(a for a in batch_axes if a in mesh.axis_names)}; lower "
            f"microbatches or raise the batch")
    xm = x.reshape((microbatches, mb) + x.shape[1:])
    exm = None if extras is None else jax.tree.map(
        lambda e: e.reshape((microbatches, mb) + e.shape[1:]), extras)

    # chunk layout: [L] → [V, P, Lc] → [P, V, Lc] → [P·V, Lc] so that
    # sharding the leading dim over pp hands rank r its V chunks
    # {c·P + r} as a contiguous local [V, Lc, ...] block
    Lc = L // (p * v)
    chunked = jax.tree.map(
        lambda leaf: jnp.moveaxis(
            leaf.reshape((v, p, Lc) + leaf.shape[1:]), 0, 1
        ).reshape((p * v, Lc) + leaf.shape[1:]),
        stacked_params)

    bspec = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    bshard = bspec if len(bspec) > 1 else (bspec[0] if bspec else None)
    x_spec = P(None, bshard, *([None] * (x.ndim - 1)))
    ex_spec = None if exm is None else jax.tree.map(
        lambda e: P(None, bshard, *([None] * (e.ndim - 2))), exm)
    if param_specs is None:
        param_spec = jax.tree.map(
            lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), chunked)
    else:
        param_spec = jax.tree.map(
            lambda leaf, extra: P(axis_name, None,
                                  *(tuple(extra) + (None,) * (leaf.ndim - 2 - len(extra)))),
            chunked, param_specs)

    body = functools.partial(
        _pp_body, layer_fn=layer_fn, axis_name=axis_name,
        microbatches=microbatches, interleave=v,
        varying_axes=tuple(mesh.axis_names))
    # with in-stage tensor parallelism the carried activation is
    # tp-invariant only because layer_fn psums — beyond the static
    # varying-axes analysis, so drop the VMA check in that case
    out = jax.shard_map(body, mesh=mesh,
                        in_specs=(x_spec, param_spec, ex_spec),
                        out_specs=x_spec,
                        check_vma=param_specs is None and extras is None)(
                            xm, chunked, exm)
    return out.reshape((b,) + x.shape[1:])
