"""Collective inventory + roofline scaling projection for the BASELINE
configs (round-4 verdict #7; reference anchor: the published 4-GPU
scaling tables, benchmark/README.md:70-95 — 3.85x on AlexNet — which
this parallels with the evidence producible without a pod).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/scaling_model.py [--out SCALING.json] [--only a,b]

For each of the five BASELINE configs this builds the sharded train
step on the 8-virtual-device CPU mesh with its representative
parallelism at SMALL probe shapes, runs ``debugger.collective_report``
over the compiled HLO (op counts + payload bytes + ring-formula wire
bytes — the committed collective inventory, pinned by
tests/test_scaling_model.py), then projects scaling efficiency to a
v5e-256 pod with an alpha-beta roofline evaluated at the FULL bench
shapes:

    grad_bytes = full-size trainable params x 4  (jax.eval_shape over
                 the real model's init — no compile, exact counts)
    T_ici  = 2 * grad_bytes * (8-1)/8 / B_ici      (intra-host ring)
    T_dcn  = 2 * grad_bytes * (H-1)/H / B_dcn      (inter-host ring)
    eff    = T_comp / (T_comp + max(0, T_comm - f_overlap * T_comp))

T_comp uses the measured on-chip compute-only MFU where one exists
(BENCH records) and a conservative default otherwise; f_overlap
reflects XLA's latency-hiding of the grad all-reduce behind the
backward pass. Non-dp axes (tp/pp) stay inside a host's ICI domain by
construction (mesh axes ordered with pp/tp innermost), so the DCN hop
only ever carries the dp all-reduce — the layout rule the projection
assumes and the mesh builders enforce.

Assumed hardware budgets (stated, not measured — this repo has one
chip): v5e ICI ~45e9 B/s effective per-direction ring bandwidth per
chip; DCN ~6.25e9 B/s per host (50 Gbps NIC), 8 chips/host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

# -- hardware model (assumptions; see module doc) ---------------------------
PEAK_BF16 = 197e12          # v5e-class chip, bf16
ICI_BW = 45e9               # B/s per-direction ring bandwidth per chip
DCN_BW = 6.25e9             # B/s per host (50 Gbps)
CHIPS_PER_HOST = 8
# measured compute-only MFU where an on-chip BENCH row exists
# (BENCH_mid_r04: resnet50 0.271, transformer 0.168); conservative
# default for configs never captured on chip
MEASURED_MFU = {"resnet50": 0.271, "transformer": 0.168}
DEFAULT_MFU = 0.30
OVERLAP = 0.5               # fraction of T_comp usable to hide all-reduce


def _param_bytes(prog, feed):
    """Full-size trainable-param bytes via eval_shape (no compile)."""
    params, _ = jax.eval_shape(lambda k: prog.init(k, **feed),
                               jax.random.PRNGKey(0))
    return float(sum(int(np.prod(p.shape)) * p.dtype.itemsize
                     for p in jax.tree.leaves(params)))


def _configs():
    """[(name, probe() -> (trainer, feed), full() -> dict)]. probe
    builds the SMALL sharded step whose compiled HLO supplies the
    collective inventory; full computes the real bench config's
    flops/step/chip and gradient bytes for the roofline."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.models import bert, deepfm, mnist, resnet, transformer
    from paddle_tpu.parallel import DistStrategy, fsdp, replicated, \
        transformer_tp_rules

    def mnist_probe():
        prog = pt.build(mnist.mlp)
        feed = {"image": np.zeros((8, 784), np.float32),
                "label": np.zeros((8, 1), np.int64)}
        tr = pt.Trainer(prog, opt.SGD(0.01), loss_name="loss",
                        mesh=pt.make_mesh({"dp": 8}),
                        sharding_rules=replicated())
        tr.startup(sample_feed=feed)
        return tr, feed

    def mnist_full():
        prog = pt.build(mnist.mlp)
        feed = {"image": np.zeros((128, 784), np.float32),
                "label": np.zeros((128, 1), np.int64)}
        return {"grad_bytes": _param_bytes(prog, feed), "pure_dp": True,
                "flops": flops.mlp_train_flops(128, (784, 200, 200, 10))}

    def resnet_probe():
        prog = pt.build(resnet.make_model(depth=50, class_num=100,
                                          image_size=64,
                                          data_format="NHWC"))
        feed = {"image": np.zeros((8, 64, 64, 3), np.float32),
                "label": np.zeros((8, 1), np.int64)}
        tr = pt.Trainer(prog, opt.Momentum(0.1, 0.9), loss_name="loss",
                        mesh=pt.make_mesh({"dp": 8}),
                        sharding_rules=replicated())
        tr.startup(sample_feed=feed)
        return tr, feed

    def resnet_full():
        prog = pt.build(resnet.make_model(depth=50, class_num=1000,
                                          image_size=224,
                                          data_format="NHWC"))
        feed = {"image": np.zeros((64, 224, 224, 3), np.float32),
                "label": np.zeros((64, 1), np.int64)}
        return {"grad_bytes": _param_bytes(prog, feed), "pure_dp": True,
                "flops": flops.convnet_train_flops(
                    flops.resnet_fwd_flops(50, 224), 64)}

    def transformer_probe():
        cfg = transformer.base_config(
            src_vocab=64, trg_vocab=64, d_model=32, d_inner=64,
            num_heads=4, num_encoder_layers=4, num_decoder_layers=4,
            dropout=0.0, stacked=True)
        prog = pt.build(transformer.make_model(cfg))
        rng = np.random.RandomState(0)
        feed = {"src_ids": rng.randint(3, 64, (8, 12)).astype(np.int32),
                "trg_ids": rng.randint(3, 64, (8, 12)).astype(np.int32),
                "labels": rng.randint(3, 64, (8, 12)).astype(np.int32)}
        tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss",
                        mesh=pt.make_mesh({"dp": 2, "tp": 2, "pp": 2}),
                        sharding_rules=transformer_tp_rules(),
                        strategy=DistStrategy(pp_microbatches=2))
        tr.startup(sample_feed=feed)
        return tr, feed

    def transformer_full():
        cfg = transformer.base_config()
        prog = pt.build(transformer.make_model(cfg))
        rng = np.random.RandomState(0)
        feed = {"src_ids": rng.randint(3, 100, (32, 256)).astype(np.int32),
                "trg_ids": rng.randint(3, 100, (32, 256)).astype(np.int32),
                "labels": rng.randint(3, 100, (32, 256)).astype(np.int32)}
        # pod layout dp64 x tp2 x pp2: each dp replica's grad ring
        # carries only its tp/pp shard of the parameters
        return {"grad_bytes": _param_bytes(prog, feed),
                "model_shards": 4,
                "flops": flops.transformer_train_flops(32, 256, cfg)}

    def bert_probe():
        cfg = bert.base_config(vocab_size=128, d_model=32, d_inner=64,
                               num_heads=4, num_layers=2, max_len=64,
                               dropout=0.0)
        prog = pt.build(bert.make_pretrain_model(cfg))
        rng = np.random.RandomState(0)
        feed = {
            "input_ids": rng.randint(0, 128, (8, 16)).astype(np.int32),
            "token_type_ids": rng.randint(0, 2, (8, 16)).astype(np.int32),
            "mlm_positions": rng.randint(0, 16, (8, 4)).astype(np.int32),
            "mlm_labels": rng.randint(0, 128, (8, 4, 1)).astype(np.int64),
            "nsp_label": rng.randint(0, 2, (8, 1)).astype(np.int64),
        }
        tr = pt.Trainer(prog, opt.AdamW(1e-4), loss_name="loss",
                        mesh=pt.make_mesh({"dp": 4, "fsdp": 2}),
                        sharding_rules=fsdp(min_size_to_shard=64))
        tr.startup(sample_feed=feed)
        return tr, feed

    def bert_full():
        cfg = bert.base_config()
        prog = pt.build(bert.make_pretrain_model(cfg))
        rng = np.random.RandomState(0)
        feed = {
            "input_ids": rng.randint(0, cfg.vocab_size, (32, 128)).astype(np.int32),
            "token_type_ids": rng.randint(0, 2, (32, 128)).astype(np.int32),
            "mlm_positions": rng.randint(0, 128, (32, 20)).astype(np.int32),
            "mlm_labels": rng.randint(0, cfg.vocab_size, (32, 20, 1)).astype(np.int64),
            "nsp_label": rng.randint(0, 2, (32, 1)).astype(np.int64),
        }
        return {"grad_bytes": _param_bytes(prog, feed),
                "flops": flops.bert_train_flops(32, 128, 20, cfg)}

    def deepfm_probe():
        prog = pt.build(deepfm.make_model(num_sparse_fields=26,
                                          sparse_feature_dim=50,
                                          embedding_size=8,
                                          hidden_dims=(32, 32)))
        rng = np.random.RandomState(0)
        feed = {"dense": rng.randn(8, 13).astype(np.float32),
                "sparse_ids": rng.randint(0, 50, (8, 26)).astype(np.int32),
                "label": rng.randint(0, 2, (8, 1)).astype(np.float32)}
        tr = pt.Trainer(prog, opt.Adagrad(0.05), loss_name="loss",
                        mesh=pt.make_mesh({"dp": 8}),
                        sharding_rules=replicated())
        tr.startup(sample_feed=feed)
        return tr, feed

    def deepfm_full():
        prog = pt.build(deepfm.make_model())
        rng = np.random.RandomState(0)
        feed = {"dense": rng.randn(2048, 13).astype(np.float32),
                "sparse_ids": rng.randint(0, 1000, (2048, 26)).astype(np.int32),
                "label": rng.randint(0, 2, (2048, 1)).astype(np.float32)}
        return {"grad_bytes": _param_bytes(prog, feed), "pure_dp": True,
                "flops": flops.deepfm_train_flops(2048, 26, 16, 13,
                                                  (400, 400, 400))}

    return [("mnist_mlp", mnist_probe, mnist_full),
            ("resnet50", resnet_probe, resnet_full),
            ("transformer", transformer_probe, transformer_full),
            ("bert", bert_probe, bert_full),
            ("deepfm", deepfm_probe, deepfm_full)]


def project(name, full, n_chips=256):
    mfu = MEASURED_MFU.get(name, DEFAULT_MFU)
    t_comp = full["flops"] / (PEAK_BF16 * mfu)
    # dp all-reduce rides ICI inside a host and DCN across hosts; the
    # cross-host stage moves (almost) the same bytes through the much
    # thinner pipe, so it dominates: model a two-stage hierarchical
    # reduce (ring over ICI per host, then ring over DCN across hosts).
    # Each dp replica's ring carries only its model shard of the grads
    # (grad_bytes / model_shards) under the pp/tp-innermost layout; an
    # fsdp axis does NOT reduce the per-chip bytes (reduce-scatter of
    # grads + all-gather of params moves the same ~2P per chip), so
    # fsdp configs keep model_shards=1.
    n_hosts = max(1, n_chips // CHIPS_PER_HOST)
    p = full["grad_bytes"] / full.get("model_shards", 1)

    def eff_with(p_bytes, compute_scale=1):
        # compute_scale > 1 models more compute per exchange: a larger
        # per-chip batch, or accum_steps under
        # DistStrategy(accum_exchange="hoisted") — the shard_map-local
        # accumulation that exchanges once per optimizer step
        # (tests/test_hoisted_accum.py). The DEFAULT gspmd accumulation
        # does NOT qualify: its all-reduce rides inside the microbatch
        # loop (pinned by tests/test_collective_report.py::
        # test_accum_grad_exchange_is_per_microbatch), which is why the
        # hoisted projection below is emitted only for the pure-dp
        # configs where the hoisted mode applies
        tc = t_comp * compute_scale
        ti = 2 * p_bytes * (CHIPS_PER_HOST - 1) / CHIPS_PER_HOST / ICI_BW
        td = (2 * p_bytes * (n_hosts - 1) / n_hosts / DCN_BW
              if n_hosts > 1 else 0.0)
        return round(tc / (tc + max(0.0, ti + td - OVERLAP * tc)), 4)

    t_ici = 2 * p * (CHIPS_PER_HOST - 1) / CHIPS_PER_HOST / ICI_BW
    t_dcn = (2 * p * (n_hosts - 1) / n_hosts / DCN_BW) if n_hosts > 1 else 0.0
    return {"grad_bytes_mb": round(full["grad_bytes"] / 1e6, 2),
            "model_shards": full.get("model_shards", 1),
            "dp_ring_bytes_mb": round(p / 1e6, 2),
            "flops_per_step_per_chip": full["flops"],
            "t_comp_ms": round(t_comp * 1e3, 3),
            "t_ici_ms": round(t_ici * 1e3, 3),
            "t_dcn_ms": round(t_dcn * 1e3, 3),
            "assumed_mfu": mfu,
            "efficiency_at_256": eff_with(p),
            # implemented counter-measures, projected: int8 ring
            # all-reduce (parallel/quantized_collectives.py) quarters
            # the wire bytes; doubling the per-chip batch (a bench
            # config knob — LAMB/LARS ship for the large-global-batch
            # regime) doubles compute per exchange; they compose
            "efficiency_at_256_int8": eff_with(p / 4),
            "efficiency_at_256_int8_2x_batch": eff_with(p / 4,
                                                        compute_scale=2),
            # pure-dp replicated stateless configs can additionally run
            # DistStrategy(accum_exchange="hoisted"): the shard_map-
            # local accumulation exchanges once per optimizer step
            # (parity- and HLO-structure-tested, tests/
            # test_hoisted_accum.py), making accum_steps=4 a real 4x
            # compute-per-exchange lever
            "efficiency_at_256_int8_hoisted_accum4": (
                eff_with(p / 4, compute_scale=4)
                if full.get("pure_dp") else None)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(ROOT, "SCALING.json"))
    ap.add_argument("--only", default=None)
    ap.add_argument("--project-only", action="store_true",
                    help="recompute roofline projections (cheap eval_shape) "
                         "into existing rows without re-lowering the probes")
    args = ap.parse_args()

    from paddle_tpu import debugger

    out = {"mesh_devices": 8, "assumptions": {
        "peak_bf16_flops": PEAK_BF16, "ici_bw_Bps": ICI_BW,
        "dcn_bw_Bps": DCN_BW, "chips_per_host": CHIPS_PER_HOST,
        "overlap_fraction": OVERLAP, "default_mfu": DEFAULT_MFU,
        "measured_mfu": MEASURED_MFU}, "configs": {}}
    if os.path.exists(args.out):
        try:
            prev = json.load(open(args.out))
            # merge prior rows ONLY under identical assumptions: stale
            # projections must never ship under a constants block they
            # were not computed with
            if prev.get("assumptions") == out["assumptions"]:
                out["configs"].update(prev.get("configs", {}))
            elif args.project_only:
                ap.error("assumptions changed since the committed record; "
                         "--project-only would strand stale probe rows — "
                         "re-run the full probes (no --project-only)")
            else:
                print("[scaling] assumptions changed — regenerating all "
                      "rows (prior rows dropped)")
        except (OSError, json.JSONDecodeError):
            pass
    names = [n for n, _, _ in _configs()]
    only = ([s.strip() for s in args.only.split(",")] if args.only else None)
    if only:
        unknown = set(only) - set(names)
        if unknown:
            ap.error(f"--only names not in the config list {names}: "
                     f"{sorted(unknown)}")
    for name, probe, full in _configs():
        if only and name not in only:
            continue
        if args.project_only:
            row = out["configs"].get(name)
            if not row or "error" in row:
                print(f"[scaling] {name}: no probe row to project onto")
                continue
            row["projection_v5e_256"] = project(name, full())
            _write(out, args.out)
            print(f"[scaling] {name} eff@256 = "
                  f"{row['projection_v5e_256']['efficiency_at_256']} "
                  f"(int8: "
                  f"{row['projection_v5e_256']['efficiency_at_256_int8']}, "
                  f"int8+2x batch: "
                  f"{row['projection_v5e_256']['efficiency_at_256_int8_2x_batch']})")
            continue
        print(f"[scaling] {name}: building + lowering ...", flush=True)
        try:
            tr, feed = probe()
            rep = debugger.collective_report(tr, feed)
            fs = full()
        except Exception as e:  # record the failure, keep going
            out["configs"][name] = {"error": f"{type(e).__name__}: {e}"}
            _write(out, args.out)
            print(f"          -> ERROR {e}")
            continue
        row = {"mesh": rep["mesh"], "collectives": rep["collectives"],
               "probe_payload_mb": rep["total_payload_mb"],
               "probe_wire_mb_per_device": rep["est_wire_mb_per_device"],
               "projection_v5e_256": project(name, fs)}
        out["configs"][name] = row
        _write(out, args.out)
        print(f"          -> {json.dumps(row['collectives'])[:140]}")
        print(f"          -> eff@256 = "
              f"{row['projection_v5e_256']['efficiency_at_256']}")
    print("wrote", args.out)


def _write(out, path):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, path)


if __name__ == "__main__":
    main()
