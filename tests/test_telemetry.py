"""Unified-telemetry suite (paddle_tpu.telemetry + the instrumentation
wired through executor/fit, data/feeder, serving, async_ps, resilience).

The acceptance contracts, all CPU + deterministic:

  * the process registry walks clean under the
    ``paddle_tpu_<subsystem>_<name>{labels}`` naming convention after a
    short train + serve run (the tier-1 CI contract);
  * ``GET /metrics`` on a live PredictorServer under load returns
    valid Prometheus text whose queue/latency/reject series agree with
    ``ServingMetrics.report()``;
  * one serving request's span id appears in journal events from
    submit through worker dispatch to completion; one training chunk's
    span is shared by its feeder fill and its dispatch;
  * a SIGTERM preemption's flight dump contains the last guard
    incident and checkpoint event; a watchdog kill-drill dumps with
    the hang's span id and ``tools/flight_dump.py`` renders it;
  * journal + registry accounting stays under 2% of a K=16 fused
    dispatch (direct-cost pin, like the PR-6 StepTimer contract).
"""

import gc
import io as _stdio
import json
import os
import signal
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import layers as L
from paddle_tpu import optimizer as opt
from paddle_tpu import resilience, serving, telemetry
from paddle_tpu.telemetry import (FlightRecorder, MetricsRegistry,
                                  RunJournal, counter_deltas)
from paddle_tpu.telemetry.registry import counter_family, gauge_family
from paddle_tpu.testing import faults

DIM, CLASSES, BS, N_BATCHES = 6, 4, 4, 8


def _net(x, label):
    h = L.fc(x, 16, name="fc1")
    logits = L.fc(h, CLASSES, name="fc2")
    return {"loss": L.mean(L.softmax_with_cross_entropy(logits, label))}


_PROG = pt.build(_net)
_FEED = {"x": np.zeros((BS, DIM), np.float32),
         "label": np.zeros((BS, 1), np.int64)}


def _trainer(guard=None):
    tr = pt.Trainer(_PROG, opt.SGD(0.1), loss_name="loss", guard=guard)
    tr.startup(sample_feed=_FEED)
    return tr


def _reader(n_batches=N_BATCHES, seed=7):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_batches):
            x = rng.randn(BS, DIM).astype(np.float32)
            y = rng.randint(0, CLASSES, (BS,)).astype(np.int64)
            yield [(x[j], y[j:j + 1]) for j in range(BS)]
    return reader


def _fit(tr, cfg=None, epochs=1, handler=None, **kw):
    return pt.fit(tr, _reader(), num_epochs=epochs,
                  feed_names=["x", "label"], dtypes=["float32", "int64"],
                  checkpoint_config=cfg, event_handler=handler, **kw)


@pytest.fixture()
def fresh_telemetry(tmp_path):
    """A fresh process journal + a flight root under tmp_path, so span
    assertions see only this test's events and dumps land where the
    test can find them. The (shared) registry is left alone — its
    naming contract must hold cumulatively anyway."""
    old = telemetry.set_journal(RunJournal())
    rec = telemetry.get_recorder()
    old_root = rec.root
    rec.set_root(str(tmp_path / "flight"))
    try:
        yield telemetry.get_journal()
    finally:
        rec.set_root(old_root)
        j = telemetry.set_journal(old)
        if j is not None:
            j.close()


def _flight_dirs(tmp_path):
    root = tmp_path / "flight"
    if not root.is_dir():
        return []
    return sorted(p for p in root.iterdir()
                  if p.name.startswith("flight_") and ".tmp." not in p.name)


def _parse_prometheus(text):
    """Minimal exposition-format parser: {series_with_labels: value},
    plus per-family TYPE/HELP — raises on malformed lines, which IS
    the 'valid Prometheus text' assertion."""
    series, types, helps = {}, {}, {}
    for line in text.strip().splitlines():
        if line.startswith("# HELP "):
            _, _, name, h = line.split(" ", 3)
            helps[name] = h
        elif line.startswith("# TYPE "):
            _, _, name, t = line.split(" ", 3)
            assert t in ("counter", "gauge", "histogram"), line
            types[name] = t
        else:
            assert not line.startswith("#"), line
            key, val = line.rsplit(" ", 1)
            assert key not in series, f"duplicate series {key}"
            series[key] = float(val)
    for name in types:
        assert name in helps and helps[name].strip(), name
    return series, types, helps


# ---------------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------------


def test_metric_naming_convention_enforced():
    r = MetricsRegistry()
    with pytest.raises(ValueError, match="convention"):
        r.counter("requests_total", "h")
    with pytest.raises(ValueError, match="convention"):
        r.counter("paddle_tpu_BadCase_total", "h")
    with pytest.raises(ValueError, match="_total"):
        r.counter("paddle_tpu_serving_requests", "h")
    with pytest.raises(ValueError, match="_total"):
        r.gauge("paddle_tpu_serving_depth_total", "h")
    with pytest.raises(ValueError, match="help"):
        r.counter("paddle_tpu_x_y_total", "  ")
    with pytest.raises(ValueError, match="label"):
        r.counter("paddle_tpu_x_y_total", "h", ("Bad-Label",))
    # re-registration with a different labelset is a hard error
    r.counter("paddle_tpu_x_a_total", "h", ("k",))
    with pytest.raises(ValueError, match="re-registered"):
        r.counter("paddle_tpu_x_a_total", "h", ("other",))


def test_counter_gauge_histogram_render_and_values():
    r = MetricsRegistry()
    c = r.counter("paddle_tpu_t_reqs_total", "requests", ("kind",))
    c.inc(kind="a")
    c.inc(2, kind="b")
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1, kind="a")
    g = r.gauge("paddle_tpu_t_depth", "depth")
    g.set(3)
    h = r.histogram("paddle_tpu_t_lat_seconds", "latency",
                    bounds=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    series, types, _ = _parse_prometheus(r.render_prometheus())
    assert series['paddle_tpu_t_reqs_total{kind="a"}'] == 1
    assert series['paddle_tpu_t_reqs_total{kind="b"}'] == 2
    assert series["paddle_tpu_t_depth"] == 3
    # histogram: cumulative _bucket series + _sum + _count
    assert series['paddle_tpu_t_lat_seconds_bucket{le="0.1"}'] == 1
    assert series['paddle_tpu_t_lat_seconds_bucket{le="1"}'] == 2
    assert series['paddle_tpu_t_lat_seconds_bucket{le="+Inf"}'] == 3
    assert series["paddle_tpu_t_lat_seconds_count"] == 3
    assert abs(series["paddle_tpu_t_lat_seconds_sum"] - 5.55) < 1e-9
    assert types["paddle_tpu_t_lat_seconds"] == "histogram"
    assert r.validate() == []
    # JSON exporter carries the same snapshot
    snap = json.loads(r.render_json())
    assert snap["paddle_tpu_t_depth"]["samples"][0]["value"] == 3


def test_collector_merge_instance_labels_and_weakref_cleanup():
    r = MetricsRegistry()

    class Owner:
        pass

    owners = [Owner(), Owner()]
    for i, o in enumerate(owners):
        # with an owner, the registry hands the LIVE owner back as the
        # callback's argument — no hand-rolled weakref dance needed
        r.add_collector(
            (lambda owner, i=i: [counter_family(
                "paddle_tpu_t_work_total", "work",
                [({"inst": str(i)}, 10 * (i + 1))])]), owner=o)
    del o  # the loop variable must not keep the last owner alive
    series, _, _ = _parse_prometheus(r.render_prometheus())
    assert series['paddle_tpu_t_work_total{inst="0"}'] == 10
    assert series['paddle_tpu_t_work_total{inst="1"}'] == 20
    assert r.validate() == []
    # a collected owner's series drop out of the next scrape
    owners.pop()
    gc.collect()
    series, _, _ = _parse_prometheus(r.render_prometheus())
    assert 'paddle_tpu_t_work_total{inst="1"}' not in series
    assert 'paddle_tpu_t_work_total{inst="0"}' in series


def test_validate_flags_collector_violations():
    r = MetricsRegistry()

    class Keep:
        pass

    keep = Keep()
    r.add_collector(lambda owner: [
        counter_family("bad_name_total", "h", [({}, 1)]),
        counter_family("paddle_tpu_x_nototal", "h", [({}, 1)]),
        gauge_family("paddle_tpu_x_dup", "h", [({}, 1)]),
        gauge_family("paddle_tpu_x_dup", "h", [({}, 2)]),  # dup series
        counter_family("paddle_tpu_x_nohelp_total", "", [({}, 1)]),
    ], owner=keep)
    v = "\n".join(r.validate())
    assert "bad_name_total" in v and "convention" in v
    assert "paddle_tpu_x_nototal" in v
    assert "duplicate series paddle_tpu_x_dup" in v
    assert "missing help" in v


def test_validate_flags_cross_publisher_type_conflict():
    """Two publishers declaring the same family with different
    types/help: the merged TYPE line is wrong for one of them —
    validate() must say so instead of shipping the conflict."""
    r = MetricsRegistry()
    r.add_collector(lambda: [gauge_family("paddle_tpu_x_thing", "a",
                                          [({"inst": "0"}, 1)])])
    r.add_collector(lambda: [counter_family("paddle_tpu_x_thing", "b",
                                            [({"inst": "1"}, 2)])])
    v = "\n".join(r.validate())
    assert "paddle_tpu_x_thing" in v and "declared as" in v


def test_server_close_removes_collector(fresh_telemetry, pred):
    """A closed-but-referenced PredictorServer must stop exporting
    live-looking queue/worker gauges."""
    srv = serving.PredictorServer(pred, workers=1, queue_size=4)
    inst = srv.telemetry_inst
    series, _, _ = _parse_prometheus(
        telemetry.get_registry().render_prometheus())
    assert f'paddle_tpu_serving_queue_depth{{inst="{inst}"}}' in series
    srv.close()
    series, _, _ = _parse_prometheus(
        telemetry.get_registry().render_prometheus())
    assert f'paddle_tpu_serving_queue_depth{{inst="{inst}"}}' not in series


def test_broken_collector_isolated_not_scrape_poison():
    """One broken collector must not take down the process-wide
    scrape: its failure becomes a validate() violation and every
    other family still exports."""
    r = MetricsRegistry()
    r.counter("paddle_tpu_t_ok_total", "fine").inc()

    def boom():
        raise RuntimeError("half-constructed owner")

    r.add_collector(boom)
    series, _, _ = _parse_prometheus(r.render_prometheus())
    assert series["paddle_tpu_t_ok_total"] == 1
    v = "\n".join(r.validate())
    assert "half-constructed owner" in v and "RuntimeError" in v


def test_counter_deltas_shape():
    before = {"a": 1.0}
    after = {"a": 5.0, "b": 2.0, "c": 0.0}
    assert counter_deltas(before, after, per=2) == {"a": 2.0, "b": 1.0}


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------


def test_journal_run_id_monotonic_seq_and_span_filter():
    j = RunJournal(ring_size=100)
    s1, s2 = j.new_span(), j.new_span()
    assert s1 != s2 and len(s1) == 16
    j.emit("a.one", span=s1, x=1)
    j.emit("a.two", span=s2)
    j.emit("b.one", span=s1)
    events = j.recent()
    assert [e["seq"] for e in events] == [1, 2, 3]
    assert all(e["run"] == j.run_id for e in events)
    assert [e["kind"] for e in j.recent(span=s1)] == ["a.one", "b.one"]
    assert [e["kind"] for e in j.recent(kind="a.")] == ["a.one", "a.two"]
    assert [e["kind"] for e in j.recent(n=1)] == ["b.one"]


def test_journal_ring_bounded_and_file_sink(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = RunJournal(ring_size=8)
    j.open(path)
    for i in range(20):
        j.emit("tick", i=i)
    j.close()
    assert len(j.recent()) == 8               # ring holds the tail
    assert j.recent()[0]["i"] == 12
    with open(path) as f:
        lines = [json.loads(line) for line in f]
    assert len(lines) == 20                   # the sink got everything
    assert [e["seq"] for e in lines] == list(range(1, 21))
    # unserializable payloads degrade per-event, never raise
    j2 = RunJournal()
    j2.open(str(tmp_path / "j2.jsonl"))
    j2.emit("weird", obj=object())
    j2.close()
    assert json.loads(open(str(tmp_path / "j2.jsonl")).read())


def test_journal_sink_safe_under_concurrent_emitters(tmp_path):
    """Serving workers, the watchdog, the fill thread, and the
    training loop all emit concurrently: the JSONL sink must hold
    intact lines in strict seq order (the write happens under the
    journal lock), never interleaved bytes."""
    path = str(tmp_path / "concurrent.jsonl")
    j = RunJournal(ring_size=16)
    j.open(path)
    n_threads, per = 4, 200

    def worker(t):
        for i in range(per):
            j.emit("tick", thread=t, i=i, pad="x" * 64)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    j.close()
    lines = [json.loads(line) for line in open(path)]  # every line parses
    seqs = [e["seq"] for e in lines]
    assert seqs == list(range(1, n_threads * per + 1))


def test_journal_sampling_deterministic_and_span_consistent():
    """The high-QPS pressure valve: per-kind sampling is keyed on the
    span's hash, so one request's submit→dispatch→complete events share
    a fate (a sampled-in submit KEEPS its lifecycle), the same traffic
    journals the same events every run (no random), unconfigured kinds
    always keep, and dropped events still consume a seq."""
    j = RunJournal(sample={"serving": 0.5})
    spans = [j.new_span() for _ in range(40)]
    for s in spans:
        j.emit("serving.submit", span=s)
        j.emit("serving.dispatch", span=s)
        j.emit("serving.complete", span=s)
        j.emit("trainer.dispatch", span=s)     # unconfigured: always kept
    events = j.recent()
    per_span = {}
    for e in events:
        per_span.setdefault(e["span"], []).append(e["kind"])
    kept = {s for s, ks in per_span.items()
            if any(k.startswith("serving.") for k in ks)}
    assert 0 < len(kept) < 40                  # some sampled out
    for s in kept:                             # span-consistent: all 3
        assert [k for k in per_span[s] if k.startswith("serving.")] == \
            ["serving.submit", "serving.dispatch", "serving.complete"]
    assert all("trainer.dispatch" in ks for ks in per_span.values())
    assert j.dropped_sampled == 3 * (40 - len(kept))
    # dropped events consume seqs: the last seq counts every emit
    assert j.seq == 4 * 40
    # deterministic: a fresh journal with the same spans keeps the same
    j2 = RunJournal(sample={"serving": 0.5})
    for s in spans:
        j2.emit("serving.submit", span=s)
    assert {e["span"] for e in j2.recent()} == kept
    # rate 0/1 edges + longest-prefix matching + the catch-all
    assert j.sample_rate("serving.submit") == 0.5
    j.set_sample({"serving": 0.0, "serving.hang": 1.0, "*": 0.25})
    assert j.sample_rate("serving.hang") == 1.0     # exact beats prefix
    assert j.sample_rate("serving.submit") == 0.0
    assert j.sample_rate("ps.push") == 0.25         # catch-all
    before = len(j.recent())
    j.emit("serving.submit", span=j.new_span())
    assert len(j.recent()) == before                # rate 0 drops
    j.emit("serving.hang", span=j.new_span())
    assert j.recent()[-1]["kind"] == "serving.hang"  # rate 1 keeps


def test_journal_sampling_env_knob(monkeypatch):
    from paddle_tpu.telemetry.journal import parse_sample

    assert parse_sample("serving=0.01, ps=0.5") == \
        {"serving": 0.01, "ps": 0.5}
    # malformed entries are skipped, rates clamp to [0, 1]
    assert parse_sample("bad, x=zz, y=3.0, z=-1") == {"y": 1.0, "z": 0.0}
    assert parse_sample(None) == {} and parse_sample("") == {}
    # the process journal honors PDTPU_JOURNAL_SAMPLE at creation
    monkeypatch.setenv("PDTPU_JOURNAL_SAMPLE", "serving=0.0")
    old = telemetry.set_journal(None)
    try:
        j = telemetry.get_journal()
        j.emit("serving.submit", span=j.new_span())
        j.emit("other.kind")
        assert [e["kind"] for e in j.recent()] == ["other.kind"]
        assert j.dropped_sampled == 1
    finally:
        telemetry.set_journal(old)


# ---------------------------------------------------------------------------
# flight recorder + dump tool
# ---------------------------------------------------------------------------


def test_flight_dump_committed_validated_and_rotated(tmp_path):
    j = RunJournal(ring_size=64)
    span = j.new_span()
    j.emit("x.boom", span=span, detail="d")
    rec = FlightRecorder(journal=j, root=str(tmp_path), max_dumps=2)
    p1 = rec.dump("unit", detail={"k": 1}, span=span)
    assert os.path.isdir(p1) and ".tmp." not in p1
    resilience.validate_checkpoint(p1)        # CRC-manifested like a ckpt
    meta = json.load(open(os.path.join(p1, "flight.json")))
    assert meta["trigger"] == "unit" and meta["span"] == span
    assert meta["run"] == j.run_id and meta["num_events"] == 1
    assert "metrics" in meta                  # registry snapshot rides along
    events = [json.loads(line)
              for line in open(os.path.join(p1, "events.jsonl"))]
    assert events[0]["kind"] == "x.boom" and events[0]["span"] == span
    # rotation: oldest dump beyond max_dumps is removed
    for i in range(3):
        j.emit("more", i=i)
        rec.dump(f"t{i}")
    dumps = [d for d in os.listdir(tmp_path) if d.startswith("flight_")]
    assert len(dumps) == 2
    assert not any(p1.endswith(d) for d in dumps)


def test_flight_dump_tool_renders_filters_and_validates(tmp_path):
    import importlib
    flight_dump_tool = importlib.import_module("tools.flight_dump")

    j = RunJournal()
    span = j.new_span()
    j.emit("serving.submit", span=span, n=4)
    j.emit("serving.hang", span=span, worker=0)
    j.emit("other.noise", span=j.new_span())
    rec = FlightRecorder(journal=j, root=str(tmp_path))
    p = rec.dump("worker_hung", span=span, detail={"worker": 0})

    meta, events = flight_dump_tool.load_dump(p)
    assert meta["trigger"] == "worker_hung"
    assert len(events) == 3
    only = flight_dump_tool.filter_events(events, span=span)
    assert [e["kind"] for e in only] == ["serving.submit", "serving.hang"]
    out = _stdio.StringIO()
    flight_dump_tool.render(meta, only, out=out)
    text = out.getvalue()
    assert "worker_hung" in text and span in text and "serving.hang" in text
    # CLI contract: 0 on success (with or without the CRC pass), 2 on
    # a corrupt dump — the manifest catches the silent bit flip
    assert flight_dump_tool.main([str(p), "--span", span]) == 0
    assert flight_dump_tool.main([str(p), "--no-validate"]) == 0
    faults.flip_byte(str(p), name="events.jsonl")
    assert flight_dump_tool.main([str(p)]) == 2


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------


def test_http_metrics_healthz_and_404():
    r = MetricsRegistry()
    r.counter("paddle_tpu_t_hits_total", "hits").inc(3)
    live = {"live": True, "state": "ready"}
    with telemetry.serve_metrics(registry=r, health_fn=lambda: dict(live)) \
            as srv:
        body = urllib.request.urlopen(srv.url + "/metrics")
        assert body.headers["Content-Type"].startswith("text/plain")
        series, _, _ = _parse_prometheus(body.read().decode())
        assert series["paddle_tpu_t_hits_total"] == 3
        health = json.loads(
            urllib.request.urlopen(srv.url + "/healthz").read())
        assert health == live
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/nope")
        assert ei.value.code == 404
        # not-live flips /healthz to 503 (the LB probe contract)
        live["live"] = False
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/healthz")
        assert ei.value.code == 503


# ---------------------------------------------------------------------------
# trainer integration
# ---------------------------------------------------------------------------


def test_trainer_dispatch_journal_and_registry_series(fresh_telemetry):
    j = fresh_telemetry
    tr = _trainer()
    tr.step(_FEED)
    tr.step(_FEED)
    disp = j.recent(kind="trainer.dispatch")
    assert len(disp) == 2
    assert disp[0]["span"] and disp[0]["num_steps"] == 1
    assert disp[0]["base_step"] == 0 and disp[1]["base_step"] == 1
    assert disp[0]["dur_s"] > 0
    reg = telemetry.get_registry()
    series, _, _ = _parse_prometheus(reg.render_prometheus())
    inst = tr.telemetry_inst
    assert series[f'paddle_tpu_trainer_steps_total{{inst="{inst}"}}'] == 2
    assert series[
        f'paddle_tpu_trainer_dispatches_total{{inst="{inst}",kind="step"}}'
    ] == 2
    assert series[f'paddle_tpu_trainer_global_step{{inst="{inst}"}}'] == 2
    assert reg.validate() == []


def test_fit_fill_span_shared_with_dispatch(fresh_telemetry):
    j = fresh_telemetry
    tr = _trainer()
    _fit(tr, steps_per_dispatch=4)
    fills = j.recent(kind="feeder.fill")
    disp = j.recent(kind="trainer.dispatch")
    assert fills and disp
    fill_spans = [e["span"] for e in fills]
    disp_spans = [e["span"] for e in disp]
    # every dispatch rides the span its fill minted, 1:1 in order
    assert fill_spans == disp_spans
    assert {e["num_steps"] for e in disp} == {4}


def test_fit_profile_interval_events(fresh_telemetry):
    events = []
    tr = _trainer()
    _fit(tr, handler=events.append, profile_interval_steps=3)
    profs = [e for e in events if e.kind == "profile"]
    # 8 steps, boundary-crossings of 3 at steps 3 and 6
    assert [e.step for e in profs] == [3, 6]
    end_epoch = [e for e in events if e.kind == "end_epoch"][0]
    # same report path as end_epoch: same schema, pipeline aliased in
    assert set(profs[0].profile.keys()) == set(end_epoch.profile.keys())
    assert profs[0].pipeline is profs[0].profile["pipeline"]
    assert profs[0].profile["steps"] == 3
    with pytest.raises(Exception, match="profile_interval_steps"):
        _fit(_trainer(), profile_interval_steps=-1)


def test_sigterm_flight_dump_has_guard_incident_and_ckpt(fresh_telemetry,
                                                         tmp_path):
    """The training black-box contract: a SIGTERM preemption dump
    contains the last guard incident and the boundary checkpoint
    event."""
    ckdir = tmp_path / "ck"
    cfg = pt.CheckpointConfig(str(ckdir), epoch_interval=0,
                              step_interval=0, max_num_checkpoints=3)
    reader = faults.nan_batch_reader(_reader(), at_batch=2)

    def handler(e):
        if e.kind == "end_step" and e.step == 5:
            os.kill(os.getpid(), signal.SIGTERM)

    tr = _trainer(guard=pt.GuardPolicy(max_incidents=8, window=100))
    pt.fit(tr, reader, num_epochs=2, feed_names=["x", "label"],
           dtypes=["float32", "int64"], checkpoint_config=cfg,
           event_handler=handler)
    assert tr.guard_incident_total == 1
    # with a checkpoint_config, dumps land next to the checkpoints
    root = ckdir / "flight"
    dumps = [p for p in root.iterdir() if p.name.startswith("flight_")]
    assert len(dumps) == 1
    meta = json.load(open(dumps[0] / "flight.json"))
    assert meta["trigger"] == "preempted"
    assert meta["detail"]["signum"] == signal.SIGTERM
    kinds = [json.loads(line)["kind"]
             for line in open(dumps[0] / "events.jsonl")]
    assert "guard.incident" in kinds and "ckpt.save" in kinds
    inc = [json.loads(line) for line in open(dumps[0] / "events.jsonl")
           if json.loads(line)["kind"] == "guard.incident"]
    assert inc[-1]["step"] == 2 and inc[-1]["outputs"]
    # the registry counted it too
    series, _, _ = _parse_prometheus(
        telemetry.get_registry().render_prometheus())
    assert series[
        f'paddle_tpu_trainer_guard_incidents_total{{inst="{tr.telemetry_inst}"}}'
    ] == 1


def test_fit_unhandled_exception_flight_dump(fresh_telemetry, tmp_path):
    def bad_reader():
        def r():
            yield from _reader(2)()
            raise RuntimeError("disk on fire")
        return r

    tr = _trainer()
    with pytest.raises(RuntimeError, match="disk on fire"):
        pt.fit(tr, bad_reader(), num_epochs=1, feed_names=["x", "label"],
               dtypes=["float32", "int64"])
    dumps = _flight_dirs(tmp_path)
    assert len(dumps) == 1
    meta = json.load(open(dumps[0] / "flight.json"))
    assert meta["trigger"] == "fit_exception"
    assert "disk on fire" in meta["detail"]["error"]


def test_guard_escalation_flight_dump(fresh_telemetry, tmp_path):
    reader = faults.nan_batch_reader(_reader(), at_batch=1)
    tr = _trainer(guard=pt.GuardPolicy(max_incidents=0, window=10,
                                       defer_readback=False))
    with pytest.raises(FloatingPointError):
        pt.fit(tr, reader, num_epochs=1, feed_names=["x", "label"],
               dtypes=["float32", "int64"])
    dumps = _flight_dirs(tmp_path)
    # exactly ONE dump: the escalation site's (fit's wrapper skips
    # FloatingPointError so the same crash is not dumped twice)
    assert len(dumps) == 1
    meta = json.load(open(dumps[0] / "flight.json"))
    assert meta["trigger"] == "guard_escalation"
    kinds = [json.loads(line)["kind"]
             for line in open(dumps[0] / "events.jsonl")]
    assert "guard.incident" in kinds


def test_trainer_serve_metrics_endpoint(fresh_telemetry):
    tr = _trainer()
    tr.step(_FEED)
    srv = tr.serve_metrics()
    try:
        # idempotent: a repeat call returns the SAME running server,
        # never a second port/daemon thread
        assert tr.serve_metrics() is srv
        health = json.loads(
            urllib.request.urlopen(srv.url + "/healthz").read())
        assert health["role"] == "trainer" and health["global_step"] == 1
        series, _, _ = _parse_prometheus(
            urllib.request.urlopen(srv.url + "/metrics").read().decode())
        assert series[
            f'paddle_tpu_trainer_steps_total{{inst="{tr.telemetry_inst}"}}'
        ] == 1
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------


def _serving_feed(n, seed=0):
    rng = np.random.RandomState(seed)
    return {"image": rng.randn(n, 784).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


@pytest.fixture(scope="module")
def pred(tmp_path_factory):
    from paddle_tpu.models import mnist

    d = str(tmp_path_factory.mktemp("telemetry_serving") / "model")
    prog = pt.build(mnist.mlp)
    feed8 = _serving_feed(8)
    params, state = prog.init(jax.random.PRNGKey(0), **feed8)
    pio.save_inference_model(d, prog, params, state, feed8,
                             batch_buckets=[4, 8])
    return pio.load_inference_model(d)


def test_request_span_correlates_submit_queue_dispatch_complete(
        fresh_telemetry, pred):
    j = fresh_telemetry
    with serving.PredictorServer(pred, workers=1, queue_size=4) as srv:
        h = srv.submit(_serving_feed(8))
        h.result(timeout=60)
        assert h.span
        ev = j.recent(span=h.span)
        kinds = [e["kind"] for e in ev]
        assert kinds == ["serving.submit", "serving.dispatch",
                         "serving.complete"]
        submit, dispatch, complete = ev
        assert "queue_depth" in submit            # queued state at submit
        assert dispatch["worker"] == 0 and dispatch["queued_s"] >= 0
        assert dispatch["bucket"] == 8 and submit["n"] == 8
        assert complete["latency_s"] > 0
        # a reject carries the same span discipline
        bad = _serving_feed(8)
        bad["image"][0, 0] = np.nan
        with pytest.raises(serving.InvalidRequest):
            srv.submit(bad)
        rej = j.recent(kind="serving.reject")
        assert rej[-1]["reason"] == "invalid" and rej[-1]["span"]


def test_metrics_endpoint_on_live_server_under_load(fresh_telemetry, pred):
    """The acceptance criterion: GET /metrics on a LIVE PredictorServer
    under load parses as Prometheus text whose queue/latency/reject
    series agree with ServingMetrics.report()."""
    with serving.PredictorServer(pred, workers=2, queue_size=8) as srv:
        ep = srv.serve_metrics()
        pending = [srv.submit(_serving_feed(8, seed=i)) for i in range(6)]
        # scrape WHILE requests are in flight: must parse regardless
        _parse_prometheus(
            urllib.request.urlopen(ep.url + "/metrics").read().decode())
        for p in pending:
            p.result(timeout=60)
        with pytest.raises(serving.InvalidRequest):
            srv.submit({"image": np.zeros((8, 3), np.float32),
                        "label": np.zeros((8, 1), np.int64)})
        series, types, _ = _parse_prometheus(
            urllib.request.urlopen(ep.url + "/metrics").read().decode())
        rep = srv.report()
        inst = srv.telemetry_inst
        assert series[
            f'paddle_tpu_serving_submitted_total{{inst="{inst}"}}'
        ] == rep["submitted"] == 6
        assert series[
            f'paddle_tpu_serving_completed_total{{inst="{inst}"}}'
        ] == rep["completed"] == 6
        assert series[
            f'paddle_tpu_serving_rejected_total{{inst="{inst}",reason="invalid"}}'
        ] == rep["rejected_invalid"] == 1
        assert series[
            f'paddle_tpu_serving_queue_depth{{inst="{inst}"}}'
        ] == rep["health"]["queue_depth"]
        assert series[
            f'paddle_tpu_serving_queue_capacity{{inst="{inst}"}}'
        ] == rep["health"]["queue_capacity"] == 8
        # the latency histogram's _count equals the report's count and
        # the +Inf bucket (series agree, not re-derived)
        hist = rep["latency_hist"]
        assert series[
            f'paddle_tpu_serving_latency_seconds_count{{inst="{inst}"}}'
        ] == hist["count"] == 6
        assert series[
            f'paddle_tpu_serving_latency_seconds_bucket{{inst="{inst}",le="+Inf"}}'
        ] == 6
        assert types["paddle_tpu_serving_latency_seconds"] == "histogram"
        # healthz agrees with health()
        health = json.loads(
            urllib.request.urlopen(ep.url + "/healthz").read())
        assert health["ready"] is True and health["state"] == "ready"
        assert telemetry.get_registry().validate() == []


def test_latency_hist_buckets_consistent_with_percentiles(fresh_telemetry,
                                                          pred):
    with serving.PredictorServer(pred, workers=1, queue_size=4) as srv:
        for i in range(4):
            srv.run(_serving_feed(8, seed=i), timeout=60)
        rep = srv.report()
        h = rep["latency_hist"]
        assert len(h["counts"]) == len(h["bounds_s"]) + 1
        assert sum(h["counts"]) == h["count"] == 4
        assert h["bounds_s"] == sorted(h["bounds_s"])
        assert h["sum_s"] > 0
        # the p50 the report derives lives inside the populated range
        p50_s = rep["latency_ms"]["p50"] / 1e3
        lo = min(b for b, c in zip(h["bounds_s"], h["counts"]) if c) \
            if any(h["counts"][:-1]) else 0.0
        assert p50_s >= lo * 0.99


def test_watchdog_kill_drill_dumps_with_hang_span(fresh_telemetry,
                                                  tmp_path, pred):
    """The kill-drill acceptance: hanging predictor → watchdog →
    flight dump on disk that tools/flight_dump.py renders with the
    hang's span id."""
    import importlib
    flight_dump_tool = importlib.import_module("tools.flight_dump")

    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = serving.PredictorServer(
        hang, workers=1, queue_size=4, warmup=False, watchdog_timeout=0.2,
        breaker=serving.BreakerPolicy(failure_threshold=5, cooldown=0.2))
    try:
        hung = srv.submit(_serving_feed(8))
        with pytest.raises(serving.WorkerHung):
            hung.result(timeout=60)
        deadline = time.monotonic() + 5
        while not _flight_dirs(tmp_path) and time.monotonic() < deadline:
            time.sleep(0.02)
        dumps = _flight_dirs(tmp_path)
        assert dumps, "watchdog produced no flight dump"
        meta = json.load(open(dumps[0] / "flight.json"))
        assert meta["trigger"] == "worker_hung"
        assert meta["span"] == hung.span
        out = _stdio.StringIO()
        m, events = flight_dump_tool.load_dump(str(dumps[0]))
        flight_dump_tool.render(
            m, flight_dump_tool.filter_events(events, span=hung.span),
            out=out)
        text = out.getvalue()
        assert hung.span in text and "serving.hang" in text
        # hang + submit of the same request share the span
        kinds = [e["kind"] for e in events if e.get("span") == hung.span]
        assert "serving.submit" in kinds and "serving.hang" in kinds
        m2 = srv.metrics.snapshot()
        assert m2["hangs"] == 1
    finally:
        release.set()
        srv.close(drain=False, timeout=5)


def test_breaker_threshold_trip_journals_and_dumps(fresh_telemetry,
                                                   tmp_path, pred):
    j = fresh_telemetry
    failing = faults.failing_predictor(pred, fail_calls=10)
    srv = serving.PredictorServer(
        failing, workers=1, queue_size=8, warmup=False,
        breaker=serving.BreakerPolicy(failure_threshold=2, cooldown=30.0))
    try:
        for i in range(2):
            with pytest.raises(Exception):
                srv.run(_serving_feed(8), timeout=60)
        assert srv.breaker.state == "open"
        trips = j.recent(kind="serving.breaker_open")
        assert trips and trips[-1]["reason"] == "failures"
        # the trip's dump is written on the WORKER thread (the request
        # completes before breaker.record runs) — wait for the commit
        deadline = time.monotonic() + 5

        def trip_dumps():
            return [d for d in _flight_dirs(tmp_path)
                    if json.load(open(d / "flight.json"))["trigger"]
                    == "breaker_trip"]

        while not trip_dumps() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert trip_dumps()
    finally:
        srv.close(drain=False, timeout=5)


# ---------------------------------------------------------------------------
# async-PS telemetry
# ---------------------------------------------------------------------------


def test_ps_client_report_and_wire_trace_echo(fresh_telemetry):
    from paddle_tpu.parallel.async_ps import PServerProcess, PSClient

    with PServerProcess(lr=0.1) as srv:
        c = PSClient(srv.addr)
        c.init_param("w", np.ones(4, np.float32))
        span = telemetry.get_journal().new_span()
        c.push("w", np.ones(4, np.float32), span=span)
        # the optional trace field rode the framed header and the NEW
        # server echoed it; positional reply fields are unchanged, so
        # an old client (int(resp.split()[1])) never notices
        assert c.last_reply.startswith("OK 1")
        assert f"trace={span}" in c.last_reply
        c.pull("w", (4,), span=span)
        assert f"trace={span}" in c.last_reply
        # without a span the header is byte-identical to the old wire
        c.push("w", np.ones(4, np.float32))
        assert "trace=" not in c.last_reply
        rep = c.report()
        assert rep["pushes"] == 2 and rep["pulls"] == 1
        assert rep["reconnects"] == 0 and rep["pushes_undelivered"] == 0
        with pytest.raises(Exception, match="whitespace"):
            c.push("w", np.ones(4, np.float32), span="bad span")


def test_ps_shard_group_totals_monotonic_across_retirement():
    """resize()/rebind() close transports to departed servers; their
    traffic folds into the retired aggregate so the exported
    paddle_tpu_ps_* counters never go backwards (a Prometheus counter
    reset would fake a huge rate)."""
    from paddle_tpu.parallel.async_ps import PSShardGroup

    g = PSShardGroup.__new__(PSShardGroup)  # no network: counters only
    g._clients, g._retired_counts, g.addrs = {}, {}, []

    class FakeClient:
        def __init__(self, n):
            self.rep = {"addr": f"h:{n}", "requests": 5 * n,
                        "pushes": 3 * n, "pulls": n, "reconnects": 2,
                        "retries": 4, "pushes_undelivered": 1}

        def report(self):
            return dict(self.rep)

        def close(self):
            self.closed = True

    g._clients[("h", 1)] = FakeClient(1)
    g._clients[("h", 2)] = FakeClient(2)
    before = g.report()
    departed = g._clients.pop(("h", 2))
    g._retire_client(departed)
    after = g.report()
    assert departed.closed
    assert "h:2" not in after["servers"] and "h:2" in before["servers"]
    for k in ("requests", "pushes", "pulls", "reconnects", "retries",
              "pushes_undelivered"):
        assert after[k] == before[k], k  # totals unchanged, not reversed


def test_async_ps_trainer_report_and_registry(fresh_telemetry):
    from paddle_tpu.parallel.async_ps import AsyncPSTrainer, PServerProcess

    j = fresh_telemetry
    with PServerProcess(lr=0.1) as srv:
        t = AsyncPSTrainer(_PROG, srv.addr, trainer_id=0)
        t.startup(sample_feed=_FEED)
        t.step(_FEED)
        rep = t.report()
        assert rep["global_step"] == 1 and rep["pushes_lost"] == 0
        assert rep["client"]["pushes"] == 4      # fc1/fc2 w+b
        assert rep["client"]["pulls"] >= 4
        steps = j.recent(kind="ps.step")
        assert len(steps) == 1 and steps[0]["span"]
        series, _, _ = _parse_prometheus(
            telemetry.get_registry().render_prometheus())
        inst = t.telemetry_inst
        assert series[
            f'paddle_tpu_ps_pushes_total{{inst="{inst}"}}'] == 4
        assert series[
            f'paddle_tpu_ps_pushes_lost_total{{inst="{inst}"}}'] == 0


# ---------------------------------------------------------------------------
# the tier-1 CI contracts: naming convention + overhead
# ---------------------------------------------------------------------------


def test_registry_naming_contract_after_train_and_serve(fresh_telemetry,
                                                        pred):
    """The CI naming-convention gate: after a short train + serve run,
    every family the process registry exports obeys
    paddle_tpu_<subsystem>_<name>{labels} with help text — and the
    full exposition parses. This walks EVERYTHING registered by the
    whole test process (trainers, servers, PS clients), so any
    instrumentation added later that violates the convention fails
    here."""
    tr = _trainer()
    _fit(tr, steps_per_dispatch=2)
    with serving.PredictorServer(pred, workers=1, queue_size=4) as srv:
        srv.run(_serving_feed(8), timeout=60)
        reg = telemetry.get_registry()
        assert reg.validate() == []
        series, types, helps = _parse_prometheus(reg.render_prometheus())
        from paddle_tpu.telemetry.registry import METRIC_NAME_RE
        for fam in reg.collect():
            assert METRIC_NAME_RE.match(fam.name), fam.name
            assert fam.help.strip(), fam.name
        # both halves of the fleet story are present in one scrape
        assert any(k.startswith("paddle_tpu_trainer_") for k in series)
        assert any(k.startswith("paddle_tpu_serving_") for k in series)
        assert any(k.startswith("paddle_tpu_feeder_") for k in series)


def test_feeder_cache_and_overlap_counter_families(fresh_telemetry):
    """The device-resident data path's counters (PR 15) export through
    the same scrape-time collector as every feeder stage: cache hit
    bytes/chunks and ring-hidden transfer seconds, naming-contract
    clean, and numerically equal to the PipelineMetrics accumulators
    they render (the can-never-disagree property)."""
    from paddle_tpu.data.feeder import PipelineMetrics
    from paddle_tpu.telemetry.registry import METRIC_NAME_RE

    m = PipelineMetrics()
    m.record_h2d(1_000, 0.25, exposed_s=0.1)   # 0.15 s hidden
    m.record_cache_hit(4_096)
    m.record_cache_hit(4_096)
    fams = {f.name: f for f in m.telemetry_families(inst="7")}
    for name, want in [
            ("paddle_tpu_feeder_overlap_hidden_seconds_total", 0.15),
            ("paddle_tpu_feeder_cache_hit_bytes_total", 8_192),
            ("paddle_tpu_feeder_cache_hits_total", 2)]:
        assert name in fams, sorted(fams)
        assert METRIC_NAME_RE.match(name), name
        fam = fams[name]
        assert fam.help.strip()
        (labels, value), = fam.samples
        assert labels == {"inst": "7"}
        assert value == pytest.approx(want)


def test_telemetry_overhead_under_2pct_at_k16(fresh_telemetry):
    """The hot-path budget (same direct-cost method as the PR-6
    StepTimer pin): the per-dispatch cost of the telemetry-bearing
    record_dispatch — ring append + journal emit with a span — stays
    under 2% of a measured K=16 fused dispatch. No device interaction
    happens anywhere in that path (zero added host syncs)."""
    from paddle_tpu.data.feeder import stack_batches
    from paddle_tpu.profiling.steptime import StepTimer

    k, n = 16, 6
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.randn(BS, DIM).astype(np.float32),
              "label": rng.randint(0, CLASSES, (BS, 1)).astype(np.int64)}
             for _ in range(4)]
    tr = _trainer()
    stacked = tr._put_feed(
        stack_batches([feeds[i % len(feeds)] for i in range(k)]),
        stacked=True)
    out = tr.run_steps(stacked, k=k)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = tr.run_steps(stacked, k=k)
    jax.block_until_ready(out)
    dispatch_s = (time.perf_counter() - t0) / n

    j = RunJournal()            # ring-only: the default hot-path config
    st = StepTimer(journal=j, inst="0")
    reps = 5_000
    t0 = time.perf_counter()
    for i in range(reps):
        st.record_dispatch(time.perf_counter(), time.perf_counter(), k,
                           "run_steps", span=None, base_step=i * k)
    per_record = (time.perf_counter() - t0) / reps
    assert per_record < 0.02 * dispatch_s, (per_record, dispatch_s)
