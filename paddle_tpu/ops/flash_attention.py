"""Flash attention — pallas TPU kernels (forward AND backward).

New first-class component per SURVEY §5/§7: the reference has no
attention kernels at all (attention was composed from mul/softmax ops in
models, e.g. benchmark/fluid/models/machine_translation.py), and no
answer to long sequences beyond LoD ragged batching. This supplies
O(seq) -memory attention on TPU:

- K/V are streamed through VMEM on the innermost grid dimension
  (Pallas double-buffers the HBM→VMEM DMA automatically), so sequence
  length is bounded by HBM, not by the ~16MB VMEM — the v1 kernel's
  whole-K/V-in-VMEM BlockSpec was the line VERDICT r1 told us to kill.
- Online softmax state (m, l, acc) lives in VMEM scratch that persists
  across the innermost grid steps; output is finalized on the last step.
- Backward is two pallas kernels of the same shape: a dq pass
  (q-block-major, streaming K/V) and a dkv pass (k-block-major,
  streaming Q/dO), both recomputing probabilities blockwise from the
  saved logsumexp — the standard flash-attention-2 decomposition.
- Masking: causal, an additive per-key bias [b, s_k] (padding), and
  segment ids (the LoD ragged-batch equivalent, layers/sequence.py
  design) — all fused into the kernels.

Ring/context-parallel attention (parallel/ring_attention.py) reuses
these kernels per shard and merges (out, lse) pairs in log-space.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# chip-tuned at seq 32k, h=8, d=64 bf16: (1024, 1024) gives 33 TFLOP/s fwd /
# 42 TFLOP/s bwd vs 19/29 at (512, 512); 2048 blocks exceed the 16MB VMEM
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def resolve_block_shapes(block_q, block_k):
    """Resolve block sizes: explicit args win; None falls to the
    ``flash_block_q``/``flash_block_k`` config flags (env
    ``PDTPU_FLASH_BLOCK_Q``/``_K`` — a microbench sweep winner applies
    without a code edit), flag 0 to the chip-tuned module defaults.
    Validated here so a typo'd env value fails naming the flag instead
    of as a Mosaic tiling error deep in kernel lowering. NOTE: like all
    shape-affecting knobs this is read at TRACE time — set the flag
    (or env) before the first jit compilation of the calling step;
    already-cached executables keep their block shapes."""
    from ..core.config import get_flag
    from ..core.errors import enforce

    if block_q is None:
        block_q = get_flag("flash_block_q") or DEFAULT_BLOCK_Q
    if block_k is None:
        block_k = get_flag("flash_block_k") or DEFAULT_BLOCK_K
    for name, val in (("flash_block_q", block_q), ("flash_block_k", block_k)):
        enforce(isinstance(val, int) and val > 0 and val % 8 == 0,
                f"{name}: block size must be a positive multiple of 8 "
                f"(TPU sublane tiling), got {val!r}")
    return block_q, block_k
NEG_INF = -1e30
LANES = 128  # lane width for 1-d-per-row scratch (m/l/lse/delta)


def _causal_mask(s, qi, kj, block_q, block_k, offset):
    """Bottom-right-aligned causal mask (decode convention: with sq < sk
    the last query sees every key), matching the XLA fallback's
    ``tril(k=sk-sq)``. ``offset`` = sk_orig - sq_orig, static."""
    q_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_idx = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_idx + offset >= k_idx, s, NEG_INF)


def _segment_mask(s, seg_q, seg_k):
    # seg_q: [block_q], seg_k: [block_k]
    return jnp.where(seg_q[:, None] == seg_k[None, :], s, NEG_INF)


def _block_scores(q_ref, k_ref, bias_ref, segq_ref, segk_ref, qi, kj, *,
                  scale, causal, block_q, block_k, causal_offset):
    """Shared score assembly for the fwd/dq/dkv kernels: q·kᵀ (scaled),
    additive key bias, segment mask, causal mask — one definition so the
    three kernels can never desynchronize.

    The dot operands stay in the INPUT dtype (bf16 in → one MXU-native
    bf16×bf16 pass with f32 accumulation; the previous f32 upcast ran
    every kernel matmul at the ~1/8-rate f32 MXU path and capped the
    whole kernel at ~17% MFU). Softmax state and masks are f32. The
    scale is applied to the f32 scores, not the bf16 operand. Returns
    (q, k) UNSCALED in their native dtype, the scaled f32 scores, and
    ``masked`` — a (possibly traced) bool: can this tile contain
    NEG_INF scores? The kernels gate :func:`_zero_masked`'s per-element
    compare/select on it, and the causal mask itself runs only on
    diagonal-crossing tiles (a tile is fully visible when its last key
    index is within the FIRST query row's allowance). The kernel is
    VPU-bound (exp + reductions), so shaving mask ops off interior
    tiles is real time, not noise."""
    q = q_ref[0]
    kb = k_ref[0]
    s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    masked = bias_ref is not None or segq_ref is not None
    if bias_ref is not None:
        s = s + bias_ref[0, 0, :][None, :]
    if segq_ref is not None:
        s = _segment_mask(s, segq_ref[0], segk_ref[0])
    if causal:
        fully_visible = (kj + 1) * block_k - 1 <= qi * block_q + causal_offset
        s = jax.lax.cond(
            fully_visible, lambda t: t,
            lambda t: _causal_mask(t, qi, kj, block_q, block_k,
                                   causal_offset), s)
        if not masked:  # keep python True static; only upgrade False
            masked = jnp.logical_not(fully_visible)
    return q, kb, s, masked


def _maybe_zero_masked(p, s, masked):
    """Apply :func:`_zero_masked` only when the tile can actually hold
    masked scores. Three cases, two static: ``masked`` is python False
    for unmasked dense attention (no select at all) and python True
    when a bias/segment mask is statically present without causal
    (unconditional select, no dead cond); a traced bool on the causal
    path (cond skips the per-element compare/select on interior
    tiles)."""
    if masked is False:
        return p
    if masked is True:
        return _zero_masked(p, s)
    return jax.lax.cond(masked, lambda t: _zero_masked(t, s),
                        lambda t: t, p)


def _zero_masked(p, s):
    """Zero probabilities where the score was masked: with every score in
    a block at NEG_INF, exp(s - m) (or exp(s - lse)) is exp(0) = 1 —
    masked positions must contribute 0, not 1."""
    return jnp.where(s <= NEG_INF / 2, 0.0, p)


def _pad_seq(x, target, axis, value=0.0):
    pad = target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _kj_clamp(causal, block_q, block_k, nk, offset):
    """Index clamp for K/V-side blocks in causal kernels: iterations
    past a q-row's last useful key block keep requesting the SAME block
    index, and Pallas's pipelining skips the HBM→VMEM DMA when the
    index does not change — the compute for those iterations is already
    gated off by ``run``, so without this the skipped upper-triangle
    tiles still paid their (dominant) K/V fetch bandwidth. Last useful
    kj for q row qi: floor(((qi+1)·bq + offset − 1)/bk), clamped to
    [0, nk−1]."""
    if not causal:
        return lambda kk, j: kk

    def clamp(kk, j):
        last = ((j + 1) * block_q + offset - 1) // block_k
        return jnp.minimum(kk, jnp.clip(last, 0, nk - 1))
    return clamp


def _qi_clamp(causal, block_q, block_k, nq, offset):
    """Mirror of :func:`_kj_clamp` for the dkv kernel's Q-side blocks:
    iterations before a key block's first useful q row re-request the
    first useful block. First useful qi for key block kj:
    max(0, floor((kj·bk − offset)/bq))."""
    if not causal:
        return lambda kk, j: kk

    def clamp(kk, j):
        first = jnp.clip((j * block_k - offset) // block_q, 0, nq - 1)
        return jnp.maximum(kk, first)
    return clamp


# ---------------------------------------------------------------------------
# forward


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, segq_ref, segk_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                num_k_blocks: int, causal_offset: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    # causal: skip key blocks strictly above the (offset) diagonal
    run = (not causal) or (kj * block_k < (qi + 1) * block_q + causal_offset)

    @pl.when(run)
    def _step():
        _, _, s, masked = _block_scores(
            q_ref, k_ref, bias_ref, segq_ref, segk_ref,
            qi, kj, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
            causal_offset=causal_offset)
        vb = v_ref[0]
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = _maybe_zero_masked(jnp.exp(s - m_new[:, None]), s, masked)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)
        # p rounded to the input dtype for the MXU pass; accumulator f32
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[...] / l[:, None]).astype(o_ref.dtype)
        # (1, block_q) row store: sublane→lane relayout, Mosaic-supported
        lse_ref[0] = (m_scr[:, 0] + jnp.log(l))[None, :]


def _pad_all(q, k, v, bias, seg_q, seg_k, block_q, block_k):
    """Pad seq dims to whole blocks. Padded keys get a NEG_INF bias;
    padded q/k segment ids get distinct negative ids so they never
    match. Returns padded operands + the original (sq, sk)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    sq_p = pl.cdiv(sq, block_q) * block_q
    sk_p = pl.cdiv(sk, block_k) * block_k
    if sq_p != sq or sk_p != sk:
        q = _pad_seq(q, sq_p, 2)
        k = _pad_seq(k, sk_p, 2)
        v = _pad_seq(v, sk_p, 2)
        if sk_p != sk:
            if bias is None:
                bias = jnp.zeros((b, sk), jnp.float32)
            bias = _pad_seq(bias, sk_p, 1, NEG_INF)
        if seg_q is not None:
            seg_q = _pad_seq(seg_q, sq_p, 1, -1)
            seg_k = _pad_seq(seg_k, sk_p, 1, -2)
    return q, k, v, bias, seg_q, seg_k, sq, sk


def _flash_fwd(q, k, v, bias, seg_q, seg_k, causal: bool,
               block_q: int, block_k: int, interpret: bool):
    block_q = min(block_q, q.shape[2])
    block_k = min(block_k, k.shape[2])
    q, k, v, bias, seg_q, seg_k, sq_orig, sk_orig = _pad_all(
        q, k, v, bias, seg_q, seg_k, block_q, block_k)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    bh = b * h
    nq = sq // block_q
    nk = sk // block_k

    q_r = q.reshape(bh, sq, d)
    k_r = k.reshape(bh, sk, d)
    v_r = v.reshape(bh, sk, d)

    ck = _kj_clamp(causal, block_q, block_k, nk, sk_orig - sq_orig)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, ck(kk, j), 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, ck(kk, j), 0)),
    ]
    args = [q_r, k_r, v_r]
    have_bias = bias is not None
    have_seg = seg_q is not None
    if have_bias:
        bias_r = jnp.broadcast_to(bias[:, None, :], (b, h, sk)).reshape(bh, 1, sk)
        in_specs.append(pl.BlockSpec((1, 1, block_k),
                                     lambda i, j, kk: (i, 0, ck(kk, j))))
        args.append(bias_r.astype(jnp.float32))
    if have_seg:
        segq_r = jnp.broadcast_to(seg_q[:, None, :], (b, h, sq)).reshape(bh, sq)
        segk_r = jnp.broadcast_to(seg_k[:, None, :], (b, h, sk)).reshape(bh, sk)
        in_specs.append(pl.BlockSpec((1, block_q), lambda i, j, kk: (i, j)))
        in_specs.append(pl.BlockSpec((1, block_k),
                                     lambda i, j, kk: (i, ck(kk, j))))
        args += [segq_r.astype(jnp.int32), segk_r.astype(jnp.int32)]

    def kernel(*refs):
        it = iter(refs)
        q_ref, k_ref, v_ref = next(it), next(it), next(it)
        b_ref = next(it) if have_bias else None
        sq_ref = next(it) if have_seg else None
        sk_ref = next(it) if have_seg else None
        o_ref, lse_ref, m_scr, l_scr, acc_scr = it
        _fwd_kernel(q_ref, k_ref, v_ref, b_ref, sq_ref, sk_ref,
                    o_ref, lse_ref, m_scr, l_scr, acc_scr,
                    scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k, num_k_blocks=nk,
                    causal_offset=sk_orig - sq_orig)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            # lse as (bh, 1, sq): the (1, 1, block_q) block satisfies the
            # Mosaic tiling rules with only 8x sublane padding in HBM
            # (a (1, block_q) 2-d block would violate the sublane rule,
            # and a lane-replicated (bh, sq, 128) layout costs 128x HBM)
            pl.BlockSpec((1, 1, block_q), lambda i, j, kk: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    out = out.reshape(b, h, sq, d)[:, :, :sq_orig]
    lse = lse[:, 0, :].reshape(b, h, sq)[:, :, :sq_orig]
    return out, lse


# ---------------------------------------------------------------------------
# backward (two pallas passes, flash-attention-2 style)


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, segq_ref, segk_ref,
               g_ref, lse_ref, delta_ref, dq_ref, dq_scr, *,
               scale: float, causal: bool, block_q: int, block_k: int,
               num_k_blocks: int, causal_offset: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    run = (not causal) or (kj * block_k < (qi + 1) * block_q + causal_offset)

    @pl.when(run)
    def _step():
        _, kb, s, masked = _block_scores(
            q_ref, k_ref, bias_ref, segq_ref, segk_ref,
            qi, kj, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
            causal_offset=causal_offset)
        vb = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        p = _maybe_zero_masked(jnp.exp(s - lse[:, None]), s, masked)
        dp = jax.lax.dot_general(g, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, segq_ref, segk_ref,
                g_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                num_q_blocks: int, causal_offset: int):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    run = (not causal) or (kj * block_k < (qi + 1) * block_q + causal_offset)

    @pl.when(run)
    def _step():
        q, _, s, masked = _block_scores(
            q_ref, k_ref, bias_ref, segq_ref, segk_ref,
            qi, kj, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
            causal_offset=causal_offset)
        vb = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        p = _maybe_zero_masked(jnp.exp(s - lse[:, None]), s, masked)  # [bq, bk]
        # dv += p^T g
        dv_scr[...] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(g, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        # ds carries the scale here (q is unscaled); rounded to the
        # input dtype for the dk MXU pass
        ds = p * (dp - delta[:, None]) * scale  # [bq, bk]
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, bias, seg_q, seg_k, causal, out, lse, g,
               block_q: int, block_k: int, interpret: bool, delta=None):
    block_q = min(block_q, q.shape[2])
    block_k = min(block_k, k.shape[2])
    if delta is None:
        delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)
    q, k, v, bias, seg_q, seg_k, sq_orig, sk_orig = _pad_all(
        q, k, v, bias, seg_q, seg_k, block_q, block_k)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    bh = b * h
    nq = sq // block_q
    nk = sk // block_k
    causal_offset = sk_orig - sq_orig

    # padded q rows: g/delta 0 and lse huge, so p=exp(s-lse)=0 — they
    # contribute nothing to dk/dv, and their dq rows are sliced off
    g = _pad_seq(g, sq, 2)
    lse = _pad_seq(lse, sq, 2, -NEG_INF)
    delta = _pad_seq(delta, sq, 2)

    q_r = q.reshape(bh, sq, d)
    k_r = k.reshape(bh, sk, d)
    v_r = v.reshape(bh, sk, d)
    g_r = g.reshape(bh, sq, d)
    lse_r = lse.reshape(bh, sq)
    delta_r = delta.reshape(bh, sq)

    have_bias = bias is not None
    have_seg = seg_q is not None
    bias_r = segq_r = segk_r = None
    if have_bias:
        bias_r = jnp.broadcast_to(bias[:, None, :], (b, h, sk)) \
            .reshape(bh, 1, sk).astype(jnp.float32)
    if have_seg:
        segq_r = jnp.broadcast_to(seg_q[:, None, :], (b, h, sq)) \
            .reshape(bh, sq).astype(jnp.int32)
        segk_r = jnp.broadcast_to(seg_k[:, None, :], (b, h, sk)) \
            .reshape(bh, sk).astype(jnp.int32)

    # ---- dq pass: grid (bh, nq, nk), K/V streamed on the inner dim;
    # causal iterations past the diagonal re-request the same block so
    # their DMA is skipped (see _kj_clamp)
    ck = _kj_clamp(causal, block_q, block_k, nk, causal_offset)
    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, ck(kk, j), 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, ck(kk, j), 0)),
    ]
    dq_args = [q_r, k_r, v_r]
    if have_bias:
        dq_specs.append(pl.BlockSpec((1, 1, block_k),
                                     lambda i, j, kk: (i, 0, ck(kk, j))))
        dq_args.append(bias_r)
    if have_seg:
        dq_specs.append(pl.BlockSpec((1, block_q), lambda i, j, kk: (i, j)))
        dq_specs.append(pl.BlockSpec((1, block_k),
                                     lambda i, j, kk: (i, ck(kk, j))))
        dq_args += [segq_r, segk_r]
    dq_specs += [
        pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, block_q), lambda i, j, kk: (i, j)),
        pl.BlockSpec((1, block_q), lambda i, j, kk: (i, j)),
    ]
    dq_args += [g_r, lse_r, delta_r]

    def dq_kernel(*refs):
        it = iter(refs)
        q_ref, k_ref, v_ref = next(it), next(it), next(it)
        b_ref = next(it) if have_bias else None
        sqr = next(it) if have_seg else None
        skr = next(it) if have_seg else None
        g_ref, lse_ref, delta_ref, dq_ref, dq_scr = it
        _dq_kernel(q_ref, k_ref, v_ref, b_ref, sqr, skr, g_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, scale=scale, causal=causal,
                   block_q=block_q, block_k=block_k, num_k_blocks=nk,
                   causal_offset=causal_offset)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    # ---- dk/dv pass: grid (bh, nk, nq), Q/dO streamed on the inner
    # dim; causal iterations before a key block's first useful q row
    # re-request that first block (DMA skipped, see _qi_clamp)
    cq = _qi_clamp(causal, block_q, block_k, nq, causal_offset)
    dkv_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, cq(kk, j), 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
    ]
    dkv_args = [q_r, k_r, v_r]
    if have_bias:
        dkv_specs.append(pl.BlockSpec((1, 1, block_k), lambda i, j, kk: (i, 0, j)))
        dkv_args.append(bias_r)
    if have_seg:
        dkv_specs.append(pl.BlockSpec((1, block_q),
                                      lambda i, j, kk: (i, cq(kk, j))))
        dkv_specs.append(pl.BlockSpec((1, block_k), lambda i, j, kk: (i, j)))
        dkv_args += [segq_r, segk_r]
    dkv_specs += [
        pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, cq(kk, j), 0)),
        pl.BlockSpec((1, block_q), lambda i, j, kk: (i, cq(kk, j))),
        pl.BlockSpec((1, block_q), lambda i, j, kk: (i, cq(kk, j))),
    ]
    dkv_args += [g_r, lse_r, delta_r]

    def dkv_kernel(*refs):
        it = iter(refs)
        q_ref, k_ref, v_ref = next(it), next(it), next(it)
        b_ref = next(it) if have_bias else None
        sqr = next(it) if have_seg else None
        skr = next(it) if have_seg else None
        g_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr = it
        _dkv_kernel(q_ref, k_ref, v_ref, b_ref, sqr, skr, g_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, scale=scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    num_q_blocks=nq, causal_offset=causal_offset)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args)

    return (dq.reshape(b, h, sq, d)[:, :, :sq_orig],
            dk.reshape(b, h, sk, d)[:, :, :sk_orig],
            dv.reshape(b, h, sk, d)[:, :, :sk_orig])


# ---------------------------------------------------------------------------
# custom VJP plumbing


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _flash_core(q, k, v, bias, seg_q, seg_k, causal, block_q, block_k,
                interpret):
    out, _ = _flash_fwd(q, k, v, bias, seg_q, seg_k, causal, block_q,
                        block_k, interpret)
    return out


def _flash_core_fwd(q, k, v, bias, seg_q, seg_k, causal, block_q, block_k,
                    interpret):
    out, lse = _flash_fwd(q, k, v, bias, seg_q, seg_k, causal, block_q,
                          block_k, interpret)
    return out, (q, k, v, bias, seg_q, seg_k, out, lse)


def _flash_core_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, bias, seg_q, seg_k, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, bias, seg_q, seg_k, causal, out, lse, g,
                            block_q, block_k, interpret)
    return dq, dk, dv, None, None, None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q, k, v,
    causal: bool = False,
    attn_mask: Optional[jax.Array] = None,
    key_bias: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    return_lse: bool = False,
):
    """Flash attention over [b, h, s, d].

    - ``key_bias``: additive [b, s_k] (padding mask).
    - ``segment_ids`` / ``kv_segment_ids``: int [b, s] ragged-batch ids
      (LoD analog); attention is masked across segment boundaries. When
      only ``segment_ids`` is given it is used for both sides (self
      attention).
    - ``attn_mask``: a [b,1,1,s_k] additive mask is converted to a key
      bias; any other dense mask falls back to the XLA composition.
    - ``block_q``/``block_k``: None resolves the ``flash_block_q``/``_k``
      config flags then the chip-tuned defaults — see
      :func:`resolve_block_shapes` (read at trace time).
    - ``return_lse``: also return the per-query logsumexp [b, h, s_q]
      (forward only — used by ring attention to merge shards).
    """
    from ..core.errors import enforce

    block_q, block_k = resolve_block_shapes(block_q, block_k)
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    enforce(kv_segment_ids is None or segment_ids is not None,
            "flash_attention: kv_segment_ids requires segment_ids (the "
            "query-side ids) as well")
    if attn_mask is not None:
        if attn_mask.ndim == 4 and attn_mask.shape[1] == 1 and attn_mask.shape[2] == 1:
            key_bias = attn_mask[:, 0, 0, :] if key_bias is None \
                else key_bias + attn_mask[:, 0, 0, :]
        else:
            # general dense mask: XLA path, with bias/segment masking
            # folded in so nothing is silently dropped
            mask = attn_mask
            if key_bias is not None:
                mask = mask + key_bias[:, None, None, :]
            if segment_ids is not None:
                seg_k_ = kv_segment_ids if kv_segment_ids is not None else segment_ids
                same = segment_ids[:, None, :, None] == seg_k_[:, None, None, :]
                mask = jnp.where(same, mask, NEG_INF)
            return _mask_fallback(q, k, v, mask, causal)
    seg_q = segment_ids
    seg_k = kv_segment_ids if kv_segment_ids is not None else segment_ids
    bias = None if key_bias is None else key_bias.astype(jnp.float32)
    if return_lse:
        return _flash_fwd(q, k, v, bias, seg_q, seg_k, causal,
                          block_q, block_k, interpret)
    return _flash_core(q, k, v, bias, seg_q, seg_k, causal,
                       block_q, block_k, interpret)


def _mask_fallback(q, k, v, attn_mask, causal):
    from .attention_scores import scores_mxu
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = scores_mxu(q, k, scale)
    s = s + attn_mask
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        s = jnp.where(cm, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
