"""Metrics.

Analog of python/paddle/fluid/metrics.py (Precision/Recall/Accuracy/
Auc/EditDistance/CompositeMetric) plus the in-graph metric ops
(accuracy_op.cc, auc_op.cc via layers.metric_op). Each metric is a
host-side accumulator fed per-batch values; the in-graph helpers
(``accuracy``/``auc_stat``) compute the per-batch tensors inside jit.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


# -- in-graph helpers (layers/metric_op.py analog) ---------------------------


def accuracy(input, label, k: int = 1):
    """Per-batch top-k accuracy tensor (accuracy_op.cc analog)."""
    lab = label.astype(jnp.int32)
    if lab.ndim == input.ndim and lab.shape[-1] == 1:
        lab = lab[..., 0]
    _, idx = jax.lax.top_k(input, k)
    correct = jnp.any(idx == lab[..., None], axis=-1)
    return correct.astype(jnp.float32).mean()


def auc_stat(pred_pos, label, num_thresholds: int = 200):
    """Per-batch AUC histogram stats (auc_op.cc analog): returns
    (tp_hist, fp_hist) over thresholds; combine in the Auc metric."""
    lab = label.reshape(-1).astype(jnp.bool_)
    p = jnp.clip(pred_pos.reshape(-1), 0.0, 1.0)
    bucket = jnp.minimum((p * num_thresholds).astype(jnp.int32), num_thresholds - 1)
    tp = jnp.zeros(num_thresholds, jnp.int32).at[bucket].add(lab.astype(jnp.int32))
    fp = jnp.zeros(num_thresholds, jnp.int32).at[bucket].add((~lab).astype(jnp.int32))
    return tp, fp


# -- host-side accumulators (metrics.py analog) ------------------------------


class MetricBase:
    def __init__(self, name: Optional[str] = None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    """Weighted running accuracy (metrics.py Accuracy)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(value) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    """Binary precision (metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).round().astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).round().astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(MetricBase):
    """Threshold-bucketed ROC AUC (metrics.py Auc / auc_op.cc)."""

    def __init__(self, name=None, num_thresholds: int = 200):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self.tp_hist = np.zeros(self.num_thresholds, np.int64)
        self.fp_hist = np.zeros(self.num_thresholds, np.int64)

    def update(self, preds, labels):
        """preds: prob of positive class [N] or [N,2]; labels: [N]."""
        p = np.asarray(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = np.clip(p.reshape(-1), 0.0, 1.0)
        lab = np.asarray(labels).reshape(-1).astype(bool)
        bucket = np.minimum((p * self.num_thresholds).astype(np.int64),
                            self.num_thresholds - 1)
        np.add.at(self.tp_hist, bucket, lab.astype(np.int64))
        np.add.at(self.fp_hist, bucket, (~lab).astype(np.int64))

    def update_stats(self, tp, fp):
        """Accumulate stats from the in-graph auc_stat helper."""
        self.tp_hist += np.asarray(tp, dtype=np.int64)
        self.fp_hist += np.asarray(fp, dtype=np.int64)

    def eval(self):
        # cumulative from the highest threshold down = ROC sweep
        tp_c = np.cumsum(self.tp_hist[::-1]).astype(np.float64)
        fp_c = np.cumsum(self.fp_hist[::-1]).astype(np.float64)
        tot_p, tot_n = tp_c[-1], fp_c[-1]
        if tot_p == 0 or tot_n == 0:
            return 0.0
        tpr = np.concatenate([[0.0], tp_c / tot_p])
        fpr = np.concatenate([[0.0], fp_c / tot_n])
        return float(np.trapezoid(tpr, fpr))


class EditDistance(MetricBase):
    """Mean Levenshtein distance (metrics.py EditDistance /
    edit_distance_op.cc) over sequence pairs."""

    def __init__(self, name=None, normalized: bool = True):
        super().__init__(name)
        self.normalized = normalized
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0
        self.seq_num_err = 0

    @staticmethod
    def _levenshtein(a: Sequence, b: Sequence) -> int:
        m, n = len(a), len(b)
        dp = list(range(n + 1))
        for i in range(1, m + 1):
            prev = dp[0]
            dp[0] = i
            for j in range(1, n + 1):
                cur = dp[j]
                dp[j] = min(dp[j] + 1, dp[j - 1] + 1, prev + (a[i - 1] != b[j - 1]))
                prev = cur
        return dp[n]

    def update(self, hyps, refs):
        for h, r in zip(hyps, refs):
            d = self._levenshtein(list(h), list(r))
            if self.normalized:
                d = d / max(len(r), 1)
            self.total += d
            self.count += 1
            if d > 0:
                self.seq_num_err += 1

    def eval(self):
        if self.count == 0:
            raise ValueError("EditDistance: no updates yet")
        return self.total / self.count, self.seq_num_err / self.count


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.metrics: List[MetricBase] = []

    def add_metric(self, m: MetricBase):
        self.metrics.append(m)

    def reset(self):
        for m in self.metrics:
            m.reset()

    def update(self, **kwargs):
        for m in self.metrics:
            m.update(**kwargs)

    def eval(self):
        return [m.eval() for m in self.metrics]


def chunk_eval(hyp_chunks, ref_chunks):
    """Chunk-level P/R/F1 (chunk_eval_op analog) over sets of
    (start, end, type) tuples per sequence."""
    tp = sum(len(set(h) & set(r)) for h, r in zip(hyp_chunks, ref_chunks))
    nh = sum(len(h) for h in hyp_chunks)
    nr = sum(len(r) for r in ref_chunks)
    p = tp / nh if nh else 0.0
    r = tp / nr if nr else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return p, r, f1


def auc(input, label, curve: str = "ROC", num_thresholds: int = 200, name=None):
    """In-graph streaming AUC (layers.auc / auc_op.cc): persistable tp/fp
    histograms (auc_stat buckets) accumulated across steps in Program
    state, integrated with the same (0,0)-anchored ROC sweep as
    Auc.eval. ``input`` [B, 2] two-class probabilities (reference
    contract); returns (auc_value, batch_auc_value)."""
    from .framework import LayerHelper
    from . import initializer as init

    from .core.errors import enforce

    enforce(curve in ("ROC", "PR"), f"auc: unknown curve {curve!r}")
    helper = LayerHelper("auc", name=name)
    tp_b, fp_b = auc_stat(input[:, 1], jnp.asarray(label), num_thresholds)

    def _auc(tp_hist, fp_hist):
        # cumulative from the highest threshold down; ROC anchored at
        # (0,0), PR anchored at (recall 0, precision 1)
        tp_c = jnp.cumsum(tp_hist[::-1]).astype(jnp.float32)
        fp_c = jnp.cumsum(fp_hist[::-1]).astype(jnp.float32)
        if curve == "PR":
            recall = jnp.concatenate([jnp.zeros(1), tp_c]) / jnp.maximum(tp_c[-1], 1e-8)
            # precision is 1 by convention while no prediction is positive
            prec = jnp.where(tp_c + fp_c > 0, tp_c / jnp.maximum(tp_c + fp_c, 1e-8), 1.0)
            precision = jnp.concatenate([jnp.ones(1), prec])
            return jnp.sum((recall[1:] - recall[:-1])
                           * (precision[1:] + precision[:-1]) / 2.0)
        tpr = jnp.concatenate([jnp.zeros(1), tp_c]) / jnp.maximum(tp_c[-1], 1e-8)
        fpr = jnp.concatenate([jnp.zeros(1), fp_c]) / jnp.maximum(fp_c[-1], 1e-8)
        return jnp.sum((fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2.0)

    stats = {}
    for nm, batch in (("tp", tp_b), ("fp", fp_b)):
        acc = helper.create_variable(nm, (num_thresholds,), jnp.int32,
                                     initializer=init.Constant(0.0))
        stats[nm] = acc + batch
        helper.assign_variable(nm, stats[nm])
    return _auc(stats["tp"], stats["fp"]), _auc(tp_b, fp_b)


class ChunkEvaluator(MetricBase):
    """metrics.py ChunkEvaluator: streaming chunk-level precision /
    recall / F1 (chunk_eval_op counts accumulated across batches)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks: int, num_label_chunks: int,
               num_correct_chunks: int):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


# re-export (reference metrics.py __all__ includes DetectionMAP; the
# implementation lives with the evaluators)
def __getattr__(name):
    if name == "DetectionMAP":
        from .evaluator import DetectionMAP
        return DetectionMAP
    raise AttributeError(name)


def chunk_eval_counts(inference, label, lengths, num_chunk_types: int,
                      chunk_scheme: str = "IOB"):
    """In-graph chunk counting (chunk_eval_op.cc analog), jittable.

    inference/label: [b, t] int tag ids with the reference's encoding
    ``tag_id = chunk_type * tag_num + tag`` (IOB: tag 0=B, 1=I, tag_num=2;
    IOE: 0=I, 1=E, tag_num=2; IOBES: 0=B,1=I,2=E,3=S, tag_num=4;
    plain: tag_num=1). Ids >=
    num_chunk_types*tag_num (and positions >= lengths) are outside (O).
    Returns (num_infer_chunks, num_label_chunks, num_correct_chunks) —
    feed ChunkEvaluator.update. A chunk is correct iff (start, end, type)
    all match, computed via begin-masks + run-length span ends (no host
    loop)."""
    tag_num = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[chunk_scheme]
    b, t = inference.shape
    pos = jnp.arange(t)[None, :]
    valid = pos < jnp.asarray(lengths)[:, None]

    def spans(tags):
        tags = jnp.asarray(tags).astype(jnp.int32)
        inside_vocab = (tags >= 0) & (tags < num_chunk_types * tag_num) & valid
        ctype = jnp.where(inside_vocab, tags // tag_num, -1)
        tag = jnp.where(inside_vocab, tags % tag_num, -1)
        prev_ctype = jnp.concatenate([jnp.full((b, 1), -1), ctype[:, :-1]], axis=1)
        prev_tag = jnp.concatenate([jnp.full((b, 1), -1), tag[:, :-1]], axis=1)
        if chunk_scheme == "plain":
            begin = inside_vocab & (ctype != prev_ctype)
        elif chunk_scheme == "IOB":
            is_b, is_i = tag == 0, tag == 1
            # B always begins; I begins when not continuing same type
            cont = is_i & (prev_ctype == ctype) & ((prev_tag == 0) | (prev_tag == 1))
            begin = inside_vocab & (is_b | (is_i & ~cont))
        elif chunk_scheme == "IOE":
            # I (tag 0) continues into the next same-type token; E closes
            cont_prev = (prev_ctype == ctype) & (prev_tag == 0)
            begin = inside_vocab & ~cont_prev
        else:  # IOBES
            is_b, is_i, is_e, is_s = tag == 0, tag == 1, tag == 2, tag == 3
            cont = (is_i | is_e) & (prev_ctype == ctype) & ((prev_tag == 0) | (prev_tag == 1))
            begin = inside_vocab & (is_b | is_s | ((is_i | is_e) & ~cont))
        # continues[i]: token i+1 belongs to the chunk containing i
        nxt_begin = jnp.concatenate([begin[:, 1:], jnp.ones((b, 1), bool)], axis=1)
        nxt_inside = jnp.concatenate([inside_vocab[:, 1:], jnp.zeros((b, 1), bool)], axis=1)
        nxt_ctype = jnp.concatenate([ctype[:, 1:], jnp.full((b, 1), -1)], axis=1)
        continues = inside_vocab & nxt_inside & ~nxt_begin & (nxt_ctype == ctype)

        # run-length of continues -> span end index per position
        def back(carry, inp):
            cont_t = inp
            run = jnp.where(cont_t, carry + 1, 0)
            return run, run
        _, runs = jax.lax.scan(back, jnp.zeros((b,), jnp.int32),
                               jnp.swapaxes(continues, 0, 1), reverse=True)
        end = pos + jnp.swapaxes(runs, 0, 1)
        return begin, ctype, end

    h_begin, h_type, h_end = spans(inference)
    r_begin, r_type, r_end = spans(label)
    correct = h_begin & r_begin & (h_type == r_type) & (h_end == r_end)
    return (jnp.sum(h_begin).astype(jnp.int32),
            jnp.sum(r_begin).astype(jnp.int32),
            jnp.sum(correct).astype(jnp.int32))
