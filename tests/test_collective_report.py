"""Collective-traffic accounting (debugger.collective_report) — the
scaling-efficiency evidence producible without pod hardware (VERDICT r2
#8; reference anchor: benchmark/README.md:70-95 scaling tables)."""

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import debugger, optimizer as opt
from paddle_tpu.debugger import _parse_hlo_collectives
from paddle_tpu.models import transformer
from paddle_tpu.parallel import transformer_tp_rules


def test_parse_hlo_collectives():
    hlo = """
  %all-reduce.7 = f32[128,64]{1,0} all-reduce(f32[128,64]{1,0} %add.3), replica_groups={{0,1,2,3}}, to_apply=%sum
  %ag = (f32[256]{0}, f32[256]{0}) all-gather-start(f32[64]{0} %p), replica_groups={{0,1},{2,3}}, dimensions={0}
  %agd = f32[256]{0} all-gather-done((f32[256]{0}, f32[256]{0}) %ag)
  %cp = bf16[32,16]{1,0} collective-permute(bf16[32,16]{1,0} %x), source_target_pairs={{0,1},{1,2}}
  %fusion.1 = f32[10]{0} fusion(f32[10]{0} %y), kind=kLoop
"""
    got = _parse_hlo_collectives(hlo)
    kinds = [k for k, _, _ in got]
    assert kinds == ["all-reduce", "all-gather", "collective-permute"]
    ar = got[0]
    assert ar[1] == 128 * 64 * 4 and ar[2] == 4
    ag = got[1]  # async start: tuple aliases (operand, result) — count
    assert ag[1] == 256 * 4 and ag[2] == 2  # the result only, once
    cp = got[2]
    assert cp[1] == 32 * 16 * 2


def test_parse_hlo_async_start_counts_result_once():
    """all-gather-start output tuples include the operand and u32
    contexts; only the (largest) result element is the payload. Variadic
    all-reduce tuples are all results and sum. Iota replica_groups and
    /*index=N*/ comments parse."""
    hlo = """
  %ags = (f32[64]{0}, f32[256]{0}, u32[], u32[]) all-gather-start(f32[64]{0} %p), replica_groups=[2,4]<=[8], dimensions={0}
  %cps = (bf16[32]{0}, bf16[32]{0}) collective-permute-start(bf16[32]{0} %x), source_target_pairs={{0,1}}
  %arv = (f32[10]{0}, /*index=1*/f32[20]{0}) all-reduce-start(f32[10]{0} %a, f32[20]{0} %b), replica_groups={}
"""
    got = _parse_hlo_collectives(hlo, fallback_group_size=8)
    assert got[0] == ("all-gather", 256 * 4, 4)       # result, iota group size
    assert got[1] == ("collective-permute", 32 * 2, 8)  # counted once
    assert got[2] == ("all-reduce", (10 + 20) * 4, 8)   # variadic: summed


def test_reduce_scatter_wire_is_result_times_n_minus_1():
    """A reduce-scatter RESULT is 1/n of the logical input; ring wire is
    result*(n-1), not result*(n-1)/n — the dominant FSDP collective must
    not be undercounted by n (review finding)."""
    from paddle_tpu.debugger import _parse_hlo_collectives as parse

    from paddle_tpu.debugger import _wire_factor

    hlo = "%rs = f32[8]{0} reduce-scatter(f32[32]{0} %g), replica_groups={{0,1,2,3}}, dimensions={0}, to_apply=%sum"
    ((kind, payload, gsize),) = parse(hlo)
    assert (kind, payload, gsize) == ("reduce-scatter", 32, 4)
    assert payload * _wire_factor(kind, gsize) == 96.0  # 32B result -> 96B wire
    assert _wire_factor("all-gather", 4) == pytest.approx(0.75)
    assert _wire_factor("all-reduce", 4) == pytest.approx(1.5)


def _trainer(mesh, rules, strategy=None):
    cfg = transformer.base_config(src_vocab=64, trg_vocab=64, d_model=32,
                                  d_inner=64, num_heads=4, num_encoder_layers=2,
                                  num_decoder_layers=2, dropout=0.0)
    prog = pt.build(transformer.make_model(cfg))
    rng = np.random.RandomState(0)
    feed = {"src_ids": rng.randint(3, 64, (8, 16)).astype(np.int32),
            "trg_ids": rng.randint(3, 64, (8, 16)).astype(np.int32),
            "labels": rng.randint(3, 64, (8, 16)).astype(np.int32)}
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    sharding_rules=rules, strategy=strategy)
    tr.startup(sample_feed=feed)
    return tr, feed


def test_collective_report_dp_sees_grad_allreduce():
    """Pure DP: the dominant collective must be the gradient all-reduce,
    with payload on the order of the param bytes."""
    mesh = pt.make_mesh({"dp": 8})
    tr, feed = _trainer(mesh, pt.parallel.replicated())
    rep = debugger.collective_report(tr, feed)
    assert "all-reduce" in rep["collectives"], rep
    param_mb = sum(v.size * 4 for v in jax.tree.leaves(tr.scope.params)) / 1e6
    ar_mb = rep["collectives"]["all-reduce"]["payload_mb"]
    # grads for every param get all-reduced at least once (loss/metrics
    # add small extras; XLA may fuse or split, so bound loosely)
    assert ar_mb > 0.5 * param_mb, (ar_mb, param_mb)
    assert rep["est_wire_mb_per_device"] > 0
    assert rep["mesh"] == {"dp": 8}


@pytest.mark.slow
def test_collective_report_interleave_traffic_tradeoff():
    """The interleaved pipeline's documented cost is V× more
    collective-permute traffic: M·V+P-1 ticks of ring hops vs M+P-1.
    collective_report's static walk counts the in-scan ppermute ONCE
    (documented limitation), so the evidence is structural: the permute
    is present in the inventory, and the tick-scan length in the traced
    program grows exactly per _schedule_ticks."""
    import re

    from paddle_tpu.parallel import DistStrategy
    from paddle_tpu.parallel.pipeline import _schedule_ticks

    def _pp_trainer(interleave):
        cfg = transformer.base_config(src_vocab=64, trg_vocab=64, d_model=32,
                                      d_inner=64, num_heads=4,
                                      num_encoder_layers=4,
                                      num_decoder_layers=4, dropout=0.0,
                                      stacked=True)
        prog = pt.build(transformer.make_model(cfg))
        rng = np.random.RandomState(0)
        feed = {"src_ids": rng.randint(3, 64, (8, 16)).astype(np.int32),
                "trg_ids": rng.randint(3, 64, (8, 16)).astype(np.int32),
                "labels": rng.randint(3, 64, (8, 16)).astype(np.int32)}
        mesh = pt.make_mesh({"pp": 2}, devices=jax.devices()[:2])
        tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                        sharding_rules=transformer_tp_rules(),
                        strategy=DistStrategy(pp_microbatches=4,
                                              pp_interleave=interleave))
        tr.startup(sample_feed=feed)
        return tr, feed

    for v in (1, 2):
        tr, feed = _pp_trainer(v)
        rep = debugger.collective_report(tr, feed)
        assert "collective-permute" in rep["collectives"], rep
        jaxpr = str(jax.make_jaxpr(
            lambda p, o, s, r, f, ls: tr._loss_and_aux(p, s, r, f))(
                tr.scope.params, tr.scope.opt_state, tr.scope.state,
                jax.random.PRNGKey(0), feed, {}))
        lengths = {int(m.group(1)) for m in re.finditer(r"length=(\d+)", jaxpr)}
        want = _schedule_ticks(4, 2, v)   # m=4, p=2: 5 ticks at v=1, 9 at v=2
        assert want in lengths, (v, want, sorted(lengths))


def test_collective_report_3d_mesh_shows_sharding_collectives():
    """dp×fsdp×tp: fsdp adds param all-gathers, tp adds activation
    collectives — the report must show more collective KINDS than pure
    DP's single fused grad all-reduce (total wire bytes can be lower:
    fsdp's gather/scatter halves beat 2x all-reduce)."""
    mesh_3d = pt.make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    tr_3d, feed_3d = _trainer(mesh_3d, transformer_tp_rules())
    rep_3d = debugger.collective_report(tr_3d, feed_3d)

    kinds_3d = set(rep_3d["collectives"])
    assert "all-gather" in kinds_3d, rep_3d  # fsdp param gathers
    assert len(kinds_3d) > 1, rep_3d  # not just the grad all-reduce
    assert rep_3d["est_wire_mb_per_device"] > 0


@pytest.mark.slow
def test_accum_grad_exchange_is_per_microbatch():
    """Pin the measured reality SCALING.md §2 is built on: under GSPMD
    the dp grad all-reduce sits INSIDE the accum_steps scan body — the
    partitioner reduces every microbatch's gradients instead of
    hoisting one exchange past the accumulator, so accumulation is a
    memory lever, NOT a wire lever. The day this fails is the day the
    exchange got hoisted (partitioner upgrade or the shard_map
    follow-up): celebrate, then upgrade SCALING.md's projection and
    invert this assertion."""
    import re

    from paddle_tpu.parallel import DistStrategy

    mesh = pt.make_mesh({"dp": 8})
    tr, feed = _trainer(mesh, pt.parallel.replicated(),
                        strategy=DistStrategy(accum_steps=4))
    rep = debugger.collective_report(tr, feed)
    assert "all-reduce" in rep["collectives"], rep

    # structural check (the static walk counts in-scan collectives once,
    # so collective_report alone cannot see loop placement): parse the
    # while-BODY computations with the same collective parser the
    # report uses (it handles variadic/tuple-typed all-reduce forms)
    # and require GRAD-ORDER payload — a stray scalar loss/metric mean
    # in some loop must neither satisfy nor break the pin
    hlo = debugger._lower_step(tr, feed).compile().as_text()
    bodies = set(re.findall(r"body=%?([\w.\-]+)", hlo))
    blocks = re.split(r"\n(?=[%\w].*\{)", hlo)
    in_body_ar_bytes = 0.0
    for block in blocks:
        header = block.split("\n", 1)[0]
        name = re.match(r"%?([\w.\-]+)", header.lstrip())
        if name and name.group(1) in bodies:
            in_body_ar_bytes += sum(
                payload for kind, payload, _ in
                _parse_hlo_collectives(block, fallback_group_size=8)
                if kind == "all-reduce")
    param_bytes = sum(v.size * 4 for v in jax.tree.leaves(tr.scope.params))
    assert in_body_ar_bytes > 0.5 * param_bytes, (
        f"only {in_body_ar_bytes:.0f}B of all-reduce inside loop bodies "
        f"vs {param_bytes:.0f}B of params: the grad exchange got hoisted "
        "— update SCALING.md §2 (accumulation became a wire lever) and "
        "invert this test")
