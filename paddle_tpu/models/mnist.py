"""MNIST models — the book/recognize_digits configs (reference:
python/paddle/fluid/tests/book/test_recognize_digits.py: mlp + conv
variants). The minimum end-to-end slice per SURVEY §7 step 5.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import layers as L
from ..metrics import accuracy


def mlp(image, label):
    """softmax_regression/mlp from the book test: 784 → 200 → 200 → 10."""
    h = L.fc(image, 200, act="tanh")
    h = L.fc(h, 200, act="tanh")
    logits = L.fc(h, 10)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    return {"loss": loss, "acc": accuracy(logits, label), "logits": logits}


def conv_net(image, label):
    """conv_pool x2 + fc (the book's convolutional_neural_network +
    nets.simple_img_conv_pool analog)."""
    x = L.reshape(image, [-1, 1, 28, 28])
    x = L.conv2d(x, num_filters=20, filter_size=5, act="relu")
    x = L.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")
    x = L.batch_norm(x)
    x = L.conv2d(x, num_filters=50, filter_size=5, act="relu")
    x = L.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")
    logits = L.fc(x, 10)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    return {"loss": loss, "acc": accuracy(logits, label), "logits": logits}
