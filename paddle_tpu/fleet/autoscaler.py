"""Closed-loop autoscaler: the telemetry plane driving the fleet size.

Production traffic is bursty and everything else in the fleet is
fixed-size: replicas are spawned once and only replaced on death. This
module closes the loop the last five PRs built the pieces for — a
control loop that watches the collector's fleet-wide read surface and
grows/shrinks the serving fleet within a configured band:

- **trends** ride ``GET /query`` range reads (per-origin queue depth,
  shed rate): a sustained-signal window must stay hot before a trend
  alone scales anything, and the trend math only ever consumes
  COMPLETE downsample buckets (:func:`complete_buckets` — a partial
  trailing bucket under-reports by construction and must never gate a
  scale decision);
- **alert transitions** from ``/alerts`` are immediate scale-up
  triggers (the paging rule already encodes "this is bad": no second
  sustain window on top), still subject to the band and the up
  cooldown;
- **scale-down** drains: :meth:`~paddle_tpu.fleet.router.FleetRouter.
  retire` removes the replica from routing first, drains in-flight
  work with the at-most-once ``ReplicaDied``/``ServerClosed``
  classification intact, then stops the process via its owning agent.

The decision core (:class:`AutoscalePolicy`) is PURE: every input —
including the clock — arrives in one :class:`ScaleSignals` value, and
the output is one :class:`ScaleDecision`. Hysteresis (separate up/down
thresholds and sustain windows), per-direction cooldowns, anti-flap (a
replica retired in the last ``flap_guard_s`` blocks the next retire),
the quorum floor (never below ``quorum`` while any alert is firing),
and the **fail-static rule** — stale or absent telemetry pauses all
scaling AND resets the sustain windows, so a collector failover
mid-decision never causes a scale on a data gap — are all unit-pinned
without a single sleep.

The wrapper (:class:`Autoscaler`) runs the loop on a daemon thread:
reads come through a reader (:class:`HttpCollectorReader` speaks the
collector's HTTP endpoints with the same failover-list discipline as
the shipper; :class:`LocalCollectorReader` wraps an in-process
:class:`~paddle_tpu.telemetry.collector.TelemetryCollector`), actions
go through ``FleetRouter.grow()`` / ``FleetRouter.retire()`` — which
spawn locally or through the per-host fleet agents, whichever the
router was built with.

The trainer-side analog (scheduled ``fit(elastic=True)`` grow/shrink
on a resize-request file/signal) is :class:`paddle_tpu.resilience.
ResizeRequest`. Drill: ``tools/fleet_drill.py autoscale`` replays a
diurnal load curve and requires 1→N→1 with zero dropped accepted
requests. See MIGRATION.md "Autoscaler".
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _log():
    import logging
    return logging.getLogger("paddle_tpu.fleet.autoscaler")


# -- pure decision core -------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ScaleSignals:
    """One control-loop tick's worth of input, clock included — the
    policy never reads ambient time. ``None`` signal values mean "this
    signal produced no verdict this tick" (series absent, too few
    points); a tick where EVERY trend signal is verdict-less should
    arrive with ``data_ok=False``."""

    now: float                     # the tick's clock (monotonic or wall)
    replicas: int                  # current fleet size (router ground truth)
    queue_per_replica: Optional[float] = None   # fleet queue depth / size
    shed_rate: Optional[float] = None           # front-door sheds per second
    p99_ms: Optional[float] = None              # served latency p99
    alert_firing: bool = False     # any scale-relevant alert firing NOW
    alert_transition: bool = False  # a not-firing -> firing edge this tick
    data_ok: bool = True           # telemetry fresh + readable


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    action: str      # "up" | "down" | "hold"
    target: int      # fleet size the action aims at (== replicas on hold)
    reason: str      # machine-stable slug (counter label, journal field)
    detail: str = ""


class AutoscalePolicy:
    """The pure policy: ``decide(signals)`` in, ``ScaleDecision`` out.

    Scale-up fires when EITHER a trend signal stays hot for
    ``up_window_s`` (sustained, not a blip) OR an alert transition
    arrives (immediate), subject to ``max_replicas`` and
    ``up_cooldown_s``. Scale-down needs every present signal cold for
    ``down_window_s``, then clears ``down_cooldown_s``, the anti-flap
    guard (no retire within ``flap_guard_s`` of the previous retire's
    COMPLETION — ``note_retired`` stamps it), and the quorum floor
    (while any alert fires the fleet never shrinks below ``quorum``,
    default ``min_replicas``). Stale/absent data (``data_ok=False``)
    holds AND resets both sustain windows: after a telemetry gap a hot
    signal must re-sustain from scratch — never scale on a gap."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 quorum: Optional[int] = None,
                 up_queue_per_replica: float = 2.0,
                 down_queue_per_replica: float = 0.5,
                 up_shed_rate: float = 1.0,
                 down_shed_rate: float = 0.0,
                 up_p99_ms: Optional[float] = None,
                 down_p99_ms: Optional[float] = None,
                 up_window_s: float = 2.0, down_window_s: float = 5.0,
                 up_cooldown_s: float = 5.0, down_cooldown_s: float = 10.0,
                 flap_guard_s: float = 10.0, step: int = 1):
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise ValueError(
                f"bad autoscale band [{min_replicas}, {max_replicas}]: "
                "need 1 <= min <= max")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.quorum = self.min_replicas if quorum is None else int(quorum)
        self.up_queue_per_replica = float(up_queue_per_replica)
        self.down_queue_per_replica = float(down_queue_per_replica)
        self.up_shed_rate = float(up_shed_rate)
        self.down_shed_rate = float(down_shed_rate)
        self.up_p99_ms = up_p99_ms if up_p99_ms is None else float(up_p99_ms)
        self.down_p99_ms = (down_p99_ms if down_p99_ms is None
                            else float(down_p99_ms))
        self.up_window_s = float(up_window_s)
        self.down_window_s = float(down_window_s)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.flap_guard_s = float(flap_guard_s)
        self.step = max(1, int(step))
        # sustain-window state (None = condition not currently met)
        self._hot_since: Optional[float] = None
        self._cold_since: Optional[float] = None
        # -inf so the first decision is never cooldown-blocked
        self._last_up_at = float("-inf")
        self._last_down_at = float("-inf")
        self._last_retire_at = float("-inf")

    # -- event stamps --------------------------------------------------------

    def note_retired(self, now: float) -> None:
        """Stamp a retire's COMPLETION (drains take real time; the
        anti-flap clock runs from when the replica actually left, not
        from when the decision was made)."""
        self._last_retire_at = float(now)

    # -- signal classification -----------------------------------------------

    def _hot(self, s: ScaleSignals) -> Optional[str]:
        """The name of the first hot trend signal, else None."""
        if s.queue_per_replica is not None and \
                s.queue_per_replica >= self.up_queue_per_replica:
            return "queue"
        if s.shed_rate is not None and s.shed_rate >= self.up_shed_rate:
            return "shed"
        if self.up_p99_ms is not None and s.p99_ms is not None and \
                s.p99_ms >= self.up_p99_ms:
            return "p99"
        return None

    def _cold(self, s: ScaleSignals) -> bool:
        """Every PRESENT signal below its down threshold (hysteresis:
        the down thresholds sit below the up ones), with at least one
        signal present — silence is not coldness."""
        seen = False
        if s.queue_per_replica is not None:
            seen = True
            if s.queue_per_replica > self.down_queue_per_replica:
                return False
        if s.shed_rate is not None:
            seen = True
            if s.shed_rate > self.down_shed_rate:
                return False
        if self.down_p99_ms is not None and s.p99_ms is not None:
            seen = True
            if s.p99_ms > self.down_p99_ms:
                return False
        return seen

    # -- the decision --------------------------------------------------------

    def decide(self, s: ScaleSignals) -> ScaleDecision:
        now = float(s.now)
        if not s.data_ok:
            # fail-static: no decision on a gap, and the gap erases any
            # partial sustain — a burst interrupted by a collector
            # failover must re-prove itself once data is back
            self._hot_since = None
            self._cold_since = None
            return ScaleDecision("hold", s.replicas, "fail-static",
                                 "telemetry stale or absent")
        if s.replicas < self.min_replicas:
            # band repair is not telemetry-driven: the floor holds even
            # through cooldowns (but NOT through a data gap, above —
            # the fleet size came from the router, the go-ahead to act
            # is still a live control loop's)
            return ScaleDecision("up", self.min_replicas, "below-band",
                                 f"{s.replicas} < min {self.min_replicas}")
        hot = self._hot(s)
        cold = self._cold(s)
        if hot is not None:
            self._hot_since = now if self._hot_since is None \
                else self._hot_since
        else:
            self._hot_since = None
        if cold:
            self._cold_since = now if self._cold_since is None \
                else self._cold_since
        else:
            self._cold_since = None

        sustained = (self._hot_since is not None
                     and now - self._hot_since >= self.up_window_s)
        if s.alert_transition or sustained:
            reason = "alert-transition" if s.alert_transition \
                else "trend-sustained"
            detail = hot or ""
            if s.replicas >= self.max_replicas:
                return ScaleDecision("hold", s.replicas, "at-max", detail)
            if now - self._last_up_at < self.up_cooldown_s:
                return ScaleDecision("hold", s.replicas, "up-cooldown",
                                     reason)
            self._last_up_at = now
            self._hot_since = None   # a fresh burst must re-sustain
            target = min(s.replicas + self.step, self.max_replicas)
            return ScaleDecision("up", target, reason, detail)

        if self._cold_since is not None and \
                now - self._cold_since >= self.down_window_s:
            if s.replicas <= self.min_replicas:
                return ScaleDecision("hold", s.replicas, "at-min")
            if now - self._last_down_at < self.down_cooldown_s:
                return ScaleDecision("hold", s.replicas, "down-cooldown")
            if now - self._last_retire_at < self.flap_guard_s:
                return ScaleDecision("hold", s.replicas, "anti-flap",
                                     "a replica retired "
                                     f"{now - self._last_retire_at:.1f}s ago")
            target = max(s.replicas - self.step, self.min_replicas)
            if s.alert_firing and target < self.quorum:
                return ScaleDecision("hold", s.replicas, "quorum-floor",
                                     f"alert firing, quorum {self.quorum}")
            self._last_down_at = now
            return ScaleDecision("down", target, "trend-cold")

        return ScaleDecision("hold", s.replicas, "steady")


# -- complete-bucket guard ----------------------------------------------------


def complete_buckets(series_points: Sequence[Sequence[float]], step: float,
                     to: float) -> List[Tuple[float, float]]:
    """Drop the trailing PARTIAL downsample bucket from one series'
    ``/query`` points. Buckets carry last-sample-per-bucket values
    stamped at the bucket START (``telemetry.store.downsample``); a
    bucket whose span ``[t, t + step)`` extends past the query's
    ``to`` has only seen part of its window and systematically
    under-represents it — the autoscaler must never act on it.
    ``step <= 0`` (raw points) passes everything at/before ``to``."""
    if step <= 0:
        return [(float(t), float(v)) for t, v in series_points
                if t <= to]
    return [(float(t), float(v)) for t, v in series_points
            if t + step <= to]


# -- collector readers --------------------------------------------------------


class HttpCollectorReader:
    """The autoscaler's read client for a collector's HTTP endpoints
    (``/query``, ``/alerts``), with the same comma-separated failover
    discipline as the shipper's push side: reads stick to the first
    URL that answers and rotate on error — a killed primary fails the
    read over to the standby, whose stale pre-promotion store then
    reads as a data gap (fail-static) until promotion catches it
    up."""

    def __init__(self, urls, timeout: float = 3.0):
        if isinstance(urls, str):
            urls = [u.strip() for u in urls.split(",") if u.strip()]
        self.urls = [u.rstrip("/") for u in urls]
        if not self.urls:
            raise ValueError("HttpCollectorReader needs at least one URL")
        self.timeout = float(timeout)
        self._i = 0   # guarded-by: GIL (single int slot; loop-thread only)

    def _get(self, path: str) -> Any:
        last: Optional[BaseException] = None
        for k in range(len(self.urls)):
            idx = (self._i + k) % len(self.urls)
            try:
                with urllib.request.urlopen(self.urls[idx] + path,
                                            timeout=self.timeout) as r:
                    out = json.loads(r.read())
                self._i = idx
                return out
            except Exception as e:
                last = e
        raise ConnectionError(
            f"no collector answered {path!r} (tried {self.urls}): "
            f"{type(last).__name__}: {last}")

    def query(self, metric: str, labels: Optional[Dict[str, str]] = None,
              start: float = 0.0, end: Optional[float] = None,
              step: float = 0.0) -> Dict[str, Any]:
        params = {"metric": metric, "from": repr(float(start)),
                  "step": repr(float(step))}
        if end is not None:
            params["to"] = repr(float(end))
        if labels:
            params["labels"] = ",".join(f"{k}={v}"
                                        for k, v in sorted(labels.items()))
        return self._get("/query?" + urllib.parse.urlencode(params))

    def alerts(self) -> Dict[str, Any]:
        return self._get("/alerts")


class LocalCollectorReader:
    """The in-process twin: wrap a live
    :class:`~paddle_tpu.telemetry.collector.TelemetryCollector` (bench
    rows, unit tests) behind the same reader surface."""

    def __init__(self, collector):
        self._col = collector

    def query(self, metric, labels=None, start=0.0, end=None, step=0.0):
        return self._col.query(metric, labels, start=start, end=end,
                               step=step)

    def alerts(self):
        return self._col.alerts_json()


# -- the control loop ---------------------------------------------------------


class Autoscaler:
    """Watch the collector, size the fleet.

    Each tick reads the queue-depth trend (``/query`` over
    ``trend_window_s`` at ``trend_step_s`` buckets, partial trailing
    bucket dropped), the shed-counter rate, and the alert snapshot;
    assembles one :class:`ScaleSignals`; asks the policy; then acts —
    ``router.grow()`` per missing replica on "up",
    ``router.retire(victim, drain=True)`` on "down" (the victim is the
    highest-numbered replica, so a 1→N→1 swing retires in LIFO order).
    A read error or a freshest-sample age beyond ``stale_after_s``
    arrives at the policy as ``data_ok=False`` — the fail-static rule
    does the rest.

    ``start()`` runs the loop on a daemon thread at ``interval``
    seconds; ``tick(now=...)`` runs ONE evaluation synchronously
    (tests, drills). ``alert_rules`` filters which rule names count as
    scale triggers (None = every firing rule)."""

    def __init__(self, router, reader, policy: AutoscalePolicy,
                 interval: float = 0.5,
                 queue_metric: str = "paddle_tpu_serving_queue_depth",
                 shed_metric: str = "paddle_tpu_fleet_shed_total",
                 labels: Optional[Dict[str, str]] = None,
                 trend_window_s: float = 5.0, trend_step_s: float = 0.5,
                 stale_after_s: float = 2.0,
                 alert_rules: Optional[Sequence[str]] = None,
                 retire_timeout: Optional[float] = 60.0):
        self.router = router
        self.reader = reader
        self.policy = policy
        self.interval = float(interval)
        self.queue_metric = queue_metric
        self.shed_metric = shed_metric
        self.labels = dict(labels or {})
        self.trend_window_s = float(trend_window_s)
        self.trend_step_s = float(trend_step_s)
        self.stale_after_s = float(stale_after_s)
        self.alert_rules = None if alert_rules is None else set(alert_rules)
        self.retire_timeout = retire_timeout
        self._last_firing: set = set()   # (rule, key) seen firing last tick
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._counters: Dict[str, float] = {"ticks": 0, "scale_ups": 0,
                                            "scale_downs": 0}
        self._holds: Dict[str, int] = {}       # guarded-by: _lock
        self._last_hold_reason: Optional[str] = None
        from ..telemetry import get_registry
        self.telemetry_inst = get_registry().next_instance("autoscaler")
        self._telemetry_cid = get_registry().add_collector(
            Autoscaler._families, owner=self)

    @property
    def journal(self):
        from ..telemetry import get_journal
        return get_journal()

    # -- signal assembly -----------------------------------------------------

    def _trend_queue(self, now: float) -> Tuple[Optional[float],
                                                Optional[float]]:
        """(fleet queue depth per replica, freshest sample age). Sums
        the newest COMPLETE bucket of every matching series (a retired
        replica's series simply stops producing buckets and drops out
        of the sum)."""
        doc = self.reader.query(
            self.queue_metric, self.labels,
            start=now - self.trend_window_s, end=now,
            step=self.trend_step_s)
        freshest: Optional[float] = None
        total = 0.0
        saw = False
        for series in doc.get("series", ()):
            pts = complete_buckets(series.get("points", ()),
                                   float(doc.get("step", 0.0)),
                                   float(doc.get("to", now)))
            raw = series.get("points", ())
            if raw:
                age = now - float(raw[-1][0])
                freshest = age if freshest is None else min(freshest, age)
            if pts:
                total += pts[-1][1]
                saw = True
        if not saw:
            return None, freshest
        return total / max(1, len(self.router.replica_names)), freshest

    def _trend_shed(self, now: float) -> Optional[float]:
        """Front-door shed rate over the trend window (counter delta /
        time between the window's first and last samples)."""
        doc = self.reader.query(self.shed_metric, self.labels,
                                start=now - self.trend_window_s, end=now,
                                step=0.0)
        rate = None
        for series in doc.get("series", ()):
            pts = series.get("points", ())
            if len(pts) < 2:
                continue
            (t0, v0), (t1, v1) = pts[0], pts[-1]
            if t1 <= t0:
                continue
            dv = v1 - v0
            if dv < 0:
                dv = v1   # counter reset: count from zero
            rate = (rate or 0.0) + dv / (t1 - t0)
        return rate

    def _alert_state(self, commit: bool = True) -> Tuple[bool, bool]:
        """(any relevant alert firing, a new firing edge this tick).

        ``commit=False`` reads without advancing the edge-detection
        baseline.  Used on stale ticks: a collector failover briefly
        serves an empty (or replayed) ``/alerts`` view, and committing
        that view would make the *old* alerts look like a fresh firing
        edge the moment data recovers — a spurious scale-up.  Fail-static
        applies to the alert baseline exactly as it does to trends.
        """
        snap = self.reader.alerts()
        firing = {(a.get("rule"), a.get("key"))
                  for a in snap.get("firing", ())
                  if self.alert_rules is None
                  or a.get("rule") in self.alert_rules}
        transition = bool(firing - self._last_firing)
        if commit:
            self._last_firing = firing
        return bool(firing), transition

    def signals(self, now: Optional[float] = None) -> ScaleSignals:
        """Assemble one tick's :class:`ScaleSignals` from the reader
        (public: the drill asserts on it directly)."""
        now = time.time() if now is None else float(now)
        replicas = len(self.router.replica_names)
        try:
            qpr, age = self._trend_queue(now)
            shed = self._trend_shed(now)
            stale = age is None or age > self.stale_after_s
            alert_firing, alert_transition = self._alert_state(
                commit=not stale)
        except Exception as e:
            _log().debug("autoscaler read failed (fail-static): %s: %s",
                         type(e).__name__, e)
            return ScaleSignals(now=now, replicas=replicas, data_ok=False)
        return ScaleSignals(now=now, replicas=replicas,
                            queue_per_replica=None if stale else qpr,
                            shed_rate=None if stale else shed,
                            alert_firing=alert_firing,
                            alert_transition=alert_transition and not stale,
                            data_ok=not stale)

    # -- acting --------------------------------------------------------------

    def _pick_victim(self) -> str:
        """Highest-numbered replica name (LIFO: the burst capacity
        leaves first; ``r0`` — the seed replica — leaves last)."""
        names = self.router.replica_names

        def rank(n: str):
            digits = "".join(c for c in n if c.isdigit())
            return (int(digits) if digits else -1, n)

        return max(names, key=rank)

    def tick(self, now: Optional[float] = None) -> ScaleDecision:
        """One full evaluate-and-act cycle; returns the decision."""
        sig = self.signals(now)
        dec = self.policy.decide(sig)
        with self._lock:
            self._counters["ticks"] += 1
            if dec.action == "hold":
                self._holds[dec.reason] = self._holds.get(dec.reason, 0) + 1
        if dec.action == "hold":
            # journal only the EDGES: a steady hold every tick would
            # drown the fleet journal
            if dec.reason != self._last_hold_reason:
                self.journal.emit("autoscale.hold", reason=dec.reason,
                                  inst=self.telemetry_inst,
                                  replicas=sig.replicas, detail=dec.detail)
            self._last_hold_reason = dec.reason
            return dec
        self._last_hold_reason = None
        if dec.action == "up":
            for _ in range(dec.target - sig.replicas):
                name = self.router.grow()
                with self._lock:
                    self._counters["scale_ups"] += 1
                self.journal.emit("autoscale.up", replica=name,
                                  inst=self.telemetry_inst,
                                  reason=dec.reason, detail=dec.detail,
                                  replicas=len(self.router.replica_names))
        elif dec.action == "down":
            for _ in range(sig.replicas - dec.target):
                victim = self._pick_victim()
                self.router.retire(victim, drain=True,
                                   timeout=self.retire_timeout)
                self.policy.note_retired(time.time() if now is None
                                         else float(now))
                with self._lock:
                    self._counters["scale_downs"] += 1
                self.journal.emit("autoscale.down", replica=victim,
                                  inst=self.telemetry_inst,
                                  reason=dec.reason,
                                  replicas=len(self.router.replica_names))
        return dec

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="pdtpu-fleet-autoscaler")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:   # the loop must outlive one bad tick
                _log().warning("autoscaler tick failed: %s: %s",
                               type(e).__name__, e)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, 2 * self.interval))
            self._thread = None
        from ..telemetry import get_registry
        get_registry().remove_collector(self._telemetry_cid)

    def __enter__(self) -> "Autoscaler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- observability -------------------------------------------------------

    def counters(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out["holds"] = dict(self._holds)
        return out

    def _families(self):
        from ..telemetry.registry import counter_family, gauge_family

        labels = {"inst": self.telemetry_inst}
        with self._lock:
            c = dict(self._counters)
            holds = dict(self._holds)
        return [
            counter_family("paddle_tpu_autoscaler_ticks_total",
                           "Autoscaler control-loop evaluations",
                           [(labels, c["ticks"])]),
            counter_family("paddle_tpu_autoscaler_scale_ups_total",
                           "Replicas grown by the autoscaler",
                           [(labels, c["scale_ups"])]),
            counter_family("paddle_tpu_autoscaler_scale_downs_total",
                           "Replicas retired by the autoscaler",
                           [(labels, c["scale_downs"])]),
            counter_family("paddle_tpu_autoscaler_holds_total",
                           "Hold decisions, by reason (fail-static = "
                           "paused on stale/absent telemetry)",
                           [({**labels, "reason": r}, v)
                            for r, v in sorted(holds.items())]),
            gauge_family("paddle_tpu_autoscaler_replicas",
                         "Current fleet size as the autoscaler sees it",
                         [(labels, len(self.router.replica_names))]),
        ]


__all__ = ["AutoscalePolicy", "Autoscaler", "HttpCollectorReader",
           "LocalCollectorReader", "ScaleDecision", "ScaleSignals",
           "complete_buckets"]
