"""Opt-in stdlib-only HTTP endpoint: ``GET /metrics`` (Prometheus text
exposition of the registry; ``?format=json`` for the JSON snapshot) +
``GET /healthz`` (JSON readiness).

One :class:`TelemetryServer` serves both a :class:`~paddle_tpu.
telemetry.registry.MetricsRegistry` and a ``health_fn`` — the SAME
class backs ``Trainer.serve_metrics()`` and
``PredictorServer.serve_metrics()``, so a trainer worker and a serving
replica look identical to the scraper. ``/healthz`` returns 200 while
``health_fn()["live"]`` is truthy (or absent) and 503 otherwise — the
shape fleet load-balancer probes expect. No third-party dependency:
``http.server.ThreadingHTTPServer`` on a daemon thread, port 0 picks a
free port (``.port`` reports it).

``extra_routes`` adds endpoints beyond the two built-ins (the
telemetry collector serves ``/alerts``, ``/timeline``, and ``/query``
through it): ``{path: fn(query_string) -> (status, content_type,
body_bytes)}``. ``post_routes`` is the write-side analog — ``{path:
fn(query_string, body_bytes) -> ...}`` — used by the collector's
``POST /rules`` hot-reload door; POST bodies are bounded (1 MiB) so a
runaway client cannot balloon the daemon.

A scraper that disconnects mid-write (curl ^C, a Prometheus timeout)
raises ``BrokenPipeError``/``ConnectionResetError`` on the handler
thread; that is the CLIENT's problem, so it is swallowed and counted
(``paddle_tpu_telemetry_scrape_aborted_total``) instead of spewing a
traceback from the daemon thread.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from .registry import MetricsRegistry, get_registry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# an extra route: fn(query_string) -> (status, content_type, body)
RouteFn = Callable[[str], Tuple[int, str, bytes]]
# a POST route: fn(query_string, body) -> (status, content_type, body)
PostRouteFn = Callable[[str, bytes], Tuple[int, str, bytes]]

MAX_POST_BODY = 1 << 20


def _scrape_aborted() -> None:
    """Count one scrape whose client vanished mid-write. The counter
    lives in the PROCESS registry regardless of which registry/view
    the aborted endpoint was serving — it describes this process's
    endpoint threads, not the scraped data."""
    try:
        get_registry().counter(
            "paddle_tpu_telemetry_scrape_aborted_total",
            "Scrapes aborted by the client disconnecting mid-write").inc()
    except Exception:  # pragma: no cover - counting must never raise
        pass


class TelemetryServer:
    """``/metrics`` + ``/healthz`` (+ ``extra_routes``) over a registry
    (daemon thread)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 extra_routes: Optional[Dict[str, RouteFn]] = None,
                 post_routes: Optional[Dict[str, PostRouteFn]] = None):
        self.registry = registry if registry is not None else get_registry()
        self.health_fn = health_fn
        self.extra_routes = dict(extra_routes or {})
        self.post_routes = dict(post_routes or {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def handle(self):  # noqa: A003 (stdlib handler name)
                # backstop for disconnect-shaped errors OUTSIDE _reply
                # (handle_one_request's final wfile.flush is unguarded
                # upstream): a vanished scraper must not traceback the
                # daemon thread
                try:
                    super().handle()
                except (BrokenPipeError, ConnectionResetError):
                    _scrape_aborted()

            def do_GET(self):  # noqa: N802 (stdlib handler name)
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    try:
                        # ?format=json serves the registry's JSON
                        # snapshot (the bench/flight-dump shape) from
                        # the same endpoint as the Prometheus text
                        if "format=json" in query.split("&"):
                            self._reply(200, "application/json",
                                        outer.registry.render_json().encode())
                        else:
                            body = outer.registry.render_prometheus().encode()
                            self._reply(200, PROMETHEUS_CONTENT_TYPE, body)
                    except Exception as e:
                        self._reply(500, "text/plain; charset=utf-8",
                                    f"scrape failed: {e}\n".encode())
                elif path == "/healthz":
                    try:
                        health = (outer.health_fn() if outer.health_fn
                                  else {"live": True})
                        code = 200 if health.get("live", True) else 503
                        self._reply(code, "application/json",
                                    json.dumps(health, sort_keys=True,
                                               default=repr).encode())
                    except Exception as e:
                        self._reply(503, "application/json",
                                    json.dumps({"live": False,
                                                "error": repr(e)}).encode())
                elif path in outer.extra_routes:
                    try:
                        code, ctype, body = outer.extra_routes[path](query)
                        self._reply(code, ctype, body)
                    except Exception as e:
                        self._reply(500, "text/plain; charset=utf-8",
                                    f"route {path} failed: {e}\n".encode())
                else:
                    self._reply(404, "text/plain; charset=utf-8",
                                b"unknown path (have /metrics, /healthz"
                                + (b", " + ", ".join(
                                    sorted(outer.extra_routes)).encode()
                                   if outer.extra_routes else b"")
                                + b")\n")

            def do_POST(self):  # noqa: N802 (stdlib handler name)
                path, _, query = self.path.partition("?")
                fn = outer.post_routes.get(path)
                if fn is None:
                    self._reply(404, "text/plain; charset=utf-8",
                                b"unknown POST path (have "
                                + ", ".join(
                                    sorted(outer.post_routes)).encode()
                                + b")\n" if outer.post_routes else
                                b"no POST endpoints\n")
                    return
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    length = -1
                if not 0 <= length <= MAX_POST_BODY:
                    self._reply(413, "text/plain; charset=utf-8",
                                f"POST body must declare 0..{MAX_POST_BODY}"
                                " bytes\n".encode())
                    self.close_connection = True
                    return
                try:
                    body = self.rfile.read(length)
                    code, ctype, out = fn(query, body)
                    self._reply(code, ctype, out)
                except (BrokenPipeError, ConnectionResetError):
                    _scrape_aborted()
                    self.close_connection = True
                except Exception as e:
                    self._reply(500, "text/plain; charset=utf-8",
                                f"route {path} failed: {e}\n".encode())

            def _reply(self, code: int, ctype: str, body: bytes):
                # a scraper disconnecting mid-write is routine (curl
                # ^C, scrape timeout): swallow + count, never let it
                # escape the handler as a daemon-thread traceback.
                # Other OSErrors are swallowed too (no traceback) but
                # NOT counted as aborted scrapes — they may be
                # server-side socket problems worth not masking
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    _scrape_aborted()
                    self.close_connection = True
                except OSError:
                    self.close_connection = True

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="pdtpu-telemetry-http")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "TelemetryServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_metrics(registry: Optional[MetricsRegistry] = None,
                  health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                  port: int = 0, host: Optional[str] = None,
                  extra_routes: Optional[Dict[str, RouteFn]] = None,
                  post_routes: Optional[Dict[str, PostRouteFn]] = None
                  ) -> TelemetryServer:
    """Start a :class:`TelemetryServer`; port 0 picks a free port.
    ``host=None`` binds ``PDTPU_BIND_ADDR`` when set (the cross-host
    knob — a scrape endpoint other machines must reach), else
    loopback."""
    import os

    if host is None:
        host = os.environ.get("PDTPU_BIND_ADDR") or "127.0.0.1"
    return TelemetryServer(registry=registry, health_fn=health_fn,
                           port=port, host=host, extra_routes=extra_routes,
                           post_routes=post_routes)


__all__ = ["PROMETHEUS_CONTENT_TYPE", "TelemetryServer", "serve_metrics"]
