"""Attention layers.

The reference has NO attention kernels — attention exists only as
composed ops in models (SURVEY §5 "long-context": e.g. benchmark
machine_translation.py builds dot-product attention from mul/softmax).
Per SURVEY §7 these are new first-class components for the TPU build:
a fused scaled-dot-product core (XLA-fused by default, pallas flash
kernel via ``paddle_tpu.ops.flash_attention`` for long sequences) and a
multi-head layer whose parameter names line up with the tensor-parallel
sharding rules (parallel.sharding.transformer_tp_rules).
"""

from __future__ import annotations


import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..framework import LayerHelper, cast_compute, in_training
from .. import initializer as init
from .nn import dropout as _dropout

from ..ops.attention_scores import scores_mxu as _scores_mxu

NEG_INF = -1e9  # matches the additive-mask convention (finite to stay bf16-safe)


def scaled_dot_product_attention(
    q, k, v,
    attn_mask: Optional[jax.Array] = None,
    causal: bool = False,
    dropout_rate: float = 0.0,
    use_flash: Optional[bool] = None,
):
    """Fused SDPA over [batch, heads, seq, head_dim] tensors.

    ``attn_mask``: additive mask broadcastable to [b, h, sq, sk] (0 keep,
    NEG_INF drop) — the convention fluid models built by hand. ``causal``
    adds the autoregressive mask. Accumulation in fp32 regardless of
    input dtype (MXU-native bf16 inputs stay bf16 on the matmul inputs).
    """
    if use_flash is None:
        use_flash = False
    # the flash kernel has no dropout, but dropout is a no-op outside
    # training — eval/serving traces of a dropout>0 model keep the
    # kernel instead of paying the dense O(s^2) path
    if use_flash and (dropout_rate == 0.0 or not in_training()):
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, attn_mask=attn_mask)

    head_dim = q.shape[-1]
    scale = 1.0 / math.sqrt(head_dim)
    logits = _scores_mxu(q, k, scale)
    if attn_mask is not None:
        logits = logits + attn_mask
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        logits = jnp.where(cm, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0:
        probs = _dropout(probs, dropout_rate, dropout_implementation="upscale_in_train")
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def multi_head_attention(
    queries,
    keys=None,
    values=None,
    num_heads: int = 8,
    d_model: Optional[int] = None,
    attn_mask: Optional[jax.Array] = None,
    causal: bool = False,
    dropout_rate: float = 0.0,
    cache: Optional[dict] = None,
    use_flash: Optional[bool] = None,
    fuse_qkv: bool = False,
    name: Optional[str] = None,
):
    """Multi-head attention over [batch, seq, d_model] inputs.

    Parameter names (q_proj/k_proj/v_proj/out_proj) are chosen to match
    transformer_tp_rules so Megatron-style TP falls out of the rule
    table. ``cache`` enables incremental decoding: pass {'k':..,'v':..,
    'index': step} and the layer updates it functionally (returned as
    second output) — the while-loop decoder analog.

    ``fuse_qkv`` computes the three projections as ONE matmul against a
    [d_in, 3, d_model] weight (self-attention; cross-attention fuses
    K/V into a [d_in, 2, d_model] ``kv_proj``). One MXU pass of
    (b·s, d)×(d, 3d) instead of three (d, d) passes — better systolic
    utilization at small d_model and a third of the weight-load
    traffic. The 3/2 axis is kept explicit (einsum ``bsd,dke->bske``)
    so the tp sharding on the last axis survives the split into q/k/v
    without GSPMD resharding (rules: transformer_tp_rules qkv_proj).
    """
    helper = LayerHelper("mha", name=name)
    self_attn = keys is None
    keys = queries if keys is None else keys
    values = keys if values is None else values
    d_model = d_model or queries.shape[-1]
    head_dim = d_model // num_heads
    dtype = queries.dtype

    def proj(x, pname, out_dim):
        w = helper.create_parameter(f"{pname}/w", (x.shape[-1], out_dim), jnp.float32,
                                    initializer=init.Xavier())
        b = helper.create_parameter(f"{pname}/b", (out_dim,), jnp.float32,
                                    initializer=init.Constant(0.0))
        x, w = cast_compute(x, w)
        return jnp.matmul(x, w) + b.astype(x.dtype)

    def fused_proj(x, pname, n_out):
        # per-sub-projection Xavier fans: variance must match the
        # unfused layout, not the concatenated shape
        w = helper.create_parameter(
            f"{pname}/w", (x.shape[-1], n_out, d_model), jnp.float32,
            initializer=init.Xavier(fan_in=x.shape[-1], fan_out=d_model))
        b = helper.create_parameter(f"{pname}/b", (n_out, d_model), jnp.float32,
                                    initializer=init.Constant(0.0))
        x, w = cast_compute(x, w)
        out = jnp.einsum("bsd,dke->bske", x, w) + b.astype(x.dtype)
        return tuple(out[:, :, i] for i in range(n_out))

    if fuse_qkv and self_attn:
        from ..core.errors import enforce
        enforce(values is queries,
                "fuse_qkv self-attention reads Q/K/V from the same "
                "source; a distinct values tensor would be silently "
                "dropped — pass fuse_qkv=False")
        q, k, v = fused_proj(queries, "qkv_proj", 3)
    elif fuse_qkv:
        # cross-attention: the fused layout needs K and V to read the
        # same source. The call signature decides the param tree
        # (keys=None → qkv_proj; keys given → q_proj+kv_proj), so a
        # distinct values tensor must fail loudly rather than silently
        # fall back to a third parameter layout.
        from ..core.errors import enforce
        enforce(values is keys,
                "fuse_qkv cross-attention requires values to be keys "
                "(or omitted); pass fuse_qkv=False for distinct K/V "
                "sources")
        q = proj(queries, "q_proj", d_model)
        k, v = fused_proj(keys, "kv_proj", 2)
    else:
        q = proj(queries, "q_proj", d_model)
        k = proj(keys, "k_proj", d_model)
        v = proj(values, "v_proj", d_model)

    def split_heads(x):
        b, s, _ = x.shape
        return x.reshape(b, s, num_heads, head_dim).transpose(0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)

    new_cache = None
    if cache is not None:
        idx = cache["index"]
        ck = jax.lax.dynamic_update_slice(cache["k"], k, (0, 0, idx, 0))
        cv = jax.lax.dynamic_update_slice(cache["v"], v, (0, 0, idx, 0))
        k, v = ck, cv
        new_cache = {"k": ck, "v": cv, "index": idx + q.shape[2]}
        # mask out cache positions beyond the current step
        kpos = jnp.arange(ck.shape[2])
        step_mask = jnp.where(kpos[None, None, None, :] <= idx, 0.0, NEG_INF)
        attn_mask = step_mask if attn_mask is None else attn_mask + step_mask
        causal = False

    out = scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, causal=causal,
                                       dropout_rate=dropout_rate, use_flash=use_flash)
    b, h, s, hd = out.shape
    out = out.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
    out = proj(out, "out_proj", d_model)
    if cache is not None:
        return out, new_cache
    return out


def ffn(x, d_inner: int, dropout_rate: float = 0.0, activation: str = "relu",
        name: Optional[str] = None):
    """Position-wise feed-forward with TP-rule-compatible names."""
    from .ops import apply_activation
    helper = LayerHelper("ffn", name=name)
    d_model = x.shape[-1]
    w1 = helper.create_parameter("ffn_in/w", (d_model, d_inner), jnp.float32,
                                 initializer=init.Xavier())
    b1 = helper.create_parameter("ffn_in/b", (d_inner,), jnp.float32,
                                 initializer=init.Constant(0.0))
    w2 = helper.create_parameter("ffn_out/w", (d_inner, d_model), jnp.float32,
                                 initializer=init.Xavier())
    b2 = helper.create_parameter("ffn_out/b", (d_model,), jnp.float32,
                                 initializer=init.Constant(0.0))
    x, w1, w2 = cast_compute(x, w1, w2)
    h = apply_activation(jnp.matmul(x, w1) + b1.astype(x.dtype), activation)
    if dropout_rate:
        h = _dropout(h, dropout_rate, dropout_implementation="upscale_in_train")
    return jnp.matmul(h, w2) + b2.astype(x.dtype)


def positional_encoding(seq_len: int, d_model: int, dtype=jnp.float32):
    """Sinusoidal position table (the position_encoding_init of the
    reference's transformer benchmark model)."""
    pos = jnp.arange(seq_len)[:, None].astype(jnp.float32)
    i = jnp.arange(d_model // 2)[None, :].astype(jnp.float32)
    angle = pos / jnp.power(10000.0, 2 * i / d_model)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=-1)
    return pe.astype(dtype)


def padding_mask(ids, pad_id: int = 0):
    """[b, s] ids -> additive mask [b, 1, 1, s]."""
    m = (ids == pad_id)
    return jnp.where(m, NEG_INF, 0.0)[:, None, None, :]
