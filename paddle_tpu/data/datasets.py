"""Dataset pack.

Analog of python/paddle/dataset/ (mnist, cifar, imdb, uci_housing,
wmt16, movielens… each a reader-creator factory with download+cache).
This environment has zero egress, so each dataset loads from a local
path when present (standard file formats, same as the reference's
cache dir) and otherwise falls back to a **deterministic synthetic
generator** with the real shapes/vocab — keeping every example and
benchmark runnable anywhere. Synthetic mode is clearly marked via
``synthetic=True`` on the reader functions.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

DATA_HOME = os.environ.get("PDTPU_DATA_HOME", os.path.expanduser("~/.cache/paddle_tpu/dataset"))


# ---------------------------------------------------------------------------
# mnist (dataset/mnist.py analog)
# ---------------------------------------------------------------------------


def _mnist_files(split: str):
    base = os.path.join(DATA_HOME, "mnist")
    if split == "train":
        return (os.path.join(base, "train-images-idx3-ubyte.gz"),
                os.path.join(base, "train-labels-idx1-ubyte.gz"))
    return (os.path.join(base, "t10k-images-idx3-ubyte.gz"),
            os.path.join(base, "t10k-labels-idx1-ubyte.gz"))


def _read_idx_images(path):
    with gzip.open(path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    return data.astype(np.float32) / 127.5 - 1.0  # reference normalization


def _read_idx_labels(path):
    with gzip.open(path, "rb") as f:
        _, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)


def _synthetic_classification(n: int, feat_shape: Tuple[int, ...], num_classes: int,
                              centers_seed: int, noise_seed: int,
                              ) -> Iterator[Tuple[np.ndarray, np.int64]]:
    """Separable synthetic data: class-dependent means so models actually
    learn — lets e2e/convergence tests be meaningful without downloads.
    ``centers_seed`` is shared between train/test splits (same underlying
    distribution); ``noise_seed`` differs per split."""
    centers = np.random.RandomState(centers_seed).randn(num_classes, *feat_shape).astype(np.float32)
    rng = np.random.RandomState(noise_seed)
    for i in range(n):
        y = i % num_classes
        x = centers[y] + 0.5 * rng.randn(*feat_shape).astype(np.float32)
        yield x, np.int64(y)


def mnist(split: str = "train", synthetic_size: int = 2048) -> Callable:
    """Reader creator for MNIST: yields (image[784] in [-1,1], label)."""
    imgs_p, lbls_p = _mnist_files(split)
    if os.path.exists(imgs_p) and os.path.exists(lbls_p):
        def reader():
            imgs = _read_idx_images(imgs_p)
            lbls = _read_idx_labels(lbls_p)
            for x, y in zip(imgs, lbls):
                yield x, y
        reader.synthetic = False
        return reader

    def reader():
        yield from _synthetic_classification(synthetic_size, (784,), 10, centers_seed=0,
                                             noise_seed=0 if split == "train" else 1)
    reader.synthetic = True
    return reader


def mnist_train():
    return mnist("train")


def mnist_test():
    return mnist("test")


# ---------------------------------------------------------------------------
# cifar (dataset/cifar.py analog)
# ---------------------------------------------------------------------------


def cifar10(split: str = "train", synthetic_size: int = 1024) -> Callable:
    """Yields (image[3*32*32] float in [0,1], label)."""
    import pickle
    base = os.path.join(DATA_HOME, "cifar-10-batches-py")
    files = ([os.path.join(base, f"data_batch_{i}") for i in range(1, 6)]
             if split == "train" else [os.path.join(base, "test_batch")])
    if all(os.path.exists(f) for f in files):
        def reader():
            for fp in files:
                with open(fp, "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                for x, y in zip(d[b"data"], d[b"labels"]):
                    yield x.astype(np.float32) / 255.0, np.int64(y)
        reader.synthetic = False
        return reader

    def reader():
        yield from _synthetic_classification(synthetic_size, (3 * 32 * 32,), 10, centers_seed=2,
                                             noise_seed=2 if split == "train" else 3)
    reader.synthetic = True
    return reader


# ---------------------------------------------------------------------------
# uci_housing (dataset/uci_housing.py analog)
# ---------------------------------------------------------------------------


def uci_housing(split: str = "train", synthetic_size: int = 404) -> Callable:
    """Yields (features[13], price[1]) — the fit_a_line dataset."""
    path = os.path.join(DATA_HOME, "housing.data")
    if os.path.exists(path):
        data = np.loadtxt(path).astype(np.float32)
        feats = (data[:, :-1] - data[:, :-1].mean(0)) / (data[:, :-1].std(0) + 1e-8)
        n = int(len(data) * 0.8)
        rows = list(range(n)) if split == "train" else list(range(n, len(data)))

        def reader():
            for i in rows:
                yield feats[i], data[i, -1:].astype(np.float32)
        reader.synthetic = False
        return reader

    def reader():
        rng = np.random.RandomState(4 if split == "train" else 5)
        w = rng.randn(13).astype(np.float32)
        for _ in range(synthetic_size):
            x = rng.randn(13).astype(np.float32)
            y = np.array([x @ w + 0.1 * rng.randn()], dtype=np.float32)
            yield x, y
    reader.synthetic = True
    return reader


# ---------------------------------------------------------------------------
# imdb-style text classification (dataset/imdb.py analog)
# ---------------------------------------------------------------------------


def imdb(split: str = "train", vocab_size: int = 5000, seq_len: int = 128,
         synthetic_size: int = 1024) -> Callable:
    """Yields (word_ids[seq_len] int64 padded, label). Synthetic mode
    generates class-correlated token distributions."""

    def reader():
        rng = np.random.RandomState(6 if split == "train" else 7)
        # two class-specific token distributions
        logits = rng.randn(2, vocab_size)
        probs = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
        for i in range(synthetic_size):
            y = i % 2
            length = rng.randint(seq_len // 2, seq_len + 1)
            ids = rng.choice(vocab_size, size=length, p=probs[y])
            padded = np.zeros(seq_len, dtype=np.int64)
            padded[:length] = ids
            yield padded, np.int64(y)
    reader.synthetic = True
    return reader


# ---------------------------------------------------------------------------
# synthetic translation pairs (wmt16 analog) & CTR (DeepFM) data
# ---------------------------------------------------------------------------


def wmt16(split: str = "train", src_vocab: int = 10000, trg_vocab: int = 10000,
          seq_len: int = 64, synthetic_size: int = 512) -> Callable:
    """Yields (src_ids, trg_ids, trg_next_ids) padded to seq_len."""

    def reader():
        rng = np.random.RandomState(8 if split == "train" else 9)
        for _ in range(synthetic_size):
            n = rng.randint(seq_len // 2, seq_len)
            src = np.zeros(seq_len, np.int64)
            src[:n] = rng.randint(3, src_vocab, n)
            trg = np.zeros(seq_len, np.int64)
            trg[0] = 1  # <s>
            trg[1:n] = (src[:n - 1] % (trg_vocab - 3)) + 3  # learnable mapping
            nxt = np.zeros(seq_len, np.int64)
            nxt[:n - 1] = trg[1:n]
            nxt[n - 1] = 2  # </s>
            yield src, trg, nxt
    reader.synthetic = True
    return reader


def ctr(split: str = "train", num_sparse_fields: int = 26, sparse_dim: int = 1000,
        num_dense: int = 13, synthetic_size: int = 4096) -> Callable:
    """Criteo-style CTR data for DeepFM (dist_ctr.py analog):
    (dense[13], sparse_ids[26], label)."""

    def reader():
        # ground-truth weights are split-INDEPENDENT (fixed seed): train
        # and test must follow the same labeling rule or generalization
        # is impossible; only the samples differ per split
        wrng = np.random.RandomState(42)
        w_d = wrng.randn(num_dense).astype(np.float32)
        w_s = wrng.randn(num_sparse_fields, sparse_dim).astype(np.float32) * 0.5
        rng = np.random.RandomState(10 if split == "train" else 11)
        for _ in range(synthetic_size):
            dense = rng.randn(num_dense).astype(np.float32)
            sparse = rng.randint(0, sparse_dim, num_sparse_fields).astype(np.int64)
            score = dense @ w_d + sum(w_s[f, sparse[f]] for f in range(num_sparse_fields))
            y = np.int64(score + 0.5 * rng.randn() > 0)
            yield dense, sparse, y
    reader.synthetic = True
    return reader


def conll05(split: str = "train", vocab_size: int = 5000, num_labels: int = 20,
            seq_len: int = 32, synthetic_size: int = 512) -> Callable:
    """CoNLL-2005 SRL-style data (dataset/conll05.py analog, synthetic-
    backed): (word_ids[t], mark_ids[t], label[t], length). Labels follow
    a learnable word→tag mapping shifted on the predicate span so the
    mark feature carries signal."""

    def reader():
        rng = np.random.RandomState(12 if split == "train" else 13)
        tag_of = rng.randint(0, num_labels, vocab_size)
        for _ in range(synthetic_size):
            n = rng.randint(seq_len // 2, seq_len)
            words = np.zeros(seq_len, np.int64)
            words[:n] = rng.randint(1, vocab_size, n)
            marks = np.zeros(seq_len, np.int64)
            p0 = rng.randint(0, n)
            marks[p0:min(n, p0 + 3)] = 1
            labels = np.zeros(seq_len, np.int64)
            labels[:n] = (tag_of[words[:n]] + marks[:n]) % num_labels
            yield words, marks, labels, np.int64(n)
    reader.synthetic = True
    return reader


def movielens(split: str = "train", num_users: int = 944, num_movies: int = 1683,
              num_categories: int = 18, title_vocab: int = 1000,
              max_categories: int = 4, title_len: int = 6,
              synthetic_size: int = 1024) -> Callable:
    """MovieLens-style data (dataset/movielens.py analog, synthetic-
    backed): (user_id[1], gender_id[1], age_id[1], job_id[1],
    movie_id[1], category_ids[max_cat], title_ids[title_len], score[1]).
    Ratings follow latent user/movie factors so the model can learn."""

    def reader():
        rng = np.random.RandomState(14 if split == "train" else 15)
        uf = rng.randn(num_users, 4).astype(np.float32)
        mf = rng.randn(num_movies, 4).astype(np.float32)
        for _ in range(synthetic_size):
            u = rng.randint(0, num_users)
            m = rng.randint(0, num_movies)
            ncat = rng.randint(1, max_categories + 1)
            cats = np.zeros(max_categories, np.int64)
            cats[:ncat] = rng.randint(1, num_categories, ncat)
            title = np.zeros(title_len, np.int64)
            nt = rng.randint(1, title_len + 1)
            title[:nt] = rng.randint(1, title_vocab, nt)
            raw = float(uf[u] @ mf[m])
            score = np.clip(2.5 + raw, 1.0, 5.0).astype(np.float32)
            yield (np.array([u], np.int64), np.array([rng.randint(0, 2)], np.int64),
                   np.array([rng.randint(0, 7)], np.int64),
                   np.array([rng.randint(0, 21)], np.int64),
                   np.array([m], np.int64), cats, title,
                   np.array([score], np.float32))
    reader.synthetic = True
    return reader


# ---------------------------------------------------------------------------
# cifar100 / flowers / voc2012 (dataset/{cifar,flowers,voc2012}.py analogs)
# ---------------------------------------------------------------------------


def cifar100(split: str = "train", synthetic_size: int = 1024) -> Callable:
    """Yields (image[3*32*32] float in [0,1], fine label 0..99)."""
    def reader():
        for x, y in _synthetic_classification(
                synthetic_size, (3 * 32 * 32,), 100, centers_seed=7,
                noise_seed=20 if split == "train" else 21):
            yield np.clip(0.25 * x + 0.5, 0.0, 1.0), y
    reader.synthetic = True
    return reader


def flowers(split: str = "train", synthetic_size: int = 256,
            image_hw: Tuple[int, int] = (224, 224)) -> Callable:
    """dataset/flowers.py (102-category Oxford flowers): yields
    (image [3*h*w] float in [0,1], label 0..101)."""
    h, w = image_hw
    def reader():
        for x, y in _synthetic_classification(
                synthetic_size, (3 * h * w,), 102, centers_seed=9,
                noise_seed=30 if split == "train" else 31):
            yield np.clip(0.25 * x + 0.5, 0.0, 1.0), y
    reader.synthetic = True
    return reader


def voc2012(split: str = "train", synthetic_size: int = 64,
            image_hw: Tuple[int, int] = (128, 128), num_classes: int = 21) -> Callable:
    """dataset/voc2012.py (segmentation): yields (image [3,h,w] float,
    label mask [h,w] int in [0, 21)). Synthetic masks are class-colored
    rectangles so a segmentation head actually converges."""
    h, w = image_hw

    def reader():
        rng = np.random.RandomState(40 if split == "train" else 41)
        for _ in range(synthetic_size):
            cls = rng.randint(1, num_classes)
            img = rng.rand(3, h, w).astype(np.float32) * 0.2
            mask = np.zeros((h, w), np.int64)
            y0, x0 = rng.randint(0, h // 2), rng.randint(0, w // 2)
            y1, x1 = y0 + h // 3, x0 + w // 3
            mask[y0:y1, x0:x1] = cls
            img[:, y0:y1, x0:x1] += cls / num_classes  # signal correlated w/ class
            yield img, mask
    reader.synthetic = True
    return reader


# ---------------------------------------------------------------------------
# imikolov (PTB LM) / sentiment / wmt14 / mq2007
# ---------------------------------------------------------------------------


class _DataType:
    NGRAM = 1
    SEQ = 2


DataType = _DataType


def imikolov_build_dict(min_word_freq: int = 50, vocab_size: int = 2073) -> dict:
    """imikolov.py build_dict analog — synthetic mode returns the id map
    of the synthetic vocabulary ("w0".."wN", <s>, <e>, <unk>)."""
    words = {f"w{i}": i for i in range(vocab_size - 2)}
    words["<s>"] = vocab_size - 2
    words["<unk>"] = vocab_size - 1
    return words


def imikolov(split: str = "train", word_idx: Optional[dict] = None, n: int = 5,
             data_type: int = DataType.NGRAM, synthetic_size: int = 4096) -> Callable:
    """imikolov.py train/test analog (PTB language model): NGRAM mode
    yields n-tuples of word ids (the word2vec/NPLM input); SEQ mode
    yields (src_seq, trg_seq) shifted pairs. Synthetic text follows a
    deterministic first-order Markov chain so an LM has real structure
    to learn."""
    vocab = len(word_idx) if word_idx else 2073

    def reader():
        rng = np.random.RandomState(50 if split == "train" else 51)
        # sparse Markov transition: each word has 8 likely successors
        succ = np.random.RandomState(52).randint(0, vocab, (vocab, 8))
        for _ in range(synthetic_size):
            length = rng.randint(n, 24)
            sent = [rng.randint(0, vocab)]
            for _ in range(length - 1):
                sent.append(int(succ[sent[-1], rng.randint(0, 8)])
                            if rng.rand() < 0.9 else rng.randint(0, vocab))
            if data_type == DataType.NGRAM:
                if len(sent) >= n:
                    for i in range(n - 1, len(sent)):
                        yield tuple(sent[i - n + 1:i + 1])
            else:
                yield sent[:-1], sent[1:]
    reader.synthetic = True
    return reader


def imikolov_train(word_idx=None, n: int = 5, data_type: int = DataType.NGRAM):
    return imikolov("train", word_idx, n, data_type)


def imikolov_test(word_idx=None, n: int = 5, data_type: int = DataType.NGRAM):
    return imikolov("test", word_idx, n, data_type)


def sentiment(split: str = "train", vocab_size: int = 5147, seq_len: int = 100,
              synthetic_size: int = 1024) -> Callable:
    """dataset/sentiment.py (NLTK movie reviews): yields
    (word-id list, label ∈ {0,1}). Synthetic mode plants
    polarity-correlated token distributions (same scheme as imdb)."""
    def reader():
        rng = np.random.RandomState(60 if split == "train" else 61)
        pos_words = np.arange(0, vocab_size // 2)
        neg_words = np.arange(vocab_size // 2, vocab_size)
        for i in range(synthetic_size):
            y = i % 2
            base = pos_words if y == 1 else neg_words
            length = rng.randint(10, seq_len)
            ids = rng.choice(base, size=length).tolist()
            # 20% noise from the full vocab
            for j in range(length // 5):
                ids[rng.randint(0, length)] = int(rng.randint(0, vocab_size))
            yield ids, np.int64(y)
    reader.synthetic = True
    return reader


def wmt14(split: str = "train", dict_size: int = 30000, seq_len: int = 24,
          synthetic_size: int = 2048) -> Callable:
    """dataset/wmt14.py analog: yields (src_ids, trg_in_ids, trg_next_ids)
    — same contract as wmt16 at the wmt14 30K dict size."""
    reader = wmt16(split, src_vocab=dict_size, trg_vocab=dict_size,
                   seq_len=seq_len, synthetic_size=synthetic_size)
    return reader


def mq2007(split: str = "train", format: str = "pairwise", n_queries: int = 256,
           docs_per_query: int = 8, feat_dim: int = 46) -> Callable:
    """dataset/mq2007.py (LETOR learning-to-rank). Synthetic queries:
    relevance = quantized linear score of the 46-dim features, so
    rankers learn a real signal.
    - pointwise: yields (feature [46], score)
    - pairwise:  yields (d_high [46], d_low [46]) for every ordered pair
    - listwise:  yields (label list, feature list) per query
    """
    def reader():
        rng = np.random.RandomState(70 if split == "train" else 71)
        w = np.random.RandomState(72).randn(feat_dim).astype(np.float32)
        for _ in range(n_queries):
            feats = rng.randn(docs_per_query, feat_dim).astype(np.float32)
            raw = feats @ w
            labels = np.digitize(raw, np.quantile(raw, [0.5, 0.8])).astype(np.float32)
            if format == "pointwise":
                for f, l in zip(feats, labels):
                    yield f, l
            elif format == "pairwise":
                for i in range(docs_per_query):
                    for j in range(docs_per_query):
                        if labels[i] > labels[j]:
                            yield feats[i], feats[j]
            else:
                yield labels.tolist(), [f for f in feats]
    reader.synthetic = True
    return reader
