"""Durable telemetry: segmented, append-only on-disk series store.

Everything the collector holds — per-origin time-series rings, the
fleet-wide journal, EVENTS dedupe high-water marks, alert
firing/pending state — lived only in memory before this module: one
collector restart erased all history, re-armed every ``for_s`` clock,
and left post-mortems with nothing to read. :class:`SegmentStore` is
the write-through log that fixes that, built on the same durability
discipline the checkpoint path proved (``resilience``):

- **Records** are CRC-framed lines (:func:`resilience.frame_record`):
  a torn tail from a ``kill -9`` mid-append or a bit-flipped byte is
  detected per-record and SKIPPED on recovery — counted
  (``paddle_tpu_collector_segments_corrupt_total``), never a crash.
- **Segments** rotate at ``segment_max_bytes``/``segment_max_s``; a
  finished segment is committed by :func:`resilience.seal_segment`
  (fsync + atomic CRC sidecar). Every segment BEGINS with a ``state``
  record (the collector's absolute counters + alert-engine state), so
  recovery from ANY retained suffix of the log reproduces exact
  counter values: absolute baseline from the first state record, then
  per-record increments.
- **Retention** is enforced by time AND bytes: sealed segments whose
  newest record is older than ``retention_s``, or the oldest ones once
  the store exceeds ``retention_bytes``, are deleted wholesale
  (segment granularity — the classic series-store trade). The active
  segment is never deleted.
- **Recovery** (:meth:`recover`) streams every retained record oldest
  → newest through a caller-supplied ``apply(kind, payload)``; the
  collector replays ``snap`` records into fresh ``SeriesStore`` rings,
  ``ev`` records into its journal + dedupe high-water marks, ``retire``
  records drop an origin, and the last ``state`` record restores the
  :class:`~paddle_tpu.telemetry.alerts.AlertEngine` without re-firing.
  A standby collector PROMOTES by exactly this replay
  (``TelemetryCollector.promote``) — the shared-filesystem HA story.
- **Range reads** (:meth:`query`) scan the retained segments for one
  metric's samples in ``[start, end]`` and downsample to ``step``
  buckets (last-sample-per-bucket, gauge semantics) — the
  ``GET /query`` endpoint the autoscaler and post-mortems read, served
  from disk so the answer survives the collector that wrote it.

Record payloads are compact JSON (one object per line), ``k``-tagged::

    {"k": "snap",   "o": origin, "t": t, "f": families_snapshot}
    {"k": "ev",     "o": origin, "t": t, "r": run, "hw": seq, "e": [...]}
    {"k": "retire", "o": origin, "t": t}
    {"k": "state",  "t": t, "engine": ..., "ctrs": ..., "rules": [...]}

Appends are buffered-write + flush (the OS page cache survives process
death; only power loss can lose a flushed-but-unfsynced tail), with
fsync at every seal. The collector's ingest path pays one ``json.dumps``
plus one buffered write per push batch — pinned under the established
<2%-of-a-K=16-dispatch telemetry budget in
``tests/test_telemetry_store.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from .. import resilience

SEGMENT_PREFIX = "segment-"
SEGMENT_SEALED = ".log"
SEGMENT_ACTIVE = ".open"
HEARTBEAT_NAME = "HEARTBEAT"


def _log():
    import logging
    return logging.getLogger("paddle_tpu.telemetry.store")


def _segment_name(index: int, active: bool) -> str:
    return (f"{SEGMENT_PREFIX}{index:08d}"
            f"{SEGMENT_ACTIVE if active else SEGMENT_SEALED}")


def _segment_index(name: str) -> Optional[int]:
    if not name.startswith(SEGMENT_PREFIX):
        return None
    stem, dot, ext = name.rpartition(".")
    if dot + ext not in (SEGMENT_SEALED, SEGMENT_ACTIVE):
        return None
    try:
        return int(stem[len(SEGMENT_PREFIX):])
    except ValueError:
        return None


def downsample(points: List[Tuple[float, float]], start: float,
               step: float) -> List[Tuple[float, float]]:
    """Last-sample-per-bucket downsampling (gauge semantics — counters
    keep their monotonic shape, quantile math happens upstream on
    bucket deltas): bucket ``i`` covers ``[start + i*step, start +
    (i+1)*step)`` and reports its newest sample at the bucket start.
    ``step <= 0`` returns the raw points."""
    if step <= 0 or not points:
        return list(points)
    out: List[Tuple[float, float]] = []
    for t, v in points:  # points arrive time-ordered (log append order)
        bucket = start + int((t - start) // step) * step
        if out and out[-1][0] == bucket:
            out[-1] = (bucket, v)
        else:
            out.append((bucket, v))
    return out


class SegmentStore:
    """One collector's segmented on-disk telemetry log (module
    docstring has the format). Thread-safe: appends, rotation,
    retention, and range reads serialize on one lock; reads of sealed
    segments happen outside it (sealed files are immutable)."""

    def __init__(self, root: str,
                 retention_s: float = 24 * 3600.0,
                 retention_bytes: int = 256 << 20,
                 segment_max_bytes: int = 4 << 20,
                 segment_max_s: float = 600.0,
                 state_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.retention_s = float(retention_s)
        self.retention_bytes = int(retention_bytes)
        self.segment_max_bytes = int(segment_max_bytes)
        self.segment_max_s = float(segment_max_s)
        # state_fn() -> the collector's current "state" payload dict;
        # written as the FIRST record of every new segment so any
        # retained suffix of the log recovers absolute counters
        self.state_fn = state_fn
        self._lock = threading.Lock()
        self._f: Optional[Any] = None
        self._active_index = 0
        self._active_path: Optional[str] = None
        self._active_bytes = 0
        self._active_opened = 0.0
        self._active_first_t: Optional[float] = None
        self._active_last_t: Optional[float] = None
        self._active_records = 0
        # monotonic counters (collector families + bench deltas); the
        # repl_* keys count the STANDBY-side replication ingest (a
        # segment corrupted in flight is rejected and re-requested
        # here — the primary's corrupt_records stays untouched)
        self.counters = {"appends": 0, "bytes": 0, "append_seconds": 0.0,
                         "append_failures": 0, "corrupt_records": 0,
                         "segments_sealed": 0, "segments_deleted": 0,
                         "repl_segments": 0, "repl_bytes": 0,
                         "repl_corrupt": 0}

    # -- layout ---------------------------------------------------------------

    def _scan(self) -> List[Tuple[int, str]]:
        """(index, filename) of every segment on disk, oldest first."""
        out = []
        for name in os.listdir(self.root):
            idx = _segment_index(name)
            if idx is not None:
                out.append((idx, name))
        return sorted(out)

    def segment_paths(self) -> List[str]:
        """Every retained segment, oldest first (the recovery / query /
        ``tools/series_dump.py`` read order)."""
        with self._lock:
            return [os.path.join(self.root, name) for _, name in self._scan()]

    # -- writer liveness (the split-brain fence) ------------------------------

    @property
    def _heartbeat_path(self) -> str:
        return os.path.join(self.root, HEARTBEAT_NAME)

    def touch_heartbeat(self) -> None:
        """The ACTIVE writer stamps this every eval tick (one utime
        syscall). A standby refuses to promote while the stamp is
        fresh — the fence that stops a transient primary stall (one
        slow flush, a GC pause) from creating TWO live writers over
        one shared store_dir."""
        try:
            with open(self._heartbeat_path, "a"):
                pass
            os.utime(self._heartbeat_path, None)
        except OSError:
            pass

    def clear_heartbeat(self) -> None:
        """Graceful shutdown removes the stamp so a standby may take
        over immediately (no takeover wait after a clean close)."""
        try:
            os.remove(self._heartbeat_path)
        except OSError:
            pass

    def heartbeat_age(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the active writer's last stamp, or None when
        no writer ever stamped (first boot / clean shutdown)."""
        try:
            mtime = os.path.getmtime(self._heartbeat_path)
        except OSError:
            return None
        return (time.time() if now is None else now) - mtime

    # -- writes ---------------------------------------------------------------

    def open(self) -> "SegmentStore":
        """Start appending: seal any leftover ``.open`` segment from a
        dead writer (its tail was recovered record-by-record; it is
        final now) and begin a fresh active segment. Called AFTER
        recovery — a standby never opens the log until it promotes."""
        with self._lock:
            if self._f is not None:
                return self
            segs = self._scan()
            for idx, name in segs:
                if name.endswith(SEGMENT_ACTIVE):
                    self._seal_leftover(idx, name)
            last = max((i for i, _ in self._scan()), default=0)
            self._open_segment(last + 1)
        self.touch_heartbeat()
        return self

    def _seal_leftover(self, idx: int, name: str) -> None:
        """A dead writer's active segment: rename to sealed and commit
        a sidecar over whatever survived. A trailing line with no
        newline is THE kill -9 artifact — it is trimmed before sealing
        so validate()/series_dump stay clean for a normal crash (the
        bytes are provably unreadable: no frame, no CRC); mid-file
        corruption is preserved as evidence and keeps flagging."""
        src = os.path.join(self.root, name)
        dst = os.path.join(self.root, _segment_name(idx, active=False))
        if os.path.exists(dst):
            # a complete replicated sealed copy already landed (the
            # standby adopted it while this partial mirror lingered):
            # the partial must never clobber it
            try:
                os.remove(src)
            except OSError:
                pass
            return
        try:
            try:
                with open(src, "r+b") as f:
                    data = f.read()
                    if data and not data.endswith(b"\n"):
                        f.truncate(data.rfind(b"\n") + 1)
            except OSError:
                pass
            os.replace(src, dst)
            resilience.seal_segment(dst, meta=self._span_meta(dst))
        except OSError as e:
            _log().warning("could not seal leftover segment %s: %s", name, e)

    def _span_meta(self, path: str) -> Dict[str, Any]:
        first_t = last_t = None
        records = 0
        for ok, payload in resilience.iter_records(path):
            if not ok:
                continue
            records += 1
            try:
                doc = json.loads(payload)
            except ValueError:
                continue
            t = doc.get("t") if isinstance(doc, dict) else None
            if doc.get("k") != "state" and isinstance(t, (int, float)):
                first_t = t if first_t is None else first_t
                last_t = t
        return {"first_t": first_t, "last_t": last_t, "records": records}

    def _open_segment(self, index: int) -> None:
        self._active_index = index
        self._active_path = os.path.join(self.root,
                                         _segment_name(index, active=True))
        self._f = open(self._active_path, "ab")
        self._active_bytes = self._f.tell()
        self._active_opened = time.monotonic()
        self._active_first_t = self._active_last_t = None
        self._active_records = 0
        if self.state_fn is not None:
            try:
                state = dict(self.state_fn())
                state["k"] = "state"
                state.setdefault("t", time.time())
                self._write_locked(state)
            except Exception as e:  # the log must not kill the collector
                _log().warning("segment state record failed: %s: %s",
                               type(e).__name__, e)

    def _write_locked(self, payload: Dict[str, Any]) -> None:
        data = resilience.frame_record(
            json.dumps(payload, separators=(",", ":"),
                       default=_json_default).encode())
        self._f.write(data)
        self._f.flush()
        self._active_bytes += len(data)
        self._active_records += 1
        t = payload.get("t")
        # the sidecar's first_t/last_t span DATA records only: state
        # records carry the append-time clock, and a segment full of
        # synthetic-timestamp test data must not be pruned (or
        # retention-aged) off the state record's wall clock
        if payload.get("k") != "state" and isinstance(t, (int, float)):
            if self._active_first_t is None:
                self._active_first_t = t
            self._active_last_t = t
        self.counters["appends"] += 1
        self.counters["bytes"] += len(data)

    def append(self, payload: Dict[str, Any]) -> bool:
        """Write-through one record (rotating first if the active
        segment is over its byte/age bound). Returns False — counted,
        logged, never raised — when the disk write fails: the
        collector keeps serving from memory."""
        t0 = time.perf_counter()
        try:
            with self._lock:
                if self._f is None:
                    return False
                if (self._active_bytes >= self.segment_max_bytes or
                        (self._active_records > 0 and
                         time.monotonic() - self._active_opened
                         >= self.segment_max_s)):
                    self._rotate_locked()
                self._write_locked(payload)
            return True
        except (OSError, ValueError, TypeError) as e:
            # counted AND exported (store_append_failures_total): the
            # collector deliberately keeps ACKing pushes it could not
            # persist (memory still serves; availability over
            # durability under disk pressure) — but that trade is only
            # safe if a rate() alert can see the log falling behind
            self.counters["append_failures"] += 1
            _log().warning("telemetry store append failed: %s: %s",
                           type(e).__name__, e)
            return False
        finally:
            self.counters["append_seconds"] += time.perf_counter() - t0

    def _rotate_locked(self) -> None:
        self._f.flush()
        self._f.close()
        sealed = os.path.join(self.root,
                              _segment_name(self._active_index, active=False))
        os.replace(self._active_path, sealed)
        resilience.seal_segment(sealed, meta={
            "first_t": self._active_first_t, "last_t": self._active_last_t,
            "records": self._active_records})
        self.counters["segments_sealed"] += 1
        self._open_segment(self._active_index + 1)

    def rotate(self) -> None:
        """Force a seal+rotate (tests, SIGTERM close path)."""
        with self._lock:
            if self._f is not None:
                self._rotate_locked()

    def close(self) -> None:
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._f.close()
            except OSError:
                pass
            self._f = None

    # -- cross-host replication (the SEGMENTS wire verb's two halves) ---------

    def replication_listing(self) -> Dict[str, Any]:
        """The PRIMARY side of segment replication: every sealed
        segment with its full CRC sidecar doc, plus the active
        segment's name and current flushed size. A standby diffs this
        against its own store and pulls what it lacks
        (:meth:`ingest_sealed` / :meth:`ingest_open_tail`). A segment
        mid-seal (sidecar not committed yet) is omitted — it shows up
        complete on the next cycle."""
        with self._lock:
            segs = self._scan()
            active = (os.path.basename(self._active_path)
                      if self._f is not None else None)
        out: Dict[str, Any] = {"segments": [], "open": None}
        for _, name in segs:
            path = os.path.join(self.root, name)
            if name.endswith(SEGMENT_SEALED):
                try:
                    with open(path + resilience.SEGMENT_META_SUFFIX) as f:
                        meta = json.load(f)
                except (OSError, ValueError):
                    continue
                out["segments"].append({"name": name, "meta": meta})
            elif name == active:
                try:
                    size = os.path.getsize(path)
                except OSError:
                    size = 0
                out["open"] = {"name": name, "size": size}
        return out

    def read_segment(self, name: str, offset: int = 0,
                     limit: Optional[int] = None) -> bytes:
        """Raw bytes of one retained segment (the SEGMENTS fetch form).
        Appends are flush-per-record, so any prefix of the ACTIVE
        segment a reader sees is a valid record stream plus at most
        one torn tail — which is exactly what the standby's mirror
        tolerates."""
        if _segment_index(name) is None:
            raise ValueError(f"not a segment name: {name!r}")
        with open(os.path.join(self.root, name), "rb") as f:
            f.seek(int(offset))
            return f.read() if limit is None else f.read(int(limit))

    def ingest_sealed(self, name: str, data: bytes,
                      meta: Dict[str, Any]) -> bool:
        """The STANDBY side: adopt one replicated sealed segment.
        The bytes are verified against the primary's sidecar (size +
        whole-file CRC32) BEFORE anything touches disk; a mismatch —
        corruption in flight — is counted (``repl_corrupt``) and
        returns False so the caller re-requests, never poisoning the
        local store. Data file and sidecar both commit tmp+rename, so
        a standby killed mid-adopt leaves either nothing or a fully
        valid sealed segment."""
        import zlib

        idx = _segment_index(name)
        if idx is None or not name.endswith(SEGMENT_SEALED):
            return False
        if (len(data) != meta.get("size") or
                zlib.crc32(data) & 0xFFFFFFFF != meta.get("crc32")):
            self.counters["repl_corrupt"] += 1
            _log().warning("replicated segment %s failed its sidecar "
                           "CRC/size check — re-requesting", name)
            return False
        path = os.path.join(self.root, name)
        # the partial .open mirror of the same index (the primary
        # rotated since we started tailing it) is superseded — dropped
        # BEFORE the sealed commit so a kill in between can only cost
        # a refetch, never leave a leftover .open for open()'s
        # leftover-seal to clobber the complete file with
        try:
            os.remove(os.path.join(self.root,
                                   _segment_name(idx, active=True)))
        except OSError:
            pass
        tmp = path + ".part"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            mtmp = path + resilience.SEGMENT_META_SUFFIX + ".part"
            with open(mtmp, "w") as f:
                json.dump(meta, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(mtmp, path + resilience.SEGMENT_META_SUFFIX)
        except OSError as e:
            _log().warning("could not adopt replicated segment %s: %s",
                           name, e)
            return False
        self.counters["repl_segments"] += 1
        self.counters["repl_bytes"] += len(data)
        return True

    def ingest_open_tail(self, name: str, offset: int, data: bytes) -> int:
        """Mirror the primary's ACTIVE segment: append ``data`` iff
        ``offset`` equals the local copy's size (the mirror is always
        an exact byte prefix of the primary's file, so the only
        possible damage is one torn final record — which
        :meth:`open`'s leftover-seal trims at promotion). Returns the
        local size after the call; a caller whose offset was stale
        re-fetches from the returned size."""
        if _segment_index(name) is None or \
                not name.endswith(SEGMENT_ACTIVE):
            raise ValueError(f"not an active segment name: {name!r}")
        path = os.path.join(self.root, name)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if int(offset) != size:
            return size
        try:
            with open(path, "ab") as f:
                f.write(data)
                f.flush()
        except OSError as e:
            _log().warning("could not mirror open segment %s: %s", name, e)
            return size
        self.counters["repl_bytes"] += len(data)
        return size + len(data)

    def mirror_size(self, name: str) -> int:
        """Current local byte size of one segment file (0 when absent)
        — the standby's next open-tail fetch offset."""
        try:
            return os.path.getsize(os.path.join(self.root, name))
        except OSError:
            return 0

    def sealed_names(self) -> set:
        """Locally present sealed segment names (the standby's diff
        base against :meth:`replication_listing`)."""
        with self._lock:
            return {n for _, n in self._scan() if n.endswith(SEGMENT_SEALED)}

    # -- retention ------------------------------------------------------------

    def enforce_retention(self, now: Optional[float] = None) -> List[str]:
        """Delete sealed segments past the time bound, then oldest-first
        past the byte bound. Returns the deleted filenames."""
        now = time.time() if now is None else now
        deleted: List[str] = []
        with self._lock:
            segs = [(i, n) for i, n in self._scan()
                    if n.endswith(SEGMENT_SEALED)]
            sizes: Dict[str, int] = {}
            last_ts: Dict[str, Optional[float]] = {}
            for _, name in segs:
                p = os.path.join(self.root, name)
                try:
                    sizes[name] = os.path.getsize(p)
                except OSError:
                    sizes[name] = 0
                last_ts[name] = None
                try:
                    with open(p + resilience.SEGMENT_META_SUFFIX) as f:
                        last_ts[name] = json.load(f).get("last_t")
                except (OSError, ValueError):
                    pass
            total = sum(sizes.values()) + self._active_bytes
            for _, name in segs:
                too_old = (last_ts[name] is not None and
                           now - last_ts[name] > self.retention_s)
                over_bytes = total > self.retention_bytes
                if not too_old and not over_bytes:
                    if last_ts[name] is None:
                        # unreadable/missing sidecar: age unknowable —
                        # skip THIS segment, but a sweep-ending break
                        # here would wedge time-retention for every
                        # newer segment behind one rotted sidecar
                        continue
                    break  # oldest-first: the first keeper ends the sweep
                p = os.path.join(self.root, name)
                for victim in (p, p + resilience.SEGMENT_META_SUFFIX):
                    try:
                        os.remove(victim)
                    except OSError:
                        pass
                total -= sizes[name]
                deleted.append(name)
                self.counters["segments_deleted"] += 1
        return deleted

    def total_bytes(self) -> int:
        with self._lock:
            total = 0
            for _, name in self._scan():
                try:
                    total += os.path.getsize(os.path.join(self.root, name))
                except OSError:
                    pass
            return total

    # -- reads ----------------------------------------------------------------

    def _iter_payloads(self, paths: Optional[List[str]] = None,
                       count: bool = False) -> Iterator[Dict[str, Any]]:
        """Every intact record's decoded payload, oldest segment first.
        Corrupt records/undecodable payloads are skipped (counted only
        when ``count`` — the RECOVERY pass; a range query re-reading
        the same damaged segment must not re-inflate the counter)."""
        for path in (self.segment_paths() if paths is None else paths):
            try:
                for ok, payload in resilience.iter_records(path):
                    if not ok:
                        if count:
                            self.counters["corrupt_records"] += 1
                            _log().warning(
                                "skipping corrupt record in %s: %s",
                                os.path.basename(path), payload)
                        continue
                    try:
                        doc = json.loads(payload)
                    except ValueError:
                        if count:
                            self.counters["corrupt_records"] += 1
                        continue
                    if isinstance(doc, dict) and "k" in doc:
                        yield doc
            except OSError as e:
                if count:
                    self.counters["corrupt_records"] += 1
                _log().warning("skipping unreadable segment %s: %s",
                               path, e)

    def recover(self, apply: Callable[[str, Dict[str, Any]], None]) -> int:
        """Replay every retained record through ``apply(kind, payload)``
        oldest → newest; returns the number applied. ``apply`` raising
        is counted and skipped — one poisoned record must not erase the
        rest of history."""
        n = 0
        for doc in self._iter_payloads(count=True):
            try:
                apply(doc["k"], doc)
                n += 1
            except Exception as e:
                self.counters["corrupt_records"] += 1
                _log().warning("recovery apply failed for %r record: "
                               "%s: %s", doc.get("k"), type(e).__name__, e)
        return n

    def _segment_overlaps(self, path: str, start: float,
                          end: float) -> bool:
        """Sidecar first_t/last_t prune for sealed segments; the active
        (or sidecar-less) segment always scans."""
        try:
            with open(path + resilience.SEGMENT_META_SUFFIX) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return True
        first_t, last_t = meta.get("first_t"), meta.get("last_t")
        if not isinstance(first_t, (int, float)) or \
                not isinstance(last_t, (int, float)):
            return True
        return first_t <= end and last_t >= start

    def query(self, metric: str, labels: Optional[Dict[str, str]] = None,
              start: float = 0.0, end: Optional[float] = None,
              step: float = 0.0) -> Dict[str, Any]:
        """Range-read one metric's value series (counters/gauges;
        histogram families expose their windowed quantiles through the
        alert engine, not here) from the retained log: every ``snap``
        record in ``[start, end]`` whose sample labels superset-match
        ``labels``, downsampled to ``step``-second buckets
        (last-sample-per-bucket). Deterministic for a fixed log — the
        restart bit-identity contract rides on that."""
        from .registry import _series_key

        labels = dict(labels or {})
        end = time.time() if end is None else end
        series: Dict[str, Dict[str, Any]] = {}
        paths = [p for p in self.segment_paths()
                 if self._segment_overlaps(p, start, end)]
        for doc in self._iter_payloads(paths):
            if doc.get("k") != "snap":
                continue
            t = doc.get("t")
            if not isinstance(t, (int, float)) or not start <= t <= end:
                continue
            fam = (doc.get("f") or {}).get(metric)
            if not isinstance(fam, dict):
                continue
            origin = str(doc.get("o", ""))
            for s in fam.get("samples") or []:
                value = s.get("value")
                if not isinstance(value, (int, float)):
                    continue  # histogram samples have no scalar read here
                slabels = dict(s.get("labels") or {})
                slabels.setdefault("origin", origin)
                if not all(slabels.get(k) == v for k, v in labels.items()):
                    continue
                key = _series_key(metric, slabels)
                ent = series.setdefault(key, {"labels": slabels,
                                              "points": []})
                ent["points"].append((float(t), float(value)))
        out_series = []
        for key in sorted(series):
            ent = series[key]
            pts = downsample(ent["points"], start, step)
            out_series.append({"key": key, "labels": ent["labels"],
                               "points": [[round(t, 6), v]
                                          for t, v in pts]})
        return {"metric": metric, "matchers": labels,
                "from": start, "to": end, "step": step,
                "series": out_series}

    def list_series(self) -> List[Dict[str, Any]]:
        """Every distinct series in the retained log with its sample
        count and time span — ``tools/series_dump.py --list``."""
        seen: Dict[str, Dict[str, Any]] = {}
        from .registry import _series_key

        for doc in self._iter_payloads():
            if doc.get("k") != "snap":
                continue
            t = doc.get("t")
            origin = str(doc.get("o", ""))
            for name, fam in (doc.get("f") or {}).items():
                if not isinstance(fam, dict):
                    continue
                for s in fam.get("samples") or []:
                    slabels = dict(s.get("labels") or {})
                    slabels.setdefault("origin", origin)
                    key = _series_key(str(name), slabels)
                    ent = seen.setdefault(key, {
                        "key": key, "metric": str(name),
                        "type": str(fam.get("type", "untyped")),
                        "samples": 0, "first_t": None, "last_t": None})
                    ent["samples"] += 1
                    if isinstance(t, (int, float)):
                        if ent["first_t"] is None:
                            ent["first_t"] = t
                        ent["last_t"] = t
        return [seen[k] for k in sorted(seen)]

    def validate(self) -> List[str]:
        """CRC sweep of every retained segment: sealed segments against
        their sidecars (whole-file CRC), then every segment
        record-by-record. Returns findings (empty == clean); the
        ``tools/series_dump.py --validate`` body."""
        findings: List[str] = []
        with self._lock:
            segs = self._scan()
            active_idx = self._active_index if self._f is not None else None
        for idx, name in segs:
            path = os.path.join(self.root, name)
            sealed = name.endswith(SEGMENT_SEALED)
            if sealed:
                ok, reason = resilience.check_segment(path)
                if not ok:
                    findings.append(f"{name}: {reason}")
            bad = []
            try:
                records = list(resilience.iter_records(path))
            except OSError as e:
                findings.append(f"{name}: unreadable: {e}")
                continue
            for i, (ok, payload) in enumerate(records):
                if not ok:
                    # the ACTIVE segment's final torn line is the
                    # normal kill -9 artifact, not bitrot
                    if (not sealed and idx == active_idx
                            and i == len(records) - 1
                            and "torn tail" in str(payload)):
                        continue
                    bad.append((i, payload))
            for i, reason in bad:
                findings.append(f"{name}: record {i}: {reason}")
        return findings


def _json_default(o):
    from .journal import _json_default as jd
    return jd(o)


__all__ = ["SegmentStore", "downsample"]
