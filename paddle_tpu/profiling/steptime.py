"""Step-time breakdown: per-dispatch wall time + unified profile report.

PR 4's ``PipelineMetrics`` named the input-pipeline bottleneck; this
module extends that discipline to the compiled step itself. The Trainer
records every ``step``/``run_steps`` dispatch into a :class:`StepTimer`
(two ``perf_counter`` reads and a list append — cheap enough to stay
always-on; the <2% overhead contract is test-pinned), and
``Trainer.profile_report()`` merges the dispatch timeline with
``pipeline_report()`` into one compute / h2d / host-encode / starvation
breakdown, emitted on ``Event.end_epoch``.

Honesty note: dispatches are ASYNC on accelerators — the recorded
per-dispatch wall time is what the *training-loop thread* spent in the
call (submission + any implicit drain when the runtime backpressures on
donated buffers). Over a steady-state run the loop thread is either
inside dispatch calls (device-bound) or starved waiting for input
(input-bound), so the two totals attribute the wall clock end to end;
single-dispatch numbers are a lower bound on device time.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# ring-buffer cap on retained spans: a week-long fit must not grow an
# unbounded list just because profiling is always-on
_MAX_SPANS = 8192


class StepTimer:
    """Per-dispatch wall-time accumulator. WRITES happen on the
    training-loop thread only (no locking needed; the DeviceFeeder
    stages have their own thread-safe PipelineMetrics); the telemetry
    scrape READS cross-thread without the loop thread's cooperation —
    plain int/float reads are monitoring-grade (exact at the next
    quiescent point), and container state is snapshotted under the GIL
    before iteration so a concurrent insert can never tear a scrape.

    ``journal`` (a :class:`paddle_tpu.telemetry.RunJournal`) makes the
    timer the journal's dispatch feed: every recorded dispatch emits a
    ``trainer.dispatch`` event carrying the chunk's span id (minted by
    the DeviceFeeder fill thread, or fresh here) — the training-side
    half of the submit→execution correlation story. One ring append +
    one journal emit per DISPATCH (not per step) keeps the cost inside
    the <2% K=16 budget the tests pin."""

    def __init__(self, journal=None, inst: Optional[str] = None):
        self.journal = journal
        self.inst = inst
        self.reset()

    def reset(self) -> None:
        self.dispatches = 0
        self.steps = 0
        self.dispatch_s = 0.0
        self.by_kind: Dict[str, int] = {}
        self.first_t0: Optional[float] = None
        self.last_t1: Optional[float] = None
        self._spans: deque = deque(maxlen=_MAX_SPANS)

    def record_dispatch(self, t0: float, t1: float, num_steps: int = 1,
                        kind: str = "step", span: Optional[str] = None,
                        base_step: Optional[int] = None) -> None:
        """Record one step()/run_steps() call: ``t0``/``t1`` are
        ``time.perf_counter()`` readings around the dispatch. ``span``
        is the chunk's trace id (one is minted when journaling without
        it); ``base_step`` is the global step the dispatch started at."""
        self.dispatches += 1
        self.steps += num_steps
        self.dispatch_s += t1 - t0
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        if self.first_t0 is None:
            self.first_t0 = t0
        self.last_t1 = t1
        self._spans.append((kind, num_steps, t0, t1))
        if self.journal is not None:
            self.journal.emit(
                "trainer.dispatch",
                span=span if span is not None else self.journal.new_span(),
                dispatch=kind, num_steps=num_steps, base_step=base_step,
                dur_s=round(t1 - t0, 6))

    def telemetry_families(self, inst: Optional[str] = None) -> list:
        """Render the accumulators as registry metric families (the
        trainer's scrape-time collector calls this — zero hot-path
        publication cost)."""
        from ..telemetry.registry import counter_family

        labels = {"inst": inst if inst is not None else (self.inst or "0")}
        # dict(d) is a GIL-atomic snapshot: the scrape thread must not
        # iterate by_kind while the loop thread inserts a new kind
        by_kind = dict(self.by_kind)
        return [
            counter_family(
                "paddle_tpu_trainer_steps_total",
                "Optimizer steps completed by this trainer",
                [(labels, self.steps)]),
            counter_family(
                "paddle_tpu_trainer_dispatches_total",
                "Device dispatches (step / fused run_steps launches)",
                [({**labels, "kind": k}, v)
                 for k, v in sorted(by_kind.items())]),
            counter_family(
                "paddle_tpu_trainer_dispatch_seconds_total",
                "Training-loop thread seconds spent inside dispatch calls",
                [(labels, round(self.dispatch_s, 6))]),
        ]

    def spans_us(self) -> List[Tuple[str, float, float, int]]:
        """Retained dispatch spans as ``(name, start_us, dur_us, tid)``
        tuples — the shape ``core.profiler.timeline`` consumes."""
        return [(f"trainer.{kind}[{n}]", t0 * 1e6, (t1 - t0) * 1e6, 1)
                for kind, n, t0, t1 in self._spans]

    def report(self) -> Dict[str, Any]:
        span = ((self.last_t1 - self.first_t0)
                if self.first_t0 is not None else 0.0)
        return {
            "steps": self.steps,
            "dispatches": self.dispatches,
            "dispatch_s": round(self.dispatch_s, 6),
            "span_s": round(span, 6),
            "avg_step_ms": (round(self.dispatch_s / self.steps * 1e3, 4)
                            if self.steps else None),
            "avg_dispatch_ms": (round(self.dispatch_s / self.dispatches * 1e3,
                                      4) if self.dispatches else None),
            "spans_retained": len(self._spans),
        }


def profile_report(trainer, fusion: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """The unified step profile: dispatch timing + input-pipeline stage
    attribution + (optionally) a cached fusion table, with a named
    bottleneck. Schema (MIGRATION.md "Profiling & memory advisor"):

    - ``steps`` / ``dispatches`` / ``avg_step_ms`` / ``span_s`` — from
      the per-dispatch :class:`StepTimer`;
    - ``breakdown`` — seconds per attribution bucket: ``compute_s``
      (training-loop thread inside dispatch calls), ``h2d_s`` (the
      EXPOSED transfer time — what the pipeline actually stalled for;
      the staging ring's hidden portion rides separately as
      ``overlap_hidden_s`` and must not crown h2d the bottleneck),
      ``host_encode_s`` (wire encode), ``reader_s`` (host reader
      wait), ``starved_s`` (loop thread waiting for input). With
      prefetch the feeder buckets overlap compute — ``starved_s`` is
      the non-overlapped input-bound signal;
    - ``bottleneck`` — the largest bucket, with ``input_bound`` carried
      from the pipeline report;
    - ``pipeline`` — the full ``pipeline_report()``;
    - ``fusion`` — the top-k fusion table when one has been computed
      (``Trainer.fusion_report``), else None;
    - ``collective`` — static bytes-on-wire attribution of the per-step
      gradient exchange (``Trainer.collective_bytes``: fp32 baseline vs
      the configured quantized wire format, per data axis), or None
      off-mesh.
    """
    st = trainer.step_timer.report()
    pipe = trainer.pipeline_report()
    stages = pipe.get("stages_s", {})
    hidden = pipe.get("overlap_hidden_s", 0.0)
    breakdown = {
        "compute_s": st["dispatch_s"],
        "h2d_s": max(0.0, stages.get("h2d", 0.0) - hidden),
        "host_encode_s": stages.get("encode", 0.0),
        "reader_s": stages.get("reader", 0.0),
        "starved_s": pipe.get("consumer_starved_s", 0.0),
    }
    bottleneck = (max(breakdown, key=breakdown.get)
                  if any(v > 0 for v in breakdown.values()) else None)
    return {
        **st,
        "breakdown": {k: round(v, 6) for k, v in breakdown.items()},
        "overlap_hidden_s": round(hidden, 6),
        "bottleneck": bottleneck,
        "input_bound": pipe.get("input_bound", False),
        "pipeline": pipe,
        "fusion": fusion,
        "collective": getattr(trainer, "collective_bytes", None),
    }


def export_chrome_trace(trainer, path: str) -> int:
    """Dump the trainer's retained dispatch spans (plus any host spans
    the ``core.profiler`` collected while enabled) as chrome://tracing
    JSON. Returns the number of events written."""
    from ..core import profiler

    return profiler.timeline(path, extra_spans=trainer.step_timer.spans_us())
