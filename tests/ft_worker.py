"""Fault-tolerance e2e worker: drains the C++ master task queue while
checkpointing; can be told to crash mid-task (lease held, work lost
since last checkpoint) to exercise lease-timeout requeue + resume.

Run: ft_worker.py <port> <ckpt_dir> <kill_after_tasks|-1> <worker_id>
Prints: RESUMED step=<s> loss=<x> | DONE <shard> step=<s>
        CKPT step=<s> loss=<x>    | EXIT ok
"""

import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")  # axon boot hook override

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import io as pio, optimizer as opt  # noqa: E402
from paddle_tpu.data.master import MasterClient  # noqa: E402
from paddle_tpu.models import mnist  # noqa: E402


def shard_batches(shard: str, n=2, bs=16):
    seed = int(shard.split("-")[1])
    rng = np.random.RandomState(1000 + seed)
    return [{"image": rng.randn(bs, 784).astype(np.float32),
             "label": rng.randint(0, 10, (bs, 1)).astype(np.int64)}
            for _ in range(n)]


def main():
    port, ckpt_dir, kill_after, worker_id = (
        int(sys.argv[1]), sys.argv[2], int(sys.argv[3]), sys.argv[4])

    probe = {"image": np.random.RandomState(999).randn(16, 784).astype(np.float32),
             "label": np.random.RandomState(999).randint(0, 10, (16, 1)).astype(np.int64)}
    prog = pt.build(mnist.mlp)
    trainer = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss")
    trainer.startup(sample_feed=probe)

    def probe_loss():
        return float(trainer.eval(probe)["loss"])

    # warm up the step/eval compiles BEFORE taking any lease — the first
    # jit compile takes longer than a realistic lease timeout, and a
    # lease must only cover actual work (the Go master's lease assumes
    # task time, not startup time). Runs before the checkpoint load, so
    # restored params/step are untouched.
    trainer.step(trainer._put_feed(shard_batches("shard-0")[0]))
    probe_loss()

    if os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir):
        pio.load_trainer_sharded(ckpt_dir, trainer)
        print(f"RESUMED step={trainer.global_step} loss={probe_loss():.6f}",
              flush=True)

    client = MasterClient(("127.0.0.1", port))
    done_since_start = 0
    idle_deadline = None
    while True:
        t = client.get_task(wait=False)
        if t is None:
            st = client.status()
            if st["todo"] == 0 and st["leased"] == 0:
                break  # queue fully drained
            # leased tasks may still requeue (a peer might have crashed)
            if idle_deadline is None:
                idle_deadline = time.time() + 30
            if time.time() > idle_deadline:
                print("EXIT idle-timeout", flush=True)
                sys.exit(3)
            time.sleep(0.2)
            continue
        idle_deadline = None
        tid, payload = t
        shard = payload.decode()
        for b in shard_batches(shard):
            trainer.step(trainer._put_feed(b))
        if kill_after >= 0 and done_since_start == kill_after:
            # crash mid-task: lease held, steps since last CKPT lost
            os._exit(137)
        client.finish_task(tid)
        done_since_start += 1
        print(f"DONE {shard} step={trainer.global_step}", flush=True)
        pio.save_trainer_sharded(ckpt_dir, trainer, async_save=False)
        print(f"CKPT step={trainer.global_step} loss={probe_loss():.6f}",
              flush=True)
    client.close()
    print("EXIT ok", flush=True)


if __name__ == "__main__":
    main()
