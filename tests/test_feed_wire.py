"""Feed wire formats (data/wire.py) + input-pipeline stage metrics.

Pinned here:
- WireSpec round-trip exactness: bf16 truncation, uint8/int8 affine
  quantization bounds and zero-point math, idempotent encode;
- wire-fed training == fp32-fed training within declared tolerance for
  plain / amp-dynamic-loss-scale / dp-sharded configs, on both the
  single-step and the stacked ``run_steps(k)`` fused path;
- the decode is FUSED into the step program: the lowered HLO of the
  fused K-step program takes uint8 parameters and converts inside, and
  a chunked fit performs exactly one device dispatch per chunk;
- ``fit(feed_wire=...)`` end-to-end incl. resume interplay and the
  ``Event.pipeline`` report;
- PipelineMetrics attribution: a synthetic slow reader names "reader"
  as the bottleneck and the h2d MB/s estimate is populated; a slow
  consumer shows up as dispatch wait instead;
- the ``feed:wire-candidate`` analysis lint;
- the bench ``input_pipeline`` row's >= 3.5x uint8 wire-byte reduction.
"""

import os
import sys
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import paddle_tpu as pt
from paddle_tpu import analysis
from paddle_tpu import optimizer as opt
from paddle_tpu.core.errors import EnforceError
from paddle_tpu.data.feeder import DeviceFeeder, PipelineMetrics, stack_batches
from paddle_tpu.data.wire import (FeedWire, WireSpec, feed_logical_nbytes,
                                  feed_wire_nbytes)
from paddle_tpu.models import mnist
from paddle_tpu.parallel import DistStrategy


def _pixel_feeds(n, bs=16, seed=0):
    """(raw uint8 feeds, logically-identical fp32 feeds)."""
    r = np.random.RandomState(seed)
    raw, logical = [], []
    for _ in range(n):
        img = r.randint(0, 256, (bs, 784)).astype(np.uint8)
        lab = r.randint(0, 10, (bs, 1)).astype(np.int64)
        raw.append({"image": img, "label": lab})
        logical.append({"image": (img.astype(np.float32) - 127.0) / 64.0,
                        "label": lab})
    return raw, logical


IMG_WIRE = {"image": WireSpec.image_uint8()}


def _trainer(feed_wire=None, **kw):
    return pt.Trainer(pt.build(mnist.mlp), opt.SGD(0.1), loss_name="loss",
                      feed_wire=feed_wire, **kw)


def _assert_scopes_match(a, b, rtol=1e-5, atol=1e-6):
    for k in a.params:
        np.testing.assert_allclose(np.asarray(a.params[k]),
                                   np.asarray(b.params[k]),
                                   rtol=rtol, atol=atol, err_msg=k)


# ---------------------------------------------------------------------------
# WireSpec round-trip exactness
# ---------------------------------------------------------------------------


def test_bf16_cast_roundtrip_exact_on_representable_values():
    spec = WireSpec.cast("bfloat16")
    x = np.asarray(jnp.arange(-8, 8, dtype=jnp.bfloat16) * 0.25,
                   dtype=np.float32)  # exactly bf16-representable
    w = spec.encode(x)
    assert w.dtype == np.dtype(jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(spec.decode(jnp.asarray(w)),
                                             np.float32), x)
    # non-representable values truncate exactly like an astype round-trip
    y = np.random.RandomState(0).randn(64).astype(np.float32)
    expect = np.asarray(y.astype(jnp.bfloat16), np.float32)
    np.testing.assert_array_equal(
        np.asarray(spec.decode(spec.encode(y)), np.float32), expect)


def test_uint8_quantize_zero_point_and_bounds():
    spec = WireSpec.quantize("uint8", scale=0.5, zero_point=10.0)
    # grid values round-trip exactly: v = (u - 10) * 0.5
    u = np.arange(0, 256, dtype=np.uint8)
    v = (u.astype(np.float32) - 10.0) * 0.5
    w = spec.encode(v)
    np.testing.assert_array_equal(w, u)
    np.testing.assert_allclose(np.asarray(spec.decode(w)), v)
    # out-of-range values clip to the wire dtype bounds, never wrap
    big = np.asarray([1e9, -1e9], np.float32)
    np.testing.assert_array_equal(spec.encode(big), [255, 0])
    # int8 wire clips at its own signed bounds
    s8 = WireSpec.quantize("int8", scale=1.0, zero_point=0.0)
    np.testing.assert_array_equal(s8.encode(np.asarray([300.0, -300.0])),
                                  [127, -128])


def test_encode_is_idempotent_on_wire_dtype():
    spec = WireSpec.image_uint8()
    raw = np.random.RandomState(0).randint(0, 256, (4, 7)).astype(np.uint8)
    enc = spec.encode(raw)
    assert enc.dtype == np.uint8
    np.testing.assert_array_equal(enc, raw)  # NOT re-quantized
    # double-encode through the FeedWire table is also a no-op
    fw = FeedWire({"x": spec})
    once = fw.encode({"x": (raw.astype(np.float32) - 127.0) / 64.0})
    twice = fw.encode(once)
    np.testing.assert_array_equal(once["x"], twice["x"])


def test_quantize_encode_refuses_nonfinite_input():
    """An integer wire dtype has no NaN/Inf: a corrupt reader batch must
    fail LOUDLY at encode, not be laundered into valid pixels the
    on-device NaN guard can never see. Cast wire formats carry the NaN
    through so the guard still fires for those."""
    spec = WireSpec.image_uint8()
    bad = np.asarray([1.0, np.nan, 3.0], np.float32)
    with pytest.raises(FloatingPointError, match="NaN/Inf"):
        spec.encode(bad)
    with pytest.raises(FloatingPointError, match="NaN/Inf"):
        spec.encode(np.asarray([np.inf], np.float32))
    enc = WireSpec.cast("bfloat16").encode(bad)
    assert np.isnan(np.asarray(enc, np.float32)[1])  # propagated, not hidden


def test_wirespec_validation():
    with pytest.raises(EnforceError, match="integer"):
        WireSpec.quantize("float16")
    with pytest.raises(EnforceError, match="label/id"):
        WireSpec.quantize("uint8", decode_dtype="int32")
    with pytest.raises(EnforceError, match="scale"):
        WireSpec.quantize("uint8", scale=0.0)
    with pytest.raises(EnforceError, match="no-op"):
        WireSpec.cast("float32", "float32")
    with pytest.raises(EnforceError, match="GROWS"):
        WireSpec.cast("float32", "float16")
    with pytest.raises(EnforceError, match="WireSpec"):
        FeedWire({"x": "uint8"})
    with pytest.raises(EnforceError, match="feed_wire"):
        FeedWire.make(["not", "a", "dict"])


def test_byte_helpers_count_wire_vs_logical():
    fw = FeedWire.make(IMG_WIRE)
    raw, logical = _pixel_feeds(1, bs=8)
    for feed in (raw[0], logical[0]):  # arrival dtype must not matter
        assert feed_wire_nbytes(feed, fw) == 8 * 784 * 1 + 8 * 8
        assert feed_logical_nbytes(feed, fw) == 8 * 784 * 4 + 8 * 8
    # no wire table: both count the raw host bytes
    assert feed_wire_nbytes(raw[0]) == feed_logical_nbytes(raw[0])


# ---------------------------------------------------------------------------
# train equivalence: wire-fed == fp32-fed within tolerance
# ---------------------------------------------------------------------------


def test_uint8_wire_training_matches_fp32_plain():
    raw, logical = _pixel_feeds(4)
    t_ref = _trainer()
    t_ref.startup(sample_feed=logical[0])
    ref = [t_ref.step(f) for f in logical]

    t_wire = _trainer(feed_wire=IMG_WIRE)
    t_wire.startup(sample_feed=raw[0])
    got = [t_wire.step(f) for f in raw]

    np.testing.assert_allclose([float(o["loss"]) for o in got],
                               [float(o["loss"]) for o in ref],
                               rtol=1e-6, atol=1e-7)
    _assert_scopes_match(t_ref.scope, t_wire.scope)


def test_bf16_wire_training_matches_fp32_within_tolerance():
    _, logical = _pixel_feeds(4, seed=1)
    t_ref = _trainer()
    t_ref.startup(sample_feed=logical[0])
    ref = [t_ref.step(f) for f in logical]

    t_wire = _trainer(feed_wire={"image": WireSpec.cast("bfloat16")})
    t_wire.startup(sample_feed=logical[0])
    got = [t_wire.step(f) for f in logical]

    # bf16 truncation of the input: ~2-3 decimal digits of mantissa
    np.testing.assert_allclose([float(o["loss"]) for o in got],
                               [float(o["loss"]) for o in ref],
                               rtol=5e-3)
    _assert_scopes_match(t_ref.scope, t_wire.scope, rtol=5e-2, atol=5e-3)


def test_uint8_wire_training_matches_fp32_amp_dynamic_loss_scale():
    raw, logical = _pixel_feeds(4, seed=2)
    strat = lambda: DistStrategy(dynamic_loss_scale=True,
                                 loss_scale_growth_interval=2)
    with pt.amp_guard("bfloat16"):
        t_ref = _trainer(strategy=strat())
        t_ref.startup(sample_feed=logical[0])
        ref = [t_ref.step(f) for f in logical]

        t_wire = _trainer(feed_wire=IMG_WIRE, strategy=strat())
        t_wire.startup(sample_feed=raw[0])
        got = [t_wire.step(f) for f in raw]

    np.testing.assert_allclose([float(o["loss"]) for o in got],
                               [float(o["loss"]) for o in ref],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        [float(o["loss_scale"]) for o in got],
        [float(o["loss_scale"]) for o in ref])
    _assert_scopes_match(t_ref.scope, t_wire.scope, rtol=1e-4, atol=1e-5)


def test_uint8_wire_training_matches_fp32_dp_sharded():
    raw, logical = _pixel_feeds(4, seed=3)
    t_ref = _trainer()
    t_ref.startup(sample_feed=logical[0])
    ref = [t_ref.step(f) for f in logical]

    mesh = pt.make_mesh({"dp": 8})
    t_wire = _trainer(feed_wire=IMG_WIRE, mesh=mesh,
                      sharding_rules=pt.parallel.replicated())
    t_wire.startup(sample_feed=raw[0])
    got = [t_wire.step(f) for f in raw]

    np.testing.assert_allclose([float(o["loss"]) for o in got],
                               [float(o["loss"]) for o in ref],
                               rtol=1e-4, atol=1e-5)
    _assert_scopes_match(t_ref.scope, t_wire.scope, rtol=1e-4, atol=1e-5)
    # the wire array really is sharded from the wire dtype
    dev = t_wire._put_feed(raw[0])
    assert dev["image"].dtype == jnp.uint8
    assert dev["image"].sharding.spec[0] == "dp"


def test_uint8_wire_stacked_run_steps_matches_sequential_fp32():
    raw, logical = _pixel_feeds(4, seed=4)
    t_ref = _trainer()
    t_ref.startup(sample_feed=logical[0])
    ref = [t_ref.step(f) for f in logical]

    t_wire = _trainer(feed_wire=IMG_WIRE)
    t_wire.startup(sample_feed=raw[0])
    outs = t_wire.run_steps(stack_batches(raw))

    assert t_wire.global_step == 4
    np.testing.assert_allclose(np.asarray(outs["loss"]),
                               [float(o["loss"]) for o in ref],
                               rtol=1e-6, atol=1e-7)
    _assert_scopes_match(t_ref.scope, t_wire.scope)


# ---------------------------------------------------------------------------
# fused decode: no extra dispatch, wire dtype on the wire
# ---------------------------------------------------------------------------


def test_decode_is_fused_into_the_step_program():
    """The lowered fused K-step program TAKES uint8 parameters and
    converts them inside — one module, no separate decode program —
    and a chunked fit dispatches exactly once per chunk."""
    raw, _ = _pixel_feeds(4, seed=5)
    tr = _trainer(feed_wire=IMG_WIRE)
    tr.startup(sample_feed=raw[0])
    feed_dev = tr._put_feed(stack_batches(raw), stacked=True)
    assert feed_dev["image"].dtype == jnp.uint8  # wire dtype crossed the link
    ls = getattr(tr.scope, "loss_scale_state", None) or {}
    lowered = tr._multi_step_fn.lower(
        tr.scope.params, tr.scope.opt_state, tr.scope.state,
        jax.random.PRNGKey(0), np.int32(0), feed_dev, ls)
    txt = lowered.as_text()
    assert ("ui8" in txt) or ("u8[" in txt), "uint8 never reached the program"
    assert "convert" in txt  # the on-device decode
    # launch count: one compiled-fn call per chunk, zero extra
    calls = {"multi": 0, "single": 0}
    multi, single = tr._multi_step_fn, tr._step_fn

    def count_multi(*a, **kw):
        calls["multi"] += 1
        return multi(*a, **kw)

    def count_single(*a, **kw):
        calls["single"] += 1
        return single(*a, **kw)

    tr._multi_step_fn, tr._step_fn = count_multi, count_single

    r = np.random.RandomState(9)
    samples = [[(r.randint(0, 256, (784,)).astype(np.uint8),
                 np.asarray([r.randint(0, 10)], np.int64))
                for _ in range(16)] for _ in range(8)]
    pt.fit(tr, lambda: iter(samples), num_epochs=1,
           feed_names=["image", "label"], dtypes=["uint8", "int64"],
           steps_per_dispatch=4, feed_wire=IMG_WIRE)
    assert calls == {"multi": 2, "single": 0}, calls


def test_prestaged_logical_device_feed_is_not_double_decoded():
    """A pre-staged device feed of LOGICAL (already-decoded) values —
    which encode cannot reach, it skips jax.Arrays — must pass through
    the decode untouched, not get dequantized a second time; and a
    dtype that is neither wire nor logical fails loudly at trace time."""
    raw, logical = _pixel_feeds(2, seed=8)
    tr = _trainer(feed_wire=IMG_WIRE)
    tr.startup(sample_feed=raw[0])
    ref = float(tr.step(raw[0])["loss"])

    tr2 = _trainer(feed_wire=IMG_WIRE)
    tr2.startup(sample_feed=raw[0])
    staged = {"image": jax.device_put(logical[0]["image"]),
              "label": jax.device_put(logical[0]["label"])}
    got = float(tr2.step(staged)["loss"])
    assert got == pytest.approx(ref, rel=1e-6)

    spec = WireSpec.image_uint8()
    with pytest.raises(EnforceError, match="decode"):
        spec.decode(np.zeros((2,), np.float16))


def test_check_accepts_plain_dict_feed_wire_with_wire_typed_feed():
    """analysis.check(feed_wire={name: WireSpec}) must map a wire-typed
    sample feed to logical dtypes exactly like a FeedWire — not trace
    uint8 into f32 matmuls and collapse to analysis:trace-failed."""
    raw, _ = _pixel_feeds(1, bs=4)
    rep = analysis.check(pt.build(_normalizing_model), raw[0],
                         feed_wire=dict(IMG_WIRE))
    assert "analysis:trace-failed" not in rep.codes(), rep.render()
    assert not rep.by_code("feed:wire-candidate"), rep.render()


def test_no_retrace_across_wire_chunks():
    raw, _ = _pixel_feeds(6, seed=6)
    tr = _trainer(feed_wire=IMG_WIRE)
    tr.startup(sample_feed=raw[0])
    tr.run_steps(stack_batches(raw[:4]))
    tr.step(raw[4])
    warm = tr._trace_count
    tr.run_steps(stack_batches(raw[:4]))
    tr.step(raw[5])
    assert tr._trace_count == warm


# ---------------------------------------------------------------------------
# fit(feed_wire=...): end-to-end, pipeline event, resume interplay
# ---------------------------------------------------------------------------


def _sample_reader(num_batches, bs=16, seed=0):
    r = np.random.RandomState(seed)
    batches = [[(r.randint(0, 256, (784,)).astype(np.uint8),
                 np.asarray([r.randint(0, 10)], np.int64))
                for _ in range(bs)] for _ in range(num_batches)]

    def f():
        yield from batches
    return f


def test_fit_feed_wire_pipeline_event_and_metrics():
    tr = _trainer(feed_wire=None)  # installed via fit below
    raw, _ = _pixel_feeds(1)
    tr.startup(sample_feed=raw[0])
    events = []
    pt.fit(tr, _sample_reader(8), num_epochs=1,
           feed_names=["image", "label"], dtypes=["uint8", "int64"],
           event_handler=events.append, steps_per_dispatch=4,
           feed_wire=IMG_WIRE)
    assert tr.global_step == 8
    end = [e for e in events if e.kind == "end_epoch"]
    assert len(end) == 1 and isinstance(end[0].pipeline, dict)
    rep = end[0].pipeline
    assert set(rep["stages_s"]) == {"reader", "encode", "stack", "h2d",
                                    "dispatch"}
    assert rep["batches"] == 8 and rep["chunks"] == 2
    # spec-aware accounting: raw-uint8 arrival still reports ~4x saving
    assert rep["wire_reduction"] is not None and rep["wire_reduction"] > 3.0
    assert rep["h2d_bytes"] < rep["logical_bytes"]
    assert tr.pipeline_report()["bottleneck"] in rep["stages_s"]


def test_fit_resume_with_wire_matches_uninterrupted():
    def run(epochs, ckpt_dir=None, resume=False):
        tr = _trainer(feed_wire=IMG_WIRE)
        raw, _ = _pixel_feeds(1)
        tr.startup(sample_feed=raw[0])
        cfg = (pt.CheckpointConfig(ckpt_dir, epoch_interval=1)
               if ckpt_dir else None)
        pt.fit(tr, _sample_reader(6), num_epochs=epochs,
               feed_names=["image", "label"], dtypes=["uint8", "int64"],
               checkpoint_config=cfg, resume=resume, steps_per_dispatch=2)
        return tr

    ref = run(2)
    with tempfile.TemporaryDirectory() as d:
        run(1, ckpt_dir=d)                      # epoch 0, checkpointed
        resumed = run(2, ckpt_dir=d, resume=True)  # continues at epoch 1
    assert resumed.global_step == ref.global_step == 12
    _assert_scopes_match(ref.scope, resumed.scope, rtol=1e-6, atol=1e-7)


def test_set_feed_wire_after_startup_rebuilds():
    raw, logical = _pixel_feeds(2, seed=7)
    tr = _trainer()
    tr.startup(sample_feed=logical[0])
    tr.step(logical[0])
    tr.set_feed_wire(IMG_WIRE)   # rebuilds the step with the decode
    out = tr.step(raw[1])
    assert np.isfinite(float(out["loss"]))
    # same table again: no rebuild (object stays)
    fn = tr._step_fn
    tr.set_feed_wire(dict(IMG_WIRE))
    assert tr._step_fn is fn


# ---------------------------------------------------------------------------
# pipeline metrics: bottleneck attribution
# ---------------------------------------------------------------------------


def test_pipeline_report_slow_reader_names_reader_bottleneck():
    def slow_batches():
        for i in range(6):
            time.sleep(0.03)
            yield {"x": np.full((32, 64), i, np.float32)}

    m = PipelineMetrics()
    f = DeviceFeeder(slow_batches, metrics=m)
    assert sum(1 for _ in f) == 6
    rep = f.pipeline_report()
    assert rep["bottleneck"] == "reader"
    assert rep["input_bound"] is True         # the consumer starved
    assert rep["batches"] == 6 and rep["chunks"] == 6
    assert rep["stages_s"]["reader"] >= 0.15
    assert rep["h2d_mbps"] is not None and rep["h2d_mbps"] > 0
    assert rep["h2d_bytes"] == 6 * 32 * 64 * 4


def test_pipeline_report_slow_consumer_accumulates_dispatch_wait():
    def batches():
        for i in range(6):
            yield {"x": np.full((8,), i, np.float32)}

    m = PipelineMetrics()
    f = DeviceFeeder(batches, metrics=m, capacity=1)
    for _ in f:
        time.sleep(0.03)  # consumer is the bottleneck
    rep = f.pipeline_report()
    assert rep["stages_s"]["dispatch"] > 0.05
    assert rep["input_bound"] is False


def test_encode_runs_on_the_fill_thread():
    main = threading.get_ident()
    seen = []
    fw = FeedWire.make(IMG_WIRE)

    def encode(b):
        seen.append(threading.get_ident())
        return fw.encode(b)

    raw, logical = _pixel_feeds(5)
    f = DeviceFeeder(lambda: iter(logical), encode_fn=encode,
                     metrics=PipelineMetrics(), stack_k=2,
                     logical_nbytes_fn=fw.logical_nbytes)
    items = list(f)
    assert [n for n, _ in items] == [2, 2, 1]
    assert seen and all(t != main for t in seen)
    # encode ran BEFORE stacking: the stacked device array is uint8
    assert np.asarray(items[0][1]["image"]).dtype == np.uint8
    rep = f.pipeline_report()
    assert rep["logical_bytes"] > rep["h2d_bytes"]


# ---------------------------------------------------------------------------
# analysis: feed:wire-candidate lint
# ---------------------------------------------------------------------------


def _normalizing_model(image, label):
    from paddle_tpu.framework import create_parameter
    img = (image - 127.0) / 64.0
    w = create_parameter((784, 10), name="fc/w")
    logits = jnp.matmul(img, w)
    return {"loss": jnp.mean((logits - 0.0) ** 2), "logits": logits}


def test_lint_flags_normalize_only_feed():
    feed = {"image": np.zeros((4, 784), np.float32),
            "label": np.zeros((4, 1), np.int64)}
    rep = analysis.check(pt.build(_normalizing_model), feed)
    hits = rep.by_code("feed:wire-candidate")
    assert [f.where for f in hits] == ["image"], rep.render()
    assert "uint8" in hits[0].message
    assert rep.ok("warning")  # info severity: advisory, not a failure


def test_lint_skips_wired_integer_and_compute_first_feeds():
    # already covered by the trainer's wire table -> not re-suggested
    feed = {"image": np.zeros((4, 784), np.float32),
            "label": np.zeros((4, 1), np.int64)}
    tr = pt.Trainer(pt.build(_normalizing_model), opt.SGD(0.1),
                    loss_name="loss", feed_wire=IMG_WIRE)
    tr.startup(sample_feed=feed)
    rep = analysis.check_trainer(tr, feed)
    assert not rep.by_code("feed:wire-candidate"), rep.render()

    # a feed consumed directly by a matmul is NOT a wire candidate
    def direct(image, label):
        from paddle_tpu.framework import create_parameter
        w = create_parameter((784, 10), name="fc/w")
        return {"loss": jnp.mean(jnp.matmul(image, w) ** 2)}

    rep2 = analysis.check(pt.build(direct), feed)
    assert not rep2.by_code("feed:wire-candidate"), rep2.render()


def test_lint_traces_wire_typed_sample_feed_at_logical_dtype():
    """A wire-typed sample feed (raw uint8 pixels) must not break the
    jaxpr-level lint families: check_trainer maps it to the logical
    dtype exactly as startup does, instead of degrading every rule to
    analysis:trace-failed on a uint8-into-f32 type error."""
    raw, _ = _pixel_feeds(1, bs=4)
    tr = pt.Trainer(pt.build(_normalizing_model), opt.SGD(0.1),
                    loss_name="loss", feed_wire=IMG_WIRE)
    tr.startup(sample_feed=raw[0], lint="error")  # must not raise
    rep = analysis.check_trainer(tr, raw[0])
    assert "analysis:trace-failed" not in rep.codes(), rep.render()
    assert "collective:step-trace-failed" not in rep.codes(), rep.render()
    assert not rep.by_code("feed:wire-candidate")  # wired: not re-suggested


def test_lint_flags_cast_only_feed_as_bf16_candidate():
    def cast_first(image, label):
        from paddle_tpu.framework import create_parameter
        w = create_parameter((784, 10), name="fc/w", dtype="bfloat16")
        h = jnp.matmul(image.astype(jnp.bfloat16), w)
        return {"loss": jnp.mean(h.astype(jnp.float32) ** 2)}

    feed = {"image": np.zeros((4, 784), np.float32),
            "label": np.zeros((4, 1), np.int64)}
    rep = analysis.check(pt.build(cast_first), feed)
    hits = rep.by_code("feed:wire-candidate")
    assert [f.where for f in hits] == ["image"], rep.render()
    assert "bfloat16" in hits[0].message


# ---------------------------------------------------------------------------
# bench: input_pipeline row on CPU
# ---------------------------------------------------------------------------


def test_bench_input_pipeline_reports_wire_reduction():
    import bench

    row = bench.bench_input_pipeline(peak=1e12, batch_size=32, iters=4, k=2)
    assert row["value"] >= 3.5, row  # the acceptance lever
    assert row["unit"].startswith("x wire-byte reduction")
    b = row["feed_wire_bytes_per_step"]
    assert b["fp32"] > b["bf16"] > b["uint8"]
    assert row["feed_logical_bytes_per_step"] == b["fp32"]
    assert set(row["step_time_ms"]) == {f"{v}_k{kk}"
                                        for v in ("fp32", "bf16", "uint8")
                                        for kk in (1, 2)}
    assert all(v > 0 for v in row["step_time_ms"].values())
