"""Dtype table.

Analog of the reference's VarType dtype enum (framework.proto:105) and
float16 support (platform/float16.h). On TPU the preferred compute dtype
is bfloat16 (MXU native); float16 is kept for API parity.
"""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp
import numpy as np

# String name -> jnp dtype. Mirrors fluid's convert_np_dtype_to_dtype_.
_STR_TO_DTYPE = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "bool": jnp.bool_,
}

DTypeLike = Union[str, np.dtype, type]


def convert_dtype(dtype: DTypeLike):
    """Normalize a user dtype spec ('float32', np.float32, jnp.float32)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR_TO_DTYPE:
            raise ValueError(
                f"Unsupported dtype {dtype!r}; expected one of {sorted(_STR_TO_DTYPE)}"
            )
        return jnp.dtype(_STR_TO_DTYPE[dtype])
    return jnp.dtype(dtype)


def is_floating(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.floating)


def is_integer(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.integer)


# Default dtypes. The reference defaults to float32 everywhere; on TPU we
# keep float32 params with optional bfloat16 compute (see core.config).
DEFAULT_DTYPE = jnp.float32
DEFAULT_INT_DTYPE = jnp.int32
