"""RNN encoder-decoder with attention — the book
rnn_encoder_decoder / machine_translation configs (test_machine_
translation.py; GRU encoder + attention decoder, the reference's only
in-tree attention, built from primitive ops)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import layers as L
from ..framework import LayerHelper
from ..layers.rnn import dynamic_gru, gru_cell_step
from .. import initializer as init


def make_model(src_vocab=2000, trg_vocab=2000, emb_dim=128, hidden=256):
    """Program fn: (src_ids [b,s], trg_ids [b,t], labels [b,t],
    src_lengths [b]) -> dict with token-mean CE loss."""

    def seq2seq(src_ids, trg_ids, labels, src_lengths):
        helper = LayerHelper("seq2seq")
        # --- encoder: bi-GRU ---
        src_emb = L.embedding(src_ids, size=[src_vocab, emb_dim])
        fwd = dynamic_gru(src_emb, hidden, sequence_length=src_lengths)
        bwd = dynamic_gru(src_emb, hidden, sequence_length=src_lengths,
                          is_reverse=True)
        enc = jnp.concatenate([fwd, bwd], axis=-1)  # [b, s, 2h]
        src_mask = (jnp.arange(src_ids.shape[1])[None, :]
                    < src_lengths[:, None])  # [b, s]

        # --- decoder: GRU with additive attention over enc ---
        b, t = trg_ids.shape
        trg_emb = L.embedding(trg_ids, size=[trg_vocab, emb_dim])

        w_att_enc = helper.create_parameter("att_enc/w", (2 * hidden, hidden),
                                            jnp.float32, initializer=init.Xavier())
        w_att_dec = helper.create_parameter("att_dec/w", (hidden, hidden),
                                            jnp.float32, initializer=init.Xavier())
        v_att = helper.create_parameter("att_v/w", (hidden, 1), jnp.float32,
                                        initializer=init.Xavier())
        w_x = helper.create_parameter("dec_gru_x/w", (emb_dim + 2 * hidden, 3 * hidden),
                                      jnp.float32, initializer=init.Xavier())
        w_h = helper.create_parameter("dec_gru_h/w", (hidden, 3 * hidden),
                                      jnp.float32, initializer=init.Xavier())
        b_g = helper.create_parameter("dec_gru/b", (3 * hidden,), jnp.float32,
                                      initializer=init.Constant(0.0))
        w_out = helper.create_parameter("dec_out/w", (hidden, trg_vocab), jnp.float32,
                                        initializer=init.Xavier())

        enc_att = jnp.matmul(enc, w_att_enc)  # precompute [b, s, h]

        def step(h, x_t):
            # additive attention
            q = jnp.matmul(h, w_att_dec)[:, None, :]           # [b,1,h]
            e = jnp.matmul(jnp.tanh(enc_att + q), v_att)[..., 0]  # [b,s]
            e = jnp.where(src_mask, e, -1e9)
            a = jax.nn.softmax(e, axis=-1)
            ctx = jnp.einsum("bs,bsd->bd", a, enc)             # [b,2h]
            inp = jnp.concatenate([x_t, ctx], axis=-1)
            x_proj = jnp.matmul(inp, w_x) + b_g
            h_new = gru_cell_step(x_proj, h, w_h)
            return h_new, h_new

        h0 = jnp.tanh(L.fc(jnp.concatenate([fwd[:, -1], bwd[:, 0]], axis=-1),
                           hidden, name="init_state"))
        xs = jnp.swapaxes(trg_emb, 0, 1)
        _, hs = jax.lax.scan(step, h0, xs)
        hs = jnp.swapaxes(hs, 0, 1)  # [b, t, h]
        logits = jnp.matmul(hs, w_out)

        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels.astype(jnp.int32)[..., None],
                                   axis=-1)[..., 0]
        nonpad = (labels != 0).astype(jnp.float32)
        loss = jnp.sum(nll * nonpad) / jnp.maximum(nonpad.sum(), 1.0)
        return {"loss": loss, "logits": logits}

    return seq2seq
