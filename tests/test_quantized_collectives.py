"""Int8-quantized ring all-reduce (parallel.quantized_collectives) —
EQuARX-inspired compressed collective for bandwidth-limited axes.
Numerics vs exact lax.psum on the 8-device CPU mesh + wire evidence
(the traced hops carry int8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.parallel import quantized_pmean, quantized_psum


def _run(fn, per_rank, mesh_axes={"dp": 8}):
    mesh = pt.make_mesh(mesh_axes)
    stacked = jnp.stack(per_rank)  # [p, ...] — one slice per rank
    return jax.shard_map(
        lambda s: fn(s[0], "dp"), mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False)(stacked)


@pytest.mark.slow
def test_exact_when_quantization_grid_is_stable():
    """With identical per-rank inputs on the int8 grid, every partial
    sum k·v re-quantizes to the same int8 code (scale scales with k),
    so the ring is bit-exact — pins that NO error source exists beyond
    quantization itself (indexing/schedule bugs would break equality)."""
    rng = np.random.RandomState(0)
    v = rng.randint(-127, 128, (24,)).astype(np.float32) / 127.0
    v[::3] = 1.0  # every ring chunk's abs-max is exactly 1.0, so each
    # hop's scale is k·1 and k·(m/127)/scale·127 = m: requantization is
    # integer-exact at every step
    per_rank = [v.copy() for _ in range(8)]
    got = np.asarray(_run(quantized_psum, per_rank)).reshape(8, 24)
    want = 8.0 * v
    for r in range(8):  # every rank holds the identical full sum
        np.testing.assert_allclose(got[r], want, rtol=0, atol=1e-6)


@pytest.mark.slow
def test_close_to_exact_psum_on_random_data():
    rng = np.random.RandomState(1)
    per_rank = [rng.randn(1000).astype(np.float32) for _ in range(8)]
    got = np.asarray(_run(quantized_psum, per_rank)).reshape(8, 1000)
    want = np.sum(per_rank, axis=0)
    scale = np.abs(want).max()
    for r in range(8):
        err = np.abs(got[r] - want).max() / scale
        assert err < 0.05, err


@pytest.mark.slow
def test_padding_and_dtype_roundtrip():
    """Sizes not divisible by the ring size pad internally; bf16 in →
    bf16 out."""
    rng = np.random.RandomState(2)
    per_rank = [rng.randn(13).astype(np.float32) for _ in range(8)]
    got = np.asarray(_run(quantized_psum,
                          [p.astype(jnp.bfloat16) for p in per_rank])
                     .astype(np.float32)).reshape(8, 13)
    want = np.sum(per_rank, axis=0)
    assert got.shape[1] == 13
    np.testing.assert_allclose(got[0], want, rtol=0.1, atol=0.1)


@pytest.mark.slow
def test_pmean_averages():
    per_rank = [np.full((8,), float(r), np.float32) for r in range(8)]
    got = np.asarray(_run(quantized_pmean, per_rank)).reshape(8, 8)
    np.testing.assert_allclose(got[0], np.full(8, 3.5), atol=0.05)


def test_hops_carry_int8_on_the_wire():
    """The point of the component: ppermute payloads in the traced
    program are int8 vectors plus f32 SCALAR scales — no f32 vector
    rides the ring."""
    import re

    mesh = pt.make_mesh({"dp": 8})
    x = jnp.zeros((8, 64), jnp.float32)
    jaxpr = str(jax.make_jaxpr(jax.shard_map(
        lambda s: quantized_psum(s[0], "dp"), mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False))(x))
    # output dtype of each ppermute: i8[...] data or f32[] scalar scale
    out_types = re.findall(r"\w+:(\w+\[[\d,]*\]) = ppermute\[", jaxpr)
    assert out_types, jaxpr[:500]
    assert any(t.startswith("i8[") for t in out_types), out_types
    for t in out_types:
        assert t.startswith("i8[") or t == "f32[]", out_types
    # 2(P-1) hops, each one i8 payload + one f32[] scale
    assert len(out_types) == 2 * 7 * 2, out_types


@pytest.mark.slow
def test_all_ranks_bitwise_identical():
    """The all-reduce contract DP replicas rely on: every rank must end
    with the SAME array, bit for bit — including the chunk each rank
    owns (which must store the quantized roundtrip, not its exact f32).

    Deliberately the ONE numeric ring test in the smoke tier (each of
    these costs ~20s of 8-device shard_map compile): bitwise identity
    catches both schedule and divergence regressions, and the cheap
    jaxpr test above pins the wire structure; the remaining numeric
    variants run in the full tier."""
    rng = np.random.RandomState(4)
    per_rank = [rng.randn(96).astype(np.float32) for _ in range(8)]
    got = np.asarray(_run(quantized_psum, per_rank)).reshape(8, 96)
    for r in range(1, 8):
        np.testing.assert_array_equal(got[r], got[0])


def test_degenerate_single_rank():
    x = jnp.arange(5, dtype=jnp.float32)
    # p==1 on an axis of size 1: identity
    mesh1 = pt.make_mesh({"one": 1, "dp": 8})
    out = jax.shard_map(lambda v: quantized_psum(v, "one"), mesh=mesh1,
                        in_specs=P(), out_specs=P(), check_vma=False)(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
