"""Ambient NHWC layout (framework.layout_mode) — the TPU-native conv
layout the benchmarks run. NHWC must compute the same function as the
reference's NCHW for every layer and zoo model: weights stay OIHW (one
checkpoint format), only the activation layout changes.
"""

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.framework import current_layout, layout_mode
from paddle_tpu.models import convnets, resnet, vgg


def _logits_pair(make_fn, img_hw, n=2, classes=5, seed=0):
    """Build the same model NCHW and ambient-NHWC with shared weights;
    return both logits on the same input."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 3, *img_hw).astype(np.float32)
    y = rng.randint(0, classes, (n, 1)).astype(np.int64)
    feed_c = {"image": x, "label": y}
    feed_h = {"image": x.transpose(0, 2, 3, 1), "label": y}

    m_c = pt.build(make_fn())
    with layout_mode("NHWC"):
        m_h = pt.build(make_fn())
    p, s = m_c.init(jax.random.PRNGKey(0), **feed_c)
    p_h, s_h = m_h.init(jax.random.PRNGKey(0), **feed_h)
    assert {k: v.shape for k, v in p.items()} \
        == {k: v.shape for k, v in p_h.items()}, "weight layout must not fork"
    out_c, _ = m_c.apply(p, s, training=False, **feed_c)
    out_h, _ = m_h.apply(p, s_h, training=False, **feed_h)
    return np.asarray(out_c["logits"]), np.asarray(out_h["logits"])


def test_layout_mode_resolution():
    assert current_layout() == "NCHW"
    with layout_mode("NHWC"):
        assert current_layout() == "NHWC"
        assert current_layout("NCHW") == "NCHW"  # explicit wins
        with layout_mode("NCHW"):
            assert current_layout() == "NCHW"
        assert current_layout() == "NHWC"
    assert current_layout() == "NCHW"


def test_program_captures_build_time_layout():
    """The ambient layout at pt.build() time governs LATER traces (init
    runs lazily, outside the with-block)."""
    def net(image):
        h = L.conv2d(image, 4, 3, padding=1, bias_attr=False, name="c")
        return {"y": L.pool2d(h, 2, "max", 2)}

    with layout_mode("NHWC"):
        prog = pt.build(net)
    x = np.random.RandomState(0).randn(2, 8, 8, 3).astype(np.float32)
    params, state = prog.init(jax.random.PRNGKey(0), image=x)  # outside ctx
    out, _ = prog.apply(params, state, image=x)
    assert out["y"].shape == (2, 4, 4, 4)  # NHWC: channels last
    assert params["c/w"].shape == (4, 3, 3, 3)  # weights stay OIHW


def test_conv_pool_bn_nhwc_matches_nchw():
    def net(image, label):
        h = L.conv2d(image, 6, 3, padding=1, bias_attr=False, name="c0")
        h = L.batch_norm(h, act="relu", name="bn")
        h = L.pool2d(h, 2, "avg", 2)
        logits = L.fc(L.to_chw_order(h), 5, name="fc")
        return {"loss": L.mean(L.softmax_with_cross_entropy(logits, label)),
                "logits": logits}

    got_c, got_h = _logits_pair(lambda: net, (8, 8))
    np.testing.assert_allclose(got_h, got_c, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_googlenet_nhwc_matches_nchw():
    """Inception concat must switch to the channel axis under NHWC."""
    got_c, got_h = _logits_pair(lambda: convnets.make_googlenet(class_num=5),
                                (64, 64))
    np.testing.assert_allclose(got_h, got_c, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_se_resnext_nhwc_matches_nchw():
    """SE scale broadcast + shortcut channel check under NHWC."""
    got_c, got_h = _logits_pair(
        lambda: convnets.make_se_resnext(depth=50, class_num=5), (64, 64))
    np.testing.assert_allclose(got_h, got_c, rtol=5e-4, atol=5e-4)


@pytest.mark.slow
def test_alexnet_and_vgg_nhwc_match_nchw():
    got_c, got_h = _logits_pair(lambda: convnets.make_alexnet(class_num=5),
                                (224, 224), n=1)
    np.testing.assert_allclose(got_h, got_c, rtol=2e-4, atol=2e-4)
    got_c, got_h = _logits_pair(lambda: vgg.make_model(depth=16, class_num=5),
                                (32, 32))
    np.testing.assert_allclose(got_h, got_c, rtol=2e-4, atol=2e-4)


def test_nhwc_model_exports_and_serves(tmp_path):
    """save_inference_model of an NHWC-built program: the build-time
    layout must govern the export trace (which runs OUTSIDE the
    layout_mode block), and the AOT Predictor must reproduce the NCHW
    export's outputs on the transposed input."""
    from paddle_tpu import io as pio

    def net(image):
        h = L.conv2d(image, 4, 3, padding=1, bias_attr=False, name="c")
        h = L.batch_norm(h, act="relu", name="bn")
        return {"y": L.fc(L.to_chw_order(h), 3, name="out")}

    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 6, 6).astype(np.float32)

    m_c = pt.build(net)
    with layout_mode("NHWC"):
        m_h = pt.build(net)
    p, s = m_c.init(jax.random.PRNGKey(0), image=x)
    _, s_h = m_h.init(jax.random.PRNGKey(0), image=x.transpose(0, 2, 3, 1))

    d_c, d_h = str(tmp_path / "nchw"), str(tmp_path / "nhwc")
    pio.save_inference_model(d_c, m_c, p, s, {"image": x})
    pio.save_inference_model(d_h, m_h, p, s_h,
                             {"image": x.transpose(0, 2, 3, 1)})
    out_c = pio.load_inference_model(d_c).run({"image": x})
    out_h = pio.load_inference_model(d_h).run(
        {"image": x.transpose(0, 2, 3, 1)})
    np.testing.assert_allclose(np.asarray(out_h["y"]),
                               np.asarray(out_c["y"]), rtol=2e-5, atol=2e-5)
