"""Numeric tests for the composite nets (paddle_tpu/nets.py —
python/paddle/fluid/nets.py analog): each helper against a hand
composition or closed-form reference.
"""

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import layers as L, nets


def _run(fn, **feed):
    prog = pt.build(fn)
    params, state = prog.init(jax.random.PRNGKey(0), **feed)
    out, _ = prog.apply(params, state, training=False, **feed)
    return out, params


def test_glu_closed_form():
    x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
    got = np.asarray(nets.glu(jnp.asarray(x), dim=-1))
    a, b = x[:, :3], x[:, 3:]
    np.testing.assert_allclose(got, a / (1 + np.exp(-b)), rtol=1e-5, atol=1e-6)


def test_simple_img_conv_pool_equals_manual_composition():
    rng = np.random.RandomState(1)
    img = rng.randn(2, 3, 8, 8).astype(np.float32)

    def net(image):
        return {"y": nets.simple_img_conv_pool(image, num_filters=4,
                                               filter_size=3, pool_size=2,
                                               pool_stride=2, act="relu")}

    def manual(image):
        h = L.conv2d(image, 4, 3, act="relu")
        return {"y": L.pool2d(h, pool_size=2, pool_type="max", pool_stride=2)}

    got, p1 = _run(net, image=img)
    want, p2 = _run(manual, image=img)
    assert sorted(v.shape for v in p1.values()) == \
        sorted(v.shape for v in p2.values())
    # same parameter shapes + same init seed => identical outputs
    np.testing.assert_allclose(np.asarray(got["y"]), np.asarray(want["y"]),
                               rtol=1e-5, atol=1e-6)


def test_img_conv_group_shapes_and_bn_branch():
    rng = np.random.RandomState(2)
    img = rng.randn(2, 3, 8, 8).astype(np.float32)

    def net(image):
        return {"y": nets.img_conv_group(image, conv_num_filter=(4, 4),
                                         pool_size=2, pool_stride=2,
                                         conv_with_batchnorm=True)}

    got, params = _run(net, image=img)
    assert got["y"].shape == (2, 4, 4, 4)
    # two convs and two BN scale/bias sets were created
    assert sum("conv2d" in k for k in params) >= 2
    assert sum("batch_norm" in k for k in params) >= 2


def test_sequence_conv_pool_masks_padding():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 5, 6).astype(np.float32)
    lengths = np.array([3, 5], np.int32)

    def net(x, lengths):
        return {"y": nets.sequence_conv_pool(x, lengths, num_filters=4,
                                             filter_size=3, pool_type="max")}

    got, _ = _run(net, x=x, lengths=lengths)
    # poison the part of sequence 0's padded tail that no VALID output
    # position can see: with a width-3 same-pad window, valid positions
    # 0..2 read x[0..3], so x[4] only feeds masked positions 3..4 — a
    # working mask must leave BOTH rows of the pooled output unchanged
    x2 = x.copy()
    x2[0, 4:] = 100.0
    got2, _ = _run(net, x=x2, lengths=lengths)
    np.testing.assert_allclose(np.asarray(got["y"]),
                               np.asarray(got2["y"]), rtol=1e-5)


def test_nets_sdpa_matches_layer_sdpa():
    from paddle_tpu.layers.attention import scaled_dot_product_attention
    rng = np.random.RandomState(4)
    q = rng.randn(2, 5, 8).astype(np.float32)
    k = rng.randn(2, 7, 8).astype(np.float32)
    v = rng.randn(2, 7, 8).astype(np.float32)
    got = np.asarray(nets.scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), num_heads=2))
    qh = q.reshape(2, 5, 2, 4).transpose(0, 2, 1, 3)
    kh = k.reshape(2, 7, 2, 4).transpose(0, 2, 1, 3)
    vh = v.reshape(2, 7, 2, 4).transpose(0, 2, 1, 3)
    want = np.asarray(scaled_dot_product_attention(
        jnp.asarray(qh), jnp.asarray(kh), jnp.asarray(vh)))
    want = want.transpose(0, 2, 1, 3).reshape(2, 5, 8)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
