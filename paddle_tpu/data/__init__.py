"""Data pipeline: reader combinators, datasets, feeders (reference:
python/paddle/reader/, python/paddle/dataset/, fluid data_feeder.py,
operators/reader/*)."""

from . import augment, datasets, device_cache, feeder, image, reader, wire
from .augment import AugmentSpec, FeedAugment
from .device_cache import DeviceCache
from .feeder import DataFeeder, DeviceFeeder, PipelineMetrics
from .reader import (Fake, PipeReader, batch, buffered, cache, chain, compose,
                     fake, firstn, map_readers, multiprocess_reader, shuffle,
                     xmap_readers)
from .wire import FeedWire, WireSpec

__all__ = [
    "augment", "datasets", "device_cache", "feeder", "reader", "wire",
    "DataFeeder", "DeviceFeeder", "PipelineMetrics",
    "FeedWire", "WireSpec", "AugmentSpec", "FeedAugment", "DeviceCache",
    "batch", "buffered", "cache", "chain", "compose", "firstn",
    "map_readers", "shuffle", "xmap_readers",
]
