"""End-to-end MNIST training — the book/test_recognize_digits analog
(SURVEY §4 "book" integration tests): train → eval → save → load →
infer round trip, plus the ParallelExecutor-comparison analog (sharded
vs single-device losses agree)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import data as pdata
from paddle_tpu import io as pio
from paddle_tpu import optimizer as opt
from paddle_tpu.models import mnist as mnist_models


def _feed_iter(batch_size=64, epochs=1):
    reader = pdata.batch(pdata.shuffle(pdata.datasets.mnist("train"), 512, seed=0),
                         batch_size)
    feeder = pdata.DataFeeder(["image", "label"], dtypes=["float32", "int64"])
    for _ in range(epochs):
        for samples in reader():
            feed = feeder.feed(samples)
            feed["label"] = feed["label"][:, None]
            yield feed


def test_mnist_mlp_trains_to_high_accuracy():
    prog = pt.build(mnist_models.mlp)
    trainer = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss")
    sample = next(_feed_iter())
    trainer.startup(sample_feed=sample)
    losses = []
    for feed in _feed_iter(epochs=3):
        out = trainer.step(feed)
        losses.append(float(out["loss"]))
    assert losses[-1] < losses[0] * 0.5, f"loss did not drop: {losses[0]} -> {losses[-1]}"
    # eval on held-out synthetic test split
    test_feed = None
    reader = pdata.batch(pdata.datasets.mnist("test"), 256)
    feeder = pdata.DataFeeder(["image", "label"], dtypes=["float32", "int64"])
    accs = []
    for samples in reader():
        feed = feeder.feed(samples)
        feed["label"] = feed["label"][:, None]
        out = trainer.eval(feed)
        accs.append(float(out["acc"]))
        test_feed = feed
    assert np.mean(accs) > 0.9, f"test acc too low: {np.mean(accs)}"

    # save → load → infer round trip (book test pattern)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        pio.save_trainer(d, trainer)
        trainer2 = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss")
        trainer2.startup(sample_feed=sample)
        pio.load_trainer(d, trainer2)
        assert trainer2.global_step == trainer.global_step
        out1 = trainer.eval(test_feed)
        out2 = trainer2.eval(test_feed)
        np.testing.assert_allclose(np.asarray(out1["logits"]), np.asarray(out2["logits"]),
                                   rtol=1e-5, atol=1e-5)


def test_mnist_conv_net_one_step():
    prog = pt.build(mnist_models.conv_net)
    trainer = pt.Trainer(prog, opt.Momentum(0.01, 0.9), loss_name="loss")
    sample = next(_feed_iter(batch_size=16))
    trainer.startup(sample_feed=sample)
    out0 = trainer.step(sample)
    out1 = trainer.step(sample)
    assert float(out1["loss"]) < float(out0["loss"])


def test_executor_forward_fetch():
    prog = pt.build(mnist_models.mlp)
    exe = pt.Executor(pt.CPUPlace())
    sample = next(_feed_iter(batch_size=8))
    exe.startup(prog, None, **{k: v for k, v in sample.items()})
    loss, acc = exe.run(prog, feed=sample, fetch_list=["loss", "acc"])
    assert np.isfinite(loss)
    assert 0.0 <= float(acc) <= 1.0


def test_sharded_dp_matches_single_device():
    """ParallelExecutor-vs-Executor loss equivalence analog
    (test_parallel_executor_* pattern, SURVEY §4): same data, same init →
    same loss trajectory on an 8-way dp mesh vs single device."""
    import jax
    prog = pt.build(mnist_models.mlp)
    sample = next(_feed_iter(batch_size=64))

    t1 = pt.Trainer(prog, opt.SGD(0.1), loss_name="loss")
    t1.startup(rng=jax.random.PRNGKey(3), sample_feed=sample)

    mesh = pt.make_mesh({"dp": 8})
    t2 = pt.Trainer(prog, opt.SGD(0.1), loss_name="loss", mesh=mesh,
                    sharding_rules=pt.parallel.replicated())
    t2.startup(rng=jax.random.PRNGKey(3), sample_feed=sample)

    for i, feed in enumerate(_feed_iter(batch_size=64)):
        o1 = t1.step(feed, rng=jax.random.PRNGKey(100 + i))
        o2 = t2.step(feed, rng=jax.random.PRNGKey(100 + i))
        np.testing.assert_allclose(float(o1["loss"]), float(o2["loss"]), rtol=2e-4,
                                   err_msg=f"diverged at step {i}")
        if i >= 4:
            break


def test_gradient_accumulation_matches_large_batch():
    """multi_batch_merge_pass analog: accum_steps=4 on bs=64 ==
    one step on the same 64 samples."""
    import jax
    prog = pt.build(mnist_models.mlp)
    sample = next(_feed_iter(batch_size=64))

    t_plain = pt.Trainer(prog, opt.SGD(0.1), loss_name="loss")
    t_plain.startup(rng=jax.random.PRNGKey(5), sample_feed=sample)
    t_acc = pt.Trainer(prog, opt.SGD(0.1), loss_name="loss",
                       strategy=pt.DistStrategy(accum_steps=4))
    t_acc.startup(rng=jax.random.PRNGKey(5), sample_feed=sample)

    o1 = t_plain.step(sample, rng=jax.random.PRNGKey(0))
    o2 = t_acc.step(sample, rng=jax.random.PRNGKey(0))
    p1 = t_plain.scope.params["fc_2/w"]
    p2 = t_acc.scope.params["fc_2/w"]
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-4, atol=1e-5)
