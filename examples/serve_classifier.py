"""Export a trained classifier and serve it — the deployment half of
the workflow (examples/train_gpt.py is the training half).

    python examples/serve_classifier.py            # fp32 serving
    python examples/serve_classifier.py --int8     # real int8 datapath
    python examples/serve_classifier.py --threads 4

Trains a small MLP classifier briefly, exports it with
save_inference_model (StableHLO), loads the AOT-compiled Predictor, and
serves from N threads (one Clone per thread — the reference's
PaddlePredictor::Clone contract), reporting throughput and tail
latency.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def batches(rng, n=64):
    img = rng.randn(n, 784).astype(np.float32)
    lbl = img[:, :780].reshape(n, 10, 78)[:, :, :4].sum(-1).argmax(1)
    return {"image": img, "label": lbl.reshape(n, 1).astype(np.int64)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--train_steps", type=int, default=30)
    p.add_argument("--calls", type=int, default=40, help="serve calls/thread")
    p.add_argument("--threads", type=int, default=2)
    p.add_argument("--int8", action="store_true",
                   help="trace the real int8 datapath into the export")
    args = p.parse_args()

    import contextlib

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import paddle_tpu as pt
    from paddle_tpu import io, optimizer as opt, quantize
    from paddle_tpu.models import mnist

    # 1. train on a stream of fresh batches (the label is a
    # deterministic function of the image, so the model generalizes)
    rng = np.random.RandomState(0)
    prog = pt.build(mnist.mlp)
    tr = pt.Trainer(prog, opt.Adam(2e-3), loss_name="loss",
                    fetch_list=["loss", "acc"])
    tr.startup(sample_feed=batches(rng))
    for s in range(args.train_steps):
        out = tr.step(batches(rng))
    print(f"trained {args.train_steps} steps: "
          f"loss {float(out['loss']):.3f} acc {float(out['acc']):.2f}")

    # 2. export (int8: quantization ops are baked into the program)
    mode = quantize.int8_serving() if args.int8 else contextlib.nullcontext()
    d = tempfile.mkdtemp()
    with mode:
        io.save_inference_model(d, prog, tr.scope.params, tr.scope.state,
                                batches(rng))
    pred = io.load_inference_model(d)  # AOT-compiled at load
    print(f"exported to {d} ({'int8' if args.int8 else 'fp32'} datapath)")

    # 3. serve: one Clone per thread
    lat_by_thread = []

    def worker(predictor, seed):
        lats = []
        feed = batches(np.random.RandomState(1000 + seed))  # per-thread data
        for _ in range(args.calls):
            t0 = time.perf_counter()
            out = predictor.run(feed)
            np.asarray(out["logits"])  # force sync
            lats.append(time.perf_counter() - t0)
        lat_by_thread.append(lats)

    threads = [threading.Thread(target=worker, args=(pred.clone(), i))
               for i in range(args.threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats = np.array(sum(lat_by_thread, []))
    total = args.threads * args.calls * 64
    print(f"{args.threads} threads x {args.calls} calls (bs=64): "
          f"{total / wall:.0f} samples/sec, "
          f"p50 {np.percentile(lats, 50) * 1e3:.1f} ms, "
          f"p99 {np.percentile(lats, 99) * 1e3:.1f} ms")
    # the served model must actually classify the learnable task
    feed = batches(np.random.RandomState(7))
    acc = float((np.asarray(pred.run(feed)["logits"]).argmax(-1)
                 == feed["label"][:, 0]).mean())
    print(f"served accuracy on the synthetic task: {acc:.2f}")
    return acc


if __name__ == "__main__":
    main()
