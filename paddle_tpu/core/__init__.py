from . import config, dtypes, errors, place, profiler, unique_name
from .errors import EnforceError, NotFoundError, ShapeError, enforce
from .place import CPUPlace, CUDAPlace, Place, TPUPlace, default_place, device_count

__all__ = [
    "config", "dtypes", "errors", "place", "profiler", "unique_name",
    "EnforceError", "NotFoundError", "ShapeError", "enforce",
    "CPUPlace", "CUDAPlace", "Place", "TPUPlace", "default_place", "device_count",
]
