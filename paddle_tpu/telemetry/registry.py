"""Process-wide metrics registry: counters, gauges, log-bucket
histograms with labels, plus pull-time *collectors*.

Every subsystem publishes into one registry under one naming
convention — ``paddle_tpu_<subsystem>_<name>{labels}`` — and two
exporters read it: Prometheus text exposition (:meth:`MetricsRegistry.
render_prometheus`, what the ``/metrics`` endpoint serves) and JSON
(:meth:`MetricsRegistry.render_json`, what bench rows and flight-dump
meta embed). :meth:`MetricsRegistry.validate` is the CI contract: a
metric violating the convention (bad name, missing help, counter
without ``_total``, duplicate series) is a named violation, not a
silently-odd scrape.

Two publication styles, chosen by cost:

- **Direct metrics** (:class:`Counter`/:class:`Gauge`/
  :class:`Histogram`) for event-shaped facts with no retained state
  (checkpoints written, preemptions). ``inc``/``set``/``observe`` take
  one small lock — fine on cold paths.
- **Collectors** (:meth:`MetricsRegistry.add_collector`) for
  subsystems that already keep thread-safe accumulators
  (``StepTimer``, ``PipelineMetrics``, ``ServingMetrics``, PS client
  counters): a callback renders their CURRENT state into metric
  families at scrape time. The hot path pays nothing — which is how
  the training-loop instrumentation stays inside the <2% dispatch
  budget with zero added device↔host syncs — and the exported series
  can never disagree with the subsystem's own ``report()`` because
  they are read from the same store. Collectors hold a weakref to
  their owner and drop out of the registry when it is collected, so
  short-lived trainers/servers (tests, notebooks) do not accumulate.
"""

from __future__ import annotations

import json
import math
import re
import threading
import weakref
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# paddle_tpu_<subsystem>_<name>, lowercase snake throughout
METRIC_NAME_RE = re.compile(r"^paddle_tpu_[a-z][a-z0-9]*(_[a-z0-9]+)+$")
LABEL_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# default histogram bounds: log-spaced seconds, ~1.6 ratio, 1us..~2000s
DEFAULT_TIME_BUCKETS = tuple(1e-6 * (1.6 ** i) for i in range(45))


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricFamily:
    """One exported family: name, type, help, and its samples.

    ``samples`` is a list of ``(labels_dict, value)``; for histograms
    ``value`` is ``{"bounds": [...], "counts": [...], "sum": s,
    "count": n}`` with ``counts`` per-bucket (NOT cumulative; one
    extra overflow bucket past the last bound — exporters derive the
    cumulative ``_bucket`` series)."""

    __slots__ = ("name", "type", "help", "samples")

    def __init__(self, name: str, type_: str, help_: str,
                 samples: Optional[List[Tuple[Dict[str, str], Any]]] = None):
        self.name = name
        self.type = type_
        self.help = help_
        self.samples = samples if samples is not None else []

    def add(self, labels: Dict[str, str], value) -> "MetricFamily":
        self.samples.append((dict(labels), value))
        return self


def counter_family(name: str, help_: str,
                   samples: Iterable[Tuple[Dict[str, str], float]] = ()
                   ) -> MetricFamily:
    return MetricFamily(name, "counter", help_, list(samples))


def gauge_family(name: str, help_: str,
                 samples: Iterable[Tuple[Dict[str, str], float]] = ()
                 ) -> MetricFamily:
    return MetricFamily(name, "gauge", help_, list(samples))


def histogram_family(name: str, help_: str, labels: Dict[str, str],
                     bounds: Sequence[float], counts: Sequence[int],
                     sum_: float, count: int) -> MetricFamily:
    fam = MetricFamily(name, "histogram", help_)
    fam.add(labels, {"bounds": list(bounds), "counts": list(counts),
                     "sum": float(sum_), "count": int(count)})
    return fam


class _Metric:
    """Base for the direct (push-style) metric types."""

    type = "untyped"

    def __init__(self, name: str, help_: str,
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return _label_key(labels)

    def collect(self) -> MetricFamily:
        with self._lock:
            fam = MetricFamily(self.name, self.type, self.help)
            for key, value in sorted(self._children.items()):
                fam.add(dict(key), value)
            return fam


class Counter(_Metric):
    """Monotonic counter; name must end in ``_total``."""

    type = "counter"

    def inc(self, by: float = 1, **labels) -> None:
        if by < 0:
            raise ValueError(f"{self.name}: counters only go up (by={by})")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0) + by

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0)


class Gauge(_Metric):
    type = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0.0)


class Histogram(_Metric):
    """Fixed log-bucket histogram (one overflow bucket past the last
    bound)."""

    type = "histogram"

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = (),
                 bounds: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(name, help_, labelnames)
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError(f"{name}: bucket bounds must be sorted")

    def observe(self, value: float, **labels) -> None:
        import bisect
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = {"counts": [0] * (len(self.bounds) + 1),
                         "sum": 0.0, "count": 0}
                self._children[key] = child
            child["counts"][bisect.bisect_left(self.bounds, value)] += 1
            child["sum"] += value
            child["count"] += 1

    def collect(self) -> MetricFamily:
        with self._lock:
            fam = MetricFamily(self.name, self.type, self.help)
            for key, child in sorted(self._children.items()):
                fam.add(dict(key), {"bounds": list(self.bounds),
                                    "counts": list(child["counts"]),
                                    "sum": child["sum"],
                                    "count": child["count"]})
            return fam


class MetricsRegistry:
    """Thread-safe registry of direct metrics + scrape-time collectors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # collector id -> (callback, owner weakref or None)
        self._collectors: Dict[int, Tuple[Callable[[], List[MetricFamily]],
                                          Optional[weakref.ref]]] = {}
        self._next_id = 0
        self._inst_counts: Dict[str, int] = {}
        self._last_merge_conflicts: List[str] = []

    # -- instance ids ------------------------------------------------------
    def next_instance(self, kind: str) -> str:
        """Process-monotonic instance id for ``kind`` (``trainer``,
        ``serving``...) — the ``inst`` label that keeps two live
        instances' series distinct."""
        with self._lock:
            n = self._inst_counts.get(kind, 0)
            self._inst_counts[kind] = n + 1
            return str(n)

    # -- direct metrics ----------------------------------------------------
    def _get_or_create(self, cls, name: str, help_: str,
                       labelnames: Sequence[str], **kw):
        _check_name(name, cls.type, help_, labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name} re-registered as a different "
                        f"type/labelset ({m.type}{m.labelnames} vs "
                        f"{cls.type}{tuple(labelnames)})")
                return m
            m = cls(name, help_, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str,
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_, labelnames)

    def gauge(self, name: str, help_: str,
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_, labelnames)

    def histogram(self, name: str, help_: str,
                  labelnames: Sequence[str] = (),
                  bounds: Sequence[float] = DEFAULT_TIME_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_, labelnames,
                                   bounds=bounds)

    # -- collectors --------------------------------------------------------
    def add_collector(self, fn: Callable[..., List[MetricFamily]],
                      owner: Optional[Any] = None) -> int:
        """Register a scrape-time callback returning metric families.
        ``owner`` (weakly referenced) scopes the collector's lifetime:
        when the owner is garbage-collected the collector drops out —
        AND the live owner is passed as the callback's one argument
        (``fn(owner)``), so publishers don't hand-roll their own
        weakref dance; with no owner the callback is called bare
        (``fn()``). Returns a handle for :meth:`remove_collector`
        (components with an explicit shutdown, e.g.
        ``PredictorServer.close``, remove theirs eagerly instead of
        exporting live-looking gauges until gc)."""
        with self._lock:
            cid = self._next_id
            self._next_id += 1
            ref = weakref.ref(owner) if owner is not None else None
            self._collectors[cid] = (fn, ref)
            return cid

    def remove_collector(self, cid: int) -> None:
        with self._lock:
            self._collectors.pop(cid, None)

    # -- scraping ----------------------------------------------------------
    def collect(self) -> List[MetricFamily]:
        """Snapshot every family, merging same-name families from
        multiple collectors (same type+help required — a conflicting
        re-declaration is recorded and surfaced by :meth:`validate`;
        the ``inst`` label keeps publishers' samples distinct)."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors.items())
        fams: List[MetricFamily] = [m.collect() for m in metrics]
        dead: List[int] = []
        errors: List[str] = []
        for cid, (fn, ref) in collectors:
            obj = None
            if ref is not None:
                obj = ref()
                if obj is None:
                    dead.append(cid)
                    continue
            # one broken collector must not poison the process-wide
            # scrape (telemetry never takes down the run it observes):
            # its failure becomes a validate() violation instead
            try:
                fams.extend(fn() if ref is None else fn(obj))
            except Exception as e:
                errors.append(
                    f"collector {getattr(fn, '__qualname__', fn)!r} "
                    f"raised {type(e).__name__}: {e}")
        if dead:
            with self._lock:
                for cid in dead:
                    self._collectors.pop(cid, None)
        merged: Dict[str, MetricFamily] = {}
        conflicts: List[str] = []
        for fam in fams:
            have = merged.get(fam.name)
            if have is None:
                merged[fam.name] = MetricFamily(fam.name, fam.type, fam.help,
                                                list(fam.samples))
            else:
                if have.type != fam.type or have.help != fam.help:
                    conflicts.append(
                        f"{fam.name}: declared as {have.type} "
                        f"({have.help!r}) by one publisher and {fam.type} "
                        f"({fam.help!r}) by another — the merged TYPE/HELP "
                        "lines are wrong for one of them")
                have.samples.extend(fam.samples)
        self._last_merge_conflicts = conflicts + errors
        return [merged[k] for k in sorted(merged)]

    def counter_values(self) -> Dict[str, float]:
        """Flat ``{name{label="v",...}: value}`` of every counter
        sample — the bench snapshot/delta surface."""
        out: Dict[str, float] = {}
        for fam in self.collect():
            if fam.type != "counter":
                continue
            for labels, value in fam.samples:
                out[_series_key(fam.name, labels)] = float(value)
        return out

    # -- validation (the CI naming-convention contract) --------------------
    def validate(self) -> List[str]:
        """Walk every exported family and return naming-convention
        violations (empty == clean): name pattern, non-empty help,
        counter ``_total`` suffix, label-name pattern, duplicate
        series, cross-publisher type/help conflicts, unit-suffix
        hygiene for histograms."""
        fams = self.collect()
        return (list(getattr(self, "_last_merge_conflicts", []))
                + validate_families(fams))

    # -- exporters ---------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every family."""
        return render_families_prometheus(self.collect())

    def render_json(self) -> str:
        """JSON export of the same snapshot (bench rows, flight dumps)."""
        return json.dumps(self.snapshot(), sort_keys=True)

    def snapshot(self) -> Dict[str, Any]:
        return families_snapshot(self.collect())


# -- family-list exporters (shared by the registry and merged views) ----------


def render_families_prometheus(fams: Iterable[MetricFamily]) -> str:
    """Prometheus text exposition (format 0.0.4) of a family list —
    the one renderer behind ``MetricsRegistry.render_prometheus`` AND
    fleet-aggregated views (:func:`merge_exports`), so a replica and a
    router scrape identically."""
    lines: List[str] = []
    for fam in fams:
        lines.append(f"# HELP {fam.name} {_esc_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        if fam.type == "histogram":
            for labels, h in fam.samples:
                cum = 0
                bounds = list(h["bounds"]) + [math.inf]
                for le, c in zip(bounds, h["counts"]):
                    cum += c
                    lab = dict(labels)
                    lab["le"] = _fmt_float(le)
                    lines.append(f"{fam.name}_bucket{_fmt_labels(lab)} "
                                 f"{cum}")
                lines.append(f"{fam.name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_float(h['sum'])}")
                lines.append(f"{fam.name}_count{_fmt_labels(labels)} "
                             f"{h['count']}")
        else:
            for labels, value in fam.samples:
                lines.append(f"{fam.name}{_fmt_labels(labels)} "
                             f"{_fmt_float(value)}")
    return "\n".join(lines) + "\n"


def families_snapshot(fams: Iterable[MetricFamily]) -> Dict[str, Any]:
    """JSON-shaped snapshot of a family list (the ``render_json``
    payload)."""
    out: Dict[str, Any] = {}
    for fam in fams:
        out[fam.name] = {
            "type": fam.type,
            "help": fam.help,
            "samples": [{"labels": labels, "value": value}
                        for labels, value in fam.samples],
        }
    return out


def families_from_snapshot(snap: Dict[str, Any]) -> List[MetricFamily]:
    """Rebuild a :func:`families_snapshot` dict into metric families —
    the inverse used wherever a registry export crossed a process
    boundary as JSON (a remote replica's ``METRICS`` verb, a shipper's
    ``SNAPSHOT`` push) and must be merged/validated/re-rendered like a
    live collection."""
    fams: List[MetricFamily] = []
    for fname in sorted(snap or {}):
        d = snap[fname]
        fam = MetricFamily(fname, d["type"], d["help"])
        for s in d["samples"]:
            fam.add(dict(s["labels"]), s["value"])
        fams.append(fam)
    return fams


def validate_families(fams: Iterable[MetricFamily]) -> List[str]:
    """Naming-convention violations of a family list (empty == clean);
    the per-family half of ``MetricsRegistry.validate``, shared with
    merged fleet exports so an aggregated ``/metrics`` is held to the
    same contract as a single process's."""
    out: List[str] = []
    seen_series: Dict[str, str] = {}
    for fam in fams:
        out.extend(_family_violations(fam))
        for labels, _ in fam.samples:
            for ln in labels:
                if not LABEL_NAME_RE.match(ln):
                    out.append(f"{fam.name}: bad label name {ln!r}")
            key = _series_key(fam.name, labels)
            if key in seen_series:
                out.append(f"duplicate series {key} (missing an "
                           "'inst' label on a per-instance collector?)")
            seen_series[key] = fam.name
    return out


def merge_exports(named: Dict[str, Iterable[MetricFamily]],
                  label: str = "replica") -> List[MetricFamily]:
    """Merge several publishers' family lists into one export, stamping
    every sample with ``{label: name}`` — the fleet-aggregation
    primitive: a router calls each replica's ``telemetry_families()``
    and serves the merged result from ONE ``/metrics`` endpoint, each
    series distinguishable by its ``replica`` label. Same-name families
    merge into one (first publisher's type/help win — replicas of one
    fleet publish identical declarations); a source whose sample
    already carries ``label`` is left alone (nested merges don't
    re-stamp)."""
    if not LABEL_NAME_RE.match(label):
        raise ValueError(f"merge label {label!r} violates the label "
                         "naming convention")
    merged: Dict[str, MetricFamily] = {}
    for name in sorted(named):
        for fam in named[name]:
            have = merged.get(fam.name)
            if have is None:
                have = merged[fam.name] = MetricFamily(fam.name, fam.type,
                                                       fam.help)
            for labels, value in fam.samples:
                stamped = dict(labels)
                stamped.setdefault(label, name)
                have.add(stamped, value)
    return [merged[k] for k in sorted(merged)]


class FamiliesView:
    """Registry-shaped read-only view over a families callback: the
    duck type :class:`~paddle_tpu.telemetry.http.TelemetryServer`
    scrapes (``render_prometheus``/``render_json``) without being a
    :class:`MetricsRegistry` — how a fleet router serves its replicas'
    MERGED series from one endpoint."""

    def __init__(self, collect_fn: Callable[[], List[MetricFamily]]):
        self._collect_fn = collect_fn

    def collect(self) -> List[MetricFamily]:
        return self._collect_fn()

    def render_prometheus(self) -> str:
        return render_families_prometheus(self.collect())

    def snapshot(self) -> Dict[str, Any]:
        return families_snapshot(self.collect())

    def render_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def validate(self) -> List[str]:
        return validate_families(self.collect())


def _series_key(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_esc_label(str(v))}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _esc_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _esc_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_float(v) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _check_name(name: str, type_: str, help_: str,
                labelnames: Sequence[str]) -> None:
    errs = _name_violations(name, type_, help_)
    for ln in labelnames:
        if not LABEL_NAME_RE.match(ln):
            errs.append(f"{name}: bad label name {ln!r}")
    if errs:
        raise ValueError("; ".join(errs))


def _name_violations(name: str, type_: str, help_: str) -> List[str]:
    out = []
    if not METRIC_NAME_RE.match(name):
        out.append(f"metric name {name!r} violates the "
                   "paddle_tpu_<subsystem>_<name> convention")
    if not (help_ or "").strip():
        out.append(f"{name}: missing help text")
    if type_ == "counter" and not name.endswith("_total"):
        out.append(f"counter {name} must end in _total")
    if type_ != "counter" and name.endswith("_total"):
        out.append(f"{type_} {name} must not end in _total")
    return out


def _family_violations(fam: MetricFamily) -> List[str]:
    out = _name_violations(fam.name, fam.type, fam.help)
    if fam.type not in ("counter", "gauge", "histogram"):
        out.append(f"{fam.name}: unknown metric type {fam.type!r}")
    if fam.type == "histogram":
        for _, h in fam.samples:
            if not isinstance(h, dict) or \
                    len(h.get("counts", [])) != len(h.get("bounds", [])) + 1:
                out.append(f"{fam.name}: histogram sample needs "
                           "len(counts) == len(bounds)+1")
    return out


def counter_deltas(before: Dict[str, float], after: Dict[str, float],
                   per: float = 1.0) -> Dict[str, float]:
    """``(after - before) / per`` for every counter series that moved —
    the bench "telemetry snapshot" shape (``per`` = steps or requests
    measured, so rows are comparable across iteration counts)."""
    out: Dict[str, float] = {}
    for key, v in after.items():
        d = v - before.get(key, 0.0)
        if d:
            out[key] = round(d / (per or 1.0), 6)
    return out


# -- the process-wide default registry ----------------------------------------

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """THE process-wide registry every subsystem publishes into (and
    the default the ``/metrics`` endpoint serves)."""
    return _default_registry


__all__ = [
    "Counter", "FamiliesView", "Gauge", "Histogram", "MetricFamily",
    "MetricsRegistry", "METRIC_NAME_RE", "DEFAULT_TIME_BUCKETS",
    "counter_deltas", "counter_family", "families_from_snapshot",
    "families_snapshot", "gauge_family",
    "get_registry", "histogram_family", "merge_exports",
    "render_families_prometheus", "validate_families",
]
