"""The lint rule families of the static program checker.

Each rule takes the artifacts ``analysis.check`` prepared (jaxpr, param
scope, mesh/rules, example arguments) and appends :class:`Finding`s to a
:class:`LintReport`. Codes are ``family:rule``:

- ``collective:*`` — collective-placement hazards (the unhoisted-accum
  class of bug pinned by SCALING.md §2): reduction collectives nested in
  loop bodies multiply their wire bytes by the trip count.
- ``dtype:*``      — mixed-precision flow: f32 MXU ops surviving under
  an amp compute dtype, f64 leaks, no-op cast round-trips.
- ``sharding:*``   — whole-program audit of the rule table against the
  actual parameter scope (per-param ``_validate`` only sees one name at
  placement time; this sees rules that match nothing and large params
  left replicated).
- ``params:*``     — dead parameters (initialized, never read) and
  trainable parameters with structurally-zero gradients.
- ``donation:*``   — fetched step outputs aliasing donated inputs (the
  donated-buffer-reuse footgun, sharpened by the K-step fused dispatch
  donating the whole training carry).
- ``retrace:*``    — recompilation hazards in the traced arg signature
  (weak python scalars, unhashable objects).
- ``feed:*``       — input-pipeline wire-format opportunities: float32
  feed inputs whose first in-program uses are a cast/normalize could
  cross the host→device link as uint8/bf16 wire (data/wire.WireSpec)
  and decode on device for free.
- ``moe:*``        — mixture-of-experts routing shape: static
  ``capacity_factor``/``top_k`` combos whose expected token drop rate
  (computable from the dispatch tensor shapes alone) exceeds a
  threshold.
- ``sharding:replicated-optstate`` — optimizer state fully replicated
  across a data-parallel axis above a size threshold: the ZeRO
  (cross-replica sharded weight update) trigger.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .report import LintReport, collect_into
from .walker import (COLLECTIVES, LOOP_PRIMS, PERMUTE_COLLECTIVES,
                     REDUCTION_COLLECTIVES, aval_bytes, eqn_out_bytes,
                     eqn_subjaxprs, in_loop, is_structural_zero, iter_eqns,
                     producer_map, used_var_ids)

# --------------------------------------------------------------------------
# 1. collective placement
# --------------------------------------------------------------------------


def _walk_with_trips(jaxpr, path=(), trips=1):
    """iter_eqns plus the product of enclosing loop trip counts (None
    once a loop with unknowable count — e.g. while — intervenes).
    Loop-primitive membership comes from walker.LOOP_PRIMS so the two
    walks can never disagree about what counts as a loop."""
    for eqn in jaxpr.eqns:
        yield eqn, path, trips
        name = eqn.primitive.name
        sub_trips = trips
        if name in LOOP_PRIMS:
            length = eqn.params.get("length")  # scan carries it; while: None
            sub_trips = (None if trips is None or length is None
                         else trips * int(length))
        for sub in eqn_subjaxprs(eqn):
            yield from _walk_with_trips(sub, path + (name,), sub_trips)


def _group_size(eqn, mesh) -> int:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    n = 1
    for a in axes:
        if mesh is not None and a in getattr(mesh, "axis_names", ()):
            n *= mesh.shape[a]
    return n


def check_collectives(closed_jaxpr, report: LintReport, mesh=None) -> None:
    """Flag reduction collectives (psum / all_gather / all_to_all /
    psum_scatter) nested inside scan/while bodies: each loop iteration
    pays the exchange, the hoisted-accumulation hazard. Neighbor
    permutes (ppermute) inside loops are the *deliberate* structure of
    ring/pipeline schedules, so they are reported at info severity with
    the same byte accounting rather than warned."""
    for eqn, path, trips in _walk_with_trips(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVES:
            continue
        if not in_loop(path):
            continue
        payload = eqn_out_bytes(eqn)
        n = _group_size(eqn, mesh)
        per_step = None if trips is None else payload * trips
        loop_desc = "while" if trips is None else f"×{trips} scan iterations"
        if name in REDUCTION_COLLECTIVES:
            report.add(
                "collective:in-scan", "warning",
                f"{name} inside a loop body ({' > '.join(path)}): the "
                f"exchange ({payload / 1e6:.3f} MB result"
                + (f", ~{per_step / 1e6:.3f} MB {loop_desc} per step"
                   if per_step is not None else f", {loop_desc}")
                + ") runs every iteration — hoist it out of the loop if it "
                "does not depend on the loop carry (the per-microbatch "
                "allreduce hazard; see DistStrategy.accum_exchange='hoisted')",
                where=name, payload_bytes=payload, trips=trips,
                per_step_bytes=per_step, group_size=n, path=list(path))
        else:
            report.add(
                "collective:permute-in-scan", "info",
                f"{name} inside a loop body ({' > '.join(path)}): "
                f"{payload / 1e6:.3f} MB neighbor-hop per iteration"
                + (f" (~{per_step / 1e6:.3f} MB per step)"
                   if per_step is not None else "")
                + " — expected for ring/pipeline schedules",
                where=name, payload_bytes=payload, trips=trips,
                per_step_bytes=per_step, group_size=n, path=list(path))


def check_accum_exchange(strategy, mesh, params, report: LintReport) -> None:
    """Config-level collective placement: ``accum_steps>1`` with the
    default GSPMD exchange on a data-parallel mesh rides one full
    gradient all-reduce INSIDE the microbatch scan per iteration (the
    collective is inserted by the SPMD partitioner, so it is invisible
    to the jaxpr walk — this rule reasons from the config, the way
    SCALING.md §2 measured it)."""
    accum = int(getattr(strategy, "accum_steps", 1) or 1) if strategy else 1
    mode = getattr(strategy, "accum_exchange", "gspmd") if strategy else "gspmd"
    if accum <= 1 or mode != "gspmd" or mesh is None:
        return
    data_n = 1
    for a in ("dp", "fsdp"):
        if a in mesh.axis_names:
            data_n *= mesh.shape[a]
    if data_n <= 1:
        return
    grad_bytes = sum(int(np.prod(v.shape)) * 4
                     for v in jax.tree.leaves(params))  # f32 grads
    wire = 2.0 * (data_n - 1) / data_n * grad_bytes
    report.add(
        "collective:microbatch-exchange", "warning",
        f"accum_steps={accum} with accum_exchange='gspmd' on a "
        f"{data_n}-way data mesh exchanges gradients once per microbatch "
        f"(~{accum * wire / 1e6:.1f} MB wire/device/step vs "
        f"{wire / 1e6:.1f} MB hoisted) — set "
        "DistStrategy.accum_exchange='hoisted' when params are replicated",
        where="DistStrategy.accum_steps",
        accum_steps=accum, data_shards=data_n,
        per_step_bytes=accum * wire, hoisted_bytes=wire)


def check_quantized_exchange(strategy, mesh, params, report: LintReport,
                             profile=None) -> None:
    """``sharding:unquantized-exchange`` advisory: the run crosses a
    data axis with full-width f32 gradients while the measured profile
    says the link is the bottleneck — the exact shape BENCH_mid_r05
    measured (19.9 img/s delivered vs 2174 compute-only at 53 MB/s).
    Fires only with profile evidence (``profile_report()``'s bottleneck
    naming the link, or an explicit ``link_bound`` flag from bench):
    quantization is a tradeoff, so config alone never triggers it."""
    qmode = ((getattr(strategy, "quantized_allreduce", "none")
              if strategy else "none") or "none")
    if qmode != "none" or mesh is None:
        return
    data_n = 1
    for a in ("dp", "fsdp"):
        if a in mesh.axis_names:
            data_n *= mesh.shape[a]
    if data_n <= 1 or not profile:
        return
    link_bound = bool(profile.get("link_bound")) or \
        profile.get("bottleneck") == "h2d_s"
    if not link_bound:
        return
    grad_bytes = sum(int(np.prod(v.shape)) * 4
                     for v in jax.tree.leaves(params))  # f32 grads
    wire = 2.0 * (data_n - 1) / data_n * grad_bytes
    report.add(
        "sharding:unquantized-exchange", "info",
        f"profile marks the run link-bound "
        f"(bottleneck={profile.get('bottleneck')!r}) while gradients "
        f"cross the {data_n}-way data mesh at full f32 width "
        f"(~{wire / 1e6:.1f} MB wire/device/step) — consider "
        "DistStrategy.quantized_allreduce='int8' (~4x less gradient "
        "wire, block-scaled with error feedback; see MIGRATION.md "
        "\"Quantized collectives\")",
        where="DistStrategy.quantized_allreduce",
        data_shards=data_n, per_step_bytes=wire,
        bottleneck=profile.get("bottleneck"))


# --------------------------------------------------------------------------
# 2. dtype flow
# --------------------------------------------------------------------------

_MXU_PRIMS = frozenset({"dot_general", "conv_general_dilated"})


def _np_dtype(dt):
    """np.dtype(dt) or None for jax extended dtypes (typed PRNG keys in
    the train-step jaxpr, fp8 wrappers) that numpy cannot interpret —
    the dtype rules simply don't apply to those avals."""
    try:
        return np.dtype(dt)
    except TypeError:
        return None


def check_dtypes(closed_jaxpr, report: LintReport,
                 compute_dtype=None, feed: Optional[Dict[str, Any]] = None) -> None:
    """Mixed-precision flow over the whole jaxpr:

    - ``dtype:amp-f32-matmul`` — a matmul/conv whose operands stayed f32
      while the ambient compute dtype is reduced (bf16/f16): the layer
      bypassed ``cast_compute`` and its MXU op runs at 1/2 the
      throughput the amp_guard asked for.
    - ``dtype:f64-leak`` — any f64 aval (TPU has no f64 MXU path), plus
      f64 feed arrays that x64-off mode will silently truncate.
    - ``dtype:cast-roundtrip`` — convert chains that return to the
      source dtype (x→b→x): a no-op pair that usually marks a missing
      dtype plumb-through.
    """
    cd = np.dtype(compute_dtype) if compute_dtype is not None else None
    reduced = cd is not None and cd.itemsize < 4 and cd.kind in ("f", "V")
    for k, v in (feed or {}).items():
        try:
            dt = v.dtype if hasattr(v, "dtype") else np.asarray(v).dtype
        except Exception:
            continue  # untraceable value: the retrace family owns it
        if np.dtype(dt) == np.float64:
            report.add("dtype:f64-leak", "warning",
                       f"feed {k!r} is float64 — under the default x64-off "
                       "config it is silently truncated to float32 at "
                       "device_put; cast at the data layer",
                       where=k)

    def visit(jaxpr):
        producers = producer_map(jaxpr)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            avals = [getattr(v, "aval", None) for v in eqn.invars]
            out_avals = [getattr(v, "aval", None) for v in eqn.outvars]
            for av in out_avals:
                if getattr(av, "dtype", None) is not None and \
                        _np_dtype(av.dtype) == np.float64:
                    report.add("dtype:f64-leak", "warning",
                               f"{name} produces float64 {av.shape} — no "
                               "f64 MXU path on TPU; cast to f32",
                               where=name)
                    break
            if reduced and name in _MXU_PRIMS:
                op_dts = [_np_dtype(av.dtype) for av in avals
                          if getattr(av, "dtype", None) is not None]
                op_dts = [dt for dt in op_dts if dt is not None]
                if op_dts and all(dt == np.float32 for dt in op_dts):
                    shapes = [tuple(getattr(av, "shape", ())) for av in avals]
                    report.add(
                        "dtype:amp-f32-matmul", "warning",
                        f"{name} on f32 operands {shapes} while the compute "
                        f"dtype is {cd} — the layer bypassed cast_compute; "
                        "this op misses the reduced-precision MXU path "
                        "amp_guard selected",
                        where=name, shapes=shapes)
            if name == "convert_element_type":
                src = eqn.invars[0]
                peqn = producers.get(id(src))
                if (peqn is not None
                        and peqn.primitive.name == "convert_element_type"):
                    orig = getattr(peqn.invars[0], "aval", None)
                    final = getattr(eqn.outvars[0], "aval", None)
                    odt = _np_dtype(orig.dtype) if orig is not None else None
                    fdt = _np_dtype(final.dtype) if final is not None else None
                    mid = _np_dtype(getattr(src, "aval").dtype)
                    if (odt is not None and fdt is not None
                            and mid is not None and odt == fdt):
                        report.add(
                            "dtype:cast-roundtrip", "info",
                            f"cast round-trip {odt} → {mid} "
                            f"→ {fdt}: the pair is a no-op "
                            "(or a silent precision truncation if the middle "
                            "dtype is narrower) — plumb the dtype through "
                            "instead",
                            where=name,
                            # the dtype triple discriminates fingerprints:
                            # a NEW f32->f16->f32 round-trip must not be
                            # suppressed by a baselined f32->bf16->f32 one
                            dtype=f"{odt}->{mid}->{fdt}")

    from .walker import walk_jaxprs
    walk_jaxprs(closed_jaxpr.jaxpr, visit)


# --------------------------------------------------------------------------
# 3. whole-program sharding audit
# --------------------------------------------------------------------------


def check_sharding(params: Dict[str, Any], mesh, rules,
                   report: LintReport, param_info=None,
                   large_param_bytes: int = 1 << 20) -> None:
    """Audit the rule table against the actual parameter scope. The
    per-param drop diagnostics (axis missing / dim not divisible /
    rank mismatch) come from routing ``sharding._warn_drop`` through
    the report collector while resolving every spec — the same code
    path placement uses, so the audit can never disagree with it."""
    if mesh is None or rules is None or not params:
        return
    from ..parallel.sharding import CANONICAL_AXES

    # typo'd axes must be read off the RAW table: adapted_to strips
    # non-mesh axes (and memoizes, so its one-shot adapt-time warning
    # may long since have fired outside any collector)
    nameset = set(mesh.axis_names)
    for i, (pat, spec) in enumerate(getattr(rules, "rules", []) or []):
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (
                (entry,) if entry is not None else ())
            for a in axes:
                if a not in nameset and a not in CANONICAL_AXES:
                    report.add(
                        "sharding:unknown-axis", "warning",
                        f"rule #{i} {pat.pattern!r} names axis {a!r} which "
                        f"is neither in the mesh {dict(mesh.shape)} nor a "
                        f"canonical axis name {sorted(CANONICAL_AXES)} — "
                        "likely a typo; that dim is silently replicated",
                        where=pat.pattern, rule_index=i, axis=a)

    adapted = rules.adapted_to(mesh)
    names = list(params)
    for i, (pat, spec) in enumerate(getattr(adapted, "rules", []) or []):
        if not any(pat.search(n) for n in names):
            report.add(
                "sharding:unmatched-rule", "warning",
                f"rule #{i} {pat.pattern!r} → {spec} matches no parameter "
                f"({len(names)} in scope) — stale pattern or renamed layer",
                where=pat.pattern, rule_index=i)

    fsdp_n = mesh.shape.get("fsdp", 1) if "fsdp" in mesh.axis_names else 1
    with collect_into(report):
        for name in names:
            v = params[name]
            spec = adapted.spec_for(name, tuple(v.shape), mesh)
            nbytes = int(np.prod(v.shape or (1,))) * np.dtype(v.dtype).itemsize
            replicated = all(e is None for e in spec)
            if replicated and fsdp_n > 1 and nbytes >= large_param_bytes:
                report.add(
                    "sharding:replicated-large", "warning",
                    f"{name} ({nbytes / 1e6:.2f} MB {v.dtype}{tuple(v.shape)}) "
                    f"is fully replicated although the mesh has an fsdp axis "
                    f"of size {fsdp_n} — each device holds a full copy "
                    f"(+{(fsdp_n - 1) / fsdp_n * nbytes / 1e6:.2f} MB/device "
                    "vs sharded)",
                    where=name, bytes=nbytes, fsdp=fsdp_n)


# --------------------------------------------------------------------------
# 4. dead / zero-gradient parameters
# --------------------------------------------------------------------------


def check_params(program, params, state, args, kwargs,
                 report: LintReport, loss_name: str = "loss",
                 closed_flat=None, invar_names=None) -> None:
    """``params:dead`` — parameters materialized by ``Program.init`` that
    never appear as live jaxpr invars (the trace never reads them: a
    created-but-unused layer, or a stale checkpoint name).
    ``params:zero-grad`` — ``trainable=True`` parameters whose gradient
    is *structurally* zero (literal-0 broadcast in the grad jaxpr):
    they consume optimizer state and exchange bandwidth every step and
    never move."""
    if closed_flat is None:
        closed_flat, invar_names = program.desc_flat(params, state, *args,
                                                     **kwargs)
    jaxpr = closed_flat.jaxpr
    used = used_var_ids(jaxpr)
    dead = set()
    for var, (kind, name) in zip(jaxpr.invars, invar_names):
        if kind == "param" and id(var) not in used:
            dead.add(name)
            report.add(
                "params:dead", "warning",
                f"parameter {name!r} "
                f"{tuple(getattr(var.aval, 'shape', ()))} is initialized "
                "but never read by the program — dead weight in every "
                "checkpoint and optimizer step",
                where=name)

    # gradient structure: only meaningful when a scalar loss is exposed
    leaves, treedef = jax.tree.flatten(params)
    pnames = sorted(params)  # jax flattens dicts in sorted-key order

    def loss_of(flat):
        p = jax.tree.unflatten(treedef, flat)
        out, _ = program.apply(p, state, *args, training=False, **kwargs)
        loss = out.get(loss_name) if isinstance(out, dict) else out
        return loss

    try:
        out_aval = jax.eval_shape(loss_of, leaves)
        if getattr(out_aval, "shape", None) != ():
            return
        closed_g = jax.make_jaxpr(jax.grad(loss_of))(leaves)
    except Exception:
        return  # no scalar loss under this name: skip the grad analysis
    gj = closed_g.jaxpr
    producers = producer_map(gj)
    info = getattr(program, "param_info", {}) or {}
    for name, gvar in zip(pnames, gj.outvars):
        pi = info.get(name)
        if pi is not None and not pi.trainable:
            continue  # frozen on purpose (stop_gradient): not a finding
        if name in dead:
            continue  # already reported with the sharper code
        if is_structural_zero(gvar, producers):
            report.add(
                "params:zero-grad", "warning",
                f"trainable parameter {name!r} has a structurally zero "
                f"gradient w.r.t. {loss_name!r} — it is read by the program "
                "but the loss does not depend on it (forgotten head? "
                "mark trainable=False to stop paying optimizer state)",
                where=name)


# --------------------------------------------------------------------------
# 5. donation aliasing
# --------------------------------------------------------------------------


def check_donation(closed_jaxpr, donated: Dict[int, str],
                   fetched: Dict[int, str], report: LintReport) -> None:
    """``donation:fetched-alias`` — a FETCHED step output that is a
    donated input passed through unchanged (the outvar IS the invar in
    the step jaxpr). With buffer donation XLA reuses the donated buffer
    for the in-place param/opt-state update, so the passthrough forces a
    defensive copy at best — and a caller that keeps the fetched handle
    across the next (donating) dispatch holds a buffer the runtime
    considers consumed: the donated-buffer-reuse footgun. The K-step
    fused dispatch (``Trainer.run_steps``) donates the whole training
    carry end-to-end, which widens the window — fetch a computed value
    (e.g. ``jnp.copy`` / a fresh reduction) instead of the raw carry
    leaf.

    ``donated`` maps flat invar index → display name for every donated
    leaf; ``fetched`` maps flat outvar index → display name for every
    leaf of the step's fetch dict."""
    jaxpr = closed_jaxpr.jaxpr
    donated_by_id = {id(jaxpr.invars[i]): name
                     for i, name in donated.items() if i < len(jaxpr.invars)}
    for i, oname in fetched.items():
        if i >= len(jaxpr.outvars):
            continue
        v = jaxpr.outvars[i]
        if type(v).__name__ == "Literal":
            continue
        src = donated_by_id.get(id(v))
        if src is not None:
            report.add(
                "donation:fetched-alias", "warning",
                f"fetched step output {oname} is donated input {src} "
                "passed through unchanged — donation hands that buffer to "
                "XLA for in-place reuse, so fetching the alias forces a "
                "copy (or, held across the next donating dispatch, reads "
                "a consumed buffer); fetch a computed value or drop it "
                "from fetch_list",
                where=oname, donated_input=src, outvar_index=i)


# --------------------------------------------------------------------------
# 6. recompilation hazards
# --------------------------------------------------------------------------


def check_signature(bound: Dict[str, Any], report: LintReport) -> None:
    """Inspect the example call signature for retrace hazards. ``bound``
    maps argument names to example values (``Program.arg_signature``)."""
    for name, val in bound.items():
        for sub, leaf in _named_leaves(name, val):
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                continue  # array-like: shape/dtype keyed, retrace-safe
            if isinstance(leaf, bool) or isinstance(leaf, (int, float)):
                report.add(
                    "retrace:weak-scalar", "info",
                    f"argument {sub!r} is a weak-typed python "
                    f"{type(leaf).__name__} ({leaf!r}) — it promotes "
                    "silently in dtype arithmetic, and if routed to a "
                    "static argument it recompiles per distinct value; "
                    "wrap in np.asarray(..., dtype=...)",
                    where=sub)
                continue
            if isinstance(leaf, str) or leaf is None:
                continue
            try:
                hash(leaf)
            except TypeError:
                report.add(
                    "retrace:unhashable-arg", "warning",
                    f"argument {sub!r} is an unhashable "
                    f"{type(leaf).__name__} — it cannot key a compile "
                    "cache (static argnums reject it; as a traced arg each "
                    "call re-converts it); pass an array or a hashable "
                    "config object",
                    where=sub)


def _named_leaves(name: str, val):
    """(name, leaf) pairs one level of dict/tuple deep — enough to name
    feed entries without flattening arrays themselves."""
    if isinstance(val, dict) and not hasattr(val, "shape"):
        for k, v in val.items():
            yield f"{name}[{k!r}]", v
    else:
        # lists/tuples are reported on the container, not per element
        # (the common hazard is a python list standing in for an array)
        yield name, val


# --------------------------------------------------------------------------
# 7. pipeline shape
# --------------------------------------------------------------------------


def check_pipeline(strategy, mesh, feed: Optional[Dict[str, Any]],
                   report: LintReport) -> None:
    """``pipeline:*`` — the pipeline-schedule shape constraints that
    used to surface only as runtime enforces inside ``pipeline_apply``
    (mid-trace, after startup cost is sunk), checked statically at
    startup from the strategy + mesh + sample feed:

    - ``pipeline:batch-indivisible`` — batch % pp_microbatches != 0
      (the trace WILL fail at the first step);
    - ``pipeline:microbatch-indivisible`` — microbatch not divisible
      by the dp/fsdp data-shard product;
    - ``pipeline:bubble`` — the exact fill/drain waste fraction of the
      schedule (``parallel.pipeline.bubble_fraction``), warned above
      20% with the microbatch/interleave levers named.

    The runtime enforces stay (defense in depth); this family names
    the fix before anything compiles."""
    pp_m = int(getattr(strategy, "pp_microbatches", 0) or 0) if strategy else 0
    if pp_m <= 0:
        return
    pp_v = max(1, int(getattr(strategy, "pp_interleave", 1) or 1))
    b = None
    for v in (feed or {}).values():
        shape = getattr(v, "shape", None)
        if shape is None:
            try:
                shape = np.asarray(v).shape
            except Exception:
                continue
        if shape:
            b = int(shape[0])
            break
    indivisible = b is not None and b % pp_m != 0
    if indivisible:
        report.add(
            "pipeline:batch-indivisible", "warning",
            f"batch {b} is not divisible by pp_microbatches={pp_m} — "
            "pipeline_apply will reject the trace at the first step; "
            "re-batch the feed or lower pp_microbatches",
            where="DistStrategy.pp_microbatches", batch=b,
            pp_microbatches=pp_m)
    dshard = 1
    if mesh is not None:
        for a in ("dp", "fsdp"):
            if a in mesh.axis_names:
                dshard *= mesh.shape[a]
    # the microbatch-divisibility math would divide by a lie when the
    # batch itself is indivisible; the bubble estimate below depends
    # only on the schedule shape and must still run
    if b is not None and not indivisible and dshard > 1 \
            and (b // pp_m) % dshard != 0:
        report.add(
            "pipeline:microbatch-indivisible", "warning",
            f"microbatch size {b // pp_m} (batch {b} / "
            f"pp_microbatches {pp_m}) is not divisible by the data-shard "
            f"product {dshard} — lower pp_microbatches or raise the batch",
            where="DistStrategy.pp_microbatches", batch=b,
            pp_microbatches=pp_m, data_shards=dshard)
    p = (mesh.shape["pp"] if mesh is not None
         and "pp" in getattr(mesh, "axis_names", ()) else 1)
    if p > 1:
        from ..parallel.pipeline import bubble_fraction
        frac = bubble_fraction(p, pp_m, pp_v)
        sev = "warning" if frac > 0.2 else "info"
        report.add(
            "pipeline:bubble", sev,
            f"schedule bubble is {frac:.1%} of ticks (pp={p}, "
            f"microbatches={pp_m}, interleave={pp_v})"
            + (" — raise pp_microbatches (ideally a multiple of pp) or "
               "pp_interleave (V× less bubble, V× more neighbor-hop "
               "activation traffic)" if frac > 0.2 else ""),
            where="DistStrategy.pp_microbatches", bubble_fraction=frac,
            pp=p, microbatches=pp_m, interleave=pp_v)


# --------------------------------------------------------------------------
# 8. HLO-level collective placement (optimized-HLO walk)
# --------------------------------------------------------------------------

_HLO_REDUCTIONS = frozenset({"all-reduce", "reduce-scatter", "all-gather",
                             "all-to-all"})
_HLO_PERMUTES = frozenset({"collective-permute", "collective-broadcast"})


def check_hlo_collectives(units, report: LintReport) -> None:
    """``collective:hlo-*`` — collective placement read off the
    OPTIMIZED HLO of the compiled step (``profiling.fusion`` units),
    catching what the jaxpr walk structurally cannot: collectives the
    GSPMD partitioner *inserted* (the per-microbatch gradient exchange
    is invisible pre-partitioning — ``collective:microbatch-exchange``
    infers it from config; this sees it directly).

    - ``collective:hlo-in-while`` (warning) — a reduction collective
      inside a compiled while-loop body pays its wire every iteration;
    - ``collective:hlo-unrolled-loop`` (warning) — N>1 copies of the
      same source-level exchange whose op_name path shows a loop body:
      XLA unrolled the loop, the per-iteration cost is now N× visible
      instances (how XLA:CPU compiles small scans);
    - ``collective:hlo-permute-in-while`` (info) — in-loop neighbor
      permutes, the deliberate ring/pipeline structure, with bytes."""
    from collections import Counter

    unrolled: Counter = Counter()
    unrolled_bytes: Dict[Any, int] = {}
    for u in units:
        src = u.source_ops[0] if u.source_ops else ""
        if u.op in _HLO_REDUCTIONS and u.in_loop:
            report.add(
                "collective:hlo-in-while", "warning",
                f"{u.op} ({u.out_bytes / 1e6:.3f} MB result, source "
                f"{src or 'unknown'}) inside compiled while-loop body "
                f"{u.computation!r} — the partitioned executable pays this "
                "exchange EVERY iteration (×trip count wire); hoist the "
                "exchange out of the loop "
                "(DistStrategy.accum_exchange='hoisted') or confirm it is "
                "deliberate schedule structure",
                where=f"{u.computation}/{u.name}",
                payload_bytes=u.out_bytes, source=src)
        elif u.op in _HLO_PERMUTES and u.in_loop:
            report.add(
                "collective:hlo-permute-in-while", "info",
                f"{u.op} ({u.out_bytes / 1e6:.3f} MB, source "
                f"{src or 'unknown'}) inside while body {u.computation!r} "
                "— expected for ring/pipeline schedules",
                where=f"{u.computation}/{u.name}",
                payload_bytes=u.out_bytes, source=src)
        elif u.op in _HLO_REDUCTIONS and "while/body" in src:
            key = (u.op, src)
            unrolled[key] += 1
            unrolled_bytes[key] = unrolled_bytes.get(key, 0) + u.out_bytes
    for (op, src), n in sorted(unrolled.items()):
        if n <= 1:
            continue  # one instance = likely the hoisted/final exchange
        total = unrolled_bytes[(op, src)]
        report.add(
            "collective:hlo-unrolled-loop", "warning",
            f"{n} copies of {op} from loop-body source {src!r} "
            f"({total / 1e6:.3f} MB total) — XLA unrolled the loop, so "
            f"the per-iteration exchange is paid {n}×; same fix as "
            "collective:hlo-in-while",
            where=src, op=op, instances=n, payload_bytes=total, source=src)


# --------------------------------------------------------------------------
# 9. feed wire-format candidates
# --------------------------------------------------------------------------

# first-use primitives that prove a feed value is only ever cast or
# affinely renormalized before real compute touches it — the static
# evidence it could cross the link in a narrower wire dtype and decode
# on device (data/wire.py) with identical results
_WIRE_FIRST_USES = frozenset({"convert_element_type", "add", "sub", "mul",
                              "div"})


def _is_const_like(var, constvar_ids, producers, _depth: int = 0) -> bool:
    """Literal, trace-time constant, or a broadcast/convert chain over
    one — the "other operand" shape of a normalize like (x-127)/64."""
    from .walker import is_literal

    if _depth > 8:
        return False
    if is_literal(var) or id(var) in constvar_ids:
        return True
    eqn = producers.get(id(var))
    if eqn is not None and eqn.primitive.name in ("broadcast_in_dim",
                                                  "convert_element_type",
                                                  "reshape"):
        return _is_const_like(eqn.invars[0], constvar_ids, producers,
                              _depth + 1)
    return False


def check_feed_wire(closed_flat, invar_names, report: LintReport,
                    already_wired=()) -> None:
    """``feed:wire-candidate`` — a float32 feed input whose every
    first use is a dtype cast or a constant affine normalize
    (``(x - mean) / std`` and friends): the program itself proves the
    field could ship as uint8 (quantized) or bf16 (truncated) wire —
    4×/2× fewer host→device bytes — with the decode fused into the step
    for free. Fields already covered by the trainer's ``feed_wire``
    table are skipped; integer feeds (labels/ids) are never candidates.
    """
    jaxpr = closed_flat.jaxpr
    constvar_ids = {id(v) for v in getattr(jaxpr, "constvars", ())}
    producers = producer_map(jaxpr)
    all_eqns = list(iter_eqns(jaxpr))
    for var, (kind, name) in zip(jaxpr.invars, invar_names):
        if kind not in ("arg", "kwarg") or name in already_wired:
            continue
        aval = getattr(var, "aval", None)
        dt = _np_dtype(getattr(aval, "dtype", None)) if aval is not None else None
        if dt != np.float32:
            continue
        consumers = [eqn for eqn, _path in all_eqns
                     if any(iv is var for iv in eqn.invars)]
        if not consumers:
            continue  # dead feed: not this rule's finding
        casts_only = True
        for eqn in consumers:
            pname = eqn.primitive.name
            if pname not in _WIRE_FIRST_USES:
                casts_only = False
                break
            if pname != "convert_element_type":
                others = [iv for iv in eqn.invars if iv is not var]
                if not all(_is_const_like(iv, constvar_ids, producers)
                           for iv in others):
                    casts_only = False
                    break
        if not casts_only:
            continue
        nbytes = aval_bytes(aval)
        arithmetic = any(e.primitive.name != "convert_element_type"
                         for e in consumers)
        suggestion = ("WireSpec.quantize('uint8', scale, zero_point) — ~4x"
                      if arithmetic else "WireSpec.cast('bfloat16') — 2x")
        report.add(
            "feed:wire-candidate", "info",
            f"feed {name!r} (float32, {nbytes / 1e6:.3f} MB/batch) is only "
            f"cast/normalized before use ({sorted({e.primitive.name for e in consumers})}) "
            f"— it can cross the host→device link in a narrower wire dtype "
            f"with the decode fused into the step: {suggestion} fewer wire "
            "bytes (Trainer(feed_wire={...}), data/wire.py). Never quantize "
            "label/id fields.",
            where=name, bytes_per_batch=nbytes,
            first_uses=sorted({e.primitive.name for e in consumers}))


def check_cacheable_dataset(sample_feed, feed_wire, num_epochs,
                            dataset_batches, residual_hbm_bytes,
                            report: LintReport,
                            cache_enabled: bool = False) -> None:
    """``feed:cacheable-dataset`` — a multi-epoch ``fit`` whose
    dataset's ENCODED wire bytes (``dataset_batches`` ×
    ``feed_wire_nbytes`` of the sample batch) fit the residual-HBM
    estimate (device budget minus the advisor's params + opt state +
    activations appetite), running with the device cache OFF: every
    epoch after the first re-sends bytes the device could simply keep
    (``fit(device_cache=True)``, data/device_cache.py). Advisory
    severity, like ``feed:wire-candidate`` — the reader must be
    epoch-stable for the cache to be sound, which only the caller
    knows."""
    if cache_enabled or not num_epochs or int(num_epochs) <= 1:
        return
    if not dataset_batches or residual_hbm_bytes is None \
            or not sample_feed:
        return
    from ..data.wire import feed_wire_nbytes
    per_batch = feed_wire_nbytes(sample_feed, feed_wire)
    total = per_batch * int(dataset_batches)
    if total <= 0 or total > int(residual_hbm_bytes):
        return
    report.add(
        "feed:cacheable-dataset", "info",
        f"{num_epochs}-epoch fit streams the full dataset "
        f"({dataset_batches} batches × {per_batch / 1e6:.3f} MB wire = "
        f"{total / 1e6:.1f} MB) across the host→device link EVERY "
        f"epoch, but it fits the {residual_hbm_bytes / 1e6:.1f} MB "
        "residual-HBM estimate — fit(device_cache=True) would keep the "
        "encoded epoch on device and feed epoch 2+ device-to-device "
        "with zero h2d bytes (requires an epoch-stable reader; see "
        "MIGRATION.md \"Device-resident data path\")",
        where="device_cache", dataset_wire_bytes=int(total),
        residual_hbm_bytes=int(residual_hbm_bytes),
        num_epochs=int(num_epochs), dataset_batches=int(dataset_batches))


# --------------------------------------------------------------------------
# 10. MoE routing capacity
# --------------------------------------------------------------------------


def _phi(z: float) -> float:
    import math
    return math.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)


def _Phi(z: float) -> float:
    import math
    return 0.5 * (1.0 + math.erf(z / math.sqrt(2.0)))


def expected_moe_drop_rate(tokens: int, top_k: int, num_experts: int,
                           capacity: int) -> float:
    """Expected fraction of routed (token, choice) assignments dropped
    by the static per-expert capacity, under the *uniform random
    routing* model (each of the ``tokens * top_k`` assignments lands on
    one of ``num_experts`` experts independently — what an untrained or
    collapsed router looks like; the load-balance aux loss pushes
    TOWARD this distribution, so it is the right static prior).

    Per-expert load L ~ Binomial(T=tokens*top_k, 1/E); expected overflow
    is E[max(L - C, 0)], evaluated with the normal approximation
    ``(mu - C) * Phi(-z) + sigma * phi(z)``, ``z = (C - mu) / sigma``.
    The total drop rate is ``E * overflow / T``. Exact at the
    deterministic limit (sigma -> 0: rate = max(mu - C, 0) * E / T,
    i.e. ``1 - capacity_factor`` for capacity_factor < 1)."""
    import math
    t_assign = tokens * top_k
    if t_assign <= 0 or num_experts <= 0:
        return 0.0
    p = 1.0 / num_experts
    mu = t_assign * p
    var = t_assign * p * (1.0 - p)
    if var <= 0.0:
        overflow = max(mu - capacity, 0.0)
    else:
        sigma = math.sqrt(var)
        z = (capacity - mu) / sigma
        overflow = (mu - capacity) * _Phi(-z) + sigma * _phi(z)
    rate = num_experts * max(overflow, 0.0) / t_assign
    return min(max(rate, 0.0), 1.0)


def check_moe_capacity(moe_configs, report: LintReport,
                       drop_threshold: float = 0.05) -> None:
    """``moe:capacity`` — a routed-expert layer whose static
    ``capacity_factor``/``top_k`` combo implies an expected token drop
    rate above ``drop_threshold``. Dropped tokens pass through the MoE
    block with a zero combine weight — silent quality loss that no
    runtime error ever surfaces; the capacity is fully determined by
    the traced shapes (``parallel.moe`` computes it before any device
    work), so this is knowable before the first step.

    ``moe_configs`` is the record list a
    ``parallel.moe.capture_moe_configs()`` block collected around the
    program trace."""
    for cfg in moe_configs or ():
        rate = expected_moe_drop_rate(cfg["tokens"], cfg["top_k"],
                                      cfg["num_experts"], cfg["capacity"])
        if rate <= drop_threshold:
            continue
        lever = (f"raise capacity_factor above "
                 f"{cfg['capacity_factor']:g} (capacity scales "
                 "linearly) or lower top_k")
        report.add(
            "moe:capacity", "warning",
            f"expert capacity {cfg['capacity']} (capacity_factor="
            f"{cfg['capacity_factor']:g}, top_k={cfg['top_k']}, "
            f"{cfg['num_experts']} experts, {cfg['tokens']} tokens"
            + (f"/device over ep={cfg['ep']}" if cfg.get("ep", 1) > 1
               else "")
            + f") drops an expected {rate:.1%} of routed tokens under "
            f"uniform routing (threshold {drop_threshold:.1%}) — dropped "
            f"tokens skip the expert FFN with zero combine weight, a "
            f"silent quality loss; {lever}",
            where=cfg.get("name", "moe"),
            expected_drop_rate=rate,
            capacity=cfg["capacity"], top_k=cfg["top_k"],
            num_experts=cfg["num_experts"], tokens=cfg["tokens"],
            capacity_factor=cfg["capacity_factor"])


# --------------------------------------------------------------------------
# 11. replicated optimizer state (the ZeRO trigger)
# --------------------------------------------------------------------------


def check_replicated_optstate(params, opt_state, mesh, rules,
                              report: LintReport,
                              replicated_optstate_bytes: int = 64 << 20,
                              zero_sharding: bool = False) -> None:
    """``sharding:replicated-optstate`` — per-parameter optimizer
    accumulators (Adam moments etc.) that every device along a
    data-parallel axis holds a full copy of, totalling more than
    ``replicated_optstate_bytes`` per device.

    In this framework optimizer accums inherit their parameter's
    sharding spec (``parallel.api.shard_scope``), and data axes shard
    only the batch — so under plain dp the ENTIRE optimizer state is
    replicated N ways. That is exactly the redundancy the ZeRO /
    cross-replica-sharded weight update removes (each replica owns a
    1/N shard of opt state, all-gathers fresh params once per step):
    this lint is the static trigger for that optimization.

    With ``zero_sharding=True`` (``DistStrategy.zero_sharding`` — the
    optimization has been APPLIED) the trigger goes quiet and the
    companion info verdict ``sharding:zero-active`` reports the
    REALIZED per-device opt-state bytes instead (from the live arrays'
    shard shapes, not a projection)."""
    if mesh is None or opt_state is None or not params:
        return
    from ..parallel import mesh as mesh_lib

    data_axes = tuple(a for a in mesh_lib.data_axis_names(mesh)
                      if mesh.shape[a] > 1)
    data_n = mesh_lib.data_parallel_size(mesh)
    if data_n <= 1:
        return
    if zero_sharding:
        per_dev = 0
        leaves = 0
        for v in jax.tree.leaves(opt_state):
            shape = tuple(getattr(v, "shape", ()))
            sharding = getattr(v, "sharding", None)
            local = (sharding.shard_shape(shape)
                     if sharding is not None and shape else shape)
            per_dev += int(np.prod(local or (1,))) * np.dtype(v.dtype).itemsize
            leaves += 1
        axes_desc = "x".join(f"{a}={mesh.shape[a]}" for a in data_axes)
        report.add(
            "sharding:zero-active", "info",
            f"ZeRO weight-update sharding is on: optimizer state is "
            f"partitioned 1/{data_n} across the data axis ({axes_desc}) "
            f"— {per_dev / 1e6:.1f} MB/device realized across "
            f"{leaves} leaves",
            where="opt_state",
            opt_state_bytes_per_device=int(per_dev),
            data_shards=data_n, leaves=leaves)
        return
    from ..parallel.api import _rules as _adapt
    table = _adapt(rules, mesh)
    data_axis_set = set(data_axes)
    repl_bytes = 0.0   # per-device bytes carrying data-axis redundancy
    saved_bytes = 0.0  # what a ZeRO 1/data_n shard would reclaim
    leaves = 0
    for pname, acc in (opt_state.get("accums") or {}).items():
        if pname not in params:
            continue
        pshape = tuple(params[pname].shape)
        spec = table.spec_for(pname, pshape, mesh)
        spec_axes = [a for e in spec if e is not None
                     for a in (e if isinstance(e, tuple) else (e,))
                     if a in mesh.axis_names]
        sharded_n = int(np.prod([mesh.shape[a] for a in spec_axes] or [1]))
        sharded_data_n = int(np.prod([mesh.shape[a] for a in spec_axes
                                      if a in data_axis_set] or [1]))
        for v in jax.tree.leaves(acc):
            shape = tuple(getattr(v, "shape", ()))
            nbytes = int(np.prod(shape or (1,))) * np.dtype(v.dtype).itemsize
            # only leaves sharing the param's shape inherit its spec
            # (shard_scope's contract); scalars/step counters replicate
            inherit = shape == pshape
            per_dev = nbytes / (sharded_n if inherit else 1)
            # redundancy is what remains across the data axes AFTER the
            # spec's own data-axis sharding: an fsdp-style rule that
            # already shards along a data axis carries none there
            repl = data_n // (sharded_data_n if inherit else 1)
            if repl <= 1:
                continue
            repl_bytes += per_dev
            saved_bytes += per_dev * (repl - 1) / repl
            leaves += 1
    if leaves == 0 or repl_bytes < replicated_optstate_bytes:
        return
    axes_desc = "x".join(f"{a}={mesh.shape[a]}" for a in data_axes)
    report.add(
        "sharding:replicated-optstate", "warning",
        f"{repl_bytes / 1e6:.1f} MB/device of optimizer state "
        f"({leaves} accumulator tensors) is replicated across the "
        f"{data_n}-way data axis ({axes_desc}) — a ZeRO-style "
        f"cross-replica sharded update (each replica owns a 1/{data_n} "
        f"shard of opt state and the update, params all-gathered once "
        f"per step) reclaims {saved_bytes / 1e6:.1f} MB/device of HBM",
        where="opt_state",
        replicated_bytes_per_device=int(repl_bytes),
        zero_saving_bytes=int(saved_bytes),
        data_shards=data_n, leaves=leaves)
