"""Device-resident data path (PR 15): HBM dataset cache, the
double-buffered h2d staging ring, and on-device augmentation.

Pinned here:
- multi-epoch cache bit-identity: epoch 2+ of a cached fit moves ZERO
  h2d wire bytes (PipelineMetrics pin) and its losses/params are
  bit-identical to the streamed reference — plain, amp-dynamic-loss-
  scale, and dp-sharded trainers;
- partial caching (budget admits a prefix, the rest streams) and the
  over-budget / no-budget fallbacks to off;
- cache invalidation on resume-restore and ``reshard_restore``
  (elastic rejoin), with re-admission on the next clean epoch;
- augmentation fused-vs-sequential equivalence (crop/flip/normalize
  keyed off the step rng: ``run_steps(K)`` == K ``step()`` calls
  exactly) and eval determinism (random ops are train-only);
- the h2d-starved slow-link story: under a ``testing.faults.slow_h2d``
  throttled put, the 2-deep staging ring recovers throughput the
  blocking put serializes away, and ``overlap_hidden_s`` attributes
  the hidden transfer time;
- honest ``h2d_mbps``: cache-served chunks contribute neither bytes
  nor h2d seconds;
- the ``feed:cacheable-dataset`` lint (check_trainer door).
"""

import os
import sys
import time

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import paddle_tpu as pt
from paddle_tpu import analysis
from paddle_tpu import io as pio
from paddle_tpu import optimizer as opt
from paddle_tpu import resilience
from paddle_tpu.data.augment import AugmentSpec, FeedAugment
from paddle_tpu.data.device_cache import (DeviceCache, device_feed_nbytes,
                                          device_feed_resident_nbytes)
from paddle_tpu.data.feeder import DeviceFeeder, PipelineMetrics
from paddle_tpu.data.wire import WireSpec
from paddle_tpu.models import mnist
from paddle_tpu.parallel import DistStrategy
from paddle_tpu.testing import faults

IMG_WIRE = {"image": WireSpec.image_uint8()}
BS = 16


def _batches(num, bs=BS, seed=0):
    r = np.random.RandomState(seed)
    return [[(r.randint(0, 256, (784,)).astype(np.uint8),
              np.asarray([r.randint(0, 10)], np.int64))
             for _ in range(bs)] for _ in range(num)]


def _sample(batches):
    return {"image": np.stack([s[0] for s in batches[0]]),
            "label": np.stack([s[1] for s in batches[0]])}


def _trainer(**kw):
    return pt.Trainer(pt.build(mnist.mlp), opt.SGD(0.1), loss_name="loss",
                      feed_wire=IMG_WIRE, **kw)


def _fit(tr, batches, epochs, device_cache=None, k=4, handler=None,
         **kw):
    return pt.fit(tr, lambda: iter(batches), num_epochs=epochs,
                  feed_names=["image", "label"], dtypes=["uint8", "int64"],
                  steps_per_dispatch=k, device_cache=device_cache,
                  event_handler=handler, **kw)


def _run(epochs=3, device_cache=None, trainer_kw=None, batches=None,
         amp=None):
    batches = batches if batches is not None else _batches(8)
    losses, epoch_reports = [], []

    def handler(e):
        if e.kind == "end_step":
            losses.extend(np.asarray(e.metrics["loss"]).reshape(-1).tolist())
        elif e.kind == "end_epoch":
            epoch_reports.append(e.pipeline)

    import contextlib
    ctx = pt.amp_guard(amp) if amp else contextlib.nullcontext()
    with ctx:
        tr = _trainer(**(trainer_kw or {}))
        tr.startup(sample_feed=_sample(batches))
        _fit(tr, batches, epochs, device_cache=device_cache,
             handler=handler)
    return tr, losses, epoch_reports


def _assert_scopes_equal(a, b):
    for k in a.params:
        np.testing.assert_array_equal(np.asarray(a.params[k]),
                                      np.asarray(b.params[k]), err_msg=k)


def _epoch_h2d_deltas(reports):
    """Per-epoch h2d byte deltas from the cumulative end_epoch pipeline
    reports."""
    vals = [r["h2d_bytes"] for r in reports]
    return [b - a for a, b in zip([0] + vals[:-1], vals)]


# ---------------------------------------------------------------------------
# multi-epoch bit-identity + the zero-h2d pin
# ---------------------------------------------------------------------------


def test_cached_epochs_zero_h2d_and_bit_identical_plain():
    ref, ref_losses, _ = _run(device_cache=None)
    tr, losses, reports = _run(device_cache=1 << 30)
    assert losses == ref_losses  # BIT-identical, not approx
    _assert_scopes_equal(ref.scope, tr.scope)
    deltas = _epoch_h2d_deltas(reports)
    assert deltas[0] > 0                      # epoch 1 streamed
    assert deltas[1] == 0 and deltas[2] == 0  # epoch 2+ moved NOTHING
    assert reports[-1]["cache_hit_bytes"] > 0
    assert reports[-1]["cache_hits"] == 4     # 2 chunks x 2 cached epochs
    assert tr.device_cache.report()["state"] == "full"


def test_cached_epochs_bit_identical_amp_dynamic_loss_scale():
    strat = lambda: DistStrategy(dynamic_loss_scale=True,
                                 loss_scale_growth_interval=2)
    ref, ref_losses, _ = _run(trainer_kw={"strategy": strat()},
                              amp="bfloat16")
    tr, losses, reports = _run(device_cache=1 << 30,
                               trainer_kw={"strategy": strat()},
                               amp="bfloat16")
    assert losses == ref_losses
    _assert_scopes_equal(ref.scope, tr.scope)
    assert _epoch_h2d_deltas(reports)[1] == 0


def test_cached_epochs_bit_identical_dp_sharded_shard_resident():
    # the reference is the STREAMED run at the SAME dp mesh: cached vs
    # streamed must be bit-identical (dp vs single-device legitimately
    # differs in reduction order and is not this test's claim)
    dp_kw = lambda: {"mesh": pt.make_mesh({"dp": 8}),
                     "sharding_rules": pt.parallel.replicated()}
    ref, ref_losses, _ = _run(device_cache=None, trainer_kw=dp_kw())
    tr, losses, reports = _run(device_cache=1 << 30, trainer_kw=dp_kw())
    assert losses == ref_losses
    _assert_scopes_equal(ref.scope, tr.scope)
    assert _epoch_h2d_deltas(reports)[1] == 0
    # sharded cache: each replica holds its shard only — per-device
    # residency is a fraction of the chunk's wire bytes (the batch
    # axis is dp-sharded; only small replicated leaves count full)
    rep = tr.device_cache.report()
    assert rep["state"] == "full"
    total_wire = rep["hit_bytes"] // 2  # one epoch's worth (2 epochs hit)
    assert rep["resident_bytes"] < total_wire


# ---------------------------------------------------------------------------
# partial cache + fallbacks
# ---------------------------------------------------------------------------


def test_partial_cache_serves_prefix_streams_rest():
    # one K=4 chunk is 4 x (784 u8 + 8 i64) x BS = 50688 B resident:
    # a budget of one-and-a-half chunks admits exactly the first chunk
    chunk_bytes = 4 * BS * (784 + 8)
    ref, ref_losses, _ = _run(device_cache=None)
    tr, losses, reports = _run(device_cache=int(1.5 * chunk_bytes))
    assert losses == ref_losses
    _assert_scopes_equal(ref.scope, tr.scope)
    rep = tr.device_cache.report()
    assert rep["state"] == "partial"
    assert rep["cached_chunks"] == 1 and rep["cached_steps"] == 4
    deltas = _epoch_h2d_deltas(reports)
    # epoch 2 streamed only the un-cached half
    assert 0 < deltas[1] < deltas[0]
    assert reports[-1]["cache_hits"] == 2  # 1 chunk x 2 cached epochs


def test_over_budget_cache_off_streams_everything():
    ref, ref_losses, _ = _run(device_cache=None)
    tr, losses, reports = _run(device_cache=64)  # smaller than any chunk
    assert losses == ref_losses
    rep = tr.device_cache.report()
    assert rep["state"] == "off"
    assert "exceeds" in rep["off_reason"]
    deltas = _epoch_h2d_deltas(reports)
    assert deltas[1] == deltas[0] > 0  # every epoch streams the same


def test_auto_budget_without_hbm_stats_degrades_off():
    # CPU exposes no memory budget: device_cache=True must degrade to
    # off (with the reason recorded), never crash the fit
    tr, losses, _ = _run(epochs=2, device_cache=True)
    rep = tr.device_cache.report()
    assert rep["state"] == "off"
    assert "budget" in rep["off_reason"]
    assert len(losses) == 16  # trained normally


def test_auto_budget_resolves_from_stacked_chunks(monkeypatch):
    """fit(device_cache=True, steps_per_dispatch=K) — the flagship
    config: the advisor's residual estimate must be computed from a
    PER-STEP slice of the (K, batch, ...) chunk, not the stacked
    shape (whose trace fails and silently turned the cache off)."""
    import paddle_tpu.profiling.advisor as advisor
    monkeypatch.setattr(advisor, "device_hbm_bytes",
                        lambda device=None: 1 << 30)
    tr, losses, reports = _run(device_cache=True)
    rep = tr.device_cache.report()
    assert rep["state"] == "full", rep
    assert rep["budget_bytes"] is not None and rep["budget_bytes"] > 0
    assert _epoch_h2d_deltas(reports)[1] == 0


def test_device_cache_make_rejects_garbage():
    with pytest.raises(TypeError, match="device_cache"):
        DeviceCache.make("yes please")
    assert DeviceCache.make(None) is None
    assert DeviceCache.make(False) is None
    assert isinstance(DeviceCache.make(True), DeviceCache)
    assert DeviceCache.make(1024).budget_bytes == 1024


# ---------------------------------------------------------------------------
# invalidation: resume restore + elastic reshard
# ---------------------------------------------------------------------------


def test_resume_restore_invalidates_then_readmits(tmp_path):
    batches = _batches(8)
    cache = DeviceCache(budget_bytes=1 << 30)
    reasons = []
    orig = cache.invalidate
    cache.invalidate = lambda reason: (reasons.append(reason),
                                       orig(reason))[1]
    cfg = pt.CheckpointConfig(str(tmp_path), epoch_interval=1)
    tr = _trainer()
    tr.startup(sample_feed=_sample(batches))
    _fit(tr, batches, 1, device_cache=cache, checkpoint_config=cfg)
    assert cache.report()["state"] == "full"

    tr2 = _trainer()
    tr2.startup(sample_feed=_sample(batches))
    _fit(tr2, batches, 2, device_cache=cache, checkpoint_config=cfg,
         resume=True)
    assert any("restore" in r for r in reasons)
    # the resumed run's epoch 2 started clean: the cache re-armed,
    # re-admitted, and sealed again
    assert cache.report()["state"] == "full"
    # continuity: resumed == uninterrupted
    ref = _trainer()
    ref.startup(sample_feed=_sample(batches))
    _fit(ref, batches, 2)
    _assert_scopes_equal(ref.scope, tr2.scope)


def test_reshard_restore_invalidates_cache(tmp_path):
    batches = _batches(2, bs=16)
    feed = _sample(batches)
    mesh4 = pt.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    src = _trainer(mesh=mesh4, sharding_rules=pt.parallel.replicated())
    src.startup(sample_feed=feed)
    ck = str(tmp_path / "ck")
    pio.save_trainer(ck, src)

    mesh2 = pt.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    tgt = _trainer(mesh=mesh2, sharding_rules=pt.parallel.replicated())
    tgt.startup(sample_feed=feed)
    cache = DeviceCache(budget_bytes=1 << 30, trainer=tgt)
    assert cache.offer(1, tgt._put_feed(feed))
    cache.seal(1)
    tgt.device_cache = cache
    assert cache.ready
    resilience.reshard_restore(ck, tgt, sample_feed=feed)
    assert cache.state == "invalid"
    assert cache.invalid_reason == "reshard_restore"
    assert not cache.ready and cache.resident_bytes == 0


# ---------------------------------------------------------------------------
# on-device augmentation
# ---------------------------------------------------------------------------


def _img_model(image, label):
    """(bs, 28, 28) image -> flatten -> linear, so crop/flip have real
    spatial axes to work on."""
    import jax.numpy as jnp
    from paddle_tpu.framework import create_parameter
    w = create_parameter((784, 10), name="fc/w")
    h = jnp.matmul(image.reshape((image.shape[0], -1)), w)
    lab = jnp.squeeze(label, -1)
    logp = jax.nn.log_softmax(h)
    return {"loss": -jnp.mean(jnp.take_along_axis(
        logp, lab[:, None], axis=1))}


AUG = {"image": AugmentSpec()
       .random_crop(padding=2, axes=(1, 2))
       .random_flip(axis=2)
       .normalize(mean=127.0, std=64.0)}


def _img_feeds(n, bs=8, seed=3):
    r = np.random.RandomState(seed)
    return [{"image": r.randint(0, 256, (bs, 28, 28)).astype(np.uint8),
             "label": r.randint(0, 10, (bs, 1)).astype(np.int64)}
            for _ in range(n)]


def _aug_trainer():
    tr = pt.Trainer(pt.build(_img_model), opt.SGD(0.1), loss_name="loss",
                    augment=AUG)
    tr.startup(sample_feed=_img_feeds(1)[0])
    return tr


def test_augment_fused_k_equals_sequential_exactly():
    from paddle_tpu.data.feeder import stack_batches
    feeds = _img_feeds(4)
    t_seq = _aug_trainer()
    seq = [float(t_seq.step(f)["loss"]) for f in feeds]
    t_fused = _aug_trainer()
    fused = np.asarray(t_fused.run_steps(stack_batches(feeds))["loss"])
    np.testing.assert_array_equal(fused, np.asarray(seq, fused.dtype))
    _assert_scopes_equal(t_seq.scope, t_fused.scope)


def test_augment_randomness_advances_with_global_step():
    feeds = _img_feeds(1)
    tr = _aug_trainer()
    l0 = float(tr.step(feeds[0])["loss"])
    l1 = float(tr.step(feeds[0])["loss"])  # same batch, new step rng
    # same data, different crop/flip draw (and one SGD update): the
    # point is the stream ADVANCES — identical values would mean the
    # augmentation rng is frozen
    assert l0 != l1


def test_augment_eval_applies_only_deterministic_ops():
    feeds = _img_feeds(2)
    tr = _aug_trainer()
    a = np.asarray(tr.eval(feeds[0])["loss"])
    b = np.asarray(tr.eval(feeds[0])["loss"])
    np.testing.assert_array_equal(a, b)  # no randomness in eval
    # eval equals a normalize-only trainer's eval: crop/flip skipped
    tn = pt.Trainer(pt.build(_img_model), opt.SGD(0.1), loss_name="loss",
                    augment={"image": AugmentSpec().normalize(127.0, 64.0)})
    tn.startup(sample_feed=feeds[0])
    np.testing.assert_array_equal(a, np.asarray(tn.eval(feeds[0])["loss"]))


def test_augment_init_sees_logical_dtype_and_cache_composes():
    # uint8 feed + normalize: the model initializes at float32, and the
    # cache serves augmented training bit-identically (augment runs
    # inside the step, downstream of the cached encoded feed)
    batches = [[(s["image"][i], s["label"][i]) for i in range(8)]
               for s in _img_feeds(4, seed=5)]

    def run(device_cache=None):
        losses = []
        tr = pt.Trainer(pt.build(_img_model), opt.SGD(0.1),
                        loss_name="loss", augment=AUG)
        tr.startup(sample_feed=_img_feeds(1, seed=5)[0])
        pt.fit(tr, lambda: iter(batches), num_epochs=2,
               feed_names=["image", "label"], dtypes=["uint8", "int64"],
               steps_per_dispatch=2, device_cache=device_cache,
               event_handler=lambda e: losses.extend(
                   np.asarray(e.metrics["loss"]).reshape(-1).tolist())
               if e.kind == "end_step" else None)
        return tr, losses

    ref, ref_losses = run()
    tr, losses = run(device_cache=1 << 30)
    assert losses == ref_losses
    _assert_scopes_equal(ref.scope, tr.scope)


def test_augment_field_stream_stable_under_table_extension():
    """A field's augmentation stream is keyed by its NAME, not its
    table position: adding an unrelated field must not perturb the
    'image' field's crops/flips (the resumed-run-with-extended-table
    reproducibility contract)."""
    spec = AugmentSpec().random_flip(axis=2)
    feed = _img_feeds(1)[0]
    key = jax.random.PRNGKey(7)
    a = FeedAugment({"image": spec}).apply(feed, key, training=True)
    extended = dict(feed, aaa=np.zeros((feed["image"].shape[0], 3, 3),
                                       np.float32))
    b = FeedAugment({"image": spec,
                     "aaa": AugmentSpec().random_flip(axis=2)}).apply(
        extended, key, training=True)
    np.testing.assert_array_equal(np.asarray(a["image"]),
                                  np.asarray(b["image"]))


def test_augment_spec_validation():
    from paddle_tpu.core.errors import EnforceError
    with pytest.raises(EnforceError, match="batch"):
        AugmentSpec().random_flip(axis=0)
    with pytest.raises(EnforceError, match="padding"):
        AugmentSpec().random_crop(padding=0)
    with pytest.raises(EnforceError, match="std"):
        AugmentSpec().normalize(std=0.0)
    with pytest.raises(EnforceError, match="AugmentSpec"):
        FeedAugment({"x": "flip"})
    # value semantics: builders return new specs
    base = AugmentSpec()
    assert base.normalize() is not base and base.ops == ()


# ---------------------------------------------------------------------------
# slow-link overlap: the staging ring vs the blocking put
# ---------------------------------------------------------------------------


def _overlap_epoch(depth, delay_ms=30.0, chunks=6, consume_s=0.010):
    done = []

    def gen():
        for i in range(chunks):
            yield {"x": np.full((64,), i, np.float32)}

    m = PipelineMetrics()
    f = DeviceFeeder(gen, metrics=m, wait_fn=faults.slow_h2d(delay_ms),
                     overlap_depth=depth)
    t0 = time.perf_counter()
    for item in f:
        time.sleep(consume_s)  # the consumer's "K-step scan"
        done.append(item)
    dt = time.perf_counter() - t0
    assert len(done) == chunks
    return dt, m.report()


def test_slow_link_overlap_recovers_throughput():
    """The h2d-starved case: a 30 ms/chunk link against a 10 ms/chunk
    consumer. The blocking put serializes fill-thread work behind each
    transfer (one in flight, ~delay per chunk); the 2-deep ring
    pipelines two transfers and hides the consumer's time under them.
    The acceptance bar is 1.5x; asserted at 1.35x for CI scheduler
    slop (the bench `device_cache` row records the real delta)."""
    dt_block, rep_block = _overlap_epoch(depth=1)
    dt_overlap, rep_overlap = _overlap_epoch(depth=2)
    assert dt_block / dt_overlap >= 1.35, (dt_block, dt_overlap)
    # attribution: the ring hid transfer time; the blocking put hid none
    assert rep_block["overlap_hidden_s"] == 0.0
    assert rep_overlap["overlap_hidden_s"] > 0.0
    # both arms saw the same simulated link in h2d (full transfer wall)
    assert rep_block["stages_s"]["h2d"] >= 0.9 * 6 * 0.030
    assert rep_overlap["stages_s"]["h2d"] >= 0.9 * 6 * 0.030
    assert rep_overlap["h2d_exposed_s"] < rep_overlap["stages_s"]["h2d"]


def test_staging_ring_reader_error_still_propagates():
    def bad():
        yield {"x": np.zeros((4,), np.float32)}
        raise RuntimeError("reader exploded")

    f = DeviceFeeder(bad, metrics=PipelineMetrics())
    got = []
    with pytest.raises(RuntimeError, match="reader exploded"):
        for item in f:
            got.append(item)
    assert len(got) == 1  # the good batch drained first


def test_staging_ring_wait_error_propagates_and_unblocks():
    def boom(dev, t_submit):
        raise OSError("DMA engine fell over")

    def gen():
        for i in range(4):
            yield {"x": np.zeros((4,), np.float32)}

    f = DeviceFeeder(gen, metrics=PipelineMetrics(), wait_fn=boom)
    with pytest.raises(OSError, match="DMA"):
        list(f)
    f.close()  # no hung threads
    assert not any(t.is_alive() for t in f._threads)


def test_h2d_mbps_excludes_cache_served_chunks():
    m = PipelineMetrics()
    m.record_h2d(1_000_000, 0.1)          # a real 10 MB/s transfer
    m.record_cache_hit(50_000_000)        # a served chunk: free
    rep = m.report()
    assert rep["h2d_mbps"] == 10.0        # the link, not the cache
    assert rep["cache_hit_bytes"] == 50_000_000
    assert rep["cache_hits"] == 1
    assert rep["chunks"] == 1             # transfers only


def test_device_feed_byte_accounting():
    feed = {"x": jax.device_put(np.zeros((8, 4), np.uint8)),
            "y": np.zeros((8, 1), np.int64)}
    assert device_feed_nbytes(feed) == 8 * 4 + 8 * 8
    assert device_feed_resident_nbytes(feed) > 0


# ---------------------------------------------------------------------------
# the feed:cacheable-dataset lint
# ---------------------------------------------------------------------------


def test_lint_cacheable_dataset_fires_and_suppresses():
    batches = _batches(1)
    feed = _sample(batches)
    tr = _trainer()
    tr.startup(sample_feed=feed)
    # multi-epoch, dataset fits the (explicit) budget, cache off: flag
    rep = analysis.check_trainer(tr, feed, num_epochs=5,
                                 dataset_batches=100,
                                 hbm_budget_bytes=1 << 30)
    hits = rep.by_code("feed:cacheable-dataset")
    assert [f.where for f in hits] == ["device_cache"], rep.render()
    assert "device_cache=True" in hits[0].message
    assert rep.ok("warning")  # advisory

    # cache already on: not re-suggested
    rep2 = analysis.check_trainer(tr, feed, num_epochs=5,
                                  dataset_batches=100,
                                  hbm_budget_bytes=1 << 30,
                                  device_cache=True)
    assert not rep2.by_code("feed:cacheable-dataset"), rep2.render()

    # dataset does NOT fit the residual budget: silent
    rep3 = analysis.check_trainer(tr, feed, num_epochs=5,
                                  dataset_batches=100,
                                  hbm_budget_bytes=1 << 20)
    assert not rep3.by_code("feed:cacheable-dataset"), rep3.render()

    # single epoch: nothing to cache for
    rep4 = analysis.check_trainer(tr, feed, num_epochs=1,
                                  dataset_batches=100,
                                  hbm_budget_bytes=1 << 30)
    assert not rep4.by_code("feed:cacheable-dataset"), rep4.render()


def test_lint_cacheable_dataset_program_door_takes_explicit_budget():
    batches = _batches(1)
    feed = _sample(batches)
    rep = analysis.check(pt.build(mnist.mlp), feed,
                         feed_wire=IMG_WIRE, num_epochs=3,
                         dataset_batches=50,
                         cache_budget_bytes=1 << 30)
    assert rep.by_code("feed:cacheable-dataset"), rep.render()
