"""paddle_tpu.fleet — continuous batching + the replicated serving
fleet.

The paper's production story is a *fleet* of processes behind a
dispatch layer; ``PredictorServer`` (PR 5) is one process padding
every request alone. This package is the next tier:

- :mod:`batching` — **continuous batching**: coalesce queued requests
  into the largest precompiled bucket within a latency budget
  (:class:`BatchPolicy`), per-request row spans slicing outputs back
  per caller, bit-identical to pad-alone with zero new compiles.
  Wired into ``PredictorServer(batch_policy=...)``.
- :mod:`router` — :class:`FleetRouter`: N ``PredictorServer`` replicas
  (spawned in-process from an artifact, or adopted) behind
  health-aware least-loaded routing with shared shed/deadline policy
  at the front door, retry-on-replica-death for never-dispatched
  requests (at-most-once for dispatched ones, mirroring ``PSClient``
  push semantics), rolling hot reload (canary one replica, fan out,
  roll back on failure), and an aggregated ``/metrics`` endpoint
  merging every replica's series under a ``replica`` label.
- :mod:`decode` — the decode-side serving workload: batched
  incremental decoding with the int8 KV cache served through the
  batching scheduler.
- :mod:`remote` + :mod:`replica_main` — the **cross-process** fleet:
  ``FleetRouter.spawn(..., remote=True)`` launches each replica as a
  separate OS process serving the submit/health/kill/reload surface
  over the length-prefixed framed wire (trace tokens ride the header;
  the at-most-once ``ReplicaDied`` contract is re-proven against real
  SIGKILL and TCP partitions; probe-latency demotion degrades
  slow-but-alive replicas gracefully).

- :mod:`autoscaler` — the **closed loop**: a control thread watching
  the telemetry collector's ``/query`` trends and ``/alerts``
  transitions and sizing the fleet within a band via
  ``FleetRouter.grow()`` / ``retire(drain=True)`` — pure decision core
  (:class:`AutoscalePolicy`: hysteresis, per-direction cooldowns,
  anti-flap, quorum floor, fail-static on stale data).

Drills: ``tools/fleet_drill.py`` (kill/hang/reload over a local
in-process fleet, pkill/partition over a process fleet, a diurnal
autoscale replay, exit 0/2). See MIGRATION.md "Serving fleet &
continuous batching", "Cross-process fleet", and "Autoscaler".
"""

from .batching import BatchPolicy

_ROUTER_NAMES = ("FleetRouter", "FleetPending", "NoReplicaAvailable")
_DECODE_NAMES = ("export_decoder", "decode_server")
_REMOTE_NAMES = ("RemoteReplica", "RemotePending", "ReplicaProcess",
                 "spawn_replica", "spawn_fleet")
_AUTOSCALER_NAMES = ("Autoscaler", "AutoscalePolicy", "HttpCollectorReader",
                     "LocalCollectorReader", "ScaleDecision", "ScaleSignals",
                     "complete_buckets")


def __getattr__(name):
    # router/decode/remote import serving (which imports batching
    # above): resolving them lazily keeps the package importable from
    # serving.py without a cycle
    if name in _ROUTER_NAMES:
        from . import router
        return getattr(router, name)
    if name in _DECODE_NAMES:
        from . import decode
        return getattr(decode, name)
    if name in _REMOTE_NAMES:
        from . import remote
        return getattr(remote, name)
    if name in _AUTOSCALER_NAMES:
        from . import autoscaler
        return getattr(autoscaler, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["BatchPolicy", *_ROUTER_NAMES, *_DECODE_NAMES, *_REMOTE_NAMES,
           *_AUTOSCALER_NAMES]
