"""Parallelism over TPU meshes — the reference's ParallelExecutor +
DistributeTranspiler capabilities re-expressed as sharding (SURVEY §2.2/§7)."""

from . import api, async_ps, mesh, moe, quantized_collectives, sharding, strategy, ulysses
from .async_ps import AsyncPSTrainer, PSClient, PServerProcess
from .quantized_collectives import quantized_pmean, quantized_psum
from .mesh import DATA_AXES, DP, EP, FSDP, PP, SP, TP, data_parallel_size, initialize, make_mesh
from .moe import moe_ep_rules
from .sharding import ShardingRules, fsdp, replicated, transformer_tp_rules
from .strategy import DistStrategy
from .ulysses import ulysses_attention

__all__ = [
    "api", "async_ps", "mesh", "moe", "quantized_collectives", "sharding",
    "strategy", "ulysses",
    "AsyncPSTrainer", "PSClient", "PServerProcess",
    "quantized_pmean", "quantized_psum",
    "DATA_AXES", "DP", "EP", "FSDP", "PP", "SP", "TP",
    "data_parallel_size", "initialize", "make_mesh",
    "moe_ep_rules", "ulysses_attention",
    "ShardingRules", "fsdp", "replicated", "transformer_tp_rules",
    "DistStrategy",
]
