"""Quantized ring collectives — int8-compressed all-reduce.

EQuARX-inspired (PAPERS.md: "Efficient Quantized AllReduce in XLA"):
a ring all-reduce whose every hop carries int8 payloads with one f32
abs-max scale per chunk instead of f32/bf16 — ~4× less wire at ~1%-of-
max per-hop quantization error. XLA's native collectives (what GSPMD
inserts for the rule-table shardings) remain the default everywhere;
this exists for custom ``shard_map`` training loops on bandwidth-
limited axes — the DCN data axis of a multi-host mesh, where the
reference's gRPC pserver transport was the analogous bottleneck
(grpc_bytebuffer_stream.cc zero-copy serde solved transport overhead;
quantization attacks the byte count itself).

Usage (inside shard_map, like lax.psum)::

    grads = quantized_psum(local_grads, "dp")
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..quantize import _quant_dynamic


def _quantize(v):
    q, scale = _quant_dynamic(v, axes=tuple(range(v.ndim)))
    return q, scale.reshape(())


def _dequantize(q, scale, qmax=127.0):
    return q.astype(jnp.float32) * (scale / qmax)


def quantized_psum(x, axis_name: str):
    """Ring all-reduce of ``x`` over ``axis_name`` with int8-quantized
    hops. Drop-in for ``lax.psum`` inside ``shard_map`` when wire bytes
    matter more than exactness; accumulation stays f32, each of the
    2(P-1) hops quantizes its payload (error per hop ≤ max/127 of the
    partial being carried).

    Ring schedule (reduce-scatter then all-gather, one neighbor
    ppermute per step): rank r first forwards chunk (r+1)%P, adds its
    own contribution to the partial arriving at step k (chunk
    (r-k+1)%P), and after P-1 steps owns fully-reduced chunk (r+2)%P;
    the all-gather phase circulates the reduced chunks back around.
    """
    p = jax.lax.axis_size(axis_name)
    if p == 1:
        return x
    r = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p) for i in range(p)]

    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    chunk = -(-n // p)
    flat = jnp.pad(flat, (0, chunk * p - n))
    chunks = flat.reshape(p, chunk)

    def take(idx):
        return jax.lax.dynamic_index_in_dim(chunks, idx, 0, keepdims=False)

    def hop(v):
        q, s = _quantize(v)
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        return _dequantize(q, s)

    # reduce-scatter: after the loop `carry` is chunk (r+2)%p summed
    # over every rank
    carry = take((r + 1) % p)
    for k in range(1, p):
        carry = hop(carry) + take((r - k + 1) % p)

    # all-gather: circulate the reduced chunks; rank r receives chunk
    # owned by rank r-k, i.e. ((r-k)+2)%p, at step k. The OWNER also
    # stores the quantized roundtrip of its chunk, not the exact f32:
    # abs-max quantization is idempotent (the max maps to exactly ±127,
    # so every further hop re-encodes to the same codes), which makes
    # the final result BITWISE IDENTICAL on every rank — the all-reduce
    # contract DP replicas rely on to not drift.
    carry = _dequantize(*_quantize(carry))
    out = jnp.zeros_like(chunks)
    out = jax.lax.dynamic_update_index_in_dim(out, carry, (r + 2) % p, 0)
    recv = carry
    for k in range(1, p):
        recv = hop(recv)
        out = jax.lax.dynamic_update_index_in_dim(out, recv, (r - k + 2) % p, 0)

    return out.reshape(-1)[:n].reshape(orig_shape).astype(orig_dtype)


def quantized_pmean(x, axis_name: str):
    """Mean-reduction sibling of :func:`quantized_psum` (the gradient
    averaging form data-parallel training actually uses)."""
    return quantized_psum(x, axis_name) / jax.lax.axis_size(axis_name)
