"""Stacked-LSTM text classification — the benchmark
stacked_dynamic_lstm model (benchmark/fluid/models/stacked_dynamic_lstm
.py; the BASELINE LSTM rows: 2 layers + fc, hid=512)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import layers as L
from ..layers.rnn import dynamic_lstm
from ..metrics import accuracy


def make_model(vocab_size=5000, emb_dim=512, hidden_dim=512, num_layers=2,
               class_num=2):
    def lstm_net(word_ids, label, sequence_length=None):
        x = L.embedding(word_ids, size=[vocab_size, emb_dim])
        for _ in range(num_layers):
            x, _ = dynamic_lstm(x, hidden_dim, sequence_length=sequence_length)
        # mean-pool over valid positions (sequence_pool 'average' analog)
        if sequence_length is not None:
            t = x.shape[1]
            mask = (jnp.arange(t)[None, :] < sequence_length[:, None]).astype(x.dtype)
            pooled = (x * mask[..., None]).sum(1) / jnp.maximum(
                mask.sum(1, keepdims=True), 1.0)
        else:
            pooled = x.mean(axis=1)
        logits = L.fc(pooled, class_num)
        loss = L.mean(L.softmax_with_cross_entropy(logits, label))
        return {"loss": loss, "acc": accuracy(logits, label), "logits": logits}

    return lstm_net
