"""Parameter initializers.

Analog of python/paddle/fluid/initializer.py (Constant/Uniform/Normal/
TruncatedNormal/Xavier/MSRA/Bilinear/NumpyArray). In the reference each
initializer appends an op to the startup program; here each is a
callable ``(key, shape, dtype) -> jax.Array`` run during Program.init.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _fan_in_out(shape: Sequence[int]):
    # Matches the reference's fan computation (initializer.py): for conv
    # filters [out_c, in_c, k...] receptive field multiplies in.
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class Initializer:
    def __call__(self, key: jax.Array, shape, dtype) -> jax.Array:
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0):
        self.loc, self.scale = loc, scale

    def __call__(self, key, shape, dtype):
        return (self.loc + self.scale * jax.random.normal(key, shape, dtype=jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0):
        self.loc, self.scale = loc, scale

    def __call__(self, key, shape, dtype):
        x = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=jnp.float32)
        return (self.loc + self.scale * x).astype(dtype)


class Xavier(Initializer):
    """Glorot init (initializer.py XavierInitializer)."""

    def __init__(self, uniform: bool = True, fan_in: Optional[int] = None,
                 fan_out: Optional[int] = None):
        self.uniform, self.fan_in, self.fan_out = uniform, fan_in, fan_out

    def __call__(self, key, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return Uniform(-limit, limit)(key, shape, dtype)
        std = math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(key, shape, dtype)


class MSRA(Initializer):
    """He/Kaiming init (initializer.py MSRAInitializer)."""

    def __init__(self, uniform: bool = True, fan_in: Optional[int] = None):
        self.uniform, self.fan_in = uniform, fan_in

    def __call__(self, key, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return Uniform(-limit, limit)(key, shape, dtype)
        return Normal(0.0, math.sqrt(2.0 / fi))(key, shape, dtype)


class Bilinear(Initializer):
    """Bilinear upsample filter for conv_transpose (initializer.py
    BilinearInitializer)."""

    def __call__(self, key, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D filter shape")
        weight = np.zeros(shape, dtype=np.float32)
        kh, kw = shape[2], shape[3]
        f_h, f_w = math.ceil(kh / 2.0), math.ceil(kw / 2.0)
        c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        for i in range(kh):
            for j in range(kw):
                v = (1 - abs(i / f_h - c_h)) * (1 - abs(j / f_w - c_w))
                weight[:, :, i, j] = v
        return jnp.asarray(weight, dtype=dtype)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, key, shape, dtype):
        if tuple(self.value.shape) != tuple(shape):
            raise ValueError(f"NumpyArrayInitializer shape {self.value.shape} != {shape}")
        return jnp.asarray(self.value, dtype=dtype)


# fluid-style aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = Xavier
MSRAInitializer = MSRA
BilinearInitializer = Bilinear


_init_on_cpu = False


def force_init_on_cpu() -> bool:
    """initializer.py force_init_on_cpu flag (reference puts e.g. LR-decay
    counters on host). Initialization placement is the runtime's call on
    TPU; the flag is kept for driver compatibility."""
    return _init_on_cpu


import contextlib as _contextlib


@_contextlib.contextmanager
def init_on_cpu():
    """initializer.py init_on_cpu context manager analog."""
    global _init_on_cpu
    old, _init_on_cpu = _init_on_cpu, True
    try:
        yield
    finally:
        _init_on_cpu = old
