"""Cross-host fleet acceptance suite: artifact distribution over
FETCH/ARTIFACT, per-host agent adoption, and the reload/death
contracts that survive links no filesystem crosses.

The acceptance contracts:

  * the ``ArtifactStore`` receiver stages chunks resumably, drops
    anything whose CRC or offset disagrees (the commit reply names it
    for re-shipping), and commits atomically — the cache holds a
    fully-validated artifact dir or nothing, never a half-write;
  * ``ship_artifact`` → a real agent process is byte-identical,
    resumes a torn transfer from the staged sizes, and a re-ship of a
    committed token is a content-addressed no-op;
  * an adopted (agent-managed) replica serves with ``feed_wire``
    narrowing the SUBMIT payload (wire vs logical bytes in the serving
    report), classifies a half-open partitioned link ``ReplicaDied``
    exactly once while the agent's PS oracle proves the process alive,
    and flips ``_provably_dead`` once the agent reports the pid reaped;
  * a partition mid-artifact-fetch during a rolling cross-host reload
    surfaces typed ``ReloadFailed``, rolls the canary back, and leaves
    no half-written dir in the host cache (staging only);
  * ``tools/fleet_drill.py host_kill`` passes: a two-"host" fleet +
    collector pair survives SIGKILL of every process on one host
    (slow tier — it spawns ~7 processes).
"""

import json
import os
import socket
import sys
import time
import zlib

import numpy as np
import pytest

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import resilience
from paddle_tpu.data.wire import WireSpec
from paddle_tpu.fleet import BatchPolicy, FleetRouter
from paddle_tpu.fleet import remote as fremote
from paddle_tpu.io import artifact_fingerprint
from paddle_tpu.serving import ReloadFailed, ReplicaDied
from paddle_tpu.testing import faults

REMOTE_KW = dict(probe_timeout=0.5, down_cooldown=0.4, submit_timeout=3.0,
                 connect_timeout=1.0, reload_timeout=12.0)


def _feed(n, seed=0):
    rng = np.random.RandomState(seed)
    return {"image": rng.randn(n, 784).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


def _single(feed, i):
    return {k: np.asarray(v)[i % 8:i % 8 + 1] for k, v in feed.items()}


def _fake_artifact(root, name="model", blob_kb=192, seed=7):
    """A manifest-committed dir that is NOT a real model — the wire
    only needs the manifest, which is what makes these tests cheap."""
    d = os.path.join(str(root), name)
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(seed)
    with open(os.path.join(d, "weights.bin"), "wb") as f:
        f.write(rng.bytes(blob_kb * 1024))
    with open(os.path.join(d, "program.json"), "w") as f:
        json.dump({"name": name, "seed": seed}, f)
    resilience.write_manifest(d, meta={"fake": True})
    return d


def _expected_table(d):
    """The FETCH negotiate file table ``ship_artifact`` would send."""
    man, token = artifact_fingerprint(d)
    expected = {n: {"crc32": int(s["crc32"]), "size": int(s["size"])}
                for n, s in man["files"].items()}
    crc, size = resilience._crc32_file(
        os.path.join(d, resilience.MANIFEST_NAME))
    expected[resilience.MANIFEST_NAME] = {"crc32": crc, "size": size}
    return token, expected


def _read(path):
    with open(path, "rb") as f:
        return f.read()


def _dirs_identical(a, b):
    names = sorted(os.listdir(a))
    assert names == sorted(os.listdir(b))
    for n in names:
        assert _read(os.path.join(a, n)) == _read(os.path.join(b, n)), n


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    from paddle_tpu.models import mnist

    d = str(tmp_path_factory.mktemp("xhost") / "model")
    prog = pt.build(mnist.mlp)
    feed8 = _feed(8)
    params, state = prog.init(jax.random.PRNGKey(0), **feed8)
    pio.save_inference_model(d, prog, jax.tree.map(np.asarray, params),
                             state, feed8, batch_buckets=[4, 8])
    return {"dir": d, "prog": prog, "params": params, "state": state,
            "feed8": feed8}


@pytest.fixture(scope="module")
def host(tmp_path_factory):
    """One real per-host agent process + its client, shared across the
    module (the artifact cache is content-addressed, so tests don't
    interfere)."""
    from paddle_tpu.fleet.agent import AgentProcess

    root = str(tmp_path_factory.mktemp("hostA"))
    agent = AgentProcess(root)
    agent.wait_ready()
    cli = fremote.AgentClient(agent.addr)
    yield {"agent": agent, "cli": cli,
           "cache": os.path.join(root, "artifacts")}
    cli.close()
    agent.stop()


# -- ArtifactStore units: staging, resume, corruption, atomic commit ----------


def _chunks(path, fname, start=0, chunk=4096):
    with open(path, "rb") as f:
        f.seek(start)
        off = start
        while True:
            data = f.read(chunk)
            if not data:
                return
            yield fname, off, zlib.crc32(data) & 0xFFFFFFFF, data
            off += len(data)


def test_artifact_store_stages_resumes_and_commits_atomically(tmp_path):
    src = _fake_artifact(tmp_path / "src", blob_kb=48)
    token, expected = _expected_table(src)
    store = fremote.ArtifactStore(str(tmp_path / "cache"))
    negotiate = json.dumps({"token": token, "files": expected,
                            "commit": False}).encode()

    st = store.handle_fetch(token, negotiate)
    assert st == {"complete": False, "have": {},
                  "path": os.path.join(store.root, token)}
    final, staging = st["path"], os.path.join(store.root,
                                              token + ".staging")

    # a torn transfer: only the first 8 KiB of the blob lands
    for fname, off, crc, data in _chunks(
            os.path.join(src, "weights.bin"), "weights.bin", chunk=4096):
        if off >= 8192:
            break
        store.handle_chunk(token, fname, off, crc, data)
    assert not os.path.isdir(final)          # nothing commits by itself

    # re-negotiation resumes from the staged sizes, never from zero
    st = store.handle_fetch(token, negotiate)
    assert st["have"] == {"weights.bin": 8192}

    # a premature commit names every incomplete file and keeps the
    # intact staged prefix... except files whose CRC can't match yet
    # are dropped (weights.bin staged partial fails the whole-file CRC)
    st = store.handle_fetch(token, json.dumps(
        {"token": token, "commit": True}).encode())
    assert st["complete"] is False
    assert sorted(st["bad"]) == sorted(expected)
    assert not os.path.isdir(final)

    # finish every file (negotiate again: the partial was dropped)
    st = store.handle_fetch(token, negotiate)
    for name in expected:
        for fname, off, crc, data in _chunks(
                os.path.join(src, name), name,
                start=int(st["have"].get(name, 0))):
            store.handle_chunk(token, fname, off, crc, data)
    st = store.handle_fetch(token, json.dumps(
        {"token": token, "commit": True}).encode())
    assert st == {"complete": True, "path": final}
    assert os.path.isdir(final) and not os.path.exists(staging)
    _dirs_identical(src, final)

    # an already-committed token is the zero-byte fast path
    st = store.handle_fetch(token, negotiate)
    assert st == {"complete": True, "path": final}


def test_artifact_store_drops_corrupt_chunks_and_reships(tmp_path):
    src = _fake_artifact(tmp_path / "src", blob_kb=16)
    token, expected = _expected_table(src)
    store = fremote.ArtifactStore(str(tmp_path / "cache"))
    negotiate = json.dumps({"token": token, "files": expected,
                            "commit": False}).encode()
    commit = json.dumps({"token": token, "commit": True}).encode()
    store.handle_fetch(token, negotiate)
    staging = os.path.join(store.root, token + ".staging")

    # ship everything, but flip one byte of one program.json chunk in
    # flight (CRC now disagrees): the staged file is poisoned/dropped
    for name in expected:
        for fname, off, crc, data in _chunks(os.path.join(src, name), name):
            if name == "program.json":
                data = b"X" + data[1:]
            store.handle_chunk(token, fname, off, crc, data)
    assert not os.path.exists(os.path.join(staging, "program.json"))

    # a chunk at the wrong offset is equally dropped (no silent gap)
    good = _read(os.path.join(src, "program.json"))
    store.handle_chunk(token, "program.json", 5,
                       zlib.crc32(good) & 0xFFFFFFFF, good)
    assert not os.path.exists(os.path.join(staging, "program.json"))

    # commit names exactly the damaged file; the intact ones held
    st = store.handle_fetch(token, commit)
    assert st["complete"] is False and st["bad"] == ["program.json"]
    assert set(st["have"]) == set(expected) - {"program.json"}

    # the re-ship lap (what ship_artifact's next attempt does)
    st = store.handle_fetch(token, negotiate)
    for fname, off, crc, data in _chunks(
            os.path.join(src, "program.json"), "program.json"):
        store.handle_chunk(token, fname, off, crc, data)
    st = store.handle_fetch(token, commit)
    assert st["complete"] is True
    _dirs_identical(src, st["path"])


def test_artifact_store_rejects_unsafe_tokens_and_names(tmp_path):
    store = fremote.ArtifactStore(str(tmp_path / "cache"))
    for bad in ("", "../up", "a/b", "a\\b"):
        with pytest.raises(ValueError):
            store.handle_fetch(bad, b"{}")
    # unsafe member names never negotiate in nor land on disk
    st = store.handle_fetch("tok-1", json.dumps(
        {"token": "tok-1", "commit": False,
         "files": {"../evil": {"crc32": 0, "size": 1},
                   ".hidden": {"crc32": 0, "size": 1},
                   "ok.bin": {"crc32": 0, "size": 1}}}).encode())
    assert st["complete"] is False
    store.handle_chunk("tok-1", "../evil", 0,
                       zlib.crc32(b"x") & 0xFFFFFFFF, b"x")
    store.handle_chunk("tok-1", ".hidden", 0,
                       zlib.crc32(b"x") & 0xFFFFFFFF, b"x")
    staging = os.path.join(store.root, "tok-1.staging")
    assert os.listdir(staging) == []
    assert not os.path.exists(os.path.join(tmp_path, "evil"))
    # a chunk for a token that never negotiated is dropped silently
    store.handle_chunk("tok-ghost", "ok.bin", 0,
                       zlib.crc32(b"x") & 0xFFFFFFFF, b"x")
    assert not os.path.exists(os.path.join(store.root,
                                           "tok-ghost.staging"))


# -- SUBMIT feed narrowing (the WireSpec satellite) ---------------------------


def test_pack_tree_wire_narrowing_and_unpack_counters():
    feed = {"image": np.linspace(-1, 1, 784, dtype=np.float32)
            .reshape(1, 784),
            "label": np.array([[3]], dtype=np.int64)}
    wire = {"image": WireSpec.cast("bfloat16")}
    meta_p, payload_p = fremote.pack_tree(feed)
    meta_w, payload_w = fremote.pack_tree(feed, wire=wire)
    # bf16 halves the image bytes; the label rides passthrough
    assert len(payload_w) == 784 * 2 + 8
    assert len(payload_p) == 784 * 4 + 8
    counters = {}
    back = fremote.unpack_tree(meta_w, payload_w, counters=counters)
    assert counters == {"wire_bytes": 784 * 2 + 8,
                        "logical_bytes": 784 * 4 + 8}
    # decode restores the logical dtype, within bf16 mantissa loss
    assert back["image"].dtype == np.float32
    np.testing.assert_allclose(back["image"], feed["image"],
                               rtol=1e-2, atol=1e-2)
    np.testing.assert_array_equal(back["label"], feed["label"])


# -- the wire end to end: a real agent's artifact door ------------------------


@pytest.mark.slow
def test_agent_ship_resumes_torn_transfer_and_noops_when_cached(host,
                                                                tmp_path):
    src = _fake_artifact(tmp_path / "src", name="shipme", blob_kb=192)
    token, expected = _expected_table(src)

    # tear a transfer by hand: negotiate + one 64 KiB chunk, then drop
    # the connection with no commit
    cli = fremote._ControlClient(host["cli"].addr, timeout=10.0,
                                 connect=False)
    negotiate = json.dumps({"token": token, "files": expected,
                            "commit": False}).encode()
    st = cli.call(f"FETCH {token} {len(negotiate)}", negotiate)
    assert st["complete"] is False and st["have"] == {}
    data = _read(os.path.join(src, "weights.bin"))[:65536]
    crc = zlib.crc32(data) & 0xFFFFFFFF
    cli._sock.sendall(
        f"ARTIFACT {token} weights.bin 0 {len(data)} {crc:08x}\n".encode()
        + data)
    # ARTIFACT frames have no reply; a round trip orders the check
    cli.call(f"FETCH {token} {len(negotiate)}", negotiate)
    cli.close()

    # a fresh negotiation sees the staged bytes — the resume point
    cli = fremote._ControlClient(host["cli"].addr, timeout=10.0,
                                 connect=False)
    st = cli.call(f"FETCH {token} {len(negotiate)}", negotiate)
    assert st["have"] == {"weights.bin": 65536}
    cli.close()

    # ship_artifact picks the transfer up from there and commits
    path = fremote.ship_artifact(host["cli"].addr, src,
                                 chunk_bytes=65536)
    assert path.startswith(host["cache"])
    _dirs_identical(src, path)
    man_c, token_c = artifact_fingerprint(path)
    # a committed copy's dir is NAMED by the token, so its token
    # regenerates prefixed — the CRC suffix is the identity
    assert token_c.rsplit("-", 1)[1] == token.rsplit("-", 1)[1]

    # content-addressed no-op: same bytes, same path, zero re-stream
    assert host["cli"].ship(src) == path


# -- adopted replicas: feed_wire, at-most-once, the agent death oracle --------


@pytest.mark.slow
def test_adopted_replica_feed_wire_half_open_and_agent_oracle(artifact,
                                                              host):
    proxy = faults.LinkProxy(("127.0.0.1", 1))   # retargeted below
    rep = None
    try:
        # adopt with every cross-"host" byte routed through the proxy
        def link(addr):
            proxy.target = (str(addr[0]), int(addr[1]))
            return proxy.addr

        rep = fremote.adopt_replica(
            host["cli"], artifact["dir"], "rw0",
            remote_kw=dict(REMOTE_KW, submit_timeout=1.0,
                           feed_wire={"image": WireSpec.cast("bfloat16")}),
            link=link, workers=1, queue_size=16,
            golden_feed=artifact["feed8"],
            batch_policy=BatchPolicy(max_wait_ms=2.0))
        assert rep.agent is host["cli"] and rep.pid is not None

        out = rep.run(_single(artifact["feed8"], 0), timeout=60)
        assert "logits" in out
        # the serving report prices the narrowing: bf16 image + i64
        # label on the wire vs the logical f32 feed
        fw = rep.report()["feed_wire"]
        assert fw["wire_bytes"] == 784 * 2 + 8
        assert fw["logical_bytes"] == 784 * 4 + 8

        # half-open partition: sent, no reply — ReplicaDied exactly
        # once (the agent's PS oracle proves the process ALIVE, so
        # this is never reclassified safe-to-resend)
        proxy.partition()
        with pytest.raises(ReplicaDied):
            rep.run(_single(artifact["feed8"], 1), timeout=10)
        assert rep._provably_dead() is False
        ps = {p["pid"]: p for p in host["cli"].ps()["procs"]}
        assert ps[rep.pid]["alive"] is True

        # healed, the same replica serves again (at-most-once, not
        # dead: nothing was torn down)
        proxy.heal()
        time.sleep(REMOTE_KW["down_cooldown"] + 0.1)
        out = rep.run(_single(artifact["feed8"], 2), timeout=60)
        assert "logits" in out

        # the death oracle: agent STOP reaps the pid; PS keeps the
        # corpse listed dead, which IS the proof across any proxy
        host["cli"].stop(rep.pid)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not rep._provably_dead():
            time.sleep(0.1)
        assert rep._provably_dead() is True
        ps = {p["pid"]: p for p in host["cli"].ps()["procs"]}
        assert ps[rep.pid]["alive"] is False
    finally:
        if rep is not None:
            rep.kill()
        proxy.close()


def _assert_no_half_written_dirs(cache_root):
    """Every non-staging entry in a host artifact cache must be a
    fully manifest-validated artifact dir — the atomic-commit
    invariant a mid-fetch partition must not break."""
    for name in os.listdir(cache_root):
        path = os.path.join(cache_root, name)
        if name.endswith(".staging") or not os.path.isdir(path):
            continue
        man = resilience.read_manifest(path)
        assert man is not None, f"committed dir {name} has no manifest"
        for fname, spec in man["files"].items():
            crc, size = resilience._crc32_file(os.path.join(path, fname))
            assert crc == int(spec["crc32"]), (name, fname)
            assert size == int(spec["size"]), (name, fname)


@pytest.mark.slow
def test_crosshost_reload_midfetch_partition_rolls_back_typed(artifact,
                                                              host,
                                                              tmp_path):
    params = jax.tree.map(np.asarray, artifact["params"])
    d_v2 = str(tmp_path / "v2")
    pio.save_inference_model(
        d_v2, artifact["prog"], jax.tree.map(lambda v: v * 0.5, params),
        artifact["state"], artifact["feed8"], batch_buckets=[4, 8])
    server_kw = dict(workers=1, queue_size=16,
                     golden_feed=artifact["feed8"])
    # r1's every byte — health, SUBMIT, and the artifact fetch its
    # reload ships through — crosses a LinkProxy; r0 is direct. A long
    # health TTL keeps r1 in the rollout order after the partition;
    # r1's short reload_timeout bounds how long the blackholed fetch
    # is retried (r0 keeps the real budget for its actual swaps).
    proxy = None
    router = None
    try:
        r0 = fremote.adopt_replica(
            host["cli"], artifact["dir"], "r0",
            remote_kw=dict(REMOTE_KW, health_ttl=30.0), **server_kw)
        proxy = faults.LinkProxy(("127.0.0.1", 1))

        def link(addr):
            proxy.target = (str(addr[0]), int(addr[1]))
            return proxy.addr

        r1 = fremote.adopt_replica(
            host["cli"], artifact["dir"], "r1",
            remote_kw=dict(REMOTE_KW, health_ttl=30.0,
                           reload_timeout=0.5),
            link=link, **server_kw)
        router = FleetRouter({"r0": r0, "r1": r1},
                             dirname=artifact["dir"], server_kw=server_kw,
                             probe_timeout=1.0, remote=True,
                             remote_kw=dict(REMOTE_KW),
                             agents=[host["cli"]], link=link)
        out_v1 = router.run(_single(artifact["feed8"], 0), timeout=60)
        router.health()                     # refresh the cache pre-cut
        proxy.partition()
        # the canary (r0) ships + swaps to v2; r1's artifact fetch
        # blackholes mid-stream → connection-shaped → typed rollback
        with pytest.raises(ReloadFailed, match="rolled back"):
            router.reload(d_v2)
        assert router.dirname == artifact["dir"]
        # canary rolled back: gen 1 → 2 (v2 swap) → 3 (rollback), and
        # it serves the ORIGINAL weights again
        assert r0.generation == 3
        out_after = router.run(_single(artifact["feed8"], 0), timeout=60)
        np.testing.assert_array_equal(np.asarray(out_v1["logits"]),
                                      np.asarray(out_after["logits"]))
        # the invariant the partition must not break: the host cache
        # holds only fully-validated dirs (v2 committed whole by the
        # canary's ship) — a torn fetch leaves staging, never a final
        _assert_no_half_written_dirs(host["cache"])
    finally:
        if proxy is not None:
            proxy.heal()   # close() must not hang on the blackhole
        if router is not None:
            router.close(drain=False, timeout=10)
        if proxy is not None:
            proxy.close()


# -- the acceptance drill: whole-host SIGKILL ---------------------------------


@pytest.mark.slow
def test_fleet_drill_host_kill_passes():
    """Two-"host" fleet + primary/standby collector pair under ~3x
    saturation survives SIGKILL of EVERY process on one host: zero
    accepted-but-undispatched requests lost, ``ReplicaDied`` once per
    in-flight casualty, ``replace()`` respawns via the surviving
    host's agent, and the standby collector promotes from replicated
    segments with zero tick loss + the firing alert carried over
    (exit 0)."""
    from tools import fleet_drill

    assert fleet_drill.main(["--drills", "host_kill",
                             "--replicas", "2", "--requests", "30"]) == 0
