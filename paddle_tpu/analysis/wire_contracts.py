"""Framed-verb wire-contract extraction and drift checking.

The repo speaks one framed protocol on three surfaces — async-PS
(``parallel/async_ps.py`` ↔ ``native/pserver.cc``), the fleet control
plane (``fleet/remote.py`` ↔ ``fleet/replica_main.py``), and telemetry
shipping (``telemetry/shipper.py`` ↔ ``telemetry/collector.py``): one
ASCII header line (``VERB arg1 arg2 ... [trace=<id>]``) followed by
zero or more length-prefixed binary bodies, with an optional framed
reply body.

This module *extracts each verb's frame schema from both sides* —
the Python client's ``_request``/``call`` f-string headers and payload
concatenations, the Python server's ``verb == "X"`` dispatch branches
(``parts[i]`` arity, ``read_exact`` body reads, ``_reply_json``
replies), and the C server's ``sscanf`` format table — into one
machine-readable verb table, then diffs the two sides:

- ``wire:schema-drift`` (error) — client and server disagree on header
  arity, request-body count, or reply-body count. The PR-8 IMPORT bug
  (client sends ``value``/``accum`` as two concatenated bodies, server
  read one combined body) is exactly this finding.
- ``wire:retry-unsafe`` (error) — the server declares a verb
  ``at-most-once`` (``# retry: at-most-once`` / ``// retry:
  at-most-once`` annotation) but the client sends it on a retrying
  path (``idempotent=True``).
- ``wire:unknown-verb`` (warning) — a verb spoken on only one side.

Retry classification comes from the client's ``idempotent=`` kwarg
(per-wrapper defaults below) plus the explicit ``retry:`` comment
annotation convention on either side.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Tuple

from .report import LintReport

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_VERB_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
_RETRY_RE = re.compile(r"(?:#|//)\s*retry:\s*(at-most-once|idempotent)")

IDEMPOTENT, AT_MOST_ONCE = "idempotent", "at-most-once"


@dataclasses.dataclass
class VerbSide:
    """One side's view of one verb's frame schema."""
    verb: str
    args: int                 # header tokens after the verb (trace excluded)
    bodies: int               # framed request bodies
    reply_bodies: int         # framed reply bodies (0 or 1)
    trace: bool = False       # optional `` trace=<id>`` token supported
    retry: str = IDEMPOTENT   # retry classification on this side
    where: str = ""           # file:line provenance

    def frame(self) -> Tuple[int, int, int]:
        return (self.args, self.bodies, self.reply_bodies)


# --------------------------------------------------------------------------
# shared helpers
# --------------------------------------------------------------------------


def _read(path_or_src: str) -> str:
    if "\n" in path_or_src or not os.path.exists(path_or_src):
        return path_or_src
    with open(path_or_src, encoding="utf-8") as fh:
        return fh.read()


def _retry_annotations(src: str) -> Dict[int, str]:
    """lineno → retry class for every ``# retry:`` comment."""
    out = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _RETRY_RE.search(line)
        if m:
            out[i] = m.group(1)
    return out


def _func_retry(annotations: Dict[int, str], start: int,
                end: int) -> Optional[str]:
    for ln, cls in annotations.items():
        if start <= ln <= end:
            return cls
    return None


def _merge(table: Dict[str, VerbSide], side: VerbSide) -> None:
    """Merge one extraction into the per-side verb table. Multiple
    callsites of the same verb keep the widest schema (they should
    agree; the cross-side diff is what matters)."""
    prev = table.get(side.verb)
    if prev is None:
        table[side.verb] = side
        return
    prev.args = max(prev.args, side.args)
    prev.bodies = max(prev.bodies, side.bodies)
    prev.reply_bodies = max(prev.reply_bodies, side.reply_bodies)
    prev.trace = prev.trace or side.trace
    if AT_MOST_ONCE in (prev.retry, side.retry):
        prev.retry = AT_MOST_ONCE


# --------------------------------------------------------------------------
# Python client scraper
# --------------------------------------------------------------------------


def _is_trace_expr(expr: ast.AST, localmap: Dict[str, ast.AST]) -> bool:
    """Is this placeholder the optional trace suffix? Either a direct
    ``self._trace_suffix(...)`` call or a local bound to one."""
    if isinstance(expr, ast.Name):
        expr = localmap.get(expr.id, expr)
    if isinstance(expr, ast.Call):
        fn = expr.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        return name == "_trace_suffix"
    return False


def _header_tokens(node: ast.AST, localmap: Dict[str, ast.AST]):
    """Parse a header template (Constant str or JoinedStr) → (verb,
    args, trace) or None when it is not a verb header."""
    pieces: List[Tuple[str, object]] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        pieces = [("lit", node.value)]
    elif isinstance(node, ast.JoinedStr):
        for v in node.values:
            if isinstance(v, ast.Constant):
                pieces.append(("lit", str(v.value)))
            elif isinstance(v, ast.FormattedValue):
                pieces.append(("ph", v.value))
    else:
        return None

    tokens: List[List[Tuple[str, object]]] = [[]]
    for kind, val in pieces:
        if kind == "lit":
            for part in re.split(r"(\s+)", val):
                if not part:
                    continue
                if part.isspace():
                    tokens.append([])
                else:
                    tokens[-1].append(("lit", part))
        else:
            tokens[-1].append(("ph", val))
    tokens = [t for t in tokens if t]
    if not tokens:
        return None
    head = tokens[0]
    if not (len(head) == 1 and head[0][0] == "lit"
            and _VERB_RE.match(str(head[0][1]))):
        return None
    verb = str(head[0][1])

    args, trace = 0, False
    for tok in tokens[1:]:
        # a literal `trace=` piece marks the WHOLE token as the optional
        # trace field (``trace={span}``); a `_trace_suffix(...)`
        # placeholder glued onto another token (``{name}{suffix}``) only
        # removes itself
        if any(kind == "lit" and str(val).startswith("trace=")
               for kind, val in tok):
            trace = True
            continue
        kept = [(kind, val) for kind, val in tok
                if not (kind == "ph" and _is_trace_expr(val, localmap))]
        if len(kept) < len(tok):
            trace = True
        if kept:
            args += 1
    return verb, args, trace


def _body_count(expr: Optional[ast.AST], localmap: Dict[str, ast.AST],
                depth: int = 0) -> int:
    """Framed request bodies = ``+``-concatenated bytes segments in the
    payload expression (this is what catches a combined-body read on
    the other side: ``v.tobytes() + a.tobytes()`` is TWO bodies)."""
    if expr is None:
        return 0
    if isinstance(expr, ast.Constant) and expr.value in (b"", "", None):
        return 0
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return (_body_count(expr.left, localmap, depth)
                + _body_count(expr.right, localmap, depth))
    if isinstance(expr, ast.Name) and depth < 3:
        bound = localmap.get(expr.id)
        if bound is not None:
            return _body_count(bound, localmap, depth + 1)
    return 1


#: per-wrapper request-call defaults: (idempotent default, reply-body
#: policy). Policy: "body_len" = framed reply iff a body_len kwarg is
#: passed; "always"/"never" = the wrapper itself decides; extra_args /
#: bodies = tokens the wrapper appends beyond the template.
DEFAULT_REQUEST_FUNCS = {
    "_request": {"idempotent": True, "reply": "body_len"},
    "call": {"idempotent": True, "reply": "always"},
    "_one_shot": {"idempotent": False, "reply": "always"},
    "_call": {"idempotent": True, "reply": "never",
              "extra_args": 1, "bodies": 1},
}


def scrape_python_client(path_or_src: str, filename: str = "",
                         request_funcs: Optional[dict] = None
                         ) -> Dict[str, VerbSide]:
    src = _read(path_or_src)
    filename = filename or (path_or_src if "\n" not in path_or_src
                            else "<client>")
    funcs_cfg = request_funcs if request_funcs is not None \
        else DEFAULT_REQUEST_FUNCS
    tree = ast.parse(src, filename=filename)
    annotations = _retry_annotations(src)
    table: Dict[str, VerbSide] = {}

    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        localmap: Dict[str, ast.AST] = {}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                localmap.setdefault(sub.targets[0].id, sub.value)
        fn_retry = _func_retry(annotations, fn.lineno,
                               getattr(fn, "end_lineno", fn.lineno))

        headers_in_calls = set()
        for call in [n for n in ast.walk(fn) if isinstance(n, ast.Call)]:
            cfn = call.func
            cname = cfn.attr if isinstance(cfn, ast.Attribute) else (
                cfn.id if isinstance(cfn, ast.Name) else "")
            if cname not in funcs_cfg or not call.args:
                continue
            cfg = funcs_cfg[cname]
            parsed = _header_tokens(call.args[0], localmap)
            if parsed is None:
                continue
            headers_in_calls.add(id(call.args[0]))
            verb, args, trace = parsed
            payload = call.args[1] if len(call.args) > 1 else None
            if payload is None:
                for kw in call.keywords:
                    if kw.arg in ("payload", "body", "data"):
                        payload = kw.value
            idempotent = cfg["idempotent"]
            body_len_kw = False
            for kw in call.keywords:
                if kw.arg == "idempotent" and isinstance(kw.value,
                                                         ast.Constant):
                    idempotent = bool(kw.value.value)
                if kw.arg == "body_len":
                    body_len_kw = True
            reply = {"always": 1, "never": 0}.get(
                cfg["reply"], 1 if body_len_kw else 0)
            retry = fn_retry or (IDEMPOTENT if idempotent else AT_MOST_ONCE)
            _merge(table, VerbSide(
                verb=verb, args=args + cfg.get("extra_args", 0),
                bodies=cfg.get("bodies", _body_count(payload, localmap)),
                reply_bodies=reply, trace=trace, retry=retry,
                where=f"{filename}:{call.lineno}"))

        # manually-framed headers: an f-string verb header assigned to a
        # local and sent via sock.sendall(header + body1 + body2 ...)
        _scrape_manual(fn, localmap, headers_in_calls, annotations,
                       filename, table)
    return table


def _flatten_add(expr: ast.AST) -> List[ast.AST]:
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _flatten_add(expr.left) + _flatten_add(expr.right)
    return [expr]


def _scrape_manual(fn, localmap, headers_in_calls, annotations, filename,
                   table: Dict[str, VerbSide]) -> None:
    fn_retry = _func_retry(annotations, fn.lineno,
                           getattr(fn, "end_lineno", fn.lineno))
    header_vars: Dict[str, Tuple[str, int, bool, int]] = {}
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                and isinstance(sub.targets[0], ast.Name):
            for j in ast.walk(sub.value):
                if isinstance(j, ast.JoinedStr) and id(j) not in \
                        headers_in_calls:
                    parsed = _header_tokens(j, localmap)
                    if parsed is not None:
                        verb, args, trace = parsed
                        header_vars[sub.targets[0].id] = (
                            verb, args, trace, sub.lineno)
    for call in [n for n in ast.walk(fn) if isinstance(n, ast.Call)]:
        cfn = call.func
        if not (isinstance(cfn, ast.Attribute) and cfn.attr == "sendall"
                and call.args):
            continue
        arg = call.args[0]
        # raw transport verb: sendall(b"QUIT\n")
        if isinstance(arg, ast.Constant) and isinstance(arg.value, bytes):
            m = re.match(rb"^([A-Z][A-Z0-9_]*)\n$", arg.value)
            if m:
                _merge(table, VerbSide(
                    verb=m.group(1).decode(), args=0, bodies=0,
                    reply_bodies=0, retry=fn_retry or IDEMPOTENT,
                    where=f"{filename}:{call.lineno}"))
            continue
        leaves = _flatten_add(arg)
        hdr = next((l for l in leaves if isinstance(l, ast.Name)
                    and l.id in header_vars), None)
        if hdr is None:
            continue
        verb, args, trace, line = header_vars[hdr.id]
        bodies = sum(_body_count(l, localmap) for l in leaves
                     if l is not hdr
                     and not (isinstance(l, ast.Constant)
                              and l.value == b"\n"))
        _merge(table, VerbSide(
            verb=verb, args=args, bodies=bodies, reply_bodies=0,
            trace=trace, retry=fn_retry or IDEMPOTENT,
            where=f"{filename}:{line}"))


# --------------------------------------------------------------------------
# Python server scraper
# --------------------------------------------------------------------------


def scrape_python_server(path_or_src: str, filename: str = "",
                         dispatchers: Tuple[str, ...] = (),
                         parts_var: str = "parts",
                         body_reader: str = "read_exact",
                         reply_marker: str = "_reply_json"
                         ) -> Dict[str, VerbSide]:
    src = _read(path_or_src)
    filename = filename or (path_or_src if "\n" not in path_or_src
                            else "<server>")
    tree = ast.parse(src, filename=filename)
    annotations = _retry_annotations(src)
    funcs: Dict[str, ast.FunctionDef] = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    table: Dict[str, VerbSide] = {}

    for dname in dispatchers:
        disp = funcs.get(dname)
        if disp is None:
            continue
        for branch in ast.walk(disp):
            if not isinstance(branch, ast.If):
                continue
            verbs = _branch_verbs(branch.test)
            if not verbs:
                continue
            contrib = _scan_branch(branch.body, parts_var, body_reader,
                                   reply_marker, funcs, annotations, src,
                                   branch_lineno=branch.lineno)
            args, bodies, reply, trace, retry, line = contrib
            for verb in verbs:
                _merge(table, VerbSide(
                    verb=verb, args=args, bodies=bodies,
                    reply_bodies=reply, trace=trace,
                    retry=retry or IDEMPOTENT,
                    where=f"{filename}:{line}"))
    return table


def _branch_verbs(test: ast.AST) -> List[str]:
    """CAPS string comparands in a dispatch test: ``verb == "X"``,
    ``parts[0] == "X"``, ``verb in ("X", "Y")`` — including inside
    ``and``/``or`` guards."""
    verbs: List[str] = []
    for cmp in [n for n in ast.walk(test) if isinstance(n, ast.Compare)]:
        for comparator in cmp.comparators:
            consts = [comparator] if isinstance(comparator, ast.Constant) \
                else (list(comparator.elts)
                      if isinstance(comparator, (ast.Tuple, ast.List,
                                                 ast.Set)) else [])
            for c in consts:
                if isinstance(c, ast.Constant) and isinstance(c.value, str) \
                        and _VERB_RE.match(c.value):
                    verbs.append(c.value)
    return verbs


def _scan_branch(stmts, parts_var, body_reader, reply_marker, funcs,
                 annotations, src, branch_lineno: int = 0):
    args, bodies, reply, trace = 0, 0, 0, False
    retry: Optional[str] = None
    line = stmts[0].lineno if stmts else 0
    regions: List[Tuple[int, int]] = []

    def scan(nodes, pvar):
        nonlocal args, bodies, reply, trace
        for node in nodes:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Subscript) and \
                        isinstance(sub.value, ast.Name) and \
                        sub.value.id == pvar:
                    sl = sub.slice
                    if isinstance(sl, ast.Constant) and \
                            isinstance(sl.value, int):
                        args = max(args, sl.value)
                    elif isinstance(sl, ast.Slice):
                        trace = True
                elif isinstance(sub, ast.Call):
                    fname = sub.func.attr \
                        if isinstance(sub.func, ast.Attribute) else (
                            sub.func.id if isinstance(sub.func, ast.Name)
                            else "")
                    if fname == body_reader:
                        bodies += 1
                    elif fname == reply_marker:
                        reply = 1

    scan(stmts, parts_var)
    # the region opens at the `if` line, not the first statement: a
    # `# retry:` comment sitting right under the dispatch test (before
    # any statement) still belongs to the branch
    start = branch_lineno or (min(s.lineno for s in stmts) if stmts else 0)
    end = max(getattr(s, "end_lineno", s.lineno) for s in stmts) \
        if stmts else 0
    regions.append((start, end))

    # one-level expansion into self.handle_*(...) — the branch passes
    # `parts` (mapped to the callee's matching param) and the callee
    # does the body reads / json reply
    for node in stmts:
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)):
                continue
            callee = funcs.get(sub.func.attr)
            if callee is None or sub.func.attr in (body_reader,
                                                   reply_marker):
                continue
            pvar = parts_var
            params = [a.arg for a in callee.args.args if a.arg != "self"]
            for pos, argnode in enumerate(sub.args):
                if isinstance(argnode, ast.Name) \
                        and argnode.id == parts_var and pos < len(params):
                    pvar = params[pos]
            scan(callee.body, pvar)
            regions.append((callee.lineno,
                            getattr(callee, "end_lineno", callee.lineno)))

    for rs, re_ in regions:
        cls = _func_retry(annotations, rs, re_)
        if cls == AT_MOST_ONCE or (cls and retry is None):
            retry = cls
    return args, bodies, reply, trace, retry, line


# --------------------------------------------------------------------------
# C server scraper (native/pserver.cc)
# --------------------------------------------------------------------------

_C_SSCANF_RE = re.compile(
    r'sscanf\(line\.c_str\(\),\s*"([A-Z][A-Z0-9_]*)((?:\s+%[^"\s]+)*)"',
    re.S)
_C_EQ_RE = re.compile(r'line\s*==\s*"([A-Z][A-Z0-9_]*)"')


def scrape_c_server(path_or_src: str, filename: str = ""
                    ) -> Dict[str, VerbSide]:
    """Scrape the C side's verb table out of ``ServeClient``'s
    ``sscanf``-format dispatch chain. The ``line.rfind(...)``
    error-backstop branch is deliberately NOT a verb definition (it
    only classifies malformed headers) and is ignored: only ``sscanf``
    formats and ``line == "VERB"`` equality branches define verbs."""
    text = _read(path_or_src)
    filename = filename or (path_or_src if "\n" not in path_or_src
                            else "<pserver.cc>")
    start = text.find("ServeClient")
    end = text.find("int main(")
    region_text = text[max(start, 0):end if end > 0 else len(text)]
    offset = max(start, 0)

    anchors: List[Tuple[int, str, int]] = []   # (pos, verb, args)
    for m in _C_SSCANF_RE.finditer(region_text):
        fmt_args = m.group(2).count("%")
        anchors.append((m.start(), m.group(1), fmt_args))
    for m in _C_EQ_RE.finditer(region_text):
        anchors.append((m.start(), m.group(1), 0))
    anchors.sort()

    table: Dict[str, VerbSide] = {}
    for i, (pos, verb, args) in enumerate(anchors):
        nxt = anchors[i + 1][0] if i + 1 < len(anchors) else len(region_text)
        branch = region_text[pos:nxt]
        line = text.count("\n", 0, offset + pos) + 1
        retry_m = _RETRY_RE.search(branch)
        _merge(table, VerbSide(
            verb=verb, args=args,
            bodies=branch.count("ReadBody("),
            reply_bodies=1 if "&payload" in branch else 0,
            trace="WithTrace(" in branch,
            retry=retry_m.group(1) if retry_m else IDEMPOTENT,
            where=f"{filename}:{line}"))
    return table


# --------------------------------------------------------------------------
# surfaces, comparison, verb table
# --------------------------------------------------------------------------

#: verbs owned by the shared framed transport (FramedClient.close), not
#: by any one surface's client module
TRANSPORT_VERBS = ("QUIT",)

SURFACES = {
    "ps": {
        "client": os.path.join(_PKG_ROOT, "parallel", "async_ps.py"),
        "server": os.path.join(_PKG_ROOT, "native", "pserver.cc"),
        "server_kind": "c",
    },
    "fleet": {
        "client": os.path.join(_PKG_ROOT, "fleet", "remote.py"),
        # the fleet control plane has TWO server processes on one
        # client module: the replica (SUBMIT/RELOAD/... + the artifact
        # door) and the per-host agent (SPAWN/STOP/PS + the same
        # artifact door) — both dispatch in a serve_conn loop
        "server": [os.path.join(_PKG_ROOT, "fleet", "replica_main.py"),
                   os.path.join(_PKG_ROOT, "fleet", "agent.py")],
        "server_kind": "py",
        "dispatchers": ("serve_conn",),
    },
    "telemetry": {
        "client": os.path.join(_PKG_ROOT, "telemetry", "shipper.py"),
        "server": os.path.join(_PKG_ROOT, "telemetry", "collector.py"),
        "server_kind": "py",
        "dispatchers": ("_serve_conn", "_dispatch"),
    },
}

#: the transport client file scanned for TRANSPORT_VERBS on surfaces
#: whose client module rides FramedClient
_TRANSPORT_CLIENT = os.path.join(_PKG_ROOT, "parallel", "async_ps.py")


def scrape_surface(name: str, cfg: Optional[dict] = None
                   ) -> Tuple[Dict[str, VerbSide], Dict[str, VerbSide]]:
    cfg = cfg or SURFACES[name]
    client = scrape_python_client(cfg["client"])
    if cfg.get("server_kind", "py") == "c":
        server = scrape_c_server(cfg["server"])
    else:
        # "server" may be ONE path or a list of server modules that
        # speak the same surface (fleet: replica + per-host agent);
        # their verb tables merge exactly like multiple callsites do
        paths = cfg["server"]
        if isinstance(paths, str):
            paths = [paths]
        server = {}
        for path in paths:
            one = scrape_python_server(
                path, dispatchers=cfg.get("dispatchers", ()),
                parts_var=cfg.get("parts_var", "parts"),
                body_reader=cfg.get("body_reader", "read_exact"),
                reply_marker=cfg.get("reply_marker", "_reply_json"))
            for side in one.values():
                _merge(server, side)
    # fleet/telemetry clients inherit the framed transport's QUIT
    if cfg.get("server_kind") != "c" and cfg["client"] != _TRANSPORT_CLIENT \
            and os.path.exists(_TRANSPORT_CLIENT):
        base = scrape_python_client(_TRANSPORT_CLIENT)
        for verb in TRANSPORT_VERBS:
            if verb in base and verb not in client:
                client[verb] = base[verb]
    return client, server


def compare_tables(surface: str, client: Dict[str, VerbSide],
                   server: Dict[str, VerbSide]) -> LintReport:
    report = LintReport(f"wire:{surface}")
    for verb in sorted(set(client) | set(server)):
        c, s = client.get(verb), server.get(verb)
        if c is None or s is None:
            side = "server" if c is None else "client"
            have = (s or c)
            report.add(
                "wire:unknown-verb", "warning",
                f"{verb} is spoken only by the {side} ({have.where}) — "
                f"the other side will reject or desync on it",
                where=f"{verb}", path=side)
            continue
        for field, cv, sv in (("arity", c.args, s.args),
                              ("bodies", c.bodies, s.bodies),
                              ("reply", c.reply_bodies, s.reply_bodies)):
            if cv != sv:
                report.add(
                    "wire:schema-drift", "error",
                    f"{verb}: client {field}={cv} ({c.where}) but server "
                    f"{field}={sv} ({s.where}) — the framed stream "
                    f"desyncs or truncates",
                    where=f"{verb}:{field}", expected=sv, got=cv)
        if s.retry == AT_MOST_ONCE and c.retry == IDEMPOTENT:
            report.add(
                "wire:retry-unsafe", "error",
                f"{verb}: server declares at-most-once ({s.where}) but "
                f"the client path retries (idempotent=True, {c.where}) — "
                f"a lost reply re-applies a non-idempotent effect",
                where=verb, expected=AT_MOST_ONCE, got="retrying-client")
    return report


def check_wire() -> List[Tuple[str, LintReport]]:
    """All three surfaces → ``(subject, report)`` pairs for the gate."""
    out = []
    for name in SURFACES:
        client, server = scrape_surface(name)
        out.append((f"wire:{name}", compare_tables(name, client, server)))
    return out


def verb_table() -> List[dict]:
    """The merged machine-readable verb table across all surfaces —
    what ``python -m paddle_tpu.analysis --wire-table`` renders and
    MIGRATION.md's "Wire contracts" section is generated from."""
    rows = []
    for name in SURFACES:
        client, server = scrape_surface(name)
        for verb in sorted(set(client) | set(server)):
            c, s = client.get(verb), server.get(verb)
            both = c is not None and s is not None
            ref = s or c
            retry = AT_MOST_ONCE if AT_MOST_ONCE in (
                (c.retry if c else None), (s.retry if s else None)) \
                else IDEMPOTENT
            rows.append({
                "surface": name, "verb": verb,
                "sides": "both" if both else
                ("client-only" if s is None else "server-only"),
                "args": ref.args,
                "bodies": ref.bodies,
                "reply_bodies": ref.reply_bodies,
                "trace": bool((c and c.trace) or (s and s.trace)),
                "retry": retry,
                "client": c.where if c else "-",
                "server": s.where if s else "-",
            })
    return rows


def render_verb_table_md(rows: Optional[List[dict]] = None) -> str:
    """Markdown for MIGRATION.md's "Wire contracts" section."""
    rows = verb_table() if rows is None else rows
    out = ["<!-- generated by: python -m paddle_tpu.analysis"
           " --wire-table -->", ""]
    for surface in dict.fromkeys(r["surface"] for r in rows):
        out.append(f"### `{surface}` surface")
        out.append("")
        out.append("| verb | sides | header args | request bodies "
                   "| reply bodies | trace | retry |")
        out.append("|---|---|---|---|---|---|---|")
        for r in rows:
            if r["surface"] != surface:
                continue
            out.append(
                f"| `{r['verb']}` | {r['sides']} | {r['args']} "
                f"| {r['bodies']} | {r['reply_bodies']} "
                f"| {'yes' if r['trace'] else '—'} | {r['retry']} |")
        out.append("")
    return "\n".join(out)
