"""Benchmark driver — fluid_benchmark.py analog (benchmark/fluid/).

Default (no args — the driver's command) runs the FULL suite in
priority order: the five BASELINE configs (MNIST MLP, ResNet-50,
Transformer-base, BERT-base, DeepFM) and the ResNet-50 serving rows
first, then GPT, VGG-16, AlexNet, GoogLeNet, SE-ResNeXt-50, LSTM
(512/1280-hidden), long-context transformer (seq 4096), GPT at seq
32k, the 10M-row sharded-embedding DeepFM, GoogLeNet serving, and
KV-cache GPT decode. The int8 serving variant runs the REAL int8
datapath (quantize.int8_serving). Each config runs in its own
subprocess under a hard timeout; on SIGTERM the suite emits the partial
record instead of losing the run. Prints ONE JSON line:

  {"metric": "suite", "value": <headline train MFU>, "unit": "MFU",
   "vs_baseline": <resnet50 imgs/sec ratio vs reference>,
   "configs": {name: {"value", "unit", "mfu", "compute_only", ...}}}

When the measured host->device bandwidth is below LINK_DEGRADED_MBPS
(no real TPU host is that slow — only the dev tunnel), the headline
switches to the compute-only MFU variant, the unit says so
("MFU (compute-only; link degraded)"), and the record carries
"link_degraded": true; per-config records keep both variants always.

Honesty rules (VERDICT r2 #1):
- throughput is measured WITH the input pipeline in the loop: host
  numpy batches stream through DeviceFeeder (double-buffered host→HBM
  transfer, data/feeder.py) exactly as `fit()` trains; the pre-staged
  compute-only number is kept as a secondary field;
- MFU uses analytic model FLOPs (paddle_tpu/core/flops.py — causal
  attention halved, elementwise excluded: undercounts, never inflates)
  over the chip's published bf16 peak (table by device_kind, measured
  matmul fallback);
- vs_baseline ratios against the reference's 2018-Xeon/K40m numbers are
  reported per config where they exist, but the headline metric is MFU.
"""

from __future__ import annotations

import argparse
import contextlib
import functools
import json
import os
import time

import numpy as np

BASELINES = {
    # reference numbers from BASELINE.md (images/sec or ms/batch-derived)
    "resnet50": 81.69,        # images/sec, bs=64 (IntelOptimizedPaddle.md:39-45)
    "vgg16": 28.46,           # images/sec, bs=64 VGG-19 row (closest config)
    "alexnet": 626.53,        # images/sec, bs=256 (IntelOptimizedPaddle.md:59-65)
    "googlenet": 250.46,      # images/sec, bs=64 (IntelOptimizedPaddle.md:49-55)
    "lstm": 64 / 0.184,       # samples/sec from 184 ms/batch bs=64 K40m
    "lstm_big": 256 / 1.655,  # bs=256 hid=1280: 1655 ms/batch K40m
    "resnet50_infer_fp32": 217.69,   # images/sec, bs=16 (IntelOptimizedPaddle.md:81-87)
    "resnet50_infer_bf16": 217.69,
    "resnet50_infer_int8": 217.69,
    "googlenet_infer": 600.94,       # images/sec, bs=16 (IntelOptimizedPaddle.md:91-97)
}


def _init_jax():
    """Make the JAX_PLATFORMS env var authoritative: the axon boot hook
    force-sets jax_platforms after env parsing, so an explicit
    JAX_PLATFORMS=cpu (tests / tunnel-down debugging) would otherwise
    still initialize the remote backend.

    Also enables the persistent XLA compile cache (BENCH_COMPILE_CACHE=0
    disables): every config runs in a fresh subprocess, so without it a
    retry after a link flake re-pays the full model compile — often the
    difference between a row landing inside its timeout window or not.
    The cache keys on the HLO hash, so edited model code can never be
    served a stale executable; compile time is outside the timed region
    either way (it only burns wall-clock budget)."""
    import os

    import jax
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    if os.environ.get("BENCH_COMPILE_CACHE", "1") != "0":
        here = os.path.dirname(os.path.abspath(__file__))
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(here, ".jax_cache_bench"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return jax


def _sync(out):
    # device_get of a scalar forces a real sync — block_until_ready alone
    # does not fully synchronize on the experimental axon transport.
    import jax
    if isinstance(out, dict):
        for v in out.values():
            jax.device_get(v)
            return
    jax.device_get(out)


def _steps_per_dispatch() -> int:
    """The fused-dispatch knob (--steps_per_dispatch / env
    BENCH_STEPS_PER_DISPATCH): K>1 runs every train config through
    Trainer.run_steps — K optimizer steps per device launch with
    stacked-batch prefetch — instead of per-step dispatch."""
    import os

    return max(1, int(os.environ.get("BENCH_STEPS_PER_DISPATCH", "1")))


def _time_trainer(trainer, host_batches, warmup=3, iters=20,
                  steps_per_dispatch=None):
    """(pipelined sec/step, compute-only sec/step).

    Pipelined = host numpy → DeviceFeeder (background-thread device_put,
    capacity 2) → step: the full input path BASELINE targets. Compute-
    only = feeds pre-staged on device (the old bench's number, kept as a
    secondary field). With steps_per_dispatch=K the feeder stacks K host
    batches per transfer and each dispatch is one fused K-step launch;
    both numbers stay per-STEP so K is directly comparable to 1."""
    from paddle_tpu.data.feeder import DeviceFeeder, stack_batches
    from paddle_tpu.telemetry import counter_deltas, get_registry

    k = steps_per_dispatch or _steps_per_dispatch()
    if k <= 1:
        staged0 = trainer._put_feed(host_batches[0])
        for _ in range(warmup):
            out = trainer.step(staged0)
        _sync(out)

        def gen():
            for i in range(iters):
                yield host_batches[i % len(host_batches)]

        tel0 = get_registry().counter_values()
        sh, ship0 = _shipper_snapshot()
        store0 = _store_snapshot(sh)
        t0 = time.perf_counter()
        for feed in DeviceFeeder(gen, put_fn=trainer._put_feed, capacity=2):
            out = trainer.step(feed)
        _sync(out)
        dt_pipe = (time.perf_counter() - t0) / iters
        # registry counter deltas over the measured window, per step —
        # the row's `telemetry` snapshot (_result picks this up)
        trainer._bench_telemetry = counter_deltas(
            tel0, get_registry().counter_values(), per=iters)
        if sh is not None:
            # a collector is attached (PDTPU_TELEMETRY_ADDR): the row
            # also records what shipping COST over the window — events
            # shipped/dropped + flush seconds per step
            trainer._bench_shipper = counter_deltas(
                ship0, sh.counters(), per=iters)
            store1 = _store_snapshot(sh)
            if store0 is not None and store1 is not None:
                # ...and, when the collector persists, what the store's
                # ingest-writes cost (appends/bytes/seconds per step)
                trainer._bench_store = counter_deltas(store0, store1,
                                                      per=iters)

        staged = [trainer._put_feed(b) for b in host_batches[:2]]
        out = trainer.step(staged[0])
        _sync(out)
        t0 = time.perf_counter()
        for i in range(iters):
            out = trainer.step(staged[i % 2])
        _sync(out)
        dt_comp = (time.perf_counter() - t0) / iters
        return dt_pipe, dt_comp

    # fused path: ceil iters up to whole chunks so per-step math is exact
    dispatches = max(1, -(-iters // k))
    steps = dispatches * k
    host_stacked = stack_batches([host_batches[i % len(host_batches)]
                                  for i in range(k)])
    staged0 = trainer._put_feed(host_stacked, stacked=True)
    for _ in range(max(1, warmup // k + 1)):
        out = trainer.run_steps(staged0, k=k)
    _sync(out)

    def gen():
        for i in range(steps):
            yield host_batches[i % len(host_batches)]

    feeder = DeviceFeeder(gen, put_fn=trainer._put_feed, capacity=2,
                          stack_k=k,
                          put_stacked_fn=lambda d: trainer._put_feed(
                              d, stacked=True))
    tel0 = get_registry().counter_values()
    sh, ship0 = _shipper_snapshot()
    store0 = _store_snapshot(sh)
    t0 = time.perf_counter()
    for n, feed in feeder:
        out = trainer.run_steps(feed, k=n) if n > 1 else trainer.step(feed)
    _sync(out)
    dt_pipe = (time.perf_counter() - t0) / steps
    trainer._bench_telemetry = counter_deltas(
        tel0, get_registry().counter_values(), per=steps)
    if sh is not None:
        trainer._bench_shipper = counter_deltas(ship0, sh.counters(),
                                                per=steps)
        store1 = _store_snapshot(sh)
        if store0 is not None and store1 is not None:
            trainer._bench_store = counter_deltas(store0, store1,
                                                  per=steps)

    # feeds are NOT donated (only the training carry is), so pre-staged
    # super-batches can be reused across dispatches like the k=1 path
    staged = [trainer._put_feed(host_stacked, stacked=True) for _ in range(2)]
    out = trainer.run_steps(staged[0], k=k)
    _sync(out)
    t0 = time.perf_counter()
    for i in range(dispatches):
        out = trainer.run_steps(staged[i % 2], k=k)
    _sync(out)
    dt_comp = (time.perf_counter() - t0) / steps
    return dt_pipe, dt_comp


def _shipper_snapshot():
    """(active shipper, its counters) when a telemetry collector is
    attached to this process, else (None, None) — the bench rows'
    shipping-cost snapshot source."""
    from paddle_tpu.telemetry import shipper as _tshipper

    sh = _tshipper.active_shipper()
    return (sh, sh.counters()) if sh is not None else (None, None)


def _store_snapshot(sh):
    """The attached collector's store counters (appends/bytes/
    append_seconds) when it runs WITH persistence, else None — the
    `collector_store` row key deltas these over the measured window,
    so a round records what the durable series store's ingest-writes
    cost alongside the shipping cost."""
    stats_fn = getattr(sh, "collector_stats", None)
    if stats_fn is None:
        return None
    stats = stats_fn()
    if not stats or not stats.get("persistence"):
        return None
    store = stats.get("store") or {}
    return {k: float(store.get(k, 0.0))
            for k in ("appends", "bytes", "append_seconds")}


def _result(n_per_step, unit, dt_pipe, dt_comp, flops_per_step, peak,
            baseline_key=None, trainer=None, feed=None):
    value = n_per_step / dt_pipe
    out = {
        "value": round(float(value), 2),
        "unit": unit,
        "compute_only": round(float(n_per_step / dt_comp), 2),
        "step_time_ms": round(dt_pipe * 1e3, 3),
        "model_flops_per_step": float(flops_per_step),
        "mfu": round(flops_per_step / dt_pipe / peak, 4),
        "mfu_compute_only": round(flops_per_step / dt_comp / peak, 4),
    }
    if trainer is not None:
        # the measured window's registry counter deltas per step
        # (steps/dispatches/h2d bytes/guard incidents...), recorded by
        # _time_trainer — rows are comparable across rounds and iters
        tel = getattr(trainer, "_bench_telemetry", None)
        if tel is not None:
            out["telemetry"] = tel
        # shipping-cost deltas ride along only when a collector was
        # attached during the measured window (PDTPU_TELEMETRY_ADDR):
        # events shipped/dropped + flush seconds per step
        ship = getattr(trainer, "_bench_shipper", None)
        if ship is not None:
            out["shipper"] = ship
        # the durable store's ingest-write cost per step, present only
        # when the attached collector persists (store_dir)
        store = getattr(trainer, "_bench_store", None)
        if store is not None:
            out["collector_store"] = store
    if feed is not None:
        # the honest h2d numerator: WIRE bytes (what actually crosses
        # the link under the trainer's feed_wire table), alongside the
        # logical bytes a passthrough transfer would have cost — a
        # uint8-wire row must not be read with fp32 byte math
        from paddle_tpu.data import wire as _wire
        fw = getattr(trainer, "feed_wire", None)
        out["feed_wire_bytes_per_step"] = int(
            _wire.feed_wire_nbytes(feed, fw))
        out["feed_logical_bytes_per_step"] = int(
            _wire.feed_logical_nbytes(feed, fw))
    if trainer is not None and feed is not None and \
            os.environ.get("BENCH_FUSIONS", "1") != "0":
        # the top-k fusion table rides every train row so two rounds
        # diff to "this fusion got slower" (tools/profile_diff.py:
        # cost_frac × step_time_ms localizes a regression to a named
        # fusion). The re-lower/re-compile this costs is served by the
        # persistent compile cache; failure must not lose the row.
        try:
            rep = trainer.fusion_report(feed)
            out["top_fusions"] = rep["top_fusions"]
            out["fusion_n_units"] = rep["n_units"]
            out["fusion_coverage_top_k"] = rep["coverage_top_k"]
            if rep.get("temp_mb") is not None:
                out["temp_mb"] = round(rep["temp_mb"], 3)
        except Exception as e:
            out["top_fusions_error"] = f"{type(e).__name__}: {e}"
    base = BASELINES.get(baseline_key or "")
    out["vs_baseline"] = round(float(value) / base, 2) if base else None
    return out


# -- train configs -----------------------------------------------------------


def bench_resnet50(peak, batch_size=64, image_size=224, iters=20,
                   data_format=None):
    """NHWC by default: the TPU-native conv layout (XLA tiles NHWC conv
    operands straight onto the MXU; NCHW graphs pay layout-assignment
    transposes). BENCH_DATA_FORMAT=NCHW A/Bs the reference's layout to
    quantify the lever on chip."""
    import os

    from paddle_tpu.core import flops
    from paddle_tpu.models import resnet

    if data_format is None:
        data_format = os.environ.get("BENCH_DATA_FORMAT", "NHWC")

    return _bench_convnet(peak,
                          resnet.make_model(depth=50, class_num=1000,
                                            image_size=image_size,
                                            data_format=data_format),
                          flops.resnet_fwd_flops(50, image_size), batch_size,
                          "resnet50", image_size=image_size, iters=iters,
                          lr=0.1, data_format=data_format)


def bench_vgg16(peak, batch_size=64, image_size=224, iters=20):
    from paddle_tpu.core import flops
    from paddle_tpu.models import vgg

    return _bench_convnet(peak, vgg.make_model(depth=16, class_num=1000),
                          flops.vgg_fwd_flops(16, image_size), batch_size,
                          "vgg16", image_size=image_size, iters=iters)


def _bench_convnet(peak, make_model_fn, fwd_flops, batch_size, baseline_key,
                   image_size=224, iters=20, lr=0.01, data_format="NHWC"):
    """All conv benches run NHWC by default — the TPU-native layout (the
    ambient framework.layout_mode is captured at build time, so the
    whole zoo needs no per-model threading); the models still default
    to the reference's NCHW outside the bench."""
    import os

    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.data.wire import WireSpec
    from paddle_tpu.framework import layout_mode

    # BENCH_FEED_DTYPE=uint8: feed raw uint8 images over the wire and
    # normalize ON DEVICE through the framework WireSpec path (what a
    # real decode-jpeg input pipeline does — 4x less host->device wire
    # than the float32 default, which stays the default because the
    # reference feeds float32). The decode is fused into the compiled
    # step by Trainer(feed_wire=...), not a bench-local model adapter.
    uint8_feed = os.environ.get("BENCH_FEED_DTYPE") == "uint8"
    feed_wire = {"image": WireSpec.image_uint8()} if uint8_feed else None

    with layout_mode(data_format):
        model = pt.build(make_model_fn)
    rng = np.random.RandomState(0)
    img_shape = ((batch_size, 3, image_size, image_size)
                 if data_format == "NCHW"
                 else (batch_size, image_size, image_size, 3))
    feeds = [{
        "image": (rng.randint(0, 256, img_shape).astype(np.uint8)
                  if uint8_feed else rng.randn(*img_shape).astype(np.float32)),
        "label": rng.randint(0, 1000, (batch_size, 1)).astype(np.int64),
    } for _ in range(4)]
    trainer = pt.Trainer(model, opt.Momentum(lr, 0.9), loss_name="loss",
                         fetch_list=["loss"], feed_wire=feed_wire)
    trainer.startup(sample_feed=feeds[0])
    dt_pipe, dt_comp = _time_trainer(trainer, feeds, iters=iters)
    f = flops.convnet_train_flops(fwd_flops, batch_size)
    return _result(batch_size, "images/sec", dt_pipe, dt_comp, f, peak,
                   baseline_key, trainer=trainer, feed=feeds[0])


def bench_alexnet(peak, batch_size=256, iters=20):
    """AlexNet bs=256 (the reference's Xeon MKL-DNN row config)."""
    from paddle_tpu.core import flops
    from paddle_tpu.models import convnets

    return _bench_convnet(peak, convnets.make_alexnet(),
                          flops.alexnet_fwd_flops(), batch_size, "alexnet",
                          iters=iters)


def bench_googlenet(peak, batch_size=64, iters=20):
    """GoogLeNet v1 bs=64 (the reference's Xeon MKL-DNN row config)."""
    from paddle_tpu.core import flops
    from paddle_tpu.models import convnets

    return _bench_convnet(peak, convnets.make_googlenet(),
                          flops.googlenet_fwd_flops(), batch_size,
                          "googlenet", iters=iters)


def bench_se_resnext(peak, batch_size=32, image_size=224, iters=15):
    """SE-ResNeXt-50 (benchmark/fluid/models/se_resnext.py is in the
    reference's benchmark model matrix; no published number)."""
    from paddle_tpu.core import flops
    from paddle_tpu.models import convnets

    return _bench_convnet(peak, convnets.make_se_resnext(depth=50),
                          flops.se_resnext_fwd_flops(50, image_size),
                          batch_size, "se_resnext", image_size=image_size,
                          iters=iters)


def _bench_transformer_config(peak, batch_size, seq, dtype, dropout,
                              max_len=256, iters=20, fuse_qkv=None):
    import os

    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.models import transformer

    # BENCH_USE_FLASH=0: A/B the pallas flash kernel against XLA's fused
    # dense attention (at short seq the dense path can win — the profile
    # decides, not the assumption). BENCH_FUSE_QKV=0 likewise A/Bs the
    # fused [d,3,d] projection against the r0[1-3] three-matmul layout.
    use_flash = os.environ.get("BENCH_USE_FLASH", "1") != "0"
    if fuse_qkv is None:
        fuse_qkv = os.environ.get("BENCH_FUSE_QKV", "1") != "0"
    # BENCH_STACKED=1: scan-compiled stacked blocks (one traced layer
    # body; per-layer dropout via rng_fold) — identical math, ~L x less
    # code to compile. A/B knob until the on-chip compile-time and
    # step-time deltas are measured.
    stacked = os.environ.get("BENCH_STACKED", "0") == "1"
    cfg = transformer.base_config(src_vocab=32000, trg_vocab=32000,
                                  dropout=dropout, max_len=max_len,
                                  dtype=dtype, use_flash=use_flash,
                                  fused_ce=True, fuse_qkv=fuse_qkv,
                                  stacked=stacked)
    model = pt.build(transformer.make_model(cfg))
    rng = np.random.RandomState(0)
    feeds = [{
        "src_ids": rng.randint(3, 32000, (batch_size, seq)).astype(np.int32),
        "trg_ids": rng.randint(3, 32000, (batch_size, seq)).astype(np.int32),
        "labels": rng.randint(3, 32000, (batch_size, seq)).astype(np.int32),
    } for _ in range(4)]
    trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss",
                         fetch_list=["loss"])
    trainer.startup(sample_feed=feeds[0])
    dt_pipe, dt_comp = _time_trainer(trainer, feeds, iters=iters)
    f = flops.transformer_train_flops(batch_size, seq, cfg)
    return _result(batch_size * seq, "tokens/sec", dt_pipe, dt_comp, f, peak,
                   trainer=trainer, feed=feeds[0])


def bench_transformer(peak, batch_size=32, seq=256, dtype="bfloat16", iters=20):
    return _bench_transformer_config(peak, batch_size, seq, dtype, dropout=0.1,
                                     iters=iters)


def bench_transformer_long(peak, batch_size=4, seq=4096, dtype="bfloat16", iters=10):
    """Long-context train step: flash attention pallas kernel (dense
    attention at this length is ~26x slower / memory-bound)."""
    return _bench_transformer_config(peak, batch_size, seq, dtype, dropout=0.0,
                                     max_len=seq, iters=iters)


def bench_bert(peak, batch_size=32, seq=128, num_masked=20, dtype="bfloat16",
               iters=20):
    import os

    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.models import bert

    cfg = bert.base_config(dtype=dtype, use_flash=True, fused_ce=True,
                           fuse_qkv=os.environ.get("BENCH_FUSE_QKV", "1") != "0",
                           max_len=512)
    model = pt.build(bert.make_pretrain_model(cfg))
    rng = np.random.RandomState(0)
    feeds = [{
        "input_ids": rng.randint(0, cfg.vocab_size, (batch_size, seq)).astype(np.int32),
        "token_type_ids": rng.randint(0, 2, (batch_size, seq)).astype(np.int32),
        "mlm_positions": rng.randint(0, seq, (batch_size, num_masked)).astype(np.int32),
        "mlm_labels": rng.randint(0, cfg.vocab_size, (batch_size, num_masked, 1)).astype(np.int64),
        "nsp_label": rng.randint(0, 2, (batch_size, 1)).astype(np.int64),
    } for _ in range(4)]
    trainer = pt.Trainer(model, opt.AdamW(1e-4, weight_decay=0.01),
                         loss_name="loss", fetch_list=["loss"])
    trainer.startup(sample_feed=feeds[0])
    dt_pipe, dt_comp = _time_trainer(trainer, feeds, iters=iters)
    f = flops.bert_train_flops(batch_size, seq, num_masked, cfg)
    return _result(batch_size * seq, "tokens/sec", dt_pipe, dt_comp, f, peak,
                   trainer=trainer, feed=feeds[0])


def bench_gpt(peak, batch_size=8, seq=1024, dtype="bfloat16", iters=15,
              warmup=3, n_feeds=4):
    """Decoder-only LM (GPT-base shape, ~124M params): the modern
    long-context flagship — flash attention + chunked logits-free CE.
    The seq-32k variant (gpt_32k) is this config at batch 1 with the
    streamed-K/V flash kernel doing the heavy lifting."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.models import gpt

    cfg = gpt.base_config(vocab_size=32000, max_len=seq, d_model=768,
                          d_inner=3072, num_heads=12, num_layers=12,
                          use_flash=True, fused_ce=True, dtype=dtype)
    model = pt.build(gpt.make_model(cfg))
    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(n_feeds):
        ids = rng.randint(3, cfg.vocab_size, (batch_size, seq)).astype(np.int32)
        labels = np.concatenate([ids[:, 1:], np.full((batch_size, 1), 2)],
                                axis=1).astype(np.int32)
        feeds.append({"ids": ids, "labels": labels})
    trainer = pt.Trainer(model, opt.AdamW(1e-4, weight_decay=0.01),
                         loss_name="loss", fetch_list=["loss"])
    trainer.startup(sample_feed=feeds[0])
    dt_pipe, dt_comp = _time_trainer(trainer, feeds, warmup=warmup,
                                     iters=iters)
    f = flops.gpt_train_flops(batch_size, seq, cfg)
    return _result(batch_size * seq, "tokens/sec", dt_pipe, dt_comp, f, peak,
                   trainer=trainer, feed=feeds[0])


# seq-32k long-context variant of the GPT config (streamed-K/V flash
# kernel + chunked CE; ~81 TFLOPs/step analytic)
bench_gpt_32k = functools.partial(bench_gpt, batch_size=1, seq=32768,
                                  iters=3, warmup=1, n_feeds=2)


def _bench_deepfm_config(peak, batch_size, sparse_feature_dim, iters=20):
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.models import deepfm

    fields, emb, dense_n, hidden = 26, 16, 13, (400, 400, 400)
    model = pt.build(deepfm.make_model(num_sparse_fields=fields,
                                       sparse_feature_dim=sparse_feature_dim,
                                       embedding_size=emb, num_dense=dense_n,
                                       hidden_dims=hidden))
    rng = np.random.RandomState(0)
    feeds = [{
        "dense": rng.randn(batch_size, dense_n).astype(np.float32),
        "sparse_ids": rng.randint(0, sparse_feature_dim, (batch_size, fields)).astype(np.int32),
        "label": rng.randint(0, 2, (batch_size, 1)).astype(np.int64),
    } for _ in range(4)]
    trainer = pt.Trainer(model, opt.Adagrad(0.01), loss_name="loss",
                         fetch_list=["loss"])
    trainer.startup(sample_feed=feeds[0])
    dt_pipe, dt_comp = _time_trainer(trainer, feeds, iters=iters)
    f = flops.deepfm_train_flops(batch_size, fields, emb, dense_n, hidden)
    res = _result(batch_size, "samples/sec", dt_pipe, dt_comp, f, peak,
                  trainer=trainer, feed=feeds[0])
    res["embedding_rows"] = fields * sparse_feature_dim
    return res


def bench_deepfm(peak, batch_size=2048, iters=20):
    """BASELINE DeepFM CTR config (Criteo-shaped: 26 sparse fields,
    13 dense)."""
    return _bench_deepfm_config(peak, batch_size, sparse_feature_dim=1000,
                                iters=iters)


def bench_deepfm_10m(peak, batch_size=2048, iters=20):
    """Vocab-at-scale variant: 26×400k ≈ 10.4M embedding rows — the
    distributed-lookup-table workload (distribute_transpiler.py:1100)
    measured single-chip (lookup + row-update throughput)."""
    return _bench_deepfm_config(peak, batch_size, sparse_feature_dim=400_000,
                                iters=iters)


def bench_dispatch_overhead(peak, batch_size=128, iters=48, k=16):
    """Dispatch-overhead microbench: per-step wall time of K=1 (one
    Python→XLA launch per optimizer step) vs K=16 fused dispatch
    (Trainer.run_steps: one launch per 16 steps) on the MNIST MLP
    config, pre-staged feeds both ways so the delta isolates launch +
    host-loop overhead. The row makes the fused-dispatch win visible
    in every BENCH capture; ``value`` is the overhead recovered per
    step in ms."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.data.feeder import stack_batches
    from paddle_tpu.models import mnist

    iters = max(k, iters // k * k)  # whole chunks
    model = pt.build(mnist.mlp)
    rng = np.random.RandomState(0)
    feeds = [{"image": rng.randn(batch_size, 784).astype(np.float32),
              "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}
             for _ in range(4)]
    trainer = pt.Trainer(model, opt.SGD(0.01), loss_name="loss",
                         fetch_list=["loss"])
    trainer.startup(sample_feed=feeds[0])

    staged = [trainer._put_feed(b) for b in feeds[:2]]
    stacked = trainer._put_feed(
        stack_batches([feeds[i % len(feeds)] for i in range(k)]),
        stacked=True)

    def time_k1():
        out = trainer.step(staged[0])
        _sync(out)
        t0 = time.perf_counter()
        for i in range(iters):
            out = trainer.step(staged[i % 2])
        _sync(out)
        return (time.perf_counter() - t0) / iters

    def time_fused():
        out = trainer.run_steps(stacked, k=k)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(iters // k):
            out = trainer.run_steps(stacked, k=k)
        _sync(out)
        return (time.perf_counter() - t0) / iters

    # best-of-3 each, INTERLEAVED: the microbench measures a sub-ms
    # delta, and a load spike across one contiguous phase would
    # otherwise swamp whichever variant it landed on
    dt1 = dtk = float("inf")
    for _ in range(3):
        dt1 = min(dt1, time_k1())
        dtk = min(dtk, time_fused())
    return {
        "value": round((dt1 - dtk) * 1e3, 4),
        "unit": "ms/step dispatch overhead recovered (K=1 vs K=16)",
        "step_time_ms_k1": round(dt1 * 1e3, 4),
        "step_time_ms_k16": round(dtk * 1e3, 4),
        "speedup_k16": round(dt1 / dtk, 3),
        "steps_per_dispatch": k,
    }


def bench_quantized_allreduce(peak, batch_size=128, iters=24, k=8):
    """Quantized gradient-exchange A/B: the MNIST MLP config on a dp=2
    sub-mesh with ``DistStrategy(quantized_allreduce="none")`` (fp32
    pmean) vs ``"int8"`` (block-scaled ring exchange + error feedback),
    fused K-step dispatch and pre-staged feeds both ways. ``value`` is
    the gradient bytes-on-wire reduction from the trainer's own
    collective-bytes attribution (acceptance: >= 3.5x for int8); the
    step times ride along so a capture also shows whether the
    quantize/dequantize math pays for itself on this interconnect
    (on single-host CPU/ICI it typically will not — the row exists to
    pin the wire-format contract, not to win on localhost)."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.data.feeder import stack_batches
    from paddle_tpu.models import mnist
    from paddle_tpu.parallel import DistStrategy

    devs = jax.devices()
    if len(devs) < 2:
        return {"value": None,
                "unit": "x gradient bytes-on-wire reduction (int8 vs fp32)",
                "skipped": f"needs >= 2 devices, have {len(devs)}"}
    iters = max(k, iters // k * k)  # whole chunks
    rng = np.random.RandomState(0)
    feeds = [{"image": rng.randn(batch_size, 784).astype(np.float32),
              "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}
             for _ in range(4)]

    def build(mode):
        mesh = pt.make_mesh({"dp": 2}, devices=devs[:2])
        tr = pt.Trainer(pt.build(mnist.mlp), opt.SGD(0.01),
                        loss_name="loss", fetch_list=["loss"], mesh=mesh,
                        sharding_rules=pt.parallel.replicated(),
                        strategy=DistStrategy(quantized_allreduce=mode))
        tr.startup(sample_feed=feeds[0])
        stacked = tr._put_feed(
            stack_batches([feeds[i % len(feeds)] for i in range(k)]),
            stacked=True)
        return tr, stacked

    variants = {m: build(m) for m in ("none", "int8")}

    def time_fused(tr, stacked):
        out = tr.run_steps(stacked, k=k)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(iters // k):
            out = tr.run_steps(stacked, k=k)
        _sync(out)
        return (time.perf_counter() - t0) / iters

    # best-of-3, interleaved (same rationale as bench_dispatch_overhead)
    best = {m: float("inf") for m in variants}
    for _ in range(3):
        for m, (tr, stacked) in variants.items():
            best[m] = min(best[m], time_fused(tr, stacked))

    coll = variants["int8"][0].collective_bytes
    return {
        "value": round(coll["reduction"], 3),
        "unit": "x gradient bytes-on-wire reduction (int8 vs fp32 exchange)",
        "step_time_ms_fp32": round(best["none"] * 1e3, 4),
        "step_time_ms_int8": round(best["int8"] * 1e3, 4),
        "wire_bytes_fp32": coll["fp32_bytes_per_step"],
        "wire_bytes_int8": coll["wire_bytes_per_step"],
        "grad_elems": coll["grad_elems"],
        "quant_block_size": coll["block_size"],
        "error_feedback": coll["error_feedback"],
        "steps_per_dispatch": k,
    }


def bench_zero_sharding(peak, batch_size=128, iters=24, k=16):
    """ZeRO weight-update sharding A/B: the MNIST MLP config with
    ``DistStrategy()`` (replicated optimizer state, today's default) vs
    ``DistStrategy(zero_sharding=True)`` (params + opt state live as
    1/N shard rows; grads reduce-scatter, the update applies
    shard-locally, fresh params all-gather at the top of each fused
    iteration) at dp in {2, 8}. ``value`` is the advisor-measured
    per-device optimizer-HBM reduction at the largest dp (acceptance:
    >= 6x at dp=8 for Momentum — 8 shards minus the replicated step
    counter); per-step times at K=1 and K=k ride along interleaved
    best-of-3 so a capture shows what the top-of-step all-gather costs
    on this interconnect, XLA's ``temp_mb`` rides when the backend
    exposes ``memory_analysis()`` (degrades to absent, never fails the
    row), and the all-gather bytes/step come from the trainer's own
    collective-bytes attribution (the ``collective`` line)."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.data.feeder import stack_batches
    from paddle_tpu.models import mnist
    from paddle_tpu.parallel import DistStrategy
    from paddle_tpu.profiling.advisor import memory_estimate

    devs = jax.devices()
    dps = [n for n in (2, 8) if len(devs) >= n]
    if not dps:
        return {"value": None,
                "unit": "x per-device optimizer-HBM reduction (ZeRO)",
                "skipped": f"needs >= 2 devices, have {len(devs)}"}
    iters = max(k, iters // k * k)  # whole chunks
    rng = np.random.RandomState(0)
    feeds = [{"image": rng.randn(batch_size, 784).astype(np.float32),
              "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}
             for _ in range(4)]

    def build(n, zero):
        mesh = pt.make_mesh({"dp": n}, devices=devs[:n])
        tr = pt.Trainer(pt.build(mnist.mlp),
                        opt.Momentum(0.01, momentum=0.9),
                        loss_name="loss", fetch_list=["loss"], mesh=mesh,
                        sharding_rules=pt.parallel.replicated(),
                        strategy=DistStrategy(zero_sharding=zero))
        tr.startup(sample_feed=feeds[0])
        staged = tr._put_feed(feeds[0])
        stacked = tr._put_feed(
            stack_batches([feeds[i % len(feeds)] for i in range(k)]),
            stacked=True)
        return tr, staged, stacked

    def time_k1(tr, staged):
        out = tr.step(staged)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = tr.step(staged)
        _sync(out)
        return (time.perf_counter() - t0) / iters

    def time_fused(tr, stacked):
        out = tr.run_steps(stacked, k=k)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(iters // k):
            out = tr.run_steps(stacked, k=k)
        _sync(out)
        return (time.perf_counter() - t0) / iters

    rows = {}
    headline = None
    for n in dps:
        variants = {"replicated": build(n, False), "zero": build(n, True)}
        best1 = {m: float("inf") for m in variants}
        bestk = {m: float("inf") for m in variants}
        # interleaved best-of-3 (same rationale as bench_dispatch_overhead)
        for _ in range(3):
            for m, (tr, staged, stacked) in variants.items():
                best1[m] = min(best1[m], time_k1(tr, staged))
                bestk[m] = min(bestk[m], time_fused(tr, stacked))
        ests = {m: memory_estimate(variants[m][0], feeds[0],
                                   project_remat=False) for m in variants}
        reduction = (ests["replicated"]["opt_state_bytes"]
                     / max(1, ests["zero"]["opt_state_bytes"]))
        row = {
            "opt_hbm_reduction_x": round(reduction, 3),
            "opt_state_bytes_replicated": ests["replicated"]["opt_state_bytes"],
            "opt_state_bytes_zero": ests["zero"]["opt_state_bytes"],
            "param_bytes_replicated": ests["replicated"]["param_bytes"],
            "param_bytes_zero": ests["zero"]["param_bytes"],
            "step_time_ms_k1_replicated": round(best1["replicated"] * 1e3, 4),
            "step_time_ms_k1_zero": round(best1["zero"] * 1e3, 4),
            f"step_time_ms_k{k}_replicated": round(
                bestk["replicated"] * 1e3, 4),
            f"step_time_ms_k{k}_zero": round(bestk["zero"] * 1e3, 4),
            "step_time_ratio_fused": round(
                bestk["zero"] / bestk["replicated"], 3),
        }
        coll = variants["zero"][0].collective_bytes or {}
        if coll.get("zero"):
            row["allgather_bytes_per_step"] = \
                coll["zero"]["allgather_bytes_per_step"]
        # XLA buffer-assignment temps (per device) — degrade gracefully
        # on backends whose memory_analysis() is absent or raises
        try:
            from paddle_tpu import debugger
            for m, (tr, _, _) in variants.items():
                mu = debugger.compiled_memory_usage(tr, feeds[0])
                row[f"temp_mb_{m}"] = round(float(mu["temp_mb"]), 3)
        except Exception:
            pass
        rows[f"dp{n}"] = row
        headline = reduction  # largest dp wins (dps is ascending)
    return {
        "value": round(headline, 3),
        "unit": (f"x per-device optimizer-HBM reduction "
                 f"(ZeRO vs replicated, dp={dps[-1]})"),
        **{f"{dp}_{key}": v for dp, r in rows.items()
           for key, v in r.items()},
        "steps_per_dispatch": k,
    }


def bench_guard_overhead(peak, batch_size=128, iters=48, k=16):
    """NaN-guard overhead microbench: per-step wall time of a guarded
    trainer (``guard=GuardPolicy()`` — the fused on-device
    ``all(isfinite)`` bitmask + host readback) vs an unguarded one, at
    K=1 and K=16 fused dispatch, on the MNIST MLP config with
    pre-staged feeds. ``value`` is the guarded-vs-unguarded per-step
    delta at K=16 in percent — the row that proves the on-device check
    is free on the fused hot path (acceptance: < 3%)."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.data.feeder import stack_batches
    from paddle_tpu.models import mnist
    from paddle_tpu.resilience import GuardPolicy

    iters = max(k, iters // k * k)  # whole chunks
    rng = np.random.RandomState(0)
    feeds = [{"image": rng.randn(batch_size, 784).astype(np.float32),
              "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}
             for _ in range(4)]

    def make(guard):
        t = pt.Trainer(pt.build(mnist.mlp), opt.SGD(0.01), loss_name="loss",
                       fetch_list=["loss"], guard=guard)
        t.startup(sample_feed=feeds[0])
        staged = [t._put_feed(b) for b in feeds[:2]]
        stacked = t._put_feed(
            stack_batches([feeds[i % len(feeds)] for i in range(k)]),
            stacked=True)
        return t, staged, stacked

    plain, guarded = make(None), make(GuardPolicy())

    def time_k1(tr, staged):
        out = tr.step(staged[0])
        _sync(out)
        t0 = time.perf_counter()
        for i in range(iters):
            out = tr.step(staged[i % 2])
        _sync(out)
        return (time.perf_counter() - t0) / iters

    def time_fused(tr, stacked):
        out = tr.run_steps(stacked, k=k)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(iters // k):
            out = tr.run_steps(stacked, k=k)
        _sync(out)
        return (time.perf_counter() - t0) / iters

    # best-of-5 each, INTERLEAVED across all four variants: the
    # microbench measures a few-percent delta and a load spike across
    # one contiguous phase would swamp whichever variant it landed on
    # (5 rounds, not dispatch_overhead's 3: the guarded-vs-unguarded
    # delta is smaller than the K=1-vs-K=16 one it is measured against)
    t = {key: float("inf") for key in ("u1", "g1", "u16", "g16")}
    for _ in range(5):
        t["u1"] = min(t["u1"], time_k1(plain[0], plain[1]))
        t["g1"] = min(t["g1"], time_k1(guarded[0], guarded[1]))
        t["u16"] = min(t["u16"], time_fused(plain[0], plain[2]))
        t["g16"] = min(t["g16"], time_fused(guarded[0], guarded[2]))
    pct = lambda g, u: round((g - u) / u * 100.0, 3)
    return {
        "value": pct(t["g16"], t["u16"]),
        "unit": "% per-step delta guarded vs unguarded (K=16)",
        "delta_k1_pct": pct(t["g1"], t["u1"]),
        "step_time_ms_unguarded_k1": round(t["u1"] * 1e3, 4),
        "step_time_ms_guarded_k1": round(t["g1"] * 1e3, 4),
        "step_time_ms_unguarded_k16": round(t["u16"] * 1e3, 4),
        "step_time_ms_guarded_k16": round(t["g16"] * 1e3, 4),
        "steps_per_dispatch": k,
    }


def bench_input_pipeline(peak, batch_size=256, iters=24, k=16):
    """Input-pipeline wire-format A/B: the MNIST MLP config trained
    end-to-end (host batches → DeviceFeeder → step) with the image feed
    crossing the host→device link as fp32 (passthrough), bf16 wire
    (WireSpec.cast — 2x fewer bytes), and uint8 wire
    (WireSpec.image_uint8 — 4x fewer bytes, device-side normalize fused
    into the step), each at K=1 and K=16 fused dispatch. All variants
    train on the SAME logical pixel values, so the step-time deltas
    isolate the wire bytes. ``value`` is the wire-byte reduction of the
    uint8 config vs fp32 (the acceptance lever: >= 3.5x); the per-cell
    times are measured interleaved best-of-3 so a load spike cannot
    swamp one variant. The fused speedup keys say "fused" rather than
    baking K into the name — ``steps_per_dispatch`` records the K they
    were measured under."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.data import wire as _wire
    from paddle_tpu.data.wire import FeedWire, WireSpec
    from paddle_tpu.models import mnist

    iters = max(k, iters // k * k)  # whole chunks at K
    rng = np.random.RandomState(0)
    raw = [rng.randint(0, 256, (batch_size, 784)).astype(np.uint8)
           for _ in range(4)]
    labels = [rng.randint(0, 10, (batch_size, 1)).astype(np.int64)
              for _ in range(4)]
    logical = [(r.astype(np.float32) - 127.0) / 64.0 for r in raw]

    variants = {
        "fp32": (None,
                 [{"image": im, "label": y} for im, y in zip(logical, labels)]),
        "bf16": ({"image": WireSpec.cast("bfloat16")},
                 [{"image": im, "label": y} for im, y in zip(logical, labels)]),
        "uint8": ({"image": WireSpec.image_uint8()},
                  [{"image": im, "label": y} for im, y in zip(raw, labels)]),
    }
    trainers = {}
    for name, (fw, feeds) in variants.items():
        tr = pt.Trainer(pt.build(mnist.mlp), opt.SGD(0.01), loss_name="loss",
                        fetch_list=["loss"], feed_wire=fw)
        tr.startup(sample_feed=feeds[0])
        trainers[name] = tr

    # interleaved best-of-3 over all (variant, K) cells
    cells = {(name, kk): float("inf")
             for name in variants for kk in (1, k)}
    for _ in range(3):
        for (name, kk) in cells:
            dt_pipe, _ = _time_trainer(trainers[name], variants[name][1],
                                       warmup=2, iters=iters,
                                       steps_per_dispatch=kk)
            cells[(name, kk)] = min(cells[(name, kk)], dt_pipe)

    fw_map = {name: FeedWire.make(fw) for name, (fw, _) in variants.items()}
    wire_bytes = {name: int(_wire.feed_wire_nbytes(variants[name][1][0],
                                                   fw_map[name]))
                  for name in variants}
    reduction = wire_bytes["fp32"] / wire_bytes["uint8"]
    sp = lambda a, b: round(cells[a] / cells[b], 3)
    return {
        "value": round(reduction, 2),
        "unit": "x wire-byte reduction (uint8 vs fp32 feed)",
        "step_time_ms": {f"{name}_k{kk}": round(cells[(name, kk)] * 1e3, 4)
                         for (name, kk) in sorted(cells)},
        # "fused" = the row's K (steps_per_dispatch below), so quick-mode
        # records (k=4) never masquerade as K=16 measurements
        "speedup_uint8_vs_fp32_k1": sp(("fp32", 1), ("uint8", 1)),
        "speedup_uint8_vs_fp32_fused": sp(("fp32", k), ("uint8", k)),
        "speedup_bf16_vs_fp32_fused": sp(("fp32", k), ("bf16", k)),
        "feed_wire_bytes_per_step": wire_bytes,
        "feed_logical_bytes_per_step": int(
            _wire.feed_logical_nbytes(variants["uint8"][1][0],
                                      fw_map["uint8"])),
        "steps_per_dispatch": k,
    }


def bench_device_cache(peak, batch_size=256, iters=24, k=16,
                       link_delay_ms=None):
    """Device-resident data path A/B (the ROADMAP "kill the host-link
    bottleneck" gate): the MNIST MLP config with a uint8 wire feed,
    measured three ways —

    - ``streamed``: every epoch crosses the link (DeviceFeeder, K-chunk
      stacking — the PR 4 baseline);
    - ``cached``: epoch 1 streams AND admits into the HBM dataset
      cache, the measured epoch serves device-to-device (zero h2d wire
      bytes, pinned in the row);
    - ``compute_only``: pre-staged feeds (the ceiling).

    ``value`` is cached-epoch throughput as a fraction of compute-only
    — the acceptance gate is ≥ 0.9× for any dataset that fits residual
    HBM. ``overlap_vs_blocking`` drives the same pipeline through a
    ``testing.faults.slow_h2d`` throttled link (delay auto-sized to
    dominate the chunk compute unless ``link_delay_ms`` pins it) with
    the 2-deep staging ring vs the blocking put — the ring pipelines
    two in-flight transfers and keeps host work off the critical path,
    so the delta is ~2x on a latency-dominated link."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.data.device_cache import DeviceCache
    from paddle_tpu.data.feeder import DeviceFeeder, stack_batches
    from paddle_tpu.data.wire import WireSpec
    from paddle_tpu.models import mnist
    from paddle_tpu.testing import faults

    iters = max(k, iters // k * k)  # whole chunks at K
    rng = np.random.RandomState(0)
    feeds = [{"image": rng.randint(0, 256, (batch_size, 784)).astype(np.uint8),
              "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}
             for _ in range(4)]

    tr = pt.Trainer(pt.build(mnist.mlp), opt.SGD(0.01), loss_name="loss",
                    fetch_list=["loss"],
                    feed_wire={"image": WireSpec.image_uint8()})
    tr.startup(sample_feed=feeds[0])
    metrics = tr.pipeline_metrics

    def gen():
        for i in range(iters):
            yield feeds[i % len(feeds)]

    def stream_epoch(cache=None, wait_fn=None, overlap_depth=2):
        feeder = DeviceFeeder(
            gen, put_fn=tr._put_feed, capacity=2, stack_k=k,
            put_stacked_fn=lambda d: tr._put_feed(d, stacked=True),
            wait_fn=wait_fn, overlap_depth=overlap_depth)
        t0 = time.perf_counter()
        for n, feed in feeder:
            out = tr.run_steps(feed, k=n) if n > 1 else tr.step(feed)
            if cache is not None:
                cache.offer(n, feed)
        _sync(out)
        return (time.perf_counter() - t0) / iters

    def cached_epoch(cache):
        t0 = time.perf_counter()
        for n, feed in cache.chunks(metrics=metrics):
            out = tr.run_steps(feed, k=n)
        _sync(out)
        return (time.perf_counter() - t0) / iters

    # warmup compiles both step programs
    stream_epoch()

    # compute-only ceiling: pre-staged, alternating super-batches
    staged = [tr._put_feed(stack_batches([feeds[j % len(feeds)]
                                          for j in range(i, i + k)]),
                           stacked=True) for i in range(2)]
    out = tr.run_steps(staged[0], k=k)
    _sync(out)
    t0 = time.perf_counter()
    for i in range(iters // k):
        out = tr.run_steps(staged[i % 2], k=k)
    _sync(out)
    dt_comp = (time.perf_counter() - t0) / iters

    dt_streamed = min(stream_epoch() for _ in range(2))

    # cache admission epoch (CPU has no HBM budget to estimate against:
    # the row states an explicit one, sized to hold the whole dataset)
    cache = DeviceCache(budget_bytes=1 << 32, trainer=tr)
    h2d0 = metrics.h2d_bytes
    stream_epoch(cache=cache)
    cache.seal(iters)
    h2d_epoch1 = metrics.h2d_bytes - h2d0
    h2d0 = metrics.h2d_bytes
    dt_cached = min(cached_epoch(cache) for _ in range(2))
    h2d_epoch2 = metrics.h2d_bytes - h2d0  # the zero-wire-bytes pin

    # overlap A/B under a throttled link: delay sized so the simulated
    # transfer dominates the chunk compute (the slow-link regime)
    delay_ms = (float(link_delay_ms) if link_delay_ms
                else max(2.5 * dt_comp * k * 1e3, 20.0))
    wait = faults.slow_h2d(delay_ms)
    dt_block = stream_epoch(wait_fn=wait, overlap_depth=1)
    dt_overlap = stream_epoch(wait_fn=wait, overlap_depth=2)

    return {
        "value": round(dt_comp / dt_cached, 3),
        "unit": "x of compute-only throughput (HBM-cached epoch 2+)",
        "step_time_ms": {
            "streamed": round(dt_streamed * 1e3, 4),
            "cached": round(dt_cached * 1e3, 4),
            "compute_only": round(dt_comp * 1e3, 4),
        },
        "cached_vs_streamed_x": round(dt_streamed / dt_cached, 3),
        "h2d_bytes_epoch1": int(h2d_epoch1),
        "h2d_bytes_epoch2": int(h2d_epoch2),
        "overlap_vs_blocking": {
            "blocking_step_ms": round(dt_block * 1e3, 4),
            "overlap_step_ms": round(dt_overlap * 1e3, 4),
            "speedup_x": round(dt_block / dt_overlap, 3),
            "link_delay_ms": round(delay_ms, 3),
        },
        "cache": cache.report(),
        "steps_per_dispatch": k,
    }


def bench_elastic_reshard(peak, batch_size=64, iters=3, n_from=4, n_to=2):
    """Elastic-reshard suite row: wall time + bytes re-placed of a
    checkpoint restore ACROSS a dp N→M mesh change
    (``resilience.reshard_restore`` — the static feasibility proof plus
    re-placement per the target rules) vs a same-mesh restore of the
    identical checkpoint. ``value`` is the reshard-restore wall time in
    ms (best of ``iters``) — the price a preempted fleet pays to rejoin
    at a different worker count; ``reshard_overhead_x`` is the ratio to
    the same-mesh restore, the honest statement of what the mesh change
    itself costs on top of an ordinary resume."""
    import tempfile

    import jax

    import paddle_tpu as pt
    from paddle_tpu import io as pio
    from paddle_tpu import optimizer as opt
    from paddle_tpu import resilience
    from paddle_tpu.models import mnist

    devs = jax.devices()
    req_from, req_to = int(n_from), int(n_to)
    n_from = max(1, min(req_from, len(devs)))
    n_to = max(1, min(req_to, len(devs)))
    rng = np.random.RandomState(0)
    feed = {"image": rng.randn(batch_size, 784).astype(np.float32),
            "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}

    def make(n):
        tr = pt.Trainer(pt.build(mnist.mlp), opt.SGD(0.01), loss_name="loss",
                        fetch_list=["loss"],
                        mesh=pt.make_mesh({"dp": n}, devices=devs[:n]))
        tr.startup(sample_feed=feed)
        return tr

    src = make(n_from)
    src.step(feed)
    with tempfile.TemporaryDirectory() as d:
        ck = os.path.join(d, "ck")
        pio.save_trainer(ck, src)
        same = make(n_from)
        t_same = float("inf")
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            pio.load_trainer(ck, same)
            t_same = min(t_same, time.perf_counter() - t0)
        tgt = make(n_to)
        t_reshard, rep = float("inf"), None
        for _ in range(max(1, iters)):
            r = resilience.reshard_restore(ck, tgt, sample_feed=feed)
            if r["seconds"] < t_reshard:
                t_reshard, rep = r["seconds"], r
    row = {
        "value": round(t_reshard * 1e3, 3),
        "unit": f"ms reshard-restore (dp {n_from}->{n_to})",
        "same_mesh_restore_ms": round(t_same * 1e3, 3),
        "reshard_overhead_x": round(t_reshard / max(t_same, 1e-9), 3),
        "bytes_moved": int(rep["bytes_moved"]),
        "from_axes": rep["saved_axes"],
        "to_axes": rep["target_axes"],
        "batch_size": batch_size,
        "iters": iters,
    }
    if n_from == n_to:
        # too few devices to express the requested mesh change: the row
        # measured a same-placement restore. Say so rather than letting
        # a round-diff read ~1.0x overhead as a cross-mesh result.
        row["degenerate"] = (f"device count clamped dp {req_from}->{req_to} "
                             f"to {n_from}->{n_to}: no mesh change measured")
    return row


def _serving_predictors(batch_size):
    """Export the MNIST MLP at fp32 and through the real int8 datapath;
    {variant: (Predictor, feed)}. Untrained weights — this row measures
    the serving runtime, not the model."""
    import contextlib
    import tempfile

    import jax

    import paddle_tpu as pt
    from paddle_tpu import io as pio, quantize
    from paddle_tpu.models import mnist

    prog = pt.build(mnist.mlp)
    rng = np.random.RandomState(0)
    feed = {"image": rng.randn(batch_size, 784).astype(np.float32),
            "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}
    params, state = prog.init(jax.random.PRNGKey(0), **feed)
    out = {}
    for variant in ("fp32", "int8"):
        ctx = (quantize.int8_serving() if variant == "int8"
               else contextlib.nullcontext())
        d = os.path.join(tempfile.mkdtemp(), "model")
        with ctx:
            pio.save_inference_model(d, prog, params, state, feed)
        out[variant] = (pio.load_inference_model(d), feed)
    return out


def _make_server(pred, workers, queue_size):
    from paddle_tpu import serving

    return serving.PredictorServer(pred, workers=workers,
                                   queue_size=queue_size)


def _calibrate_serving(server, feed, iters=8):
    """Mean per-request service time through the full server path."""
    for _ in range(2):
        server.run(feed, timeout=120)
    t0 = time.perf_counter()
    for _ in range(iters):
        server.run(feed, timeout=120)
    return (time.perf_counter() - t0) / iters


def _drive_serving(server, feed, n, rate):
    """Open-loop driver: ``n`` submits at fixed offered ``rate`` req/s
    (no backpressure from the client — rejected submits don't slow the
    arrival process). Returns (per-request latencies of completed
    requests in seconds, rejected count)."""
    from paddle_tpu import serving

    pending, rejected = [], 0
    interval = 1.0 / rate
    next_t = time.perf_counter()
    for _ in range(n):
        now = time.perf_counter()
        if now < next_t:
            time.sleep(next_t - now)
        next_t += interval
        try:
            pending.append(server.submit(feed))
        except serving.ServerOverloaded:
            rejected += 1
    lats = []
    for p in pending:
        p.result(timeout=120)
        lats.append(p.latency)
    return lats, rejected


def bench_serving(peak, batch_size=64, requests=240, workers=2,
                  queue_size=16):
    """Serving-runtime suite row: end-to-end p50/p99 latency through
    ``PredictorServer`` (bounded queue + validation + AOT predictor
    pool) at a fixed offered load of 0.6x measured capacity, plus the
    reject rate with the queue saturated at 3x capacity — fp32 vs the
    real int8 datapath. ``value`` is the fp32 steady-state p99 in ms;
    the saturated phase proves overload sheds (typed rejects) instead
    of queueing without bound."""
    from paddle_tpu.telemetry import counter_deltas, get_registry

    latency = {}
    reject_rate = {}
    offered = {}
    telemetry = {}
    shipper = {}
    collector_store = {}
    for variant, (pred, feed) in sorted(_serving_predictors(batch_size).items()):
        server = _make_server(pred, workers, queue_size)
        try:
            svc = _calibrate_serving(server, feed)
            capacity = workers / svc            # req/s the pool sustains
            steady_rate = max(1.0, 0.6 * capacity)
            tel0 = get_registry().counter_values()
            sh, ship0 = _shipper_snapshot()
            store0 = _store_snapshot(sh)
            lats, _ = _drive_serving(server, feed, requests, steady_rate)
            # steady-phase registry COUNTER deltas per REQUEST — the
            # serving row's `telemetry` snapshot (submitted/completed/
            # reject series; histograms are not counters and are
            # deliberately excluded — latency lives in latency_ms)
            telemetry[variant] = counter_deltas(
                tel0, get_registry().counter_values(), per=requests)
            if sh is not None:
                # collector attached: record what shipping cost over
                # the steady phase (events shipped/dropped, flush
                # seconds) per request
                shipper[variant] = counter_deltas(ship0, sh.counters(),
                                                  per=requests)
                store1 = _store_snapshot(sh)
                if store0 is not None and store1 is not None:
                    # persistence on: the store's ingest-write cost
                    # per request rides the row too
                    collector_store[variant] = counter_deltas(
                        store0, store1, per=requests)
            sat_rate = 3.0 * capacity
            _, rejected = _drive_serving(server, feed, requests, sat_rate)
        finally:
            server.close(drain=True, timeout=120)
        lat = np.array(lats)
        latency[variant] = {
            "p50": round(float(np.percentile(lat, 50)) * 1e3, 4),
            "p99": round(float(np.percentile(lat, 99)) * 1e3, 4),
        }
        reject_rate[variant] = round(rejected / requests, 4)
        offered[variant] = {"steady_rps": round(steady_rate, 2),
                            "saturated_rps": round(sat_rate, 2)}
    out = {
        "value": latency["fp32"]["p99"],
        "unit": f"ms p99 steady-state served latency (fp32, bs={batch_size}, "
                "0.6x capacity offered load)",
        "latency_ms": latency,
        "reject_rate_saturated": reject_rate,
        "offered_rps": offered,
        "telemetry": telemetry,
        "requests": requests,
        "workers": workers,
        "queue_size": queue_size,
        "batch_size": batch_size,
    }
    if shipper:
        out["shipper"] = shipper
    if collector_store:
        out["collector_store"] = collector_store
    return out


def _fleet_artifact(batch_size):
    """Export the MNIST MLP with bucket set {1, batch_size}; returns
    (artifact dir, single-row feed). Untrained weights — the row
    measures the fleet/batching runtime, not the model."""
    import tempfile

    import jax

    import paddle_tpu as pt
    from paddle_tpu import io as pio
    from paddle_tpu.models import mnist

    prog = pt.build(mnist.mlp)
    rng = np.random.RandomState(0)
    feed = {"image": rng.randn(batch_size, 784).astype(np.float32),
            "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}
    params, state = prog.init(jax.random.PRNGKey(0), **feed)
    d = os.path.join(tempfile.mkdtemp(), "model")
    pio.save_inference_model(d, prog, params, state, feed,
                             batch_buckets=[1, batch_size])
    feed1 = {k: np.asarray(v)[:1] for k, v in feed.items()}
    return d, feed1


def _make_fleet_front(dirname, variant, replicas, workers, queue_size,
                      max_wait_ms):
    """One serving front per variant: ``single`` = one PredictorServer
    holding ALL the workers (the pre-fleet deployment), ``fleet`` = a
    FleetRouter over ``replicas`` pad-alone servers, ``fleet_coalesced``
    = the same fleet with continuous batching on. Total worker count
    AND aggregate queue capacity are identical across variants (the
    single front gets replicas x queue_size) — the deltas isolate the
    runtime, not the parallelism or the queueing headroom."""
    from paddle_tpu import io as pio, serving
    from paddle_tpu.fleet import BatchPolicy, FleetRouter

    if variant == "single":
        return serving.PredictorServer(pio.load_inference_model(dirname),
                                       workers=replicas * workers,
                                       queue_size=replicas * queue_size)
    policy = (BatchPolicy(max_wait_ms=max_wait_ms)
              if variant == "fleet_coalesced" else None)
    return FleetRouter.spawn(dirname, replicas=replicas, workers=workers,
                             queue_size=queue_size, batch_policy=policy)


def _drive_fleet(front, feed, n, rate):
    """Open-loop driver at fixed offered ``rate`` req/s (rejects don't
    slow the arrival process). Returns (latencies of completed requests
    in seconds, rejected count, elapsed seconds submit-to-last-
    result)."""
    from paddle_tpu import serving

    pending, rejected = [], 0
    interval = 1.0 / rate
    t0 = time.perf_counter()
    next_t = t0
    for _ in range(n):
        now = time.perf_counter()
        if now < next_t:
            time.sleep(next_t - now)
        next_t += interval
        try:
            pending.append(front.submit(feed))
        except (serving.ServerOverloaded, serving.CircuitOpen,
                serving.ServingError):
            rejected += 1
    lats = []
    for p in pending:
        try:
            p.result(timeout=120)
            lats.append(p.latency)
        except serving.ServingError:
            rejected += 1
    return lats, rejected, time.perf_counter() - t0


def bench_serving_fleet(peak, batch_size=8, requests=240, replicas=3,
                        workers=1, queue_size=32, max_wait_ms=2.0):
    """Fleet suite row: p99 + per-worker throughput at 3x measured
    saturation for three fronts over the SAME artifact and total
    worker count — one big PredictorServer (``single``), a FleetRouter
    over N pad-alone replicas (``fleet``), and the same fleet with
    continuous batching (``fleet_coalesced``) — plus the two deltas
    the ROADMAP item asks for: fleet-vs-single-process and
    coalesced-vs-pad-alone. Traffic is single-row requests (the
    coalescable worst case for pad-alone: every dispatch is 7/8 pad
    rows at bucket 8). ``value`` is the coalesced p99 in ms; the
    offered rate is 3x the single front's measured capacity for every
    variant, so the deltas compare like with like."""
    from paddle_tpu.telemetry import counter_deltas, get_registry

    dirname, feed1 = _fleet_artifact(batch_size)
    total_workers = replicas * workers
    latency = {}
    throughput_per_worker = {}
    reject_rate = {}
    telemetry = {}
    sat_rate = None
    for variant in ("single", "fleet", "fleet_coalesced"):
        front = _make_fleet_front(dirname, variant, replicas, workers,
                                  queue_size, max_wait_ms)
        try:
            if sat_rate is None:   # calibrate ONCE (on the single front)
                svc = _calibrate_serving(front, feed1)
                sat_rate = 3.0 * total_workers / max(svc, 1e-9)
            tel0 = get_registry().counter_values()
            lats, rejected, elapsed = _drive_fleet(front, feed1, requests,
                                                   sat_rate)
            telemetry[variant] = counter_deltas(
                tel0, get_registry().counter_values(), per=requests)
        finally:
            front.close(drain=True, timeout=120)
        lat = np.array(lats) if lats else np.array([0.0])
        latency[variant] = {
            "p50": round(float(np.percentile(lat, 50)) * 1e3, 4),
            "p99": round(float(np.percentile(lat, 99)) * 1e3, 4),
        }
        throughput_per_worker[variant] = round(
            len(lats) / max(elapsed, 1e-9) / total_workers, 2)
        reject_rate[variant] = round(rejected / requests, 4)
    deltas = {
        "fleet_vs_single": {
            "p99_ms": round(latency["fleet"]["p99"]
                            - latency["single"]["p99"], 4),
            "throughput_per_worker_ratio": round(
                throughput_per_worker["fleet"]
                / max(throughput_per_worker["single"], 1e-9), 4),
        },
        "coalesced_vs_pad_alone": {
            "p99_ms": round(latency["fleet_coalesced"]["p99"]
                            - latency["fleet"]["p99"], 4),
            "throughput_per_worker_ratio": round(
                throughput_per_worker["fleet_coalesced"]
                / max(throughput_per_worker["fleet"], 1e-9), 4),
        },
    }
    return {
        "value": latency["fleet_coalesced"]["p99"],
        "unit": f"ms p99 coalesced-fleet served latency ({replicas}x"
                f"{workers} workers, single-row requests, 3x saturation "
                "offered load)",
        "latency_ms": latency,
        "throughput_per_worker_rps": throughput_per_worker,
        "reject_rate": reject_rate,
        "deltas": deltas,
        "telemetry": telemetry,
        "offered_rps": round(sat_rate, 2),
        "requests": requests,
        "replicas": replicas,
        "workers": workers,
        "queue_size": queue_size,
        "batch_size": batch_size,
        "max_wait_ms": max_wait_ms,
    }


def _saturation_probe(front, feed, n=128, inflight=16):
    """One replica's COALESCED capacity in req/s: a closed loop holding
    ``inflight`` single-row submits in flight (below the queue bound,
    so nothing sheds) and measuring drain throughput over ``n``
    completions — a sequential probe would miss the continuous-batching
    multiplier entirely."""
    import collections

    for _ in range(2):
        front.run(feed, timeout=120)
    pending = collections.deque()
    submitted = done = 0
    t0 = time.perf_counter()
    while done < n:
        while submitted < n and len(pending) < inflight:
            pending.append(front.submit(feed))
            submitted += 1
        pending.popleft().result(timeout=120)
        done += 1
    return n / max(time.perf_counter() - t0, 1e-9)


def _drive_diurnal(front, feed, phases, nworkers):
    """Open-loop driver over a piecewise-constant offered-rate curve
    (``phases`` = [(n, rate), ...]) that also integrates the fleet's
    worker-seconds (live worker count x wall time, sampled at every
    submit slot and once more when the last result lands — the
    resolution is one submit interval, plenty against multi-second
    phases). Returns (latencies s, rejected, elapsed s,
    worker_seconds)."""
    from paddle_tpu import serving

    pending, rejected = [], 0
    t0 = time.perf_counter()
    last = t0
    worker_seconds = 0.0
    for n, rate in phases:
        interval = 1.0 / max(rate, 1e-9)
        next_t = time.perf_counter()
        for _ in range(n):
            now = time.perf_counter()
            if now < next_t:
                time.sleep(next_t - now)
                now = time.perf_counter()
            worker_seconds += (now - last) * nworkers()
            last = now
            next_t += interval
            try:
                pending.append(front.submit(feed))
            except (serving.ServerOverloaded, serving.CircuitOpen,
                    serving.ServingError):
                rejected += 1
    lats = []
    for p in pending:
        try:
            p.result(timeout=120)
            lats.append(p.latency)
        except serving.ServingError:
            rejected += 1
    now = time.perf_counter()
    worker_seconds += (now - last) * nworkers()
    return lats, rejected, now - t0, worker_seconds


def _run_autoscale_variant(dirname, variant, max_replicas, workers,
                           queue_size, max_wait_ms, feed, phases):
    """One diurnal replay: ``fixed`` = statically provisioned at the
    peak (``max_replicas``, the pre-autoscaler deployment),
    ``autoscaled`` = a 1-replica fleet + an in-process telemetry
    collector fed by a registry-snapshot pump + the closed-loop
    :class:`~paddle_tpu.fleet.autoscaler.Autoscaler` over a
    ``LocalCollectorReader``. Returns (latencies s, rejected,
    elapsed s, worker_seconds, scale_info)."""
    import threading

    from paddle_tpu.telemetry import collector as tcollector
    from paddle_tpu.telemetry import get_registry

    replicas0 = max_replicas if variant == "fixed" else 1
    front = _make_fleet_front(dirname, "fleet_coalesced", replicas0,
                              workers, queue_size, max_wait_ms)
    col = scaler = pump = None
    stop = threading.Event()
    peak_replicas = replicas0
    try:
        if variant == "autoscaled":
            from paddle_tpu.fleet.autoscaler import (
                AutoscalePolicy, Autoscaler, LocalCollectorReader)

            col = tcollector.TelemetryCollector(origin_expiry_s=60.0)

            def _pump():
                nonlocal peak_replicas
                while not stop.wait(0.1):
                    try:
                        col.store.ingest("bench",
                                         get_registry().snapshot(),
                                         t=time.time())
                    except Exception:
                        pass   # a torn-down registry must not kill the pump
                    peak_replicas = max(peak_replicas,
                                        len(front.replica_names))

            pump = threading.Thread(target=_pump, daemon=True,
                                    name="bench-autoscale-pump")
            pump.start()
            scaler = Autoscaler(
                front, LocalCollectorReader(col),
                AutoscalePolicy(min_replicas=1, max_replicas=max_replicas,
                                up_queue_per_replica=2.0,
                                down_queue_per_replica=0.5,
                                up_window_s=0.3, down_window_s=1.5,
                                up_cooldown_s=0.8, down_cooldown_s=0.7,
                                flap_guard_s=0.4),
                interval=0.1, trend_window_s=2.0, trend_step_s=0.2,
                stale_after_s=1.0).start()
        lats, rejected, elapsed, ws = _drive_diurnal(
            front, feed, phases, lambda: len(front.replica_names) * workers)
        info = {"provisioned": replicas0, "peak_replicas": peak_replicas}
        if scaler is not None:
            c = scaler.counters()
            info["scale_ups"] = c["scale_ups"]
            info["scale_downs"] = c["scale_downs"]
        return lats, rejected, elapsed, ws, info
    finally:
        stop.set()
        if scaler is not None:
            scaler.close()
        if pump is not None:
            pump.join(timeout=2.0)
        if col is not None:
            col.close()
        front.close(drain=True, timeout=120)


def bench_autoscale(peak, batch_size=8, low_s=2.0, burst_s=4.0,
                    max_replicas=3, workers=1, queue_size=32,
                    max_wait_ms=2.0, slo_ms=50.0):
    """Fleet suite row: the closed-loop autoscaler vs a statically
    peak-provisioned fleet over the SAME diurnal curve — low
    (0.4x one replica's measured capacity, ``low_s`` seconds), burst
    (2.5x, ``burst_s``), low again — single-row coalesced traffic.
    ``value`` is the autoscaled p99 in ms; the headline comparison is
    ``worker_seconds_per_1k`` (provisioned worker-seconds per 1k
    completed requests) at the recorded ``slo_attainment`` — an
    autoscaler that holds roughly the fixed fleet's SLO while spending
    meaningfully fewer worker-seconds through the valleys is doing its
    job."""
    dirname, feed1 = _fleet_artifact(batch_size)
    front = _make_fleet_front(dirname, "fleet_coalesced", 1, workers,
                              queue_size, max_wait_ms)
    try:
        cap = _saturation_probe(front, feed1)
    finally:
        front.close(drain=True, timeout=120)
    # the curve is cut against ONE replica's COALESCED saturation (a
    # sequential calibration would undershoot ~bucket-x and the "burst"
    # would never overload anything); the open-loop driver is a single
    # python thread, so cap the offered rate where the arrival process
    # stays faithful
    low_rate = min(0.4 * cap, 800.0)
    burst_rate = min(2.5 * cap, 2000.0)

    def _n(rate, seconds):
        return max(8, min(6000, int(rate * seconds)))

    phases = [(_n(low_rate, low_s), low_rate),
              (_n(burst_rate, burst_s), burst_rate),
              (_n(low_rate, low_s), low_rate)]
    offered = sum(n for n, _ in phases)

    latency, slo_attainment, wsp1k, reject_rate, scale = {}, {}, {}, {}, {}
    for variant in ("fixed", "autoscaled"):
        lats, rejected, elapsed, ws, info = _run_autoscale_variant(
            dirname, variant, max_replicas, workers, queue_size,
            max_wait_ms, feed1, phases)
        lat = np.array(lats) if lats else np.array([0.0])
        latency[variant] = {
            "p50": round(float(np.percentile(lat, 50)) * 1e3, 4),
            "p99": round(float(np.percentile(lat, 99)) * 1e3, 4),
        }
        slo_attainment[variant] = round(
            float((lat <= slo_ms / 1e3).mean()), 4)
        wsp1k[variant] = round(ws / max(len(lats), 1) * 1000.0, 2)
        reject_rate[variant] = round(rejected / offered, 4)
        scale[variant] = info
    return {
        "value": latency["autoscaled"]["p99"],
        "unit": f"ms p99 autoscaled-fleet latency (diurnal "
                f"low/burst/low, band 1..{max_replicas}, single-row "
                "coalesced traffic)",
        "latency_ms": latency,
        "worker_seconds_per_1k": wsp1k,
        "slo_attainment": slo_attainment,
        "slo_ms": slo_ms,
        "reject_rate": reject_rate,
        "scale": scale,
        "offered_rps": {"low": round(low_rate, 2),
                        "burst": round(burst_rate, 2)},
        "phases": {"low_s": low_s, "burst_s": burst_s},
        "requests": offered,
        "max_replicas": max_replicas,
        "workers": workers,
        "queue_size": queue_size,
        "batch_size": batch_size,
        "max_wait_ms": max_wait_ms,
    }


def bench_fusion_profile(peak, batch_size=16, seq=128, iters=8, top_k=8):
    """Observability suite row: the fusion-aware profiler pointed at a
    transformer train step. A short pipelined window (host feeds through
    ``Trainer.step`` so the dispatch timer and pipeline metrics carry
    real numbers) followed by ``fusion_report`` + ``profile_report``.
    ``value`` is the top-k roofline-cost coverage — the fraction of the
    compiled step's static cost the named top-k fusion rows explain;
    ``top_fusions`` is the same table every train row records, and
    ``breakdown``/``bottleneck`` are the unified step profile."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.models import transformer

    cfg = transformer.base_config(src_vocab=4000, trg_vocab=4000,
                                  dropout=0.0, max_len=seq, dtype="bfloat16",
                                  fused_ce=True)
    model = pt.build(transformer.make_model(cfg))
    rng = np.random.RandomState(0)
    feeds = [{
        "src_ids": rng.randint(3, 4000, (batch_size, seq)).astype(np.int32),
        "trg_ids": rng.randint(3, 4000, (batch_size, seq)).astype(np.int32),
        "labels": rng.randint(3, 4000, (batch_size, seq)).astype(np.int32),
    } for _ in range(4)]
    trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss",
                         fetch_list=["loss"])
    trainer.startup(sample_feed=feeds[0])
    out = trainer.step(feeds[0])
    _sync(out)
    trainer.reset_profile()  # measured window excludes warmup/compile
    for i in range(iters):
        out = trainer.step(feeds[i % len(feeds)])
    _sync(out)
    fus = trainer.fusion_report(feeds[0], top_k=top_k)
    prof = trainer.profile_report()
    res = {
        "value": fus["coverage_top_k"],
        "unit": f"top-{top_k} fusion roofline-cost coverage "
                "(transformer train step)",
        "top_fusions": fus["top_fusions"],
        "n_units": fus["n_units"],
        "n_in_loop": fus["n_in_loop"],
        "avg_step_ms": prof["avg_step_ms"],
        "breakdown": prof["breakdown"],
        "bottleneck": prof["bottleneck"],
        "batch_size": batch_size,
        "seq": seq,
    }
    if fus.get("temp_mb") is not None:
        res["temp_mb"] = round(fus["temp_mb"], 3)
    return res


def bench_mnist_mlp(peak, batch_size=128, iters=50):
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.models import mnist

    model = pt.build(mnist.mlp)
    rng = np.random.RandomState(0)
    feeds = [{"image": rng.randn(batch_size, 784).astype(np.float32),
              "label": rng.randint(0, 10, (batch_size, 1)).astype(np.int64)}
             for _ in range(4)]
    trainer = pt.Trainer(model, opt.SGD(0.01), loss_name="loss")
    trainer.startup(sample_feed=feeds[0])
    dt_pipe, dt_comp = _time_trainer(trainer, feeds, warmup=5, iters=iters)
    f = flops.mlp_train_flops(batch_size, (784, 200, 200, 10))
    return _result(batch_size, "samples/sec", dt_pipe, dt_comp, f, peak,
                   trainer=trainer, feed=feeds[0])


def bench_lstm(peak, batch_size=64, seq=128, hidden=512, iters=20,
               baseline_key="lstm"):
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.models import lstm

    model = pt.build(lstm.make_model(vocab_size=10000, emb_dim=hidden,
                                     hidden_dim=hidden, num_layers=2))
    rng = np.random.RandomState(0)
    feeds = [{"word_ids": rng.randint(0, 10000, (batch_size, seq)).astype(np.int64),
              "label": rng.randint(0, 2, (batch_size, 1)).astype(np.int64),
              "sequence_length": np.full((batch_size,), seq, np.int64)}
             for _ in range(4)]
    trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss")
    trainer.startup(sample_feed=feeds[0])
    dt_pipe, dt_comp = _time_trainer(trainer, feeds, iters=iters)
    f = flops.lstm_train_flops(batch_size, seq, hidden, num_layers=2)
    return _result(batch_size, "samples/sec", dt_pipe, dt_comp, f, peak,
                   baseline_key, trainer=trainer, feed=feeds[0])


def bench_lstm_big(peak, batch_size=256, iters=10):
    """The reference's large text-cls row: bs=256, hidden=1280 (K40m
    1655 ms/batch)."""
    return bench_lstm(peak, batch_size=batch_size, hidden=1280, iters=iters,
                      baseline_key="lstm_big")


def bench_seq2seq(peak, batch_size=128, seq=30, emb_dim=512, hidden=512,
                  vocab=30000, iters=20):
    """GRU seq2seq with additive attention — the benchmark/fluid
    machine_translation model (WMT16-ish dims: vocab 30k, hidden 512,
    ~30-token sentences). Completes the reference benchmark-matrix
    parity: mnist/resnet/se_resnext/vgg/lstm rows all exist, this was
    the remaining model family."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import flops
    from paddle_tpu.models import seq2seq

    model = pt.build(seq2seq.make_model(src_vocab=vocab, trg_vocab=vocab,
                                        emb_dim=emb_dim, hidden=hidden))
    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(4):
        src = rng.randint(3, vocab, (batch_size, seq)).astype(np.int64)
        trg = np.zeros_like(src)
        trg[:, 0] = 1
        trg[:, 1:] = src[:, :-1]
        labels = np.concatenate([trg[:, 1:], np.full((batch_size, 1), 2)],
                                axis=1).astype(np.int64)
        feeds.append({"src_ids": src, "trg_ids": trg, "labels": labels,
                      "src_lengths": np.full((batch_size,), seq, np.int64)})
    trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss",
                         fetch_list=["loss"])
    trainer.startup(sample_feed=feeds[0])
    dt_pipe, dt_comp = _time_trainer(trainer, feeds, iters=iters)
    f = flops.seq2seq_train_flops(batch_size, seq, seq, emb_dim, hidden, vocab)
    return _result(batch_size * seq, "tokens/sec", dt_pipe, dt_comp, f, peak,
                   trainer=trainer, feed=feeds[0])


# -- inference configs -------------------------------------------------------


def bench_gpt_decode(peak, batch_size=8, prompt=128, new_tokens=128, iters=5):
    """Autoregressive serving: KV-cache prefill + greedy decode
    (models/gpt.make_generator), generated tokens/sec. Decode is
    memory-bound — expect MFU well below the train configs."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.core import flops
    from paddle_tpu.core.config import set_flag
    from paddle_tpu.models import gpt

    import os

    # don't inherit whatever dtype the previous config left in the flag
    set_flag("default_compute_dtype", "bfloat16")
    # BENCH_KV_DTYPE=int8: A/B the int8 KV cache (half the bf16 cache
    # bytes on the HBM-bound decode read; layers/stacked.quantize_kv)
    kv = os.environ.get("BENCH_KV_DTYPE", "compute")
    cfg = gpt.base_config(vocab_size=32000, max_len=prompt + new_tokens,
                          d_model=768, d_inner=3072, num_heads=12,
                          num_layers=12, use_flash=False, dtype="bfloat16",
                          kv_cache_dtype=kv)
    prog = pt.build(gpt.make_generator(cfg, max_new_tokens=new_tokens))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(3, cfg.vocab_size,
                           (batch_size, prompt)).astype(np.int32)
               for _ in range(2)]
    params, state = prog.init(jax.random.PRNGKey(0), prompts[0])
    run = jax.jit(lambda p, s, ids: prog.apply(p, s, ids)[0]["ids"])
    out = run(params, state, prompts[0])
    _sync(out)
    t0 = time.perf_counter()
    for i in range(iters):
        out = run(params, state, prompts[i % 2])
    _sync(out)
    dt = (time.perf_counter() - t0) / iters
    f = flops.gpt_decode_flops(batch_size, prompt, new_tokens, cfg)
    res = _result(batch_size * new_tokens, "tokens/sec", dt, dt, f, peak)
    del res["compute_only"], res["mfu_compute_only"]
    return res


def _bench_infer(peak, make_model_fn, fwd_flops_per_image, baseline_key,
                 variant="bf16", batch_size=16, image_size=224, iters=50):
    """AOT Predictor serving loop (api_impl.cc Run analog): host numpy →
    device → compiled executable, per call. Variants: fp32, bf16 (weights
    + compute cast), int8 (REAL int8 datapath: dynamic int8×int8→int32
    convs/matmuls baked into the exported program via
    quantize.int8_serving — the MXU's 2× int8 mode, not just weight
    compression)."""
    import contextlib as _ctxlib
    import tempfile

    import jax
    import paddle_tpu as pt
    from paddle_tpu import io as pio, quantize
    from paddle_tpu.core.config import set_flag

    from paddle_tpu.framework import layout_mode

    set_flag("default_compute_dtype",
             "float32" if variant == "fp32" else "bfloat16")
    with layout_mode("NHWC"):  # serving runs the TPU-native layout too
        model = pt.build(make_model_fn)
    rng = np.random.RandomState(0)
    feed = {"image": rng.randn(batch_size, image_size, image_size, 3).astype(np.float32),
            "label": rng.randint(0, 1000, (batch_size, 1)).astype(np.int64)}
    params, state = model.init(jax.random.PRNGKey(0), **feed)
    if variant in ("bf16", "int8"):
        params = quantize.cast_params_for_inference(params)
    mode = quantize.int8_serving() if variant == "int8" \
        else _ctxlib.nullcontext()
    with tempfile.TemporaryDirectory() as d:
        with mode:  # int8: quant ops traced into the exported program
            pio.save_inference_model(d, model, params, state, feed)
        pred = pio.load_inference_model(d)
    feeds = [{"image": rng.randn(batch_size, 3, image_size, image_size).astype(np.float32),
              "label": feed["label"]} for _ in range(4)]
    for i in range(5):
        out = pred.run(feeds[i % len(feeds)])
    _sync(out)
    lat = []
    for i in range(iters):
        t0 = time.perf_counter()
        out = pred.run(feeds[i % len(feeds)])
        _sync(out)  # per-call sync: serving latency, not pipelined rate
        lat.append(time.perf_counter() - t0)
    dt = sum(lat) / len(lat)
    f = fwd_flops_per_image * batch_size
    res = _result(batch_size, "images/sec", dt, dt, f, peak, baseline_key)
    del res["compute_only"], res["mfu_compute_only"]  # serving loop has no pre-staged variant
    res["latency_ms_p50"] = round(float(np.percentile(lat, 50)) * 1e3, 3)
    if len(lat) >= 20:  # a p99 from a 3-sample quick run is just the max
        res["latency_ms_p99"] = round(float(np.percentile(lat, 99)) * 1e3, 3)
    else:
        res["latency_ms_max"] = round(float(max(lat)) * 1e3, 3)
    return res


def bench_resnet50_infer(peak, variant="fp32", batch_size=16, image_size=224,
                         iters=50):
    from paddle_tpu.core import flops
    from paddle_tpu.models import resnet

    return _bench_infer(peak,
                        resnet.make_model(depth=50, class_num=1000,
                                          image_size=image_size),
                        flops.resnet_fwd_flops(50, image_size),
                        f"resnet50_infer_{variant}", variant=variant,
                        batch_size=batch_size, image_size=image_size,
                        iters=iters)


def bench_googlenet_infer(peak, batch_size=16, image_size=224, iters=50):
    """GoogLeNet serving loop, bf16 (reference row: 600.94 img/s bs=16,
    IntelOptimizedPaddle.md:91-97)."""
    from paddle_tpu.core import flops
    from paddle_tpu.models import convnets

    return _bench_infer(peak, convnets.make_googlenet(),
                        flops.googlenet_fwd_flops(image_size),
                        "googlenet_infer", variant="bf16",
                        batch_size=batch_size, image_size=image_size,
                        iters=iters)


# -- suite -------------------------------------------------------------------

TRAIN_CONFIGS = {
    "mnist_mlp": bench_mnist_mlp,
    "resnet50": bench_resnet50,
    "vgg16": bench_vgg16,
    "alexnet": bench_alexnet,
    "googlenet": bench_googlenet,
    "se_resnext": bench_se_resnext,
    "lstm": bench_lstm,
    "lstm_big": bench_lstm_big,
    "seq2seq": bench_seq2seq,
    "transformer": bench_transformer,
    "transformer_long": bench_transformer_long,
    "bert": bench_bert,
    "gpt": bench_gpt,
    "gpt_32k": bench_gpt_32k,
    "deepfm": bench_deepfm,
    "deepfm_10m": bench_deepfm_10m,
}

INFER_VARIANTS = ("fp32", "bf16", "int8")

INFER_CONFIGS = {
    **{f"resnet50_infer_{v}": functools.partial(bench_resnet50_infer, variant=v)
       for v in INFER_VARIANTS},
    "googlenet_infer": bench_googlenet_infer,
}


class _ConfigTimeout(Exception):
    pass


@contextlib.contextmanager
def _deadline(seconds: int):
    """Per-config SIGALRM deadline so one wedged config does not cost
    the whole suite record. CPython only runs signal handlers between
    bytecodes, so this catches Python-level stalls (slow iteration, a
    runaway retry loop) but NOT a hang inside a C call (wedged XLA
    compile / blocked transfer) — those need the driver's process-level
    timeout."""
    import signal

    def _raise(signum, frame):
        raise _ConfigTimeout(f"config exceeded {seconds}s")

    old = signal.signal(signal.SIGALRM, _raise)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _suite_names():
    import os

    names = [*TRAIN_CONFIGS, *INFER_CONFIGS, "gpt_decode",
             "dispatch_overhead", "guard_overhead", "quantized_allreduce",
             "zero_sharding", "input_pipeline", "device_cache", "serving",
             "serving_fleet", "autoscale", "fusion_profile",
             "elastic_reshard"]
    # the BASELINE five first, then the reference's headline serving
    # rows, then gpt — a driver that kills the suite early (the partial
    # SIGTERM record) still captures the configs that matter most
    priority = ["mnist_mlp", "resnet50", "transformer", "bert", "deepfm",
                "resnet50_infer_bf16", "resnet50_infer_int8",
                "resnet50_infer_fp32", "gpt"]
    names.sort(key=lambda n: priority.index(n) if n in priority
               else len(priority))  # stable: non-priority keep their order
    only = os.environ.get("BENCH_ONLY")  # comma-list filter (debug/tests)
    if only:
        keep = {s.strip() for s in only.split(",")}
        names = [n for n in names if n in keep]
    return names


def _result_key(name: str) -> str:
    return f"{name}_train" if name in TRAIN_CONFIGS else name


# quick mode shrinks iters everywhere; configs whose COMPILE dominates
# also shrink their shape so the harness smoke test stays a smoke test
QUICK_OVERRIDES = {"gpt_32k": {"seq": 2048, "iters": 2}}


def _run_one(name: str, peak: float, quick: bool = False, batch_size=None):
    """Run a single named config in-process."""
    kw = {}
    if batch_size:
        kw["batch_size"] = batch_size
    if name in TRAIN_CONFIGS:
        if quick:
            kw["iters"] = 3
            kw.update(QUICK_OVERRIDES.get(name, {}))
        res = TRAIN_CONFIGS[name](peak, **kw)
        if isinstance(res, dict):
            res.setdefault("steps_per_dispatch", _steps_per_dispatch())
        return res
    if name in INFER_CONFIGS:
        if quick:
            kw["iters"] = 3
        return INFER_CONFIGS[name](peak, **kw)
    if name == "gpt_decode":
        if quick:
            kw.update(iters=2, new_tokens=16)
        return bench_gpt_decode(peak, **kw)
    if name == "dispatch_overhead":
        if quick:
            kw.update(iters=8, k=4)
        return bench_dispatch_overhead(peak, **kw)
    if name == "guard_overhead":
        if quick:
            kw.update(iters=8, k=4)
        return bench_guard_overhead(peak, **kw)
    if name == "quantized_allreduce":
        if quick:
            kw.update(iters=8, k=4)
        return bench_quantized_allreduce(peak, **kw)
    if name == "zero_sharding":
        if quick:
            kw.update(iters=8, k=4)
        return bench_zero_sharding(peak, **kw)
    if name == "input_pipeline":
        if quick:
            kw.update(iters=8, k=4)
        return bench_input_pipeline(peak, **kw)
    if name == "device_cache":
        if quick:
            kw.update(iters=8, k=4, link_delay_ms=20.0)
        return bench_device_cache(peak, **kw)
    if name == "serving":
        if quick:
            kw.update(requests=40)
        return bench_serving(peak, **kw)
    if name == "serving_fleet":
        if quick:
            kw.update(requests=60, replicas=2)
        return bench_serving_fleet(peak, **kw)
    if name == "autoscale":
        if quick:
            kw.update(low_s=0.8, burst_s=1.5, max_replicas=2)
        return bench_autoscale(peak, **kw)
    if name == "fusion_profile":
        if quick:
            kw.update(iters=2, batch_size=4, seq=64)
        return bench_fusion_profile(peak, **kw)
    if name == "elastic_reshard":
        if quick:
            kw.update(iters=1)
        return bench_elastic_reshard(peak, **kw)
    raise ValueError(f"unknown config {name}")


def _probe_device(timeout: int = 240):
    """Run a tiny matmul in a SUBPROCESS with a hard timeout. The axon
    transport can wedge inside a C call where no in-process guard fires;
    a dead tunnel must fail the suite fast with a recorded reason, not
    hang the driver.

    Also measures host→device transfer bandwidth (16 MB device_put,
    best of 2): with-pipeline throughput is feed-bound when the tunnel
    degrades, and recording the day's link speed in the suite record is
    what lets a reader tell a framework regression from a bad tunnel.
    Returns (device_kind, mbps) — (None, None) on a dead tunnel."""
    import subprocess
    import sys

    code = ("import os, time, jax, numpy as np;"
            "w = os.environ.get('JAX_PLATFORMS');"
            "w and jax.config.update('jax_platforms', w);"
            "import jax.numpy as jnp;"
            "d = jax.devices()[0];"
            "x = jnp.ones((256, 256));"
            "jax.device_get((x @ x).sum());"
            "print('KIND', getattr(d, 'device_kind', str(d)));"
            "h = np.ones((4 * 1024 * 1024,), np.float32);"
            "ts = [];\n"
            "for _ in range(2):\n"
            "    t0 = time.perf_counter()\n"
            "    jax.block_until_ready(jax.device_put(h))\n"
            "    ts.append(time.perf_counter() - t0)\n"
            "print('XFER', round(16.0 / min(ts), 1))")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, None
    kind = mbps = None
    for line in r.stdout.splitlines():
        if line.startswith("KIND "):
            kind = line[5:]
        elif line.startswith("XFER "):
            mbps = float(line[5:])
    return kind, mbps


def run_suite(compute_dtype="bfloat16", quick=False, config_timeout=1200):
    """Each config runs in its OWN subprocess under a hard wall-clock
    timeout: a wedged XLA compile / blocked transfer (uninterruptible in
    Python) costs one config slot, never the suite record. Child stderr
    streams through for progress; the one-line JSON comes from child
    stdout."""
    import os
    import subprocess
    import sys

    kind, h2d_mbps = _probe_device()
    # load once up-front: the SIGTERM partial handler must not do fresh
    # file I/O between the signal and emitting the one JSON line, and
    # carried rows only make sense under the same measurement settings
    # (quick mode uses 3-iter smoke shapes; a different compute_dtype is
    # a different measurement)
    mid = None if quick else _load_mid_round()
    # an unstamped record is a mismatch too: rows of unknown dtype must
    # not be presented as this run's compute_dtype
    if mid and mid.get("compute_dtype") != compute_dtype:
        mid = None
    # backfill scope: only configs this run was asked to measure
    # (respects BENCH_ONLY) — applies to the wholesale fallback below too
    scheduled = {_result_key(n) for n in _suite_names()}
    if kind is None:
        # the tunnel is down at suite time — fall back to the committed
        # mid-round on-chip capture (tools/chip_queue.py merges rows into
        # BENCH_mid_r*.json whenever a link window opens) so the round
        # record preserves every measurement actually taken, instead of
        # recording nothing the way round 3 did; one carry policy for
        # both paths: the helper fills the (here: all) holes
        mid_configs = {}
        _backfill_from_mid_round(mid_configs, scheduled=scheduled, mid=mid)
        if mid_configs:
            # a failed probe means there is no usable link right now, so
            # the compute-only headline applies regardless of what (if
            # anything) the mid-round run measured for h2d bandwidth:
            # always pass 0.0 and restore the mid record's value after
            # the dtype gate above guarantees mid's compute_dtype == ours
            res = _assemble(mid_configs, mid.get("device"),
                            mid.get("peak_flops"), mid.get("peak_source"),
                            compute_dtype, 0.0)
            res["host_to_device_mbps"] = mid.get("host_to_device_mbps")
            res["link_down_at_suite_time"] = True
            res["probe_error"] = (PROBE_FAILED_MSG +
                                  "; nothing was measured in THIS run")
            res["note"] = ("configs are the committed mid-round on-chip "
                           "capture "
                           f"({mid.get('_source', 'BENCH_mid record')})")
            return res
        return {"metric": "suite", "value": 0.0, "unit": "MFU",
                "vs_baseline": None, "error": PROBE_FAILED_MSG,
                "compute_dtype": compute_dtype, "configs": {}}
    if h2d_mbps is not None and h2d_mbps < LINK_DEGRADED_MBPS:
        # same threshold _assemble uses for the headline switch: below
        # it the pipelined numbers are link-bound, so configs that wedge
        # would eat the caller's whole window at the full timeout —
        # shrink it so more configs get a chance to record, and the
        # per-config records say why the numbers look link-bound
        config_timeout = min(config_timeout, 600)
        print(f"[bench] degraded h2d link ({h2d_mbps} MB/s): "
              f"per-config timeout capped at {config_timeout}s",
              file=sys.stderr, flush=True)

    configs = {}
    device = peak = peak_source = None
    child = [None]  # the in-flight config subprocess, for the handler

    def _die_with_parent():
        # PR_SET_PDEATHSIG: the kernel kills the child whenever the suite
        # parent exits — closes the race where a signal lands between one
        # child's cleanup and the next Popen's assignment, which would
        # otherwise orphan a device-holding benchmark process
        import ctypes
        try:
            ctypes.CDLL("libc.so.6", use_errno=True).prctl(1, 9)  # SIGKILL
        except OSError:
            pass

    def _partial(signum, frame):
        # a driver timeout must not lose the record: kill the in-flight
        # child (it holds the device), emit whatever completed
        # (priority-ordered, so the BASELINE configs are in), exit 0 so
        # the one JSON line is recorded as the run's output
        if child[0] is not None and child[0].poll() is None:
            child[0].kill()
        _backfill_from_mid_round(configs, scheduled=scheduled, mid=mid)
        res = _assemble(configs, device or kind, peak, peak_source,
                        compute_dtype, h2d_mbps)
        res["partial"] = f"suite interrupted by signal {signum}"
        print(json.dumps(res), flush=True)
        os._exit(0)

    def _run_config(name, timeout=None):
        nonlocal device, peak, peak_source
        timeout = timeout or config_timeout
        key = _result_key(name)
        print(f"[bench] {name} ...", file=sys.stderr, flush=True)
        cmd = [sys.executable, os.path.abspath(__file__), "--model", name,
               "--compute_dtype", compute_dtype, "--emit", "raw",
               "--config_timeout", str(timeout)]
        if quick:
            cmd.append("--quick")
        # +180s startup slack: the child's own _deadline(config_timeout)
        # wraps only _run_one; the parent clock also covers jax import
        # and backend connect, which must not eat the config's budget
        child[0] = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                                    preexec_fn=_die_with_parent)
        try:
            stdout, _ = child[0].communicate(timeout=timeout + 180)
            rc = child[0].returncode
        except subprocess.TimeoutExpired:
            child[0].kill()
            child[0].communicate()
            configs[key] = {"error": f"Timeout: config exceeded "
                                     f"{timeout}s (subprocess killed)",
                            "timed_out": True}
            print(f"[bench] {name} TIMED OUT", file=sys.stderr, flush=True)
            return
        finally:
            child[0] = None
        line = (stdout.strip().splitlines() or [""])[-1]
        try:
            payload = json.loads(line)
        except json.JSONDecodeError:
            payload = {"error": f"rc={rc}, no JSON (crash/OOM?)"}
        if "error" in payload:
            configs[key] = {"error": payload["error"]}
            if "_ConfigTimeout" in payload["error"]:
                # the child's own SIGALRM deadline fired — same rescue
                # case as a parent-level kill: mark it so the retry
                # pass (cached compile + doubled budget) picks it up
                configs[key]["timed_out"] = True
            print(f"[bench] {name} failed: {payload['error']}",
                  file=sys.stderr, flush=True)
            return
        configs[key] = payload["result"]
        device = payload.get("device", device)
        peak = payload.get("peak_flops", peak)
        peak_source = payload.get("peak_source", peak_source)
        c = configs[key]
        print(f"[bench] {name}: {c.get('value')} {c.get('unit')} "
              f"mfu={c.get('mfu')}", file=sys.stderr, flush=True)

    import signal
    old_term = signal.signal(signal.SIGTERM, _partial)
    old_int = signal.signal(signal.SIGINT, _partial)
    try:
        for name in _suite_names():
            _run_config(name)
        # second chance for timed-out configs: the persistent compile
        # cache means attempt 1's compile work is NOT lost — attempt 2
        # typically skips straight to the timed steps, which is exactly
        # what rescues the big rows inside the degraded-link 600 s cap
        retry = [n for n in _suite_names()
                 if configs.get(_result_key(n), {}).get("timed_out")]
        for name in retry:
            # doubled budget: if attempt 1 was SIGKILLed mid-compile the
            # cache has nothing to reuse, and the end-of-pass retry only
            # re-runs the few configs that actually failed
            print(f"[bench] retrying {name} (compile cached or 2x budget)",
                  file=sys.stderr, flush=True)
            # never LESS than attempt 1's budget (a caller may pass
            # --config_timeout above the 1800 cap)
            _run_config(name,
                        timeout=max(config_timeout,
                                    min(config_timeout * 2, 1800)))
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)

    _backfill_from_mid_round(configs, scheduled=scheduled, mid=mid)
    return _assemble(configs, device or kind, peak, peak_source,
                     compute_dtype, h2d_mbps)


# Below this host->device bandwidth the pipelined numbers measure the
# dev-tunnel link, not the framework: any real TPU host feeds over
# PCIe/NVMe at GB/s (the axon SSH tunnel has degraded to ~12 MB/s
# mid-round twice). The record keeps BOTH variants per config either
# way; this only selects which one the one-line headline summarizes.
LINK_DEGRADED_MBPS = 500.0

# one string for both the hard-error record and the fallback's
# probe_error field — they must never drift apart
PROBE_FAILED_MSG = ("device probe failed: backend unreachable or wedged "
                    "(tiny-matmul subprocess timed out)")


def _load_mid_round(root=None):
    """Latest committed mid-round capture (BENCH_mid_r*.json), or None.

    tools/chip_queue.py appends on-chip rows to this record during link
    windows; the suite uses it two ways: wholesale when the device probe
    fails outright, and per-config to backfill rows the live run lost to
    a timeout/crash that an earlier window captured successfully."""
    import glob
    import os
    import re

    def _round_no(path):
        m = re.search(r"BENCH_mid_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    here = root or os.path.dirname(os.path.abspath(__file__))
    # numeric round order, not lexicographic: r100 must beat r99
    paths = sorted(glob.glob(os.path.join(here, "BENCH_mid_r*.json")),
                   key=_round_no)
    for path in reversed(paths):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(rec, dict) and rec.get("configs"):
            for k, c in rec["configs"].items():
                # normalize rows a pre-fix chip_queue stored in raw-
                # envelope shape ({"result": {...}, "device": ...}) —
                # the writer migrates too, but this reader is also the
                # tunnel-down path where the writer never runs
                if isinstance(c, dict) and isinstance(c.get("result"), dict):
                    rec["configs"][k] = c["result"]
            rec["_source"] = os.path.basename(path)
            return rec
    return None


_UNSET = object()


def _backfill_from_mid_round(configs, scheduled=None, mid=_UNSET):
    """Replace errored/missing live rows with mid-round on-chip rows.

    Only fills holes — a live measurement (even a worse one) always wins
    over a carried row, because it reflects the code being judged — and
    only for configs the caller scheduled this run (a BENCH_ONLY debug
    run must not sprout rows it never attempted). Carried rows are
    marked per-config and never drive the headline (_assemble skips
    them unless NO live train row exists at all). Pass mid explicitly
    to avoid file I/O at call time (the SIGTERM handler must not read
    files between the signal and emitting the record)."""
    if mid is _UNSET:
        mid = _load_mid_round()
    if not mid or not mid.get("configs"):
        return
    for key, row in mid["configs"].items():
        if not isinstance(row, dict) or "error" in row:
            continue
        # A/B variant rows (chip_queue's "transformer_train@no_flash")
        # stay in the mid record for the judge but do NOT carry into
        # suite records: the suite never measures variant keys itself,
        # so carrying them just accumulates stale historical rows
        if "@" in key:
            continue
        if scheduled is not None and key not in scheduled:
            continue
        live = configs.get(key)
        if live is None or "error" in live:
            carried = dict(row)
            carried["carried_from_mid_round"] = True
            if live is not None and "error" in live:
                carried["live_error"] = live["error"]
            configs[key] = carried


def _assemble(configs, device, peak, peak_source, compute_dtype,
              h2d_mbps=None):
    # run_suite's internal retry marker must not ship in the record (a
    # double-timeout row would carry it, a timeout-then-crash row would
    # not — meaningless downstream); _assemble is the single choke point
    # both the normal and the SIGTERM-partial paths go through
    for c in configs.values():
        if isinstance(c, dict):
            c.pop("timed_out", None)
    degraded = h2d_mbps is not None and h2d_mbps < LINK_DEGRADED_MBPS
    key = "mfu_compute_only" if degraded else "mfu"
    carried = sorted(n for n, c in configs.items()
                     if isinstance(c, dict) and c.get("carried_from_mid_round"))
    # the headline must reflect the code under test: carried rows (old
    # measurements backfilled for provenance) count only when this run
    # measured NO train row at all — and then the unit says so
    live_mfus = [c[key] for n, c in configs.items()
                 if n.endswith("_train") and key in c
                 and n not in carried]
    all_mfus = [c[key] for n, c in configs.items()
                if n.endswith("_train") and key in c]
    headline_carried = not live_mfus and bool(all_mfus)
    mfus = live_mfus or all_mfus
    headline = max(mfus) if mfus else 0.0
    rn = configs.get("resnet50_train", {})
    # a carried resnet row may only feed the top-level ratio when the
    # whole headline is carried (and the unit discloses it); a live
    # headline must not sit next to an old-code vs_baseline
    if rn.get("carried_from_mid_round") and not headline_carried:
        rn = {}
    vs = rn.get("vs_baseline")
    if degraded and rn.get("compute_only") and BASELINES.get("resnet50"):
        vs = round(rn["compute_only"] / BASELINES["resnet50"], 2)
    unit = "MFU (compute-only; link degraded)" if degraded else "MFU"
    if headline_carried:
        unit += "; carried from mid-round capture"
    out = {
        "metric": "suite",
        "value": round(headline, 4),
        "unit": unit,
        "vs_baseline": vs,
        "device": device,
        "peak_flops": peak,
        "peak_source": peak_source,
        "compute_dtype": compute_dtype,
        "host_to_device_mbps": h2d_mbps,
        "configs": configs,
    }
    if degraded:
        out["link_degraded"] = True
    if carried:
        out["carried_configs"] = carried
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default=None,
                   choices=sorted(_suite_names()) + ["suite"],
                   help="single config (default: full suite)")
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--compute_dtype", default="bfloat16",
                   choices=["float32", "bfloat16"],
                   help="mixed-precision compute dtype (master params stay f32)")
    p.add_argument("--quick", action="store_true",
                   help="3 timing iters per config (harness smoke test)")
    p.add_argument("--steps_per_dispatch", type=int, default=None, metavar="K",
                   help="fuse K optimizer steps per device launch "
                        "(Trainer.run_steps) in every train config; "
                        "recorded per config. Env BENCH_STEPS_PER_DISPATCH")
    p.add_argument("--config_timeout", type=int, default=1200,
                   help="hard per-config wall-clock limit in suite mode")
    p.add_argument("--emit", default="pretty", choices=["pretty", "raw"],
                   help="raw: suite-internal single-config JSON envelope")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="single --model only: dump a jax.profiler trace "
                        "(xplane/perfetto) of the run into DIR")
    args = p.parse_args()

    if args.steps_per_dispatch is not None:
        # via env so suite-mode child subprocesses inherit the knob
        import os
        os.environ["BENCH_STEPS_PER_DISPATCH"] = str(args.steps_per_dispatch)

    if args.model in (None, "suite"):
        if args.batch_size:
            p.error("--batch_size applies to a single --model config, "
                    "not the full suite")
        if args.profile:
            p.error("--profile applies to a single --model config, "
                    "not the full suite")
        print(json.dumps(run_suite(args.compute_dtype, quick=args.quick,
                                   config_timeout=args.config_timeout)))
        return

    jax = _init_jax()
    from paddle_tpu.core import flops
    from paddle_tpu.core.config import set_flag

    set_flag("default_compute_dtype", args.compute_dtype)
    dev = jax.devices()[0]
    peak, peak_source = flops.device_peak_flops(dev)
    prof = (jax.profiler.trace(args.profile) if args.profile
            else contextlib.nullcontext())
    try:
        with _deadline(args.config_timeout), prof:
            res = _run_one(args.model, peak, quick=args.quick,
                           batch_size=args.batch_size)
    except Exception as e:  # the suite parent records the reason
        if args.emit == "raw":
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return
        raise
    if args.emit == "raw":
        print(json.dumps({
            "result": res,
            "device": getattr(dev, "device_kind", str(dev)),
            "peak_flops": peak,
            "peak_source": peak_source,
        }))
        return
    print(json.dumps({
        "metric": f"{args.model}_throughput_{args.compute_dtype}",
        "peak_source": peak_source,
        **res,
    }))


if __name__ == "__main__":
    main()
