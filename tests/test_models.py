"""Model-zoo smoke + convergence tests (book-test analog for each
BASELINE config, at toy scale)."""

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.models import bert, deepfm, lstm, resnet, transformer, vgg, word2vec


@pytest.mark.slow
def test_resnet50_forward_backward():
    model = pt.build(resnet.make_model(depth=50, class_num=10, image_size=32))
    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    y = np.random.randint(0, 10, (2, 1)).astype(np.int64)
    trainer = pt.Trainer(model, opt.Momentum(0.1, 0.9), loss_name="loss")
    trainer.startup(sample_feed={"image": x, "label": y})
    # param count sanity: ResNet-50 ImageNet head ~25.5M params; 10-class head smaller
    n_params = sum(int(np.prod(v.shape)) for v in trainer.scope.params.values())
    assert 23e6 < n_params < 26e6, f"ResNet-50 param count off: {n_params}"
    out = trainer.step({"image": x, "label": y})
    assert np.isfinite(float(out["loss"]))


@pytest.mark.slow
def test_vgg16_forward():
    model = pt.build(vgg.make_model(depth=16, class_num=10))
    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    y = np.random.randint(0, 10, (2, 1)).astype(np.int64)
    trainer = pt.Trainer(model, opt.SGD(0.01), loss_name="loss")
    trainer.startup(sample_feed={"image": x, "label": y})
    out = trainer.step({"image": x, "label": y})
    assert np.isfinite(float(out["loss"]))


def test_lstm_text_classification_learns():
    model = pt.build(lstm.make_model(vocab_size=50, emb_dim=16, hidden_dim=16,
                                     num_layers=2))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50, (8, 12)).astype(np.int64)
    # learnable rule: label = whether token 7 appears early
    label = (ids[:, :4] == 7).any(axis=1).astype(np.int64)[:, None]
    seq_len = np.full((8,), 12, np.int64)
    feed = {"word_ids": ids, "label": label, "sequence_length": seq_len}
    trainer = pt.Trainer(model, opt.Adam(0.01), loss_name="loss")
    trainer.startup(sample_feed=feed)
    losses = [float(trainer.step(feed)["loss"]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5


def test_lstm_sequence_length_masking():
    """Padded positions must not affect pooled output (LoD analog)."""
    model = pt.build(lstm.make_model(vocab_size=20, emb_dim=8, hidden_dim=8,
                                     num_layers=1))
    ids1 = np.zeros((1, 10), np.int64)
    ids1[0, :5] = [3, 4, 5, 6, 7]
    ids2 = ids1.copy()
    ids2[0, 5:] = 9  # different padding content
    label = np.zeros((1, 1), np.int64)
    sl = np.array([5], np.int64)
    f1 = {"word_ids": ids1, "label": label, "sequence_length": sl}
    trainer = pt.Trainer(model, opt.SGD(0.1), loss_name="loss")
    trainer.startup(sample_feed=f1)
    o1 = trainer.eval(f1)
    o2 = trainer.eval({"word_ids": ids2, "label": label, "sequence_length": sl})
    np.testing.assert_allclose(np.asarray(o1["logits"]), np.asarray(o2["logits"]),
                               atol=1e-5)


def _tiny_transformer_cfg(**kw):
    d = dict(src_vocab=60, trg_vocab=60, d_model=32, d_inner=64, num_heads=4,
             num_encoder_layers=2, num_decoder_layers=2, dropout=0.0)
    d.update(kw)
    return transformer.base_config(**d)


def _translation_batch(bs=8, s=16, vocab=60, seed=0):
    rng = np.random.RandomState(seed)
    src = rng.randint(3, vocab, (bs, s)).astype(np.int64)
    trg = np.zeros_like(src)
    trg[:, 0] = 1
    trg[:, 1:] = (src[:, :-1] % (vocab - 3)) + 3
    labels = np.concatenate([trg[:, 1:], np.full((bs, 1), 2)], axis=1).astype(np.int64)
    return {"src_ids": src, "trg_ids": trg, "labels": labels}


def test_transformer_learns_copy_task():
    cfg = _tiny_transformer_cfg()
    model = pt.build(transformer.make_model(cfg))
    feed = _translation_batch()
    trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss")
    trainer.startup(sample_feed=feed)
    losses = [float(trainer.step(feed)["loss"]) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.5, f"{losses[0]} -> {losses[-1]}"


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_transformer_flash_matches_xla():
    feed = _translation_batch(bs=2, s=32)
    m_x = pt.build(transformer.make_model(_tiny_transformer_cfg(use_flash=False)))
    m_f = pt.build(transformer.make_model(_tiny_transformer_cfg(use_flash=True)))
    p, s = m_x.init(jax.random.PRNGKey(0), **feed)
    out_x, _ = m_x.apply(p, s, **feed)
    out_f, _ = m_f.apply(p, s, **feed)
    np.testing.assert_allclose(float(out_x["loss"]), float(out_f["loss"]),
                               rtol=1e-4)


def _fuse_param_tree(unfused, fused_names):
    """Stitch an unfused q/k/v param tree into the fuse_qkv layout:
    qkv_proj/w = stack([q,k,v], axis=1), kv_proj/w = stack([k,v], 1)."""
    import numpy as _np
    out = {}
    for fname in fused_names:
        for tag, parts in (("qkv_proj", "qkv"), ("kv_proj", "kv")):
            if f"{tag}/w" in fname or f"{tag}/b" in fname:
                leaf = "w" if fname.endswith("/w") else "b"
                prefix = fname[: fname.index(tag)]
                out[fname] = _np.stack(
                    [unfused[f"{prefix}{p}_proj/{leaf}"] for p in parts], axis=-2)
                break
        else:
            out[fname] = unfused[fname]
    return out


def test_transformer_fused_qkv_matches_unfused():
    """fuse_qkv is one [d,3,d] (self) / [d,2,d] (cross) matmul; with
    tied weights the math is identical to three separate projections."""
    feed = _translation_batch(bs=2, s=16)
    m_u = pt.build(transformer.make_model(_tiny_transformer_cfg()))
    m_f = pt.build(transformer.make_model(_tiny_transformer_cfg(fuse_qkv=True)))
    p_u, s_u = m_u.init(jax.random.PRNGKey(0), **feed)
    p_f, s_f = m_f.init(jax.random.PRNGKey(0), **feed)
    assert any(k.endswith("qkv_proj/w") for k in p_f), sorted(p_f)[:5]
    assert any(k.endswith("kv_proj/w") for k in p_f)  # decoder cross-attn
    p_f2 = _fuse_param_tree(p_u, list(p_f))
    out_u, _ = m_u.apply(p_u, s_u, **feed)
    out_f, _ = m_f.apply(p_f2, s_f, **feed)
    np.testing.assert_allclose(float(out_u["loss"]), float(out_f["loss"]),
                               rtol=1e-5)


@pytest.mark.slow
def test_transformer_fused_qkv_decode_matches():
    """The incremental-decode (KV cache) path honors fuse_qkv and its
    param names round-trip from a trained scope."""
    cfg = _tiny_transformer_cfg(fuse_qkv=True)
    model = pt.build(transformer.make_model(cfg))
    feed = _translation_batch(bs=2, s=8)
    trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss")
    trainer.startup(sample_feed=feed)
    trainer.step(feed)
    dec = pt.build(transformer.make_decoder(cfg, max_len=8))
    out = dec.apply(trainer.scope.params, trainer.scope.state,
                    feed["src_ids"])[0]
    ids = np.asarray(out["ids"])
    assert ids.shape == (2, 8)


@pytest.mark.slow
def test_transformer_fused_qkv_tp_sharding():
    """Fused [d,3,d] params shard on the last axis over tp with no
    resharding warnings."""
    import warnings
    from paddle_tpu.parallel import sharding as _sh
    mesh = pt.make_mesh({"dp": 2, "tp": 4})
    cfg = _tiny_transformer_cfg(fuse_qkv=True)
    model = pt.build(transformer.make_model(cfg))
    feed = _translation_batch(bs=4)
    trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                         sharding_rules=pt.parallel.transformer_tp_rules())
    # the one-shot warning dedup would let an earlier test consume the
    # warning this test asserts against — reset it first
    _sh.reset_drop_warnings()
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        trainer.startup(sample_feed=feed)
    qkvw = [k for k in trainer.scope.params if k.endswith("qkv_proj/w")][0]
    spec = trainer.scope.params[qkvw].sharding.spec
    assert spec[-1] == "tp", f"qkv_proj/w last axis not tp: {spec}"
    out = trainer.step(feed)
    assert np.isfinite(float(out["loss"]))


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_transformer_tp_sharding_compiles():
    """TP+DP mesh on 8 virtual devices — the multi-chip path at toy size."""
    mesh = pt.make_mesh({"dp": 2, "tp": 4})
    cfg = _tiny_transformer_cfg()
    model = pt.build(transformer.make_model(cfg))
    feed = _translation_batch(bs=4)
    trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                         sharding_rules=pt.parallel.transformer_tp_rules())
    trainer.startup(sample_feed=feed)
    # check a TP rule actually sharded a weight over tp
    qw = [k for k in trainer.scope.params if k.endswith("q_proj/w")][0]
    sh = trainer.scope.params[qw].sharding
    assert "tp" in str(sh.spec), f"q_proj/w not TP-sharded: {sh.spec}"
    out = trainer.step(feed)
    assert np.isfinite(float(out["loss"]))


def test_deepfm_learns():
    model = pt.build(deepfm.make_model(num_sparse_fields=5, sparse_feature_dim=20,
                                       embedding_size=4, num_dense=3,
                                       hidden_dims=(16, 16)))
    rng = np.random.RandomState(0)
    bs = 64
    dense = rng.randn(bs, 3).astype(np.float32)
    sparse = rng.randint(0, 20, (bs, 5)).astype(np.int64)
    label = (dense.sum(1, keepdims=True) > 0).astype(np.int64)
    feed = {"dense": dense, "sparse_ids": sparse, "label": label}
    trainer = pt.Trainer(model, opt.Adam(0.01), loss_name="loss")
    trainer.startup(sample_feed=feed)
    losses = [float(trainer.step(feed)["loss"]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7


@pytest.mark.slow
def test_bert_pretrain_step():
    cfg = bert.base_config(vocab_size=100, max_len=32, d_model=32, d_inner=64,
                           num_heads=4, num_layers=2, dropout=0.0)
    model = pt.build(bert.make_pretrain_model(cfg))
    rng = np.random.RandomState(0)
    bs, s, m = 2, 16, 3
    feed = {
        "input_ids": rng.randint(0, 100, (bs, s)).astype(np.int64),
        "token_type_ids": np.zeros((bs, s), np.int64),
        "mlm_positions": rng.randint(0, s, (bs, m)).astype(np.int64),
        "mlm_labels": rng.randint(0, 100, (bs, m, 1)).astype(np.int64),
        "nsp_label": rng.randint(0, 2, (bs, 1)).astype(np.int64),
    }
    trainer = pt.Trainer(model, opt.AdamW(1e-3), loss_name="loss")
    trainer.startup(sample_feed=feed)
    o0 = trainer.step(feed)
    o1 = trainer.step(feed)
    assert float(o1["loss"]) < float(o0["loss"])


@pytest.mark.slow
def test_bert_fused_ce_matches_dense_head():
    """BERT MLM head with chunked logits-free CE == the dense-logits
    head on identical params (loss and gradients) — the bench config's
    path, and the fix for the fsdp scatter-grad resharding."""
    kw = dict(vocab_size=100, max_len=32, d_model=32, d_inner=64,
              num_heads=4, num_layers=2, dropout=0.0)
    rng = np.random.RandomState(0)
    bs, s, m = 2, 16, 3
    feed = {
        "input_ids": rng.randint(0, 100, (bs, s)).astype(np.int32),
        "token_type_ids": np.zeros((bs, s), np.int32),
        "mlm_positions": rng.randint(0, s, (bs, m)).astype(np.int32),
        "mlm_labels": rng.randint(0, 100, (bs, m, 1)).astype(np.int64),
        "nsp_label": rng.randint(0, 2, (bs, 1)).astype(np.int64),
    }
    dense = pt.build(bert.make_pretrain_model(bert.base_config(**kw)))
    fused = pt.build(bert.make_pretrain_model(
        bert.base_config(fused_ce=True, ce_chunk=32, **kw)))
    params, state = dense.init(jax.random.PRNGKey(0), **feed)

    def loss_of(prog):
        def f(p):
            out, _ = prog.apply(p, state, **feed)
            return out["loss"]
        return f

    ld, gd = jax.value_and_grad(loss_of(dense))(params)
    lf, gf = jax.value_and_grad(loss_of(fused))(params)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5)
    for k in gd:
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(gd[k]),
                                   atol=1e-5, rtol=1e-4, err_msg=k)


def test_word2vec_learns():
    model = pt.build(word2vec.make_model(dict_size=30, emb_dim=8, hidden=32))
    rng = np.random.RandomState(0)
    ctx = rng.randint(0, 30, (32, 4)).astype(np.int64)
    label = ((ctx.sum(axis=1)) % 30)[:, None].astype(np.int64)  # learnable fn
    feed = {"context_ids": ctx, "label": label}
    trainer = pt.Trainer(model, opt.Adam(0.05), loss_name="loss")
    trainer.startup(sample_feed=feed)
    # shared embedding used once
    assert "shared_emb/w" in trainer.scope.params
    losses = [float(trainer.step(feed)["loss"]) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.5


@pytest.mark.slow
def test_resnet_nhwc_matches_nchw():
    """NHWC (the TPU-native conv layout the benchmark runs) computes the
    same function as the reference's NCHW: identical loss/logits for the
    transposed input with identically-seeded params."""
    import jax

    def tiny(df):
        return resnet.make_model(depth=50, class_num=7, image_size=24,
                                 data_format=df)

    x = np.random.randn(2, 3, 24, 24).astype(np.float32)
    y = np.random.randint(0, 7, (2, 1)).astype(np.int64)
    m_nchw = pt.build(tiny("NCHW"))
    m_nhwc = pt.build(tiny("NHWC"))
    feed_c = {"image": x, "label": y}
    feed_h = {"image": x.transpose(0, 2, 3, 1), "label": y}
    p_c, s_c = m_nchw.init(jax.random.PRNGKey(0), **feed_c)
    p_h, s_h = m_nhwc.init(jax.random.PRNGKey(0), **feed_h)
    # same param tree (conv weights stay OIHW in both layouts)
    assert {k: v.shape for k, v in p_c.items()} \
        == {k: v.shape for k, v in p_h.items()}
    out_c, _ = m_nchw.apply(p_c, s_c, training=False, **feed_c)
    out_h, _ = m_nhwc.apply(p_c, s_h, training=False, **feed_h)
    np.testing.assert_allclose(np.asarray(out_h["logits"]),
                               np.asarray(out_c["logits"]),
                               rtol=2e-4, atol=2e-4)
