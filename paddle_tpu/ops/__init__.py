"""Custom kernels (pallas) — the operators/math kernel-library analog
(jit_kernel.h xbyak JIT, fused LSTM/softmax kernels): here, hand-written
TPU kernels for the few ops XLA fusion doesn't already cover."""

from . import flash_attention

__all__ = ["flash_attention"]
