"""The runnable examples stay runnable (book-chapter rot guard):
each is executed as a real subprocess at tiny settings."""

import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(__file__)
EXAMPLES = os.path.join(HERE, os.pardir, "examples")


def _run(script, *args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, os.path.join(EXAMPLES, script), *args],
                       capture_output=True, text=True, env=env,
                       timeout=timeout)
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


@pytest.mark.slow
def test_train_gpt_example_runs_and_resumes(tmp_path):
    ck = str(tmp_path / "ck")
    out = _run("train_gpt.py", "--steps", "6", "--d_model", "64",
               "--layers", "1", "--batch", "8", "--ckpt", ck)
    assert "checkpoint saved" in out
    out2 = _run("train_gpt.py", "--steps", "2", "--d_model", "64",
                "--layers", "1", "--batch", "8", "--ckpt", ck)
    assert "resumed from" in out2 and "at step 6" in out2


@pytest.mark.slow
def test_train_gpt_example_hoisted_accum_and_int8_generate():
    """The round-5 features end to end in the runnable example: dp +
    hoisted accumulation trains, and the trained weights decode a
    continuation through the int8 KV cache."""
    out = _run("train_gpt.py", "--steps", "6", "--d_model", "64",
               "--layers", "1", "--batch", "16", "--dp", "--accum", "2",
               "--hoisted", "--generate", "4", "--int8-kv")
    assert "hoisted: one exchange/step" in out
    assert "continuation (int8 KV cache)" in out


@pytest.mark.slow
def test_serve_classifier_example_runs_int8():
    """The PredictorServer-backed example end to end: int8 export with
    buckets, steady traffic, overload shedding, zero-drop drain
    (--threads is kept as the pre-PredictorServer alias of --workers)."""
    out = _run("serve_classifier.py", "--train_steps", "8", "--calls", "3",
               "--threads", "2", "--int8")
    assert "int8 datapath" in out and "buckets [16, 64]" in out
    assert "rejected with ServerOverloaded" in out
    assert "served accuracy" in out
    assert "drained: state=stopped" in out and "errors=0" in out


@pytest.mark.slow
def test_translate_example_decodes_reversal():
    out = _run("translate.py", "--steps", "120", "--seq", "5", "--beam", "2")
    assert "decode LoD:" in out
    assert "best-hypothesis token accuracy:" in out
    # trained attention model should reverse most tokens
    frac = out.rsplit("accuracy:", 1)[1].strip()
    hits, total = (int(v) for v in frac.split("/"))
    assert total > 0 and hits / total > 0.6, frac
