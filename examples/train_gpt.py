"""Train a small GPT language model end to end — the runnable
counterpart of MIGRATION.md's patterns (the reference's book chapters
played this role).

Single device:
    python examples/train_gpt.py --steps 50

Data parallel over every local device (TPU chips or a virtual CPU mesh
via XLA_FLAGS=--xla_force_host_platform_device_count=8):
    python examples/train_gpt.py --steps 50 --dp

Resume from a checkpoint directory:
    python examples/train_gpt.py --steps 50 --ckpt /tmp/gpt_ckpt

Gradient accumulation with the hoisted (once-per-step) exchange:
    python examples/train_gpt.py --steps 50 --dp --accum 2 --hoisted

Generate a continuation with the trained weights (optionally with the
int8 KV cache — half the bf16 cache bytes on the HBM-bound decode):
    python examples/train_gpt.py --steps 100 --generate 16 --int8-kv
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def synthetic_batches(vocab, batch, seq, seed=0):
    """A learnable synthetic LM stream: each sequence is an arithmetic
    progression mod vocab, so next-token prediction is solvable."""
    rng = np.random.RandomState(seed)
    while True:
        start = rng.randint(3, vocab, (batch, 1))
        step = rng.randint(1, 7, (batch, 1))
        ids = (start + step * np.arange(seq)[None, :]) % (vocab - 3) + 3
        ids = ids.astype(np.int32)
        labels = np.concatenate([ids[:, 1:], ids[:, :1]], axis=1)
        yield {"ids": ids, "labels": labels.astype(np.int32)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d_model", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--dp", action="store_true",
                   help="data-parallel over all local devices")
    p.add_argument("--accum", type=int, default=1,
                   help="gradient-accumulation microbatches per step")
    p.add_argument("--hoisted", action="store_true",
                   help="with --dp --accum N: shard_map-local "
                        "accumulation, ONE gradient exchange per step")
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, greedy-decode N tokens from a "
                        "training-stream prompt")
    p.add_argument("--int8-kv", action="store_true",
                   help="decode with the int8 KV cache")
    p.add_argument("--ckpt", default=None,
                   help="checkpoint dir: resumes if present, saves at end")
    args = p.parse_args()
    if args.hoisted and args.accum <= 1:
        p.error("--hoisted requires --accum N>1 (there is no "
                "accumulation loop to hoist the exchange out of)")
    if args.int8_kv and not args.generate:
        p.error("--int8-kv applies to decoding: pass --generate N")

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # the env var is authoritative even where a boot hook force-sets
        # the platform list after env parsing (e.g. remote-TPU images)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import paddle_tpu as pt
    from paddle_tpu import io, optimizer as opt
    from paddle_tpu.models import gpt

    cfg = gpt.base_config(vocab_size=args.vocab, max_len=args.seq,
                          d_model=args.d_model, d_inner=4 * args.d_model,
                          num_heads=4, num_layers=args.layers,
                          fused_ce=False, use_flash=False)
    prog = pt.build(gpt.make_model(cfg))

    mesh = rules = strategy = None
    if args.dp:
        mesh = pt.make_mesh({"dp": jax.device_count()})
        rules = pt.parallel.replicated()
        print(f"data-parallel over {jax.device_count()} devices")
    if args.accum > 1:
        from paddle_tpu.parallel import DistStrategy
        strategy = DistStrategy(
            accum_steps=args.accum,
            accum_exchange="hoisted" if args.hoisted else "gspmd")
        print(f"accumulating {args.accum} microbatches per step"
              + (" (hoisted: one exchange/step)" if args.hoisted else ""))

    trainer = pt.Trainer(prog, opt.AdamW(3e-3, weight_decay=0.01),
                         loss_name="loss", fetch_list=["loss"],
                         mesh=mesh, sharding_rules=rules,
                         strategy=strategy)
    batches = synthetic_batches(args.vocab, args.batch, args.seq)
    trainer.startup(sample_feed=next(batches))
    if args.ckpt and os.path.isdir(args.ckpt):
        io.load_trainer(args.ckpt, trainer)
        print(f"resumed from {args.ckpt} at step {trainer.global_step}")

    first = last = None
    for i in range(args.steps):
        out = trainer.step(next(batches))
        loss = float(out["loss"])
        first = loss if first is None else first
        last = loss
        if i % max(1, args.steps // 10) == 0:
            print(f"step {trainer.global_step:5d}  loss {loss:.4f}")

    if first is not None:
        print(f"loss {first:.4f} -> {last:.4f} over {args.steps} steps")
    if args.ckpt:
        io.save_trainer(args.ckpt, trainer)
        print(f"checkpoint saved to {args.ckpt}")

    if args.generate:
        import dataclasses

        import jax.numpy as jnp
        gen_cfg = dataclasses.replace(
            cfg, max_len=args.seq + args.generate,
            kv_cache_dtype="int8" if args.int8_kv else "compute")
        gen = pt.build(gpt.make_generator(gen_cfg,
                                          max_new_tokens=args.generate))
        prompt = next(batches)["ids"][:2, : args.seq // 2]
        outs, _ = gen.apply(dict(trainer.scope.params), {},
                            jnp.asarray(prompt))
        kv = "int8" if args.int8_kv else "compute-dtype"
        print(f"prompt[0] tail: {prompt[0, -8:].tolist()}")
        print(f"continuation ({kv} KV cache): "
              f"{np.asarray(outs['ids'])[0].tolist()}")
    return last


if __name__ == "__main__":
    main()
