"""DeepFM CTR model — the BASELINE "DeepFM CTR (sparse embedding +
pserver distributed transpiler)" config.

The reference served this workload with the distributed lookup table
(row-sharded embedding across pservers, distribute_transpiler.py:1100)
and sparse SelectedRows grads. TPU-native: one [fields*dim] embedding
table marked is_distributed → row-sharded over the mesh's 'ep'/'fsdp'
axis by sharding rules; gathers are XLA all-gather/dynamic-gather over
ICI (see paddle_tpu.sparse for the sparse-grad machinery).
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import layers as L
from ..framework import LayerHelper
from .. import initializer as init


def make_model(num_sparse_fields=26, sparse_feature_dim=1000, embedding_size=16,
               num_dense=13, hidden_dims=(400, 400, 400)):
    def deepfm(dense, sparse_ids, label):
        """dense [b, 13], sparse_ids [b, 26] (field-offset ids), label [b, 1]."""
        helper = LayerHelper("deepfm")
        # first-order weights + second-order factor table, row-sharded
        w1 = helper.create_parameter(
            "fm_w1/w", (num_sparse_fields * sparse_feature_dim, 1), jnp.float32,
            initializer=init.Normal(0, 0.01), is_distributed=True)
        v = helper.create_parameter(
            "fm_v/w", (num_sparse_fields * sparse_feature_dim, embedding_size),
            jnp.float32, initializer=init.Normal(0, 0.01), is_distributed=True)

        # offset ids into the flat table: field f occupies rows [f*dim, (f+1)*dim)
        offsets = (jnp.arange(num_sparse_fields) * sparse_feature_dim)[None, :]
        flat_ids = sparse_ids.astype(jnp.int32) + offsets

        first = jnp.take(w1, flat_ids, axis=0)[..., 0].sum(axis=1, keepdims=True)
        emb = jnp.take(v, flat_ids, axis=0)  # [b, fields, k]
        sum_sq = jnp.square(emb.sum(axis=1))
        sq_sum = jnp.square(emb).sum(axis=1)
        second = 0.5 * (sum_sq - sq_sum).sum(axis=1, keepdims=True)

        deep = jnp.concatenate([emb.reshape(emb.shape[0], -1), dense], axis=1)
        for h in hidden_dims:
            deep = L.fc(deep, h, act="relu")
        deep_out = L.fc(deep, 1)

        dense_lin = L.fc(dense, 1)
        logit = first + second + deep_out + dense_lin
        labelf = label.astype(jnp.float32)
        loss = L.mean(L.sigmoid_cross_entropy_with_logits(logit, labelf))
        prob = L.sigmoid(logit)
        return {"loss": loss, "prob": prob, "logit": logit}

    return deepfm
