// Python-free native predictor over the PJRT C API.
//
// Capability parity with the reference's C++ inference entry
// (inference/io.h:35 LoadInferenceModel; api_impl.cc:64
// NativePaddlePredictor::Init — load a saved model + params and run it
// from C++ with no Python in the process). Our export artifact
// (io.py save_inference_model) is:
//   model.mlir   — raw StableHLO bytecode of the inference function
//   params.npz / state.npz — weights (uncompressed zip of .npy members)
//   meta.json    — ordered flat input signature: which npz member (or
//                  runtime feed) supplies each executable argument
// This binary dlopens a PJRT plugin (libtpu.so on TPU hosts; any
// GetPjrtApi-exporting .so), compiles the StableHLO, stages weights and
// feeds as device buffers, executes, and prints per-output checksums.
//
//   predictor <artifact_dir> <plugin.so> [--probe]
//
// --probe stops after the Python-free half that needs no accelerator:
// plugin dlopen + PJRT version handshake + full artifact load/validation
// (meta.json vs npz shapes/dtypes/sizes). The full run requires a local
// device for the plugin (the CI box reaches its TPU through an IFRT
// proxy tunnel, which is not a PJRT C API endpoint — see
// DESIGN.md "native predictor").
//
// Build (test_native_predictor.py does this):
//   g++ -O2 -std=c++17 -I$TF_INCLUDE predictor.cc -o predictor -ldl

#include <dlfcn.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>

#include <cmath>
#include <map>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

[[noreturn]] void Die(const std::string& msg) {
  fprintf(stderr, "predictor: %s\n", msg.c_str());
  exit(1);
}

std::string ReadFileOrDie(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) Die("cannot open " + path);
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string out(size_t(n), '\0');
  if (fread(out.data(), 1, size_t(n), f) != size_t(n)) Die("short read " + path);
  fclose(f);
  return out;
}

// ---- npz (uncompressed zip of .npy) -------------------------------------

struct Array {
  std::string dtype;          // numpy descr without byte order, e.g. "f4"
  std::vector<int64_t> shape;
  const char* data = nullptr; // points into the owning zip blob
  size_t nbytes = 0;
};

uint32_t rd32(const char* p) { uint32_t v; memcpy(&v, p, 4); return v; }
uint16_t rd16(const char* p) { uint16_t v; memcpy(&v, p, 2); return v; }

// Parse one .npy payload (v1/v2 header) into an Array.
Array ParseNpy(const char* p, size_t n, const std::string& ctx) {
  if (n < 10 || memcmp(p, "\x93NUMPY", 6) != 0) Die("bad npy magic in " + ctx);
  int major = p[6];
  size_t hlen, hoff;
  if (major == 1) { hlen = rd16(p + 8); hoff = 10; }
  else if (n >= 12) { hlen = rd32(p + 8); hoff = 12; }
  else Die("truncated npy v2 header in " + ctx);
  if (hoff + hlen > n) Die("npy header overruns member in " + ctx);
  std::string hdr(p + hoff, hlen);
  Array a;
  // descr: '<f4' etc. — reject non-little-endian; '|' (byte-order-less)
  // covers bool/int8
  size_t dp = hdr.find("'descr':");
  if (dp == std::string::npos) Die("npy header missing descr in " + ctx);
  size_t q1 = hdr.find('\'', dp + 8), q2 = hdr.find('\'', q1 + 1);
  std::string descr = hdr.substr(q1 + 1, q2 - q1 - 1);
  if (descr[0] == '>') Die("big-endian npy unsupported: " + ctx);
  a.dtype = (descr[0] == '<' || descr[0] == '|' || descr[0] == '=')
                ? descr.substr(1) : descr;
  if (hdr.find("'fortran_order': False") == std::string::npos)
    Die("fortran-order npy unsupported: " + ctx);
  size_t sp = hdr.find("'shape':");
  size_t o1 = hdr.find('(', sp), o2 = hdr.find(')', o1);
  std::string dims = hdr.substr(o1 + 1, o2 - o1 - 1);
  size_t elems = 1;
  for (size_t i = 0; i < dims.size();) {
    while (i < dims.size() && (dims[i] == ' ' || dims[i] == ',')) ++i;
    if (i >= dims.size()) break;
    int64_t d = strtoll(dims.c_str() + i, nullptr, 10);
    if (d < 0) Die("negative npy dim in " + ctx);
    a.shape.push_back(d);
    if (d != 0 && elems > SIZE_MAX / size_t(d))
      Die("npy shape overflows size_t in " + ctx);
    elems *= size_t(d);
    while (i < dims.size() && dims[i] != ',') ++i;
  }
  size_t esize = strtoull(a.dtype.c_str() + 1, nullptr, 10);
  if (esize == 0) Die("npy dtype " + a.dtype + " has no size in " + ctx);
  if (elems > SIZE_MAX / esize) Die("npy size overflows size_t in " + ctx);
  a.data = p + hoff + hlen;
  a.nbytes = elems * esize;
  if (hoff + hlen + a.nbytes > n) Die("npy data overruns member in " + ctx);
  return a;
}

// np.savez writes STORED (method 0) members; walk local file headers.
std::map<std::string, Array> ParseNpz(const std::string& blob,
                                      const std::string& ctx) {
  std::map<std::string, Array> out;
  size_t off = 0;
  while (off + 30 <= blob.size() && rd32(blob.data() + off) == 0x04034b50) {
    const char* h = blob.data() + off;
    uint16_t method = rd16(h + 8);
    uint16_t flags = rd16(h + 6);
    uint64_t csize = rd32(h + 18);
    uint16_t nlen = rd16(h + 26), xlen = rd16(h + 28);
    if (off + 30 + size_t(nlen) + size_t(xlen) > blob.size())
      Die("npz member header overruns archive in " + ctx);
    std::string name(h + 30, nlen);
    const char* data = h + 30 + nlen + xlen;
    if (csize == 0xffffffffu) {
      // numpy writes zip64 members: real sizes live in extra field 0x0001
      // as two u64s (uncompressed, then compressed)
      const char* x = h + 30 + nlen;
      const char* xe = x + xlen;
      csize = SIZE_MAX;
      while (x + 4 <= xe) {
        uint16_t id = rd16(x), sz = rd16(x + 2);
        if (x + 4 + sz > xe) break;  // field claims more than the extra area holds
        if (id == 0x0001 && sz >= 16) {
          memcpy(&csize, x + 4 + 8, 8);  // second u64 = compressed size
          break;
        }
        x += 4 + sz;
      }
      if (csize == SIZE_MAX) Die("zip64 member without size extra in " + ctx);
    }
    if (flags & 0x8) Die("zip data-descriptor members unsupported: " + ctx);
    if (method != 0) Die("compressed npz member " + name + " in " + ctx +
                         " (np.savez_compressed unsupported)");
    if (csize > blob.size() - (size_t(data - blob.data())))
      Die("npz member " + name + " payload overruns archive in " + ctx);
    if (name.size() > 4 && name.substr(name.size() - 4) == ".npy")
      out[name.substr(0, name.size() - 4)] =
          ParseNpy(data, csize, ctx + ":" + name);
    off = size_t(data - blob.data()) + csize;
  }
  if (out.empty()) Die("no npy members found in " + ctx);
  return out;
}

// ---- meta.json (our own generator's fixed structure) --------------------

struct InputSpec {
  std::string source;  // "params.npz" | "state.npz" | "feed"
  std::string name;
  std::string dtype;   // numpy name, e.g. "float32"
  std::vector<int64_t> shape;
};

std::string JStr(const std::string& s, size_t& i) {
  if (s[i] != '"') Die("meta.json parse error (expected string)");
  size_t j = s.find('"', i + 1);
  std::string out = s.substr(i + 1, j - i - 1);
  i = j + 1;
  return out;
}

// Minimal parser for the exact meta.json shape io.py writes. Tolerates
// whitespace; dies loudly on anything structurally unexpected.
std::vector<InputSpec> ParseMetaInputs(const std::string& js) {
  std::vector<InputSpec> specs;
  size_t p = js.find("\"inputs\"");
  if (p == std::string::npos)
    Die("meta.json has no \"inputs\" — re-export with the current "
        "save_inference_model (older artifacts lack the native signature)");
  p = js.find('[', p);
  size_t end = p;
  int depth = 0;
  for (size_t i = p; i < js.size(); ++i) {
    if (js[i] == '[') ++depth;
    if (js[i] == ']' && --depth == 0) { end = i; break; }
  }
  size_t i = p + 1;
  while (true) {
    size_t ob = js.find('{', i);
    if (ob == std::string::npos || ob > end) break;
    size_t cb = js.find('}', ob);
    std::string obj = js.substr(ob, cb - ob + 1);
    InputSpec sp;
    for (const char* key : {"source", "name", "dtype"}) {
      size_t kp = obj.find(std::string("\"") + key + "\"");
      if (kp == std::string::npos) Die(std::string("meta input missing ") + key);
      size_t vp = obj.find(':', kp) + 1;
      while (obj[vp] == ' ') ++vp;
      std::string val = JStr(obj, vp);
      if (!strcmp(key, "source")) sp.source = val;
      else if (!strcmp(key, "name")) sp.name = val;
      else sp.dtype = val;
    }
    size_t shp = obj.find("\"shape\"");
    size_t sb = obj.find('[', shp), se = obj.find(']', sb);
    std::string dims = obj.substr(sb + 1, se - sb - 1);
    for (size_t k = 0; k < dims.size();) {
      while (k < dims.size() && (dims[k] == ' ' || dims[k] == ',')) ++k;
      if (k >= dims.size()) break;
      sp.shape.push_back(strtoll(dims.c_str() + k, nullptr, 10));
      while (k < dims.size() && dims[k] != ',') ++k;
    }
    specs.push_back(std::move(sp));
    i = cb + 1;
  }
  if (specs.empty()) Die("meta.json inputs empty");
  return specs;
}

// ---- dtype mapping ------------------------------------------------------

struct DType {
  PJRT_Buffer_Type pjrt;
  size_t size;
  const char* npy;  // descr suffix ("f4")
};

DType DtypeOrDie(const std::string& numpy_name) {
  if (numpy_name == "float32") return {PJRT_Buffer_Type_F32, 4, "f4"};
  if (numpy_name == "float64") return {PJRT_Buffer_Type_F64, 8, "f8"};
  // io._flatten stores bfloat16 npz members as uint16 views ("u2",
  // '@bfloat16' name suffix); the device buffer is still BF16
  if (numpy_name == "bfloat16") return {PJRT_Buffer_Type_BF16, 2, "u2"};
  if (numpy_name == "float16") return {PJRT_Buffer_Type_F16, 2, "f2"};
  if (numpy_name == "int64") return {PJRT_Buffer_Type_S64, 8, "i8"};
  if (numpy_name == "int32") return {PJRT_Buffer_Type_S32, 4, "i4"};
  if (numpy_name == "int16") return {PJRT_Buffer_Type_S16, 2, "i2"};
  if (numpy_name == "int8") return {PJRT_Buffer_Type_S8, 1, "i1"};
  if (numpy_name == "uint8") return {PJRT_Buffer_Type_U8, 1, "u1"};
  if (numpy_name == "uint32") return {PJRT_Buffer_Type_U32, 4, "u4"};
  if (numpy_name == "bool") return {PJRT_Buffer_Type_PRED, 1, "b1"};
  Die("unsupported dtype " + numpy_name);
}

// ---- PJRT plumbing ------------------------------------------------------

const PJRT_Api* g_api = nullptr;

void Check(PJRT_Error* err, const char* what) {
  if (!err) return;
  PJRT_Error_Message_Args m;
  memset(&m, 0, sizeof m);
  m.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  m.error = err;
  g_api->PJRT_Error_Message(&m);
  std::string msg(m.message, m.message_size);
  PJRT_Error_Destroy_Args d;
  memset(&d, 0, sizeof d);
  d.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  d.error = err;
  g_api->PJRT_Error_Destroy(&d);
  Die(std::string(what) + ": " + msg);
}

void AwaitAndDestroy(PJRT_Event* ev, const char* what) {
  PJRT_Event_Await_Args aw;
  memset(&aw, 0, sizeof aw);
  aw.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  aw.event = ev;
  Check(g_api->PJRT_Event_Await(&aw), what);
  PJRT_Event_Destroy_Args ed;
  memset(&ed, 0, sizeof ed);
  ed.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  ed.event = ev;
  Check(g_api->PJRT_Event_Destroy(&ed), "event destroy");
}

// Minimal serialized xla.CompileOptionsProto:
//   field 3 (executable_build_options) {
//     field 4 (num_replicas) = 1; field 5 (num_partitions) = 1; }
// Hand-encoded: protoc isn't needed for two varints.
std::string MinimalCompileOptions() {
  const char inner[] = {0x20, 0x01, 0x28, 0x01};        // 4:1, 5:1
  std::string opts;
  opts.push_back(0x1a);                                  // field 3, wire 2
  opts.push_back(char(sizeof inner));
  opts.append(inner, sizeof inner);
  return opts;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: predictor <artifact_dir> <pjrt_plugin.so> [--probe]\n");
    return 2;
  }
  std::string dir = argv[1], plugin = argv[2];
  bool probe = argc > 3 && std::string(argv[3]) == "--probe";

  // ---- artifact load + validation (no accelerator needed) ---------------
  std::string mlir = ReadFileOrDie(dir + "/model.mlir");
  std::string meta = ReadFileOrDie(dir + "/meta.json");
  std::string params_blob = ReadFileOrDie(dir + "/params.npz");
  std::string state_blob = ReadFileOrDie(dir + "/state.npz");
  auto params = ParseNpz(params_blob, "params.npz");
  std::map<std::string, Array> state;
  if (state_blob.size() > 4 && rd32(state_blob.data()) == 0x04034b50)
    state = ParseNpz(state_blob, "state.npz");
  auto inputs = ParseMetaInputs(meta);

  size_t feed_args = 0, weight_bytes = 0;
  for (const auto& sp : inputs) {
    DType dt = DtypeOrDie(sp.dtype);
    size_t want = dt.size;
    for (int64_t d : sp.shape) want *= size_t(d);
    if (sp.source == "feed") { ++feed_args; continue; }
    auto& table = sp.source == "params.npz" ? params : state;
    auto it = table.find(sp.name);
    if (it == table.end()) Die("meta input " + sp.name + " missing from " +
                               sp.source);
    const Array& got = it->second;
    if (got.nbytes != want)
      Die("weight " + sp.name + " is " + std::to_string(got.nbytes) +
          " bytes, signature expects " + std::to_string(want));
    if (got.dtype != dt.npy)
      Die("weight " + sp.name + " stored as npy '" + got.dtype +
          "', signature expects '" + dt.npy + "' (" + sp.dtype + ")");
    if (got.shape != sp.shape) {
      std::string g, w;
      for (int64_t v : got.shape) g += std::to_string(v) + ",";
      for (int64_t v : sp.shape) w += std::to_string(v) + ",";
      Die("weight " + sp.name + " has shape [" + g +
          "], signature expects [" + w + "]");
    }
    weight_bytes += want;
  }
  fprintf(stderr,
          "predictor: artifact ok — %zu args (%zu weights %.1f MB, %zu feeds), "
          "stablehlo %zu bytes\n",
          inputs.size(), inputs.size() - feed_args,
          weight_bytes / 1048576.0, feed_args, mlir.size());

  // ---- plugin handshake -------------------------------------------------
  void* lib = dlopen(plugin.c_str(), RTLD_NOW | RTLD_LOCAL);
  if (!lib) Die(std::string("dlopen failed: ") + dlerror());
  auto get_api = reinterpret_cast<const PJRT_Api* (*)()>(
      dlsym(lib, "GetPjrtApi"));
  if (!get_api) Die("plugin has no GetPjrtApi symbol");
  g_api = get_api();
  if (!g_api) Die("GetPjrtApi returned null");
  fprintf(stderr, "predictor: plugin PJRT API v%d.%d (header v%d.%d)\n",
          g_api->pjrt_api_version.major_version,
          g_api->pjrt_api_version.minor_version, PJRT_API_MAJOR,
          PJRT_API_MINOR);
  if (g_api->pjrt_api_version.major_version != PJRT_API_MAJOR)
    Die("PJRT major version mismatch");

  if (probe) {
    printf("PROBE OK\n");
    return 0;
  }

  PJRT_Plugin_Initialize_Args pi;
  memset(&pi, 0, sizeof pi);
  pi.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  Check(g_api->PJRT_Plugin_Initialize(&pi), "plugin init");

  PJRT_Client_Create_Args cc;
  memset(&cc, 0, sizeof cc);
  cc.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  Check(g_api->PJRT_Client_Create(&cc), "client create");
  PJRT_Client* client = cc.client;

  PJRT_Client_AddressableDevices_Args ad;
  memset(&ad, 0, sizeof ad);
  ad.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  ad.client = client;
  Check(g_api->PJRT_Client_AddressableDevices(&ad), "devices");
  if (ad.num_addressable_devices == 0) Die("no addressable devices");
  PJRT_Device* dev = ad.addressable_devices[0];
  fprintf(stderr, "predictor: %zu addressable device(s)\n",
          ad.num_addressable_devices);

  // ---- compile ----------------------------------------------------------
  PJRT_Program prog;
  memset(&prog, 0, sizeof prog);
  prog.struct_size = PJRT_Program_STRUCT_SIZE;
  prog.code = mlir.data();
  prog.code_size = mlir.size();
  static const char kFmt[] = "mlir";
  prog.format = kFmt;
  prog.format_size = 4;
  std::string copts = MinimalCompileOptions();
  PJRT_Client_Compile_Args comp;
  memset(&comp, 0, sizeof comp);
  comp.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  comp.client = client;
  comp.program = &prog;
  comp.compile_options = copts.data();
  comp.compile_options_size = copts.size();
  Check(g_api->PJRT_Client_Compile(&comp), "compile");
  fprintf(stderr, "predictor: stablehlo compiled\n");

  // ---- stage inputs (weights from npz; feeds zero-filled or from
  //      <dir>/feed_<name>.npy if present) --------------------------------
  std::vector<PJRT_Buffer*> arg_bufs;
  std::vector<std::string> feed_storage;
  for (const auto& sp : inputs) {
    DType dt = DtypeOrDie(sp.dtype);
    size_t nbytes = dt.size;
    for (int64_t d : sp.shape) nbytes *= size_t(d);
    const char* data;
    if (sp.source == "feed") {
      std::string path = dir + "/feed_" + sp.name + ".npy";
      FILE* f = fopen(path.c_str(), "rb");
      if (f) {
        fclose(f);
        std::string blob = ReadFileOrDie(path);
        feed_storage.push_back(std::move(blob));
        Array a = ParseNpy(feed_storage.back().data(),
                           feed_storage.back().size(), path);
        if (a.nbytes != nbytes) Die("feed " + sp.name + " wrong size");
        if (a.dtype != dt.npy)
          Die("feed " + sp.name + " is npy '" + a.dtype + "', signature "
              "expects '" + dt.npy + "' (" + sp.dtype + ")");
        if (a.shape != sp.shape) Die("feed " + sp.name + " wrong shape");
        data = a.data;
      } else {
        feed_storage.emplace_back(nbytes, '\0');
        data = feed_storage.back().data();
      }
    } else {
      auto& table = sp.source == "params.npz" ? params : state;
      data = table.at(sp.name).data;
    }
    PJRT_Client_BufferFromHostBuffer_Args hb;
    memset(&hb, 0, sizeof hb);
    hb.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    hb.client = client;
    hb.data = data;
    hb.type = dt.pjrt;
    hb.dims = sp.shape.data();
    hb.num_dims = sp.shape.size();
    hb.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
    hb.device = dev;
    Check(g_api->PJRT_Client_BufferFromHostBuffer(&hb),
          ("h2d " + sp.name).c_str());
    AwaitAndDestroy(hb.done_with_host_buffer, "h2d done");
    arg_bufs.push_back(hb.buffer);
  }

  // ---- execute ----------------------------------------------------------
  PJRT_LoadedExecutable_GetExecutable_Args ge;
  memset(&ge, 0, sizeof ge);
  ge.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
  ge.loaded_executable = comp.executable;
  Check(g_api->PJRT_LoadedExecutable_GetExecutable(&ge), "get executable");
  PJRT_Executable_NumOutputs_Args no;
  memset(&no, 0, sizeof no);
  no.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
  no.executable = ge.executable;
  Check(g_api->PJRT_Executable_NumOutputs(&no), "num outputs");

  std::vector<PJRT_Buffer*> outs(no.num_outputs, nullptr);
  PJRT_Buffer** out_list = outs.data();
  PJRT_Buffer* const* arg_list = arg_bufs.data();
  PJRT_ExecuteOptions eo;
  memset(&eo, 0, sizeof eo);
  eo.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;
  PJRT_Event* done = nullptr;
  PJRT_LoadedExecutable_Execute_Args ex;
  memset(&ex, 0, sizeof ex);
  ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
  ex.executable = comp.executable;
  ex.options = &eo;
  ex.argument_lists = &arg_list;
  ex.num_devices = 1;
  ex.num_args = arg_bufs.size();
  ex.output_lists = &out_list;
  ex.device_complete_events = &done;
  ex.execute_device = dev;
  Check(g_api->PJRT_LoadedExecutable_Execute(&ex), "execute");
  AwaitAndDestroy(done, "execute done");

  // ---- fetch outputs, print checksums ------------------------------------
  for (size_t i = 0; i < outs.size(); ++i) {
    PJRT_Buffer_ToHostBuffer_Args th;
    memset(&th, 0, sizeof th);
    th.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    th.src = outs[i];
    Check(g_api->PJRT_Buffer_ToHostBuffer(&th), "d2h size query");
    std::vector<char> host(th.dst_size);
    th.dst = host.data();
    Check(g_api->PJRT_Buffer_ToHostBuffer(&th), "d2h");
    AwaitAndDestroy(th.event, "d2h done");
    PJRT_Buffer_ElementType_Args et;
    memset(&et, 0, sizeof et);
    et.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
    et.buffer = outs[i];
    Check(g_api->PJRT_Buffer_ElementType(&et), "element type");
    double sum = 0;
    if (et.type == PJRT_Buffer_Type_F32) {
      const float* v = reinterpret_cast<const float*>(host.data());
      for (size_t k = 0; k < host.size() / 4; ++k) sum += v[k];
    }
    printf("OUTPUT %zu bytes=%zu f32sum=%.6f\n", i, host.size(), sum);
  }
  printf("RUN OK\n");
  return 0;
}
