// RecordIO — chunked record file format, C++ core.
//
// TPU-native rebuild of the reference's recordio subsystem
// (paddle/fluid/recordio/{header,chunk,scanner,writer}.{h,cc}): chunked
// stream of length-prefixed records with CRC32 integrity and optional
// zlib compression, plus an index-free sequential scanner. Exposed to
// Python through a C ABI (ctypes — no pybind11 in this image); the
// Python side lives in paddle_tpu/recordio.py.
//
// On-disk layout per chunk:
//   u32 magic (0x50445452 "PDTR") | u32 flags (bit0: zlib)
//   u32 num_records | u32 raw_len | u32 stored_len | u32 crc32(stored)
//   payload[stored_len]   (payload = concat of (u32 len | bytes) records,
//                          zlib-deflated when flags&1)
//
// The writer batches records into ~1MB chunks (same default spirit as
// the reference's chunk.h); the scanner streams chunks and yields
// records without loading the whole file.

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50445452;  // "PDTR"
constexpr size_t kDefaultChunkBytes = 1 << 20;

struct Writer {
  FILE* f = nullptr;
  bool compress = false;
  size_t chunk_limit = kDefaultChunkBytes;
  std::vector<uint8_t> buf;  // packed (len|bytes) records
  uint32_t num_records = 0;
  bool error = false;
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<uint8_t> payload;  // current chunk, decompressed
  size_t pos = 0;                // cursor into payload
  uint32_t remaining = 0;        // records left in current chunk
  bool error = false;
};

bool write_u32(FILE* f, uint32_t v) { return fwrite(&v, 4, 1, f) == 1; }
bool read_u32(FILE* f, uint32_t* v) { return fread(v, 4, 1, f) == 1; }

bool flush_chunk(Writer* w) {
  if (w->num_records == 0) return true;
  const std::vector<uint8_t>& raw = w->buf;
  std::vector<uint8_t> deflated;
  const std::vector<uint8_t>* stored = &raw;
  uint32_t flags = 0;
  if (w->compress) {
    uLongf bound = compressBound(raw.size());
    deflated.resize(bound);
    if (compress2(deflated.data(), &bound, raw.data(), raw.size(),
                  Z_DEFAULT_COMPRESSION) != Z_OK) {
      return false;
    }
    deflated.resize(bound);
    stored = &deflated;
    flags |= 1;
  }
  uint32_t crc = crc32(0L, stored->data(), stored->size());
  bool ok = write_u32(w->f, kMagic) && write_u32(w->f, flags) &&
            write_u32(w->f, w->num_records) &&
            write_u32(w->f, static_cast<uint32_t>(raw.size())) &&
            write_u32(w->f, static_cast<uint32_t>(stored->size())) &&
            write_u32(w->f, crc) &&
            fwrite(stored->data(), 1, stored->size(), w->f) == stored->size();
  w->buf.clear();
  w->num_records = 0;
  return ok;
}

bool load_chunk(Scanner* s) {
  uint32_t magic, flags, num, raw_len, stored_len, crc;
  if (!read_u32(s->f, &magic)) return false;  // clean EOF
  if (magic != kMagic || !read_u32(s->f, &flags) || !read_u32(s->f, &num) ||
      !read_u32(s->f, &raw_len) || !read_u32(s->f, &stored_len) ||
      !read_u32(s->f, &crc)) {
    s->error = true;
    return false;
  }
  std::vector<uint8_t> stored(stored_len);
  if (fread(stored.data(), 1, stored_len, s->f) != stored_len) {
    s->error = true;
    return false;
  }
  if (crc32(0L, stored.data(), stored.size()) != crc) {
    s->error = true;
    return false;
  }
  if (flags & 1) {
    s->payload.resize(raw_len);
    uLongf out_len = raw_len;
    if (uncompress(s->payload.data(), &out_len, stored.data(), stored.size()) !=
            Z_OK ||
        out_len != raw_len) {
      s->error = true;
      return false;
    }
  } else {
    s->payload = std::move(stored);
  }
  s->pos = 0;
  s->remaining = num;
  return true;
}

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, int compress, int chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  w->compress = compress != 0;
  if (chunk_bytes > 0) w->chunk_limit = static_cast<size_t>(chunk_bytes);
  return w;
}

int rio_writer_write(void* handle, const uint8_t* data, uint32_t len) {
  Writer* w = static_cast<Writer*>(handle);
  uint8_t hdr[4];
  memcpy(hdr, &len, 4);
  w->buf.insert(w->buf.end(), hdr, hdr + 4);
  w->buf.insert(w->buf.end(), data, data + len);
  w->num_records++;
  if (w->buf.size() >= w->chunk_limit) {
    if (!flush_chunk(w)) {
      w->error = true;
      return -1;
    }
  }
  return 0;
}

int rio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int rc = 0;
  if (!flush_chunk(w)) rc = -1;
  if (w->error) rc = -1;
  fclose(w->f);
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Scanner* s = new Scanner();
  s->f = f;
  return s;
}

// Returns record length and sets *out to an internal pointer valid until
// the next call; returns -1 on EOF, -2 on corruption.
int64_t rio_scanner_next(void* handle, const uint8_t** out) {
  Scanner* s = static_cast<Scanner*>(handle);
  if (s->remaining == 0) {
    if (!load_chunk(s)) return s->error ? -2 : -1;
  }
  if (s->pos + 4 > s->payload.size()) {
    s->error = true;
    return -2;
  }
  uint32_t len;
  memcpy(&len, s->payload.data() + s->pos, 4);
  s->pos += 4;
  if (s->pos + len > s->payload.size()) {
    s->error = true;
    return -2;
  }
  *out = s->payload.data() + s->pos;
  s->pos += len;
  s->remaining--;
  return static_cast<int64_t>(len);
}

void rio_scanner_close(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
