"""Machine translation end to end — the book machine_translation
chapter as a runnable example: train the attention seq2seq model, then
beam-decode with the trained weights and print the ragged 2-level LoD
output (sentence → hypotheses → tokens) exactly as the reference's
demo consumes it.

    python examples/translate.py --steps 150 --beam 3
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def batches(rng, vocab, bs, s):
    """Toy 'translation': target = source reversed (forces real use of
    attention, unlike plain copy)."""
    src = rng.randint(3, vocab, (bs, s)).astype(np.int64)
    out = src[:, ::-1]
    # standard teacher forcing: input [BOS, out], predict [out, EOS]
    trg = np.concatenate([np.ones((bs, 1), np.int64), out], axis=1)
    labels = np.concatenate([out, np.full((bs, 1), 2, np.int64)], axis=1)
    return {"src_ids": src, "trg_ids": trg, "labels": labels,
            "src_lengths": np.full((bs,), s, np.int64)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--vocab", type=int, default=20)
    p.add_argument("--seq", type=int, default=6)
    p.add_argument("--hidden", type=int, default=48)
    p.add_argument("--beam", type=int, default=3)
    args = p.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.layers.beam_search import beam_search_decode_lod
    from paddle_tpu.models import seq2seq

    rng = np.random.RandomState(0)
    dims = dict(src_vocab=args.vocab, trg_vocab=args.vocab,
                emb_dim=24, hidden=args.hidden)

    # 1. train
    model = pt.build(seq2seq.make_model(**dims))
    tr = pt.Trainer(model, opt.Adam(5e-3), loss_name="loss")
    tr.startup(sample_feed=batches(rng, args.vocab, 32, args.seq))
    for s in range(args.steps):
        out = tr.step(batches(rng, args.vocab, 32, args.seq))
        if (s + 1) % 50 == 0:
            print(f"step {s + 1}: loss {float(out['loss']):.3f}")

    # 2. beam-decode with the trained weights (shared param names)
    dec = pt.build(seq2seq.make_decoder(**dims, max_len=args.seq + 2,
                                        beam_size=args.beam))
    feed = batches(rng, args.vocab, 4, args.seq)
    out, _ = dec.apply(tr.scope.params, tr.scope.state,
                       jnp.asarray(feed["src_ids"]),
                       jnp.asarray(feed["src_lengths"]))
    seqs, scores = np.asarray(out["ids"]), np.asarray(out["scores"])

    # 3. package as the reference's 2-level LoD and consume it
    valid = (np.cumsum(seqs == 2, axis=-1) - (seqs == 2)) == 0
    ids, sc = beam_search_decode_lod(seqs, valid, scores=scores)
    print(f"decode LoD: {ids.recursive_sequence_lengths()}")
    hits = total = 0
    for b, grp in enumerate(ids.sequences(0)):
        src = feed["src_ids"][b]
        want = src[::-1]
        print(f"src {src.tolist()}")
        for k, hyp in enumerate(grp):
            toks = hyp.ravel()
            body = toks[:-1] if len(toks) and toks[-1] == 2 else toks
            print(f"  hyp{k} (score {float(np.asarray(sc.sequences(0)[b][k])):.2f}): "
                  f"{body.tolist()}")
        best = grp[0].ravel()
        n = min(len(best), args.seq)
        hits += (best[:n] == want[:n]).sum()
        total += n
    print(f"best-hypothesis token accuracy: {hits}/{total}")


if __name__ == "__main__":
    main()
