"""GPT — decoder-only causal language model.

No reference counterpart (the 2018 reference predates decoder-only LMs;
its closest config is the transformer benchmark,
benchmark/fluid/models/machine_translation.py) — this is the modern
long-context flagship the TPU build adds on top of the capability set,
and the model family that exercises sequence/context parallelism as a
TRAINING PATH:

- blocks are the stacked causal self-attention blocks (layers/stacked.py),
  so pipeline parallelism (DistStrategy.pp_microbatches) works unchanged;
- with DistStrategy.sequence_parallel on an ``sp`` mesh, the input ids /
  labels / positions are permuted ONCE into the zigzag order and the
  whole stack runs in that layout — attention is zigzag ring attention
  (parallel/ring_attention.py) with shard-local entry/exit, positions
  travel with their tokens, and the mean loss is permutation-invariant,
  so nothing is ever permuted back.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import initializer as init
from .. import layers as L
from ..core.errors import enforce
from ..framework import LayerHelper, cast_compute, name_scope, sp_config
from ..layers import attention as A
from ..layers import stacked as S
from .lm_head import lm_head_loss


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 32000
    max_len: int = 1024
    d_model: int = 768
    d_inner: int = 3072
    num_heads: int = 12
    num_layers: int = 12
    use_flash: bool = True
    fused_ce: bool = True
    ce_chunk: int = 4096
    remat: bool = False
    # residual/softmax/ffn dropout inside the stacked blocks (per-layer
    # rng via framework.rng_fold; rate > 0 disables the flash kernel the
    # same way the unrolled attention layer does)
    dropout: float = 0.0
    dtype: str = "float32"
    # KV cache storage for the incremental generator: "compute" keeps
    # the compute dtype; "int8" stores symmetric per-vector int8 with
    # f32 scales (layers/stacked.quantize_kv) — half the bf16 cache
    # bytes on the HBM-bound decode read, scales factored out of both
    # attention matmuls so nothing is dequantized into memory
    kv_cache_dtype: str = "compute"


def base_config(**kw) -> GPTConfig:
    return GPTConfig(**kw)


def make_model(cfg: GPTConfig):
    """Program fn: (ids [b, s], labels [b, s]) -> {"loss", "token_count"}.
    Next-token CE over non-pad labels (pad id 0)."""

    def gpt(ids, labels):
        dtype = jnp.dtype(cfg.dtype)
        s = ids.shape[1]
        enforce(s <= cfg.max_len, f"seq {s} exceeds max_len {cfg.max_len}")
        sp = sp_config()
        if sp is not None and sp.get("impl", "ring") == "ring":
            from ..parallel.ring_attention import zigzag_order
            n = sp["mesh"].shape[sp["axis"]]
            enforce(s % (2 * n) == 0,
                    f"sequence parallelism needs seq {s} divisible by 2·sp={2 * n}")
            order = zigzag_order(s, n)
            ids = jnp.take(ids, order, axis=1)
            labels = jnp.take(labels, order, axis=1)
            positions = order
            # this model keeps activations in zigzag order end-to-end, so
            # the ring may skip its per-call entry/exit gathers; models
            # that do NOT permute get the safe "natural" default
            sp["layout"] = "zigzag"
        else:
            if sp is not None:  # ulysses: natural order, no permutation
                n = sp["mesh"].shape[sp["axis"]]
                enforce(s % n == 0,
                        f"ulysses sequence parallelism needs seq {s} "
                        f"divisible by sp={n}")
            positions = jnp.arange(s)

        with name_scope("tok"):
            x = L.embedding(ids, size=[cfg.vocab_size, cfg.d_model],
                            dtype=cfg.dtype)
        pe = A.positional_encoding(cfg.max_len, cfg.d_model, dtype)
        x = x + pe[positions][None]

        with name_scope("gpt"):
            stack = S.encoder_stack_params(cfg.num_layers, cfg.d_model,
                                           cfg.d_inner)
            x = S.apply_stacked(x, stack, S.make_encoder_block,
                                num_heads=cfg.num_heads,
                                use_flash=cfg.use_flash, causal=True,
                                remat=cfg.remat,
                                dropout_rate=cfg.dropout)
            x = L.layer_norm(x, begin_norm_axis=2)

        loss, token_count = lm_head_loss(x, labels, cfg.vocab_size, dtype,
                                         cfg.fused_ce, cfg.ce_chunk)
        return {"loss": loss, "token_count": token_count}

    return gpt


def make_generator(cfg: GPTConfig, max_new_tokens: int, beam_size: int = 1,
                   bos_id: int = 1, eos_id: int = 2,
                   length_penalty_alpha: float = 0.0):
    """Incremental generation program with a KV cache over the stacked
    params (beam_search_op capability for the decoder-only family; the
    transformer zoo's make_decoder sibling). Parameter names match
    make_model's train program, so trained params load directly.

    Returns a program fn: (prompt_ids [b, p]) -> {"ids": [b, max_new]}
    (greedy) or {"ids": [b, beam, max_new], "scores": [b, beam]} (beam).
    """
    from ..layers.beam_search import beam_search, greedy_search

    def generate(prompt_ids):
        dtype = jnp.dtype(cfg.dtype)
        b, p = prompt_ids.shape
        total = p + max_new_tokens
        enforce(total <= cfg.max_len,
                f"prompt {p} + max_new {max_new_tokens} exceeds max_len "
                f"{cfg.max_len}")
        pe = A.positional_encoding(cfg.max_len, cfg.d_model, dtype)

        # ---- create/fetch every parameter ONCE, with the exact names the
        # train program uses; the decode loop then closes over the arrays
        # (no LayerHelper calls inside scan — nothing to re-resolve)
        with name_scope("tok"):
            w_emb = LayerHelper("embedding").create_parameter(
                "w", (cfg.vocab_size, cfg.d_model), dtype,
                initializer=init.Xavier())
        with name_scope("gpt"):
            stack = S.encoder_stack_params(cfg.num_layers, cfg.d_model,
                                           cfg.d_inner)
            ln = LayerHelper("layer_norm")
            ln_scale = ln.create_parameter("scale", (cfg.d_model,), jnp.float32,
                                           initializer=init.Constant(1.0))
            ln_bias = ln.create_parameter("bias", (cfg.d_model,), jnp.float32,
                                          initializer=init.Constant(0.0))
        w_head = LayerHelper("lm_head").create_parameter(
            "w", (cfg.d_model, cfg.vocab_size), dtype,
            initializer=init.Xavier())

        def head(x_last):  # [rows, d] -> log-probs [rows, vocab]
            h = S._ln(x_last[:, None, :], ln_scale, ln_bias)[:, 0]
            return jax.nn.log_softmax(
                jnp.matmul(h, w_head).astype(jnp.float32), axis=-1)

        # ---- prefill: run the prompt causally, capture per-layer k/v
        # (cast_compute keeps the scan carry dtype consistent with the
        # blocks' compute dtype regardless of cfg.dtype)
        x = cast_compute(w_emb[prompt_ids] + pe[:p][None])

        def pre(a, lp):
            return S.prefill_block(a, lp, cfg.num_heads, cfg.use_flash)

        x, (ks, vs) = jax.lax.scan(pre, x, stack)
        logp0 = head(x[:, -1])  # first generated token comes from here

        K = beam_size
        rows = b * K
        L = cfg.num_layers

        def grow(a):  # [b, h, p, hd] -> [rows, h, total, hd]
            a = jnp.repeat(a, K, axis=0) if K > 1 else a
            pad = jnp.zeros(a.shape[:2] + (total - p, a.shape[3]), a.dtype)
            return jnp.concatenate([a, pad], axis=2)

        # caches are PER-LAYER lists of [rows, ...] arrays — beam_search
        # reorders state leaves whose leading dim is batch*beam, so the
        # layer axis must NOT lead (the transformer decoder's contract,
        # layers/beam_search.py _gather_beams)
        enforce(cfg.kv_cache_dtype in ("compute", "int8"),
                f"kv_cache_dtype={cfg.kv_cache_dtype!r} (compute|int8)")
        int8_kv = cfg.kv_cache_dtype == "int8"
        if int8_kv:
            # quantize the prefix BEFORE growing: padded tail positions
            # get int8 zeros with zero scales (dequantize to exact 0)
            kq, ksc = zip(*(S.quantize_kv(ks[i]) for i in range(L)))
            vq, vsc = zip(*(S.quantize_kv(vs[i]) for i in range(L)))
            state0 = {"kq": [grow(a) for a in kq],
                      "ks": [grow(a) for a in ksc],
                      "vq": [grow(a) for a in vq],
                      "vs": [grow(a) for a in vsc]}
        else:
            state0 = {"k": [grow(ks[i]) for i in range(L)],
                      "v": [grow(vs[i]) for i in range(L)]}
        state0.update(
            index=jnp.asarray(p, jnp.int32),
            logp0=jnp.repeat(logp0, K, axis=0) if K > 1 else logp0,
            first=jnp.asarray(True))
        layer_params = [jax.tree.map(lambda a, i=i: a[i], stack)
                        for i in range(L)]
        cache_keys = ("kq", "ks", "vq", "vs") if int8_kv else ("k", "v")

        def step_fn(tokens, state):
            # the prefill already produced the first step's distribution;
            # afterwards embed the chosen token and run the cached stack
            def incremental(_):
                xt = cast_compute(w_emb[tokens][:, None, :]
                                  + pe[state["index"]][None, None])
                new = tuple([] for _ in cache_keys)
                for i, lp in enumerate(layer_params):
                    caches = tuple(state[k][i] for k in cache_keys)
                    if int8_kv:
                        xt, *caches = S.decode_block_q8(
                            xt, lp, *caches, state["index"], cfg.num_heads)
                    else:
                        xt, *caches = S.decode_block(
                            xt, lp, *caches, state["index"], cfg.num_heads)
                    for dst, c in zip(new, caches):
                        dst.append(c)
                return (head(xt[:, 0]),) + new

            logp, *new = jax.lax.cond(
                state["first"],
                lambda _: ((state["logp0"],)
                           + tuple(state[k] for k in cache_keys)),
                incremental, operand=None)
            # the first step consumes the prefill's distribution without
            # writing a token; the index advances only once a generated
            # token has actually been cached (position p holds token 1)
            new_state = dict(zip(cache_keys, new))
            new_state.update(
                index=jnp.where(state["first"], state["index"],
                                state["index"] + 1),
                logp0=state["logp0"], first=jnp.asarray(False))
            return logp, new_state

        if K > 1:
            seqs, scores = beam_search(step_fn, state0, b, K, max_new_tokens,
                                       bos_id=bos_id, eos_id=eos_id,
                                       length_penalty_alpha=length_penalty_alpha)
            return {"ids": seqs, "scores": scores}
        return {"ids": greedy_search(step_fn, state0, rows, max_new_tokens,
                                     bos_id=bos_id, eos_id=eos_id)}

    return generate
