"""Ring attention — sequence/context parallelism over the mesh ICI.

Gap-fill component (SURVEY §2.2/§5): the reference has NO sequence
parallelism — nothing distributes a single sequence. Here, attention
over a sequence sharded on the mesh's ``sp`` axis: each device holds a
query/key/value shard, K/V shards rotate around the ring via
``ppermute`` (neighbor ICI hops), and per-shard results merge in
log-space from the flash kernel's (out, lse) pairs.

Each ring step runs the pallas flash kernel (ops/flash_attention) on
the local Q shard against the visiting K/V shard, so per-chip memory is
O(S/n · d) for the shard buffers plus O(block²) inside the kernel —
never an S/n × S/n score matrix. The backward is a second ring pass
reusing the flash backward kernels with the COMBINED logsumexp
(flash-attention-2 style): dq accumulates locally, dk/dv accumulate on
buffers that travel with their K/V shard and arrive home after the full
cycle. Differentiable end-to-end via a custom VJP. One scan/ppermute/
accumulate machinery serves every schedule; schedules differ only in the
three visibility branches (earlier/own/later visiting rank).

Causal schedules:

- ``"ring"``: the visiting shard is fully visible (earlier ranks),
  causally visible (own rank), or invisible (later ranks) — selected
  with lax.switch so invisible steps do no FLOPs. Load-imbalanced: rank
  r does r+1 real steps (the last rank ~2n-1× the first's work, and the
  step time is the max over ranks).
- ``"zigzag"`` (default for causal): the sequence is split into 2n
  blocks and rank r holds blocks (r, 2n-1-r) — the standard
  context-parallel zigzag layout. Each ring step then costs EVERY rank
  exactly half a shard-pair of attention: own shard = local causal over
  the zigzag-ordered shard; an earlier rank's visit = all local queries
  attend its first half-block; a later rank's visit = the local second
  half-block attends all of it. Per-rank work is 2n units/rank vs
  (4r+2) for "ring" (see :func:`causal_work_per_rank`), identical
  numerics (tested).

Zigzag layout cost: with the default ``layout="natural"`` each call
gathers q/k/v into zigzag order and the output back — cross-shard
reshuffles per attention call. A transformer stack should instead keep
activations in zigzag order end-to-end (permute token ids once before
the embedding, unpermute once after the stack — positions travel with
the tokens) and pass ``layout="zigzag"`` so the ring sees shard-local
data only.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.errors import enforce
from ..ops import flash_attention as fa
from .mesh import pvary

NEG_INF = -1e30


def _merge(acc, lse_c, out_i, lse_i):
    """Log-space merge of per-shard flash results."""
    lse_new = jnp.logaddexp(lse_c, lse_i)
    w_old = jnp.exp(lse_c - lse_new)[..., None]
    w_new = jnp.exp(lse_i - lse_new)[..., None]
    return acc * w_old + out_i.astype(jnp.float32) * w_new, lse_new


# --------------------------------------------------------------------------
# Schedules: each provides the three visibility branches (visiting rank
# earlier than / equal to / later than the local rank) for the forward
# and backward ring passes. `None` branch list means "every step is a
# full step" (non-causal).
# --------------------------------------------------------------------------


class _RingSchedule:
    """Contiguous shards; visiting shard fully/causally/in-visible."""

    def __init__(self, causal: bool, block_q: int, block_k: int):
        self.causal = causal
        self.block_q, self.block_k = block_q, block_k

    def fwd_branches(self, q):
        b, h, sl, d = q.shape

        def full(k_cur, v_cur):
            return fa.flash_attention(q, k_cur, v_cur, causal=False,
                                      block_q=self.block_q, block_k=self.block_k,
                                      return_lse=True)

        def diag(k_cur, v_cur):
            return fa.flash_attention(q, k_cur, v_cur, causal=True,
                                      block_q=self.block_q, block_k=self.block_k,
                                      return_lse=True)

        def masked(k_cur, v_cur):
            return (jnp.zeros_like(q), jnp.full((b, h, sl), NEG_INF, jnp.float32))

        return [full, diag, masked] if self.causal else None

    def bwd_branches(self, q, out, lse, g, delta, interpret):
        def grads(k_cur, v_cur, caus):
            return fa._flash_bwd(q, k_cur, v_cur, None, None, None, caus,
                                 out, lse, g, self.block_q, self.block_k,
                                 interpret=interpret, delta=delta)

        def full(k_cur, v_cur):
            return grads(k_cur, v_cur, False)

        def diag(k_cur, v_cur):
            return grads(k_cur, v_cur, True)

        def masked(k_cur, v_cur):
            return (jnp.zeros_like(q), jnp.zeros_like(k_cur), jnp.zeros_like(v_cur))

        return [full, diag, masked] if self.causal else None


class _ZigzagSchedule:
    """Rank r holds blocks (r, 2n-1-r) of the 2n-block split: every step
    costs exactly half a shard-pair on every rank (balanced causal)."""

    def __init__(self, block_q: int, block_k: int):
        self.block_q, self.block_k = block_q, block_k

    def fwd_branches(self, q):
        b, h, sl, d = q.shape
        h2 = sl // 2

        def earlier(k_cur, v_cur):
            # visiting rank s < r: its first half (block s) precedes both
            # local blocks — fully visible; its second half (block
            # 2n-1-s) follows both — invisible
            return fa.flash_attention(q, k_cur[:, :, :h2], v_cur[:, :, :h2],
                                      causal=False, block_q=self.block_q,
                                      block_k=self.block_k, return_lse=True)

        def diag(k_cur, v_cur):
            # own shard: local causal is exactly the zigzag visibility
            # (block r precedes block 2n-1-r in both q and k order)
            return fa.flash_attention(q, k_cur, v_cur, causal=True,
                                      block_q=self.block_q, block_k=self.block_k,
                                      return_lse=True)

        def later(k_cur, v_cur):
            # visiting rank s > r: both its blocks fall between the local
            # blocks — visible only to the local second half
            out2, lse2 = fa.flash_attention(q[:, :, h2:], k_cur, v_cur,
                                            causal=False, block_q=self.block_q,
                                            block_k=self.block_k, return_lse=True)
            out = jnp.concatenate(
                [jnp.zeros((b, h, h2, d), out2.dtype), out2], axis=2)
            lse = jnp.concatenate(
                [jnp.full((b, h, h2), NEG_INF, jnp.float32), lse2], axis=2)
            return out, lse

        return [earlier, diag, later]

    def bwd_branches(self, q, out, lse, g, delta, interpret):
        b, h, sl, d = q.shape
        h2 = sl // 2

        def earlier(k_cur, v_cur):
            dq_i, dk_h, dv_h = fa._flash_bwd(
                q, k_cur[:, :, :h2], v_cur[:, :, :h2], None, None, None, False,
                out, lse, g, self.block_q, self.block_k,
                interpret=interpret, delta=delta)
            pad = jnp.zeros((b, h, sl - h2, d), dk_h.dtype)
            return (dq_i, jnp.concatenate([dk_h, pad], axis=2),
                    jnp.concatenate([dv_h, pad], axis=2))

        def diag(k_cur, v_cur):
            return fa._flash_bwd(q, k_cur, v_cur, None, None, None, True,
                                 out, lse, g, self.block_q, self.block_k,
                                 interpret=interpret, delta=delta)

        def later(k_cur, v_cur):
            dq_h, dk_i, dv_i = fa._flash_bwd(
                q[:, :, h2:], k_cur, v_cur, None, None, None, False,
                out[:, :, h2:], lse[:, :, h2:], g[:, :, h2:],
                self.block_q, self.block_k, interpret=interpret,
                delta=delta[:, :, h2:])
            dq_i = jnp.concatenate(
                [jnp.zeros((b, h, h2, d), dq_h.dtype), dq_h], axis=2)
            return dq_i, dk_i, dv_i

        return [earlier, diag, later]


def _dispatch(branches, idx, src, k_cur, v_cur):
    """Visibility dispatch shared by fwd/bwd: [earlier, own, later]."""
    b_ = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
    return jax.lax.switch(b_, branches, k_cur, v_cur)


def _ring_fwd_body(q, k0, v0, *, axis_name, varying_axes, schedule):
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    branches = schedule.fwd_branches(q)

    def step(carry, i):
        k_cur, v_cur, acc, lse_c = carry
        if branches is None:  # non-causal: every step is a full step
            out_i, lse_i = fa.flash_attention(
                q, k_cur, v_cur, causal=False, block_q=schedule.block_q,
                block_k=schedule.block_k, return_lse=True)
        else:
            out_i, lse_i = _dispatch(branches, idx, (idx - i) % n, k_cur, v_cur)
        acc, lse_c = _merge(acc, lse_c, out_i, lse_i)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, lse_c), None

    vaxes = tuple(varying_axes) or (axis_name,)
    acc0 = pvary(jnp.zeros((b, h, sl, d), jnp.float32), vaxes)
    lse0 = pvary(jnp.full((b, h, sl), NEG_INF, jnp.float32), vaxes)
    (_, _, acc, lse), _ = jax.lax.scan(step, (k0, v0, acc0, lse0), jnp.arange(n))
    return acc.astype(q.dtype), lse


def _ring_bwd_body(q, k0, v0, out, lse, g, *, axis_name, varying_axes, schedule):
    """Second ring pass: flash backward kernels with the combined lse.
    dk/dv ride with their shard and come home after n rotations."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    interpret = jax.devices()[0].platform == "cpu"
    # delta is k/v-shard-invariant: compute once, not per ring step
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)
    branches = schedule.bwd_branches(q, out, lse, g, delta, interpret)

    def step(carry, i):
        k_cur, v_cur, dk_cur, dv_cur, dq_acc = carry
        if branches is None:
            dq_i, dk_i, dv_i = fa._flash_bwd(
                q, k_cur, v_cur, None, None, None, False, out, lse, g,
                schedule.block_q, schedule.block_k, interpret=interpret,
                delta=delta)
        else:
            dq_i, dk_i, dv_i = _dispatch(branches, idx, (idx - i) % n,
                                         k_cur, v_cur)
        dq_acc = dq_acc + dq_i.astype(jnp.float32)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq_acc), None

    vaxes = tuple(varying_axes) or (axis_name,)
    dk0 = pvary(jnp.zeros(k0.shape, jnp.float32), vaxes)
    dv0 = pvary(jnp.zeros(v0.shape, jnp.float32), vaxes)
    dq0 = pvary(jnp.zeros(q.shape, jnp.float32), vaxes)
    (_, _, dk, dv, dq), _ = jax.lax.scan(
        step, (k0, v0, dk0, dv0, dq0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k0.dtype), dv.astype(v0.dtype)


def _make_sp_attention(axis_name, varying_axes, schedule):
    """custom_vjp wrapper shared by every schedule."""

    @jax.custom_vjp
    def attn(q, k, v):
        out, _ = _ring_fwd_body(q, k, v, axis_name=axis_name,
                                varying_axes=varying_axes, schedule=schedule)
        return out

    def attn_fwd(q, k, v):
        out, lse = _ring_fwd_body(q, k, v, axis_name=axis_name,
                                  varying_axes=varying_axes, schedule=schedule)
        return out, (q, k, v, out, lse)

    def attn_bwd(res, g):
        q, k, v, out, lse = res
        return _ring_bwd_body(q, k, v, out, lse, g, axis_name=axis_name,
                              varying_axes=varying_axes, schedule=schedule)

    attn.defvjp(attn_fwd, attn_bwd)
    return attn


# --------------------------------------------------------------------------
# Schedule accounting & zigzag layout helpers
# --------------------------------------------------------------------------


def causal_work_per_rank(n: int, schedule: str = "zigzag"):
    """Attention compute per rank over the full causal pass, in units of
    (sl/2)² score tiles (sl = local shard length). Plain ring: rank r
    does r full-shard steps (4 units) plus its causal diagonal (2);
    zigzag: every rank does 2 units on every one of the n steps. Both
    sum to 2n² (same total FLOPs); zigzag is flat."""
    if schedule == "ring":
        return [4 * r + 2 for r in range(n)]
    if schedule == "zigzag":
        return [2 * n] * n
    raise ValueError(f"unknown schedule {schedule!r}")


def zigzag_order(seq_len: int, n: int):
    """Global sequence index order that places blocks (r, 2n-1-r) of the
    2n-block split contiguously on rank r."""
    block = seq_len // (2 * n)
    idx = []
    for r in range(n):
        idx.extend(range(r * block, (r + 1) * block))
        idx.extend(range((2 * n - 1 - r) * block, (2 * n - r) * block))
    return jnp.asarray(idx, jnp.int32)


# --------------------------------------------------------------------------
# Public API
# --------------------------------------------------------------------------


def ring_attention(
    q, k, v,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = False,
    batch_axes: Optional[tuple] = ("dp", "fsdp"),
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    schedule: str = "auto",
    layout: str = "natural",
):
    """Attention over [b, h, s, d] with s sharded on ``axis_name``.

    Batch may additionally be sharded over ``batch_axes``; heads stay
    unsharded here (combine with TP by sharding h outside via shard_map
    composition). ``block_q``/``block_k`` default through the same
    flag resolution as :func:`flash_attention` (flash_block_q/_k), so
    a tuned block shape reaches the ring schedules too.

    ``schedule``: "auto" picks the load-balanced "zigzag" for causal
    attention (falling back to "ring" when s is not divisible by 2n) and
    the plain "ring" otherwise.

    ``layout``: "natural" inputs are gathered into zigzag order and the
    output gathered back — cross-shard traffic per call. Pass "zigzag"
    when activations already live in zigzag order (permute once outside
    the layer stack; see module docstring) to keep the ring shard-local.
    """
    enforce(schedule in ("auto", "ring", "zigzag"),
            f"unknown schedule {schedule!r} (auto|ring|zigzag)")
    enforce(layout in ("natural", "zigzag"),
            f"unknown layout {layout!r} (natural|zigzag)")
    block_q, block_k = fa.resolve_block_shapes(block_q, block_k)
    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        # degenerate ring: single-shard flash attention
        return fa.flash_attention(q, k, v, causal=causal,
                                  block_q=block_q, block_k=block_k)

    n = mesh.shape[axis_name]
    if schedule == "auto":
        schedule = "zigzag" if (causal and q.shape[2] % (2 * n) == 0) else "ring"
    if schedule == "zigzag" and not causal:
        schedule = "ring"  # zigzag only changes causal visibility
    # zigzag-ordered activations under the contiguous ring schedule would
    # mask the wrong token pairs — silently wrong attention
    enforce(not (layout == "zigzag" and schedule != "zigzag"),
            f"layout='zigzag' requires the zigzag schedule, but schedule "
            f"resolved to {schedule!r} (causal={causal}, seq={q.shape[2]}, "
            f"2n={2 * n}); un-permute the activations or fix seq divisibility")

    bspec = tuple(a for a in (batch_axes or ()) if a in mesh.axis_names)
    bshard = bspec if len(bspec) > 1 else (bspec[0] if bspec else None)
    spec = P(bshard, None, axis_name, None)
    vaxes = tuple(mesh.axis_names)

    if schedule == "zigzag":
        s = q.shape[2]
        enforce(s % (2 * n) == 0,
                f"zigzag needs seq {s} divisible by 2n={2 * n}")
        body = _make_sp_attention(axis_name, vaxes,
                                  _ZigzagSchedule(block_q, block_k))
        fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
        if layout == "zigzag":
            return fn(q, k, v)
        order = zigzag_order(s, n)
        inv = jnp.argsort(order)
        out = fn(jnp.take(q, order, axis=2), jnp.take(k, order, axis=2),
                 jnp.take(v, order, axis=2))
        return jnp.take(out, inv, axis=2)

    body = _make_sp_attention(axis_name, vaxes,
                              _RingSchedule(causal, block_q, block_k))
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
