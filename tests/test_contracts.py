"""analysis.contracts — the cross-artifact contract verifier, plus the
CI surface it feeds (fingerprints, baselines, SARIF, tools/lint_gate).

The acceptance shape of every contract test here: the STATIC finding
and its RUNTIME counterpart error are pinned in the same test, so the
claim "check_artifacts reports what the runtime would raise" is never
aspirational. Fault injection reuses paddle_tpu.testing.faults
(flip_byte) plus hand-edited manifest specs for the drift classes a
byte flip can't express deterministically."""

import json
import os
import shutil
import sys

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import analysis
from paddle_tpu import io as pio
from paddle_tpu import layers as L
from paddle_tpu import optimizer as opt
from paddle_tpu import resilience
from paddle_tpu.analysis import report as lint_report
from paddle_tpu.analysis.report import Finding, LintReport
from paddle_tpu.core.errors import EnforceError
from paddle_tpu.parallel import DistStrategy
from paddle_tpu.parallel.sharding import ShardingRules
from paddle_tpu.resilience import CheckpointCorrupt
from paddle_tpu.serving import PredictorServer, ReloadFailed
from paddle_tpu.testing import faults
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from tools import lint_gate

DIM, CLASSES, BS = 6, 4, 4


def _net(dim_h=16):
    def net(x, label):
        h = L.fc(x, dim_h, name="fc1")
        logits = L.fc(h, CLASSES, name="fc2")
        return {"loss": L.mean(L.softmax_with_cross_entropy(logits, label))}
    return net


def _feed(batch=BS, dim=DIM):
    return {"x": np.zeros((batch, dim), np.float32),
            "label": np.zeros((batch, 1), np.int64)}


def _trainer(dim_h=16, mesh=None, rules=None, strategy=None, optim=None,
             feed=None):
    tr = pt.Trainer(pt.build(_net(dim_h)), optim or opt.SGD(0.1),
                    loss_name="loss", mesh=mesh, sharding_rules=rules,
                    strategy=strategy)
    tr.startup(sample_feed=feed or _feed())
    return tr


def _checkpoint(tmp_path, tr, name="ck", **kw):
    d = str(tmp_path / name)
    pio.save_trainer(d, tr, **kw)
    return d


def _edit_manifest(ck, mutate):
    p = os.path.join(ck, resilience.MANIFEST_NAME)
    with open(p) as f:
        man = json.load(f)
    mutate(man)
    with open(p, "w") as f:
        json.dump(man, f)


# --------------------------------------------------------------------------
# ckpt:* — checkpoint vs trainer, static finding + runtime counterpart
# --------------------------------------------------------------------------


def test_clean_pair_has_no_findings(tmp_path):
    tr = _trainer()
    tr.step(_feed())
    ck = _checkpoint(tmp_path, tr)
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck)
    assert rep.ok("info"), rep.render("info")
    pio.load_trainer(ck, tr)  # and the runtime agrees


def test_shape_drifted_checkpoint_static_and_runtime(tmp_path):
    """Acceptance (a): a checkpoint whose param shapes drifted from the
    trainer's model config is a named error finding — and load_trainer
    raises CheckpointCorrupt naming the same param."""
    ck = _checkpoint(tmp_path, _trainer(dim_h=16))
    tr24 = _trainer(dim_h=24)
    rep = analysis.check_artifacts(trainer=tr24, checkpoint_dir=ck)
    drift = rep.by_code("ckpt:shape-drift")
    assert drift and all(f.severity == "error" for f in drift)
    assert {f.where for f in drift} == {
        "params.npz:fc1/w", "params.npz:fc1/b", "params.npz:fc2/w"}
    f = next(f for f in drift if f.where == "params.npz:fc1/w")
    assert f.data["got"] == [6, 16] and f.data["expected"] == [6, 24]
    with pytest.raises(CheckpointCorrupt, match="fc1/b.*drifted"):
        pio.load_trainer(ck, tr24)


def test_missing_and_extra_entries_static_and_runtime(tmp_path):
    """A renamed layer shows up as a missing+extra pair; load_trainer's
    runtime verdict is the same divergence, raised as
    CheckpointCorrupt."""
    def renamed(x, label):
        h = L.fc(x, 16, name="fc1")
        logits = L.fc(h, CLASSES, name="head")   # fc2 renamed
        return {"loss": L.mean(L.softmax_with_cross_entropy(logits, label))}

    ck = _checkpoint(tmp_path, _trainer())
    tr2 = pt.Trainer(pt.build(renamed), opt.SGD(0.1), loss_name="loss")
    tr2.startup(sample_feed=_feed())
    rep = analysis.check_artifacts(trainer=tr2, checkpoint_dir=ck)
    missing = rep.by_code("ckpt:missing-entry")
    extra = rep.by_code("ckpt:extra-entry")
    assert {f.where for f in missing} >= {"params.npz:head/w"}
    assert {f.where for f in extra} >= {"params.npz:fc2/w"}
    assert all(f.severity == "error" for f in missing + extra)
    with pytest.raises(CheckpointCorrupt, match="diverge"):
        pio.load_trainer(ck, tr2)


def test_manifest_bitrot_static_and_runtime(tmp_path):
    """faults.flip_byte on the manifest itself: statically
    ckpt:unreadable, at runtime CheckpointCorrupt — a torn manifest must
    never read as 'legacy, validate nothing'."""
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)
    faults.flip_byte(ck, name=resilience.MANIFEST_NAME, offset=0)
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck)
    (f,) = rep.by_code("ckpt:unreadable")
    assert f.severity == "error" and "unreadable" in f.message
    assert not rep.by_code("ckpt:legacy")
    with pytest.raises(CheckpointCorrupt, match="manifest"):
        pio.load_trainer(ck, tr)


def test_manifest_spec_hand_edit_shape_static_and_runtime(tmp_path):
    """Satellite: rewrite one manifest spec entry's shape. Statically
    ckpt:shape-drift names the entry; at runtime the manifest/npz
    cross-check in load_trainer raises CheckpointCorrupt on the same
    entry."""
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)

    def grow_fc1(man):
        man["arrays"]["params.npz"]["fc1/w"]["shape"] = [DIM, 99]
    _edit_manifest(ck, grow_fc1)

    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck)
    (f,) = rep.by_code("ckpt:shape-drift")
    assert f.where == "params.npz:fc1/w" and f.severity == "error"
    assert f.data["got"] == [DIM, 99]
    with pytest.raises(CheckpointCorrupt,
                       match="fc1/w.*manifest records"):
        pio.load_trainer(ck, tr)


def test_manifest_spec_hand_edit_dtype_static_and_runtime(tmp_path):
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)

    def retype_fc1_b(man):
        man["arrays"]["params.npz"]["fc1/b"]["dtype"] = "float64"
    _edit_manifest(ck, retype_fc1_b)

    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck)
    (f,) = rep.by_code("ckpt:dtype-drift")
    assert f.where == "params.npz:fc1/b"
    assert f.data == {"got": "float64", "expected": "float32"}
    with pytest.raises(CheckpointCorrupt,
                       match="fc1/b.*manifest records"):
        pio.load_trainer(ck, tr)


def test_loss_scale_drift_static_and_runtime(tmp_path):
    """Both drift directions are warnings (the runtime warns and falls
    back — it never crashes), so they must not block a gate at
    fail-on=error."""
    plain = _trainer()
    ck_plain = _checkpoint(tmp_path, plain, "ck_plain")
    scaled = _trainer(strategy=DistStrategy(loss_scale=2.0 ** 10,
                                            dynamic_loss_scale=True))
    rep = analysis.check_artifacts(trainer=scaled, checkpoint_dir=ck_plain)
    (f,) = rep.by_code("ckpt:loss-scale-drift")
    assert f.severity == "warning" and "no loss_scale_state" in f.message
    assert rep.ok("error")
    with pytest.warns(UserWarning, match="no loss_scale_state"):
        pio.load_trainer(ck_plain, scaled)

    ck_scaled = _checkpoint(tmp_path, scaled, "ck_scaled")
    rep = analysis.check_artifacts(trainer=plain, checkpoint_dir=ck_scaled)
    (f,) = rep.by_code("ckpt:loss-scale-drift")
    assert f.severity == "warning" and "no loss scaler" in f.message
    with pytest.warns(UserWarning, match="no loss scaler"):
        pio.load_trainer(ck_scaled, plain)


def test_malformed_metadata_degrades_to_finding_not_crash(tmp_path):
    """Metadata that parses but is internally inconsistent is a
    *finding* (the artifact is corrupt), never a checker crash — a CI
    caller must see exit 1 with the artifact named, not exit 3."""
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)

    def drop_shape(man):
        del man["arrays"]["params.npz"]["fc1/w"]["shape"]
    _edit_manifest(ck, drop_shape)
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck,
                                   mesh=pt.make_mesh({"dp": 8}),
                                   sample_feed=_feed(batch=8))
    assert rep.by_code("ckpt:malformed"), rep.render("info")

    art, _ = _export(tmp_path)
    mpath = os.path.join(art, "meta.json")
    with open(mpath) as fh:
        meta = json.load(fh)
    # inputs table disagrees with feed_names: a torn partial rewrite
    meta["inputs"] = [e for e in meta["inputs"]
                      if not (e.get("source") == "feed"
                              and e["name"] == "x")]
    with open(mpath, "w") as fh:
        json.dump(meta, fh)
    rep = analysis.check_artifacts(trainer=tr, artifact_dir=art,
                                   sample_feed=_feed())
    (f,) = rep.by_code("artifact:malformed")
    assert f.severity == "error" and "EnforceError" in f.message


def test_legacy_checkpoint_is_info_only(tmp_path):
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)
    os.remove(os.path.join(ck, resilience.MANIFEST_NAME))
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck)
    (f,) = rep.by_code("ckpt:legacy")
    assert f.severity == "info"
    assert rep.ok("warning")


# --------------------------------------------------------------------------
# restore-at-a-different-mesh feasibility (the dp N->M reshard verdicts)
# --------------------------------------------------------------------------


def test_reshard_infeasible_static_and_runtime(tmp_path):
    """Acceptance (c): restoring a single-host checkpoint at dp=8 with a
    batch the data axis can't split is statically ckpt:reshard-infeasible
    — the runtime counterpart being put_batch's NamedSharding rejecting
    the first feed."""
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)
    mesh8 = pt.make_mesh({"dp": 8})
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck,
                                   mesh=mesh8, sample_feed=_feed(batch=4))
    (f,) = rep.by_code("ckpt:reshard-infeasible")
    assert f.severity == "error"
    assert f.data == {"got": [4], "expected": [8]}
    assert not rep.by_code("ckpt:mesh-reshard")  # no feasible verdict
    with pytest.raises(ValueError, match="divisible by 8"):
        tr8 = pt.Trainer(pt.build(_net()), opt.SGD(0.1), loss_name="loss",
                         mesh=mesh8)
        tr8.startup(sample_feed=_feed(batch=4))
        tr8.step(_feed(batch=4))


def test_reshard_feasible_n_to_m_static_and_runtime(tmp_path):
    """The positive verdict: a dp 2->8 resize whose batch divides the
    target data shards is expressible (arrays are stored unsharded) —
    an info finding naming reshard_restore as the remedy, and that
    remedy actually restores + steps. The implicit path (plain
    load_trainer) is gated: the mesh mismatch is a structured
    ReshardError naming saved vs target axes, not a device_put crash
    later."""
    mesh2 = pt.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    tr2 = _trainer(mesh=mesh2, feed=_feed(batch=8))
    ck = _checkpoint(tmp_path, tr2)
    man = resilience.read_manifest(ck)
    assert man["meta"]["mesh_axes"] == {"dp": 2}  # the saved-at mesh

    mesh8 = pt.make_mesh({"dp": 8})
    tr8 = _trainer(mesh=mesh8, feed=_feed(batch=8))
    rep = analysis.check_artifacts(trainer=tr8, checkpoint_dir=ck,
                                   sample_feed=_feed(batch=8))
    (f,) = rep.by_code("ckpt:mesh-reshard")
    assert f.severity == "info"
    assert "{'dp': 2} -> {'dp': 8}" in f.message
    assert "reshard_restore" in f.message  # the verdict names the remedy
    assert not rep.by_code("ckpt:reshard-infeasible")
    assert rep.ok("warning"), rep.render("info")
    with pytest.raises(resilience.ReshardError) as ei:
        pio.load_trainer(ck, tr8)
    assert ei.value.saved_axes == {"dp": 2}
    assert ei.value.target_axes == {"dp": 8}
    assert "reshard_restore" in str(ei.value)
    out = resilience.reshard_restore(ck, tr8, sample_feed=_feed(batch=8))
    assert out["saved_axes"] == {"dp": 2}
    assert out["target_axes"] == {"dp": 8}
    assert out["bytes_moved"] > 0
    tr8.step(_feed(batch=8))


def test_reshard_verdict_and_runtime_agree_pairwise(tmp_path):
    """The static↔runtime closure, pinned pairwise: for every dp N→M
    pair, ckpt:mesh-reshard ⇒ reshard_restore succeeds with bit-exact
    params, and ckpt:reshard-infeasible ⇒ ReshardError carrying the SAME
    finding text. The checker and the runtime can never split."""
    import numpy as np

    mesh_of = {n: (pt.make_mesh({"dp": n}, devices=jax.devices()[:n])
                   if n > 1 else None) for n in (1, 2, 4, 8)}
    feed6 = _feed(batch=6)   # divides 1/2, not 4/8
    saved = {}
    for n in (2, 4):
        tr = _trainer(mesh=mesh_of[n], feed=_feed(batch=8))
        tr.step(_feed(batch=8))
        saved[n] = _checkpoint(tmp_path, tr, name=f"ck_dp{n}")
    for n, ck in saved.items():
        want = pio.load_persistables(ck)[0]
        for m in (1, 2, 4, 8):
            if m == n:
                continue
            tr = _trainer(mesh=mesh_of[m], feed=_feed(batch=8))
            rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck,
                                           sample_feed=feed6)
            bad = rep.by_code("ckpt:reshard-infeasible")
            if bad:
                assert not rep.by_code("ckpt:mesh-reshard")
                with pytest.raises(resilience.ReshardError) as ei:
                    resilience.reshard_restore(ck, tr, sample_feed=feed6)
                # the runtime error IS the static verdict, verbatim
                assert ei.value.reason == bad[0].message
            else:
                if m > 1:  # meshless target: no verdict to emit
                    assert rep.by_code("ckpt:mesh-reshard"), rep.render("info")
                resilience.reshard_restore(ck, tr, sample_feed=feed6)
                got = jax.device_get(tr.scope.params)
                assert all(np.array_equal(got[k], want[k]) for k in want)


def test_reshard_same_placement_size_one_axes_is_silent(tmp_path):
    """The checker compares NORMALIZED axes like the load gate: a
    {'dp': 2, 'pp': 1} checkpoint restored at {'dp': 2} is the same
    placement — no verdict, and plain load_trainer passes (the pinned
    pairwise agreement holds for size-1 axes too)."""
    mesh_a = pt.make_mesh({"dp": 2, "pp": 1}, devices=jax.devices()[:2])
    tr_a = _trainer(mesh=mesh_a, feed=_feed(batch=8))
    ck = _checkpoint(tmp_path, tr_a)
    mesh_b = pt.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    tr_b = _trainer(mesh=mesh_b, feed=_feed(batch=8))
    rep = analysis.check_artifacts(trainer=tr_b, checkpoint_dir=ck,
                                   sample_feed=_feed(batch=8))
    assert not [f for f in rep.findings if f.code.startswith("ckpt:")], \
        rep.render("info")
    pio.load_trainer(ck, tr_b)  # gate agrees: nothing to reshard


def test_reshard_same_mesh_is_silent(tmp_path):
    mesh8 = pt.make_mesh({"dp": 8})
    tr = _trainer(mesh=mesh8, feed=_feed(batch=8))
    ck = _checkpoint(tmp_path, tr)
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck,
                                   sample_feed=_feed(batch=8))
    assert not [f for f in rep.findings if f.code.startswith("ckpt:")], \
        rep.render("info")


def test_reshard_honors_rules_batch_axes(tmp_path):
    """The feasibility verdict must mirror put_batch, which shards the
    batch per ShardingRules.batch_axes — NOT the mesh's nominal data
    axes. On a {dp:2, fsdp:4} mesh with batch_axes=('dp',), batch 4
    splits 2-way and restores fine; calling it infeasible against the
    8-way data-axis product would be a false alarm (and the runtime
    step is the proof)."""
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)
    mesh = pt.make_mesh({"dp": 2, "fsdp": 4})
    rules = ShardingRules(batch_axes=("dp",))
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck,
                                   mesh=mesh, sharding_rules=rules,
                                   sample_feed=_feed(batch=4))
    assert not rep.by_code("ckpt:reshard-infeasible"), rep.render("info")
    (f,) = rep.by_code("ckpt:mesh-reshard")
    assert "2-way" in f.message
    # runtime counterpart: the restore + step actually works (through
    # the elastic door — the checkpoint was saved single-device)
    tr_m = _trainer(mesh=mesh, rules=rules, feed=_feed(batch=4))
    resilience.reshard_restore(ck, tr_m, sample_feed=_feed(batch=4))
    tr_m.step(_feed(batch=4))
    # and WITHOUT the batch_axes restriction the same batch is honestly
    # infeasible (8-way product), so the rules truly drive the verdict
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck,
                                   mesh=mesh, sample_feed=_feed(batch=4))
    (f,) = rep.by_code("ckpt:reshard-infeasible")
    assert f.data == {"got": [4], "expected": [8]}


def test_reshard_dropped_rule_is_warning_not_error(tmp_path):
    """A target mesh that can't honor a sharding rule (dim 6 over tp=8)
    is feasible-but-degraded: the param replicates. Warning, with the
    feasibility verdict still emitted."""
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)
    mesh_tp = pt.make_mesh({"tp": 8})
    rules = ShardingRules([(r".*fc1/w", P("tp", None))])
    rep = analysis.check_artifacts(trainer=tr, checkpoint_dir=ck,
                                   mesh=mesh_tp, sharding_rules=rules,
                                   sample_feed=_feed())
    dropped = rep.by_code("ckpt:reshard-dropped-rule")
    assert dropped and all(f.severity == "warning" for f in dropped)
    (f,) = rep.by_code("ckpt:mesh-reshard")
    assert "some rules drop" in f.message


# --------------------------------------------------------------------------
# artifact:* — serving artifact internal consistency + drift
# --------------------------------------------------------------------------


def _export(tmp_path, name="art", dim_h=16, feed=None, **kw):
    prog = pt.build(_net(dim_h))
    feed = feed or _feed(batch=8)
    params, state = prog.init(jax.random.PRNGKey(0), **feed)
    d = str(tmp_path / name)
    pio.save_inference_model(d, prog, jax.tree.map(np.asarray, params),
                             state, feed, **kw)
    return d, prog


def test_stale_bucket_static_and_runtime(tmp_path):
    """Acceptance (b): meta.json names bucket 4 but its StableHLO file
    is gone — statically artifact:stale-bucket, at runtime
    load_inference_model raises CheckpointCorrupt naming the file."""
    art, _ = _export(tmp_path, batch_buckets=[4, 8])
    os.remove(os.path.join(art, "model.b4.stablehlo"))
    rep = analysis.check_artifacts(artifact_dir=art)
    (f,) = rep.by_code("artifact:stale-bucket")
    assert f.severity == "error" and f.data["bucket"] == 4
    with pytest.raises(CheckpointCorrupt, match="model.b4.stablehlo"):
        pio.load_inference_model(art)


def test_missing_model_file_static_and_runtime(tmp_path):
    art, _ = _export(tmp_path)
    os.remove(os.path.join(art, "model.stablehlo"))
    rep = analysis.check_artifacts(artifact_dir=art)
    assert rep.by_code("artifact:missing-model")
    with pytest.raises(CheckpointCorrupt, match="model.stablehlo"):
        pio.load_inference_model(art)


def test_torn_artifact_dir_is_unreadable_finding(tmp_path):
    d = str(tmp_path / "torn")
    os.makedirs(d)
    rep = analysis.check_artifacts(artifact_dir=d)
    (f,) = rep.by_code("artifact:unreadable")
    assert "meta.json" in f.message


def test_artifact_param_and_feed_drift_vs_trainer(tmp_path):
    """The re-export contract: an artifact from an older model config
    diverges from the trainer that would hot-reload over it — weights
    at warning (the next export replaces them), feed signature at error
    (every trainer-contract request fails validation). Runtime
    counterpart: the loaded predictor rejects the trainer's feed."""
    art, _ = _export(tmp_path, dim_h=16)          # exported with x[_,6]
    tr = _trainer(dim_h=24, feed=_feed(dim=8))    # now feeds x[_,8]
    rep = analysis.check_artifacts(trainer=tr, artifact_dir=art,
                                   sample_feed=_feed(dim=8))
    (pdrift,) = rep.by_code("artifact:param-drift")
    assert pdrift.severity == "warning"
    (fdrift,) = rep.by_code("artifact:feed-drift")
    assert fdrift.severity == "error" and fdrift.where == "x"
    pred = pio.load_inference_model(art)
    from paddle_tpu.io import InvalidRequest
    with pytest.raises(InvalidRequest, match="x.*shape"):
        pred.run({k: v[:8] for k, v in _feed(batch=8, dim=8).items()})


def test_artifact_feed_names_drift(tmp_path):
    art, _ = _export(tmp_path)

    def other(image, label):
        logits = L.fc(image, CLASSES, name="fc")
        return {"loss": L.mean(L.softmax_with_cross_entropy(logits, label))}

    tr = pt.Trainer(pt.build(other), opt.SGD(0.1), loss_name="loss")
    feed = {"image": np.zeros((BS, DIM), np.float32),
            "label": np.zeros((BS, 1), np.int64)}
    tr.startup(sample_feed=feed)
    rep = analysis.check_artifacts(trainer=tr, artifact_dir=art,
                                   sample_feed=feed)
    (f,) = rep.by_code("artifact:feed-names")
    assert f.data["expected"] == ["image", "label"]
    assert f.data["got"] == ["label", "x"]


# --------------------------------------------------------------------------
# the serving pre-reload contract
# --------------------------------------------------------------------------


def test_reload_preflight_rejects_statically_without_paying_load(tmp_path):
    """PredictorServer.reload over a shrunk-bucket candidate fails from
    metadata alone: the load + per-bucket AOT compile is never paid.
    reload_preflight exposes the same report for fleet controllers."""
    import types

    art_full, prog = _export(tmp_path, "full", batch_buckets=[4, 8])
    art_small, _ = _export(tmp_path, "small")     # bucket set {8} only
    pred = pio.load_inference_model(art_full)
    srv = PredictorServer(pred, workers=1, queue_size=4, warmup=False)
    try:
        rep = srv.reload_preflight(art_small)
        (f,) = rep.by_code("artifact:bucket-shrank")
        assert f.data["buckets"] == [4]
        assert srv.reload_preflight(art_full).ok("error")

        def _never(*a, **k):
            raise AssertionError("static reject must not pay a load")
        srv._io = types.SimpleNamespace(
            read_artifact_meta=pio.read_artifact_meta,
            load_inference_model=_never,
            aot_compile_count=pio.aot_compile_count)
        with pytest.raises(ReloadFailed, match="bucket set shrank"):
            srv.reload(art_small, block=True)
        assert srv.generation == 1
    finally:
        srv._io = pio
        srv.close(drain=False)


def test_check_reload_compat_feed_drift_per_bucket(tmp_path):
    art_full, _ = _export(tmp_path, "full", batch_buckets=[4, 8])
    art_drift, _ = _export(tmp_path, "drift", feed=_feed(batch=8, dim=8),
                           batch_buckets=[4, 8])
    pred = pio.load_inference_model(art_full)
    served = analysis.serving_spec(pred)
    rep = analysis.check_reload_compat(
        served, pio.read_artifact_meta(art_drift))
    drift = rep.by_code("artifact:feed-drift")
    assert {f.data["bucket"] for f in drift} == {4, 8}
    assert all("x" in f.data["expected"] for f in drift)


# --------------------------------------------------------------------------
# sharding:replicated-optstate — the ZeRO trigger
# --------------------------------------------------------------------------


def test_replicated_optstate_flags_adam_on_dp_mesh():
    mesh8 = pt.make_mesh({"dp": 8})
    tr = _trainer(mesh=mesh8, optim=opt.Adam(1e-3), feed=_feed(batch=8))
    rep = analysis.check_artifacts(trainer=tr, replicated_optstate_bytes=1)
    (f,) = rep.by_code("sharding:replicated-optstate")
    assert f.severity == "warning"
    assert f.data["data_shards"] == 8
    # Adam: m+v per param leaf; a 1/8 shard reclaims 7/8
    assert f.data["zero_saving_bytes"] == pytest.approx(
        f.data["replicated_bytes_per_device"] * 7 / 8, rel=1e-6)
    # same trigger through the check_trainer door
    rep2 = analysis.check_trainer(tr, sample_feed=_feed(batch=8),
                                  replicated_optstate_bytes=1)
    assert rep2.by_code("sharding:replicated-optstate")


def test_replicated_optstate_not_fooled_by_fsdp_sharding():
    """Accums sharded ALONG a data axis (fsdp rules) carry no data-axis
    redundancy — the ZeRO saving is already realized, so no trigger.
    Only the leaves the rule table leaves replicated count."""
    from paddle_tpu.parallel.sharding import fsdp

    mesh = pt.make_mesh({"fsdp": 8})
    tr = _trainer(mesh=mesh, rules=fsdp(min_size_to_shard=1),
                  optim=opt.Adam(1e-3), feed=_feed(batch=8, dim=8))
    # every param has an 8-divisible dim: fc1/w (8,16), fc1/b (16,),
    # fc2/w (16,4), fc2/b (4,)... fc2/b's largest dim is 4 -> replicated
    rep = analysis.check_artifacts(trainer=tr, replicated_optstate_bytes=1)
    hits = rep.by_code("sharding:replicated-optstate")
    if hits:   # only the un-shardable fc2/b moments may contribute
        assert hits[0].data["replicated_bytes_per_device"] <= 2 * 4 * 4, \
            hits[0].message


def test_replicated_optstate_quiet_below_threshold_and_for_sgd():
    mesh8 = pt.make_mesh({"dp": 8})
    tr = _trainer(mesh=mesh8, optim=opt.Adam(1e-3), feed=_feed(batch=8))
    rep = analysis.check_artifacts(trainer=tr)   # default 64 MB floor
    assert not rep.by_code("sharding:replicated-optstate")
    sgd = _trainer(mesh=mesh8, feed=_feed(batch=8))
    rep = analysis.check_artifacts(trainer=sgd, replicated_optstate_bytes=1)
    assert not rep.by_code("sharding:replicated-optstate")


# --------------------------------------------------------------------------
# moe:capacity — the drop-rate model (golden finding lives in the zoo test)
# --------------------------------------------------------------------------


def test_expected_moe_drop_rate_limits():
    from paddle_tpu.analysis.rules import expected_moe_drop_rate

    # deterministic limit: cf=0.5 -> half the assignments drop
    big = expected_moe_drop_rate(tokens=1 << 20, top_k=1, num_experts=4,
                                 capacity=(1 << 20) // 8)
    assert big == pytest.approx(0.5, abs=0.01)
    # ample capacity -> essentially nothing drops
    assert expected_moe_drop_rate(1024, 2, 4, 4096) < 1e-6
    # monotone non-increasing in capacity
    rates = [expected_moe_drop_rate(4096, 2, 8, c)
             for c in (128, 256, 512, 1024, 4096)]
    assert all(a >= b for a, b in zip(rates, rates[1:]))
    assert expected_moe_drop_rate(0, 2, 4, 16) == 0.0


def test_moe_configs_recorded_under_full_scoped_name():
    """Two MoE layers in DIFFERENT name scopes must record distinct
    names — the scope-local helper name ('moe_0') would collide their
    fingerprints and baselining one would suppress the other."""
    import jax
    from paddle_tpu.framework import name_scope
    from paddle_tpu.parallel.moe import capture_moe_configs, moe

    def net(x):
        with name_scope("enc"):
            a, _ = moe(x, num_experts=4, d_ff=8, capacity_factor=0.5)
        with name_scope("dec"):
            b, _ = moe(a, num_experts=4, d_ff=8, capacity_factor=4.0)
        return {"loss": L.mean(b)}

    prog = pt.build(net)
    feed = {"x": np.zeros((2, 4, 8), np.float32)}
    with capture_moe_configs() as log:
        prog.init(jax.random.PRNGKey(0), **feed)
    names = sorted(c["name"] for c in log)
    # the context-global counter already distinguishes same-trace
    # layers; the scope prefix additionally pins the name to the param
    # path (stable when an unrelated layer shifts the counter)
    assert names == ["dec/moe_1", "enc/moe_0"], names
    rep = LintReport("t")
    from paddle_tpu.analysis.rules import check_moe_capacity
    check_moe_capacity(log, rep)
    (f,) = rep.by_code("moe:capacity")   # only the under-capacitied one
    assert f.where == "enc/moe_0"


def test_check_moe_capacity_threshold():
    from paddle_tpu.analysis.rules import check_moe_capacity

    cfg = dict(name="moe_0", tokens=4096, top_k=2, num_experts=8,
               capacity=256, capacity_factor=0.25)
    rep = LintReport("t")
    check_moe_capacity([cfg], rep)
    (f,) = rep.by_code("moe:capacity")
    assert 0.7 < f.data["expected_drop_rate"] < 0.8   # ~1 - cf
    rep2 = LintReport("t")
    check_moe_capacity([dict(cfg, capacity=2048, capacity_factor=2.0)], rep2)
    assert not rep2.findings


# --------------------------------------------------------------------------
# report CI machinery: fingerprints, dedupe, baselines, severity, SARIF
# --------------------------------------------------------------------------


def test_fingerprint_dedupe_bumps_count():
    rep = LintReport("t")
    f1 = rep.add("moe:capacity", "warning", "msg v1", where="moe_0",
                 expected_drop_rate=0.5)
    f2 = rep.add("moe:capacity", "warning", "msg v2 (improved wording)",
                 where="moe_0", expected_drop_rate=0.493)
    assert f1 is f2 and f1.count == 2 and len(rep.findings) == 1
    # measurements are NOT identity; structural keys are
    rep.add("moe:capacity", "warning", "other layer", where="moe_1")
    assert len(rep.findings) == 2


def test_extend_dedupes_repeated_checks():
    """Satellite: startup lint + an explicit re-run merged into one
    report keep one stable key per finding (counts accumulate)."""
    def one():
        r = LintReport("t")
        r.add("ckpt:shape-drift", "error", "m", where="params.npz:w",
              got=[2], expected=[3])
        return r

    merged = LintReport("t").extend(one()).extend(one())
    assert len(merged.findings) == 1
    assert merged.findings[0].count == 2
    # extend copies: mutating the merged finding leaves the source alone
    src = one()
    LintReport("t").extend(src).findings[0].count = 99
    assert src.findings[0].count == 1


def test_fingerprint_discriminates_distinct_sites():
    """Findings whose `where` is a bare primitive name must still get
    distinct fingerprints per SITE, or a baseline accepting one
    instance silently suppresses every future new one of that class:
    `path` (loop nesting) and `dtype` (cast triple) are structural
    identity, so two collectives in different loops — or two cast
    round-trips through different dtypes — are two baseline entries."""
    rep = LintReport("t")
    a = rep.add("collective:in-scan", "warning", "m", where="psum",
                payload_bytes=100, path=["scan", "fwd"])
    b = rep.add("collective:in-scan", "warning", "m", where="psum",
                payload_bytes=100, path=["scan", "bwd"])
    assert a.fingerprint != b.fingerprint and len(rep.findings) == 2
    c = rep.add("dtype:cast-roundtrip", "info", "m",
                where="convert_element_type",
                dtype="float32->bfloat16->float32")
    d = rep.add("dtype:cast-roundtrip", "info", "m",
                where="convert_element_type",
                dtype="float32->float16->float32")
    assert c.fingerprint != d.fingerprint
    # but payload measurements still are NOT identity
    e = rep.add("collective:in-scan", "warning", "m", where="psum",
                payload_bytes=999, path=["scan", "fwd"])
    assert e is a and a.count == 2


def test_same_fingerprint_different_severity_kept_separate():
    rep = LintReport("t")
    rep.add("a:b", "warning", "m", where="w")
    rep.add("a:b", "error", "m", where="w")
    assert len(rep.findings) == 2


def test_apply_severity_exact_beats_family():
    rep = LintReport("t")
    rep.add("moe:capacity", "warning", "m", where="moe_0")
    rep.add("moe:other", "warning", "m", where="moe_0")
    lint_report.apply_severity(rep, {"moe": "info", "moe:capacity": "error"})
    sev = {f.code: f.severity for f in rep.findings}
    assert sev == {"moe:capacity": "error", "moe:other": "info"}
    with pytest.raises(EnforceError, match="severity override"):
        lint_report.apply_severity(rep, {"moe": "fatal"})


def test_baseline_roundtrip_and_new_findings(tmp_path):
    rep = LintReport("t")
    rep.add("a:b", "warning", "m", where="w", shape=[2, 3])
    rep.add("c:d", "error", "m2", where="v")
    path = str(tmp_path / "base.json")
    doc = lint_report.write_baseline(path, [("subj", rep)])
    assert len(doc["baseline"]) == 2
    base = lint_report.load_baseline(path)
    assert lint_report.new_findings("subj", rep, base) == []
    # count growth stays suppressed (counts are measurements)
    rep.add("a:b", "warning", "m again", where="w", shape=[2, 3])
    assert lint_report.new_findings("subj", rep, base) == []
    # the SAME fingerprint on a different subject is a new finding
    assert len(lint_report.new_findings("other", rep, base)) == 2
    # a genuinely new finding surfaces
    f = rep.add("e:f", "warning", "fresh", where="w")
    assert lint_report.new_findings("subj", rep, base) == [f]
    # info-level findings don't gate at the default level
    rep.add("g:h", "info", "note", where="w")
    assert lint_report.new_findings("subj", rep, base) == [f]
    # missing file == empty baseline
    assert lint_report.load_baseline(str(tmp_path / "nope.json")) == {}


def test_bad_baseline_file_is_enforced(tmp_path):
    p = str(tmp_path / "bad.json")
    with open(p, "w") as fh:
        json.dump(["not", "a", "baseline"], fh)
    with pytest.raises(EnforceError, match="baseline file"):
        lint_report.load_baseline(p)
    with open(p, "w") as fh:
        json.dump({"version": 99, "baseline": {}}, fh)
    with pytest.raises(EnforceError, match="version"):
        lint_report.load_baseline(p)


def test_sarif_emitter_shape():
    rep = LintReport("t")
    rep.add("a:b", "warning", "m", where="w")
    rep.add("a:b", "warning", "m", where="w")   # count=2
    rep.add("c:d", "error", "m2", where="")
    doc = lint_report.to_sarif([("subj", rep)])
    assert doc["version"] == "2.1.0" and len(doc["runs"]) == 1
    run = doc["runs"][0]
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == ["a:b", "c:d"]
    by_rule = {r["ruleId"]: r for r in run["results"]}
    assert by_rule["a:b"]["occurrenceCount"] == 2
    assert by_rule["a:b"]["level"] == "warning"
    assert by_rule["c:d"]["level"] == "error"
    fp = by_rule["a:b"]["partialFingerprints"]["paddleTpuLint/v1"]
    assert fp == lint_report.baseline_key("subj", rep.findings[0])
    assert by_rule["c:d"]["locations"][0]["logicalLocations"][0][
        "name"] == "subj"


# --------------------------------------------------------------------------
# tools/lint_gate.py — the CI gate over the analysis zoo
# --------------------------------------------------------------------------


def test_lint_gate_clean_on_committed_baseline(capsys):
    """Tier-1 gate: the full zoo sweep against the committed baseline
    must be clean. A PR that introduces a new finding on any zoo
    program fails THIS test with the fingerprint named — fix the
    finding or re-write tools/analysis_baseline.json deliberately."""
    rc = lint_gate.main(["--ci"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "lint gate clean" in out
    # the two golden true positives are present AND baselined
    base = lint_report.load_baseline(lint_gate.DEFAULT_BASELINE)
    assert any(k.startswith("moe_transformer.tight::moe:capacity")
               for k in base)
    assert any(k.startswith("gpt.amp::dtype:amp-f32-matmul") for k in base)


def test_lint_gate_exit1_on_injected_new_finding(tmp_path, monkeypatch,
                                                 capsys):
    """Acceptance: removing a fingerprint from (a copy of) the committed
    baseline makes that finding 'new' — exit 1, fingerprint printed."""
    base = lint_report.load_baseline(lint_gate.DEFAULT_BASELINE)
    trimmed = {k: v for k, v in base.items() if "moe:capacity" not in k}
    assert len(trimmed) < len(base)
    p = str(tmp_path / "trimmed.json")
    with open(p, "w") as fh:
        json.dump({"version": 1, "baseline": trimmed}, fh)
    monkeypatch.setattr(lint_gate, "GATE_CONFIGS", [
        {"subject": "moe_transformer.tight", "model": "moe_transformer",
         "variant": "tight"}])
    rc = lint_gate.main(["--ci", "--baseline", p])
    out = capsys.readouterr().out
    assert rc == 1
    assert "moe_transformer.tight::moe:capacity" in out
    assert "--write-baseline" in out   # the remediation is named


def test_lint_gate_exit3_on_checker_crash(monkeypatch, capsys):
    """Acceptance: a crash inside the sweep is exit 3 — never a pass,
    never the PR author's finding."""
    monkeypatch.setattr(lint_gate, "GATE_CONFIGS",
                        [{"subject": "broken", "model": "no_such_model"}])
    rc = lint_gate.main(["--ci"])
    assert rc == 3
    assert "internal error" in capsys.readouterr().err


def test_lint_gate_write_baseline_roundtrip(tmp_path, monkeypatch, capsys):
    monkeypatch.setattr(lint_gate, "GATE_CONFIGS", [
        {"subject": "moe_transformer.tight", "model": "moe_transformer",
         "variant": "tight"},
        {"subject": "mnist.mlp", "model": "mnist", "variant": "mlp"}])
    p = str(tmp_path / "fresh.json")
    assert lint_gate.main(["--write-baseline", p]) == 0
    assert lint_gate.main(["--ci", "--baseline", p]) == 0
    # severity overrides re-gate without forking rules: demoting the
    # capacity lint to info takes it out of a warning-level gate
    assert lint_gate.main(["--ci", "--baseline", str(tmp_path / "none.json"),
                           "--severity", "moe:capacity=info"]) == 0


# --------------------------------------------------------------------------
# io.flat_spec — the spec-only flattener can never drift from the saver
# --------------------------------------------------------------------------


def test_flat_spec_matches_saved_manifest(tmp_path):
    tr = _trainer()
    ck = _checkpoint(tmp_path, tr)
    man = resilience.read_manifest(ck)
    assert pio.flat_spec(tr.scope.params) == man["arrays"]["params.npz"]


def test_flat_spec_exotic_dtype_mangling():
    import ml_dtypes

    tree = {"a": {"w": np.zeros((2, 3), ml_dtypes.bfloat16)},
            "plain": np.zeros((4,), np.int32)}
    spec = pio.flat_spec(tree)
    assert spec == {
        "a||w@bfloat16": {"shape": [2, 3], "dtype": "uint16"},
        "plain": {"shape": [4], "dtype": "int32"},
    }
    # and the escape hatch: a genuine name collision gets @raw
    raw = pio.flat_spec({"x@bfloat16": np.zeros((1,), np.uint16)})
    assert list(raw) == ["x@bfloat16@raw"]
