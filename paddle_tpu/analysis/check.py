"""Entry points of the static program checker.

``check(program, ...)`` lints a built :class:`~paddle_tpu.framework.Program`
— the jaxpr (ProgramDesc analog) plus its parameter scope — against the
five rule families in :mod:`.rules`. ``check_trainer`` additionally
traces the Trainer's *compiled step function* (microbatch scan, loss
scaling, optimizer update included), which is where collective-placement
hazards actually live.

Usage::

    report = analysis.check(program, sample_feed={"ids": ids, "labels": labels},
                            mesh=mesh, rules=pt.parallel.fsdp())
    print(report.render())
    report.enforce_clean("warning")   # raise LintError on findings
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

from ..core.config import get_flag, make_prng_key
from ..core.errors import enforce
from . import rules as _rules
from .report import LintReport


def _traceable(v) -> bool:
    """Can ``v`` enter a trace as an array? (Non-array objects are left
    to the retrace-hazard rule and excluded from the example feed.)"""
    try:
        return np.asarray(v).dtype != np.dtype(object)
    except Exception:
        return False


def _concrete_feed(feed: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    out = {}
    for k, v in (feed or {}).items():
        if isinstance(v, jax.ShapeDtypeStruct):
            out[k] = jax.numpy.zeros(v.shape,
                                     jax.dtypes.canonicalize_dtype(v.dtype))
        else:
            out[k] = v
    return out


def check(
    program,
    sample_feed: Optional[Dict[str, Any]] = None,
    *,
    params: Optional[Dict[str, Any]] = None,
    state: Optional[Dict[str, Any]] = None,
    mesh=None,
    rules=None,
    strategy=None,
    rng: Optional[jax.Array] = None,
    amp: Optional[str] = None,
    loss_name: str = "loss",
    large_param_bytes: int = 1 << 20,
    select: Optional[set] = None,
    feed_wire=None,
    num_epochs: Optional[int] = None,
    dataset_batches: Optional[int] = None,
    cache_budget_bytes: Optional[int] = None,
    device_cache: bool = False,
) -> LintReport:
    """Statically lint ``program``. ``sample_feed`` supplies example
    inputs (arrays or ShapeDtypeStructs) keyed by the program fn's arg
    names; ``params``/``state`` default to a fresh ``Program.init``.
    ``mesh``+``rules`` enable the sharding audit, ``strategy`` the
    config-level collective checks and the pipeline-shape lints,
    ``amp`` re-traces under ``amp_guard(amp)`` so the dtype-flow rules
    see the mixed-precision graph. ``select`` restricts to a subset of
    rule families ({"collective", "dtype", "sharding", "params",
    "retrace", "feed", "pipeline", "moe"}).
    ``feed_wire`` (a ``FeedWire`` or ``{name: WireSpec}``) maps a
    wire-typed sample feed to its logical dtypes for the trace and
    keeps the ``feed:wire-candidate`` rule from re-suggesting fields
    already carried in a wire format.

    ``num_epochs`` + ``dataset_batches`` + ``cache_budget_bytes``
    describe the fit the program will run under and arm the
    ``feed:cacheable-dataset`` rule: a multi-epoch run whose encoded
    dataset fits the residual-HBM budget but streams every epoch
    (``device_cache=False``) is flagged. At this (program-level) door
    the residual budget is EXPLICIT — there is no live trainer to
    estimate the step's appetite from; ``check_trainer`` computes it
    from the advisor."""
    from ..framework import amp_guard
    import contextlib

    report = LintReport(subject=program.name)
    from ..data.wire import FeedWire
    feed_wire = FeedWire.make(feed_wire)  # accept a plain {name: WireSpec}
    if sample_feed and feed_wire is not None:
        # a wire-typed sample feed (raw uint8 pixels) must trace the
        # program at its LOGICAL dtype, exactly as Trainer.startup
        # initializes it — otherwise the trace fails (uint8 into f32
        # convs) and every jaxpr-level family silently degrades to
        # analysis:trace-failed
        sample_feed = feed_wire.logical_feed(sample_feed)
    feed = _concrete_feed(sample_feed)
    fam = (lambda f: select is None or f in select)

    # 5. retrace hazards: inspect BEFORE abstractification loses the
    # python types (this is the raw user-facing call signature)
    if fam("retrace"):
        _rules.check_signature(program.arg_signature(**(sample_feed or {})),
                               report)

    dropped = sorted(k for k, v in feed.items() if not _traceable(v))
    feed = {k: v for k, v in feed.items() if _traceable(v)}
    amp_ctx = amp_guard(amp) if amp else contextlib.nullcontext()
    # the MoE capacity rule reads the static routing configs every
    # moe() layer records at trace time — capture them around the same
    # traces the jaxpr families already pay for (duplicate records from
    # init + desc_flat dedupe by finding fingerprint)
    from ..parallel.moe import capture_moe_configs
    with amp_ctx, capture_moe_configs() as moe_configs:
        closed = invar_names = None
        try:
            if params is None:
                params, state = program.init(
                    rng if rng is not None else make_prng_key(get_flag("seed")),
                    **feed)
            state = state or {}
            if fam("collective") or fam("dtype") or fam("params") \
                    or fam("feed") or fam("moe"):
                closed, invar_names = program.desc_flat(params, state, **feed)
        except Exception as e:
            # a trace that can't run (e.g. a required arg was dropped as
            # untraceable — already reported by the retrace family) must
            # degrade to a finding, not crash the lint
            report.add(
                "analysis:trace-failed", "info",
                f"could not trace the program for the jaxpr-level rules "
                f"({type(e).__name__}: {e})"
                + (f"; untraceable feed entries dropped: {dropped}"
                   if dropped else ""))
        if fam("collective"):
            if closed is not None:
                _rules.check_collectives(closed, report, mesh=mesh)
            _rules.check_accum_exchange(strategy, mesh, params or {}, report)
        if fam("dtype") and closed is not None:
            from ..framework import compute_dtype
            cd = compute_dtype() if amp else None
            _rules.check_dtypes(closed, report, compute_dtype=cd,
                                feed=sample_feed)
        if fam("params") and closed is not None:
            _rules.check_params(program, params, state, (), feed, report,
                                loss_name=loss_name, closed_flat=closed,
                                invar_names=invar_names)
        if fam("feed") and closed is not None:
            wired = set(feed_wire.specs) if feed_wire is not None else set()
            _rules.check_feed_wire(closed, invar_names, report,
                                   already_wired=wired)
    if fam("feed"):
        # multi-epoch streaming of a dataset that would fit residual
        # HBM: needs no jaxpr, only the sample batch's wire byte math
        _rules.check_cacheable_dataset(
            sample_feed, feed_wire, num_epochs, dataset_batches,
            cache_budget_bytes, report, cache_enabled=bool(device_cache))
    if fam("moe"):
        _rules.check_moe_capacity(moe_configs, report)
    if fam("sharding"):
        _rules.check_sharding(params, mesh, rules, report,
                              param_info=getattr(program, "param_info", None),
                              large_param_bytes=large_param_bytes)
    if fam("pipeline"):
        _rules.check_pipeline(strategy, mesh, sample_feed, report)
    return report


def check_trainer(trainer, sample_feed: Optional[Dict[str, Any]] = None,
                  **kwargs) -> LintReport:
    """Lint a started Trainer: the program-level rules over its scope +
    rule table, plus collective/dtype/donation rules over the jaxpr of
    the *compiled train step* — the microbatch scan, the strategy's
    loss scaling, the optimizer update, and every shard_map the model
    routed through are visible there, which is exactly where the
    unhoisted-accum class of hazard (and train-only dtype flow: branches
    gated on ``in_training()``, scaler casts, grad math) sits. Pass
    ``amp="bfloat16"|"float16"`` to re-trace the step under that
    compute dtype, the way the real amp training run traces it.

    Two families reach past the jaxpr:

    - ``memory`` — the HBM/remat advisor (``profiling.advisor``):
      per-device params + opt state + backward-held activations vs the
      device budget, emitting ``memory:remat-candidate``. Needs a
      budget: automatic where the backend exposes ``memory_stats()``
      (TPU), or pass ``hbm_budget_bytes=...`` explicitly (CPU).
    - ``hlo`` — collective placement over the *optimized HLO* of the
      compiled step (``profiling.fusion`` walk): GSPMD-inserted
      all-reduces inside while bodies are caught directly instead of
      inferred from config. OFF by default (it compiles the step a
      second time); enable with ``hlo=True`` or ``select={"hlo",...}``.

    Pass ``num_epochs=`` + ``dataset_batches=`` (the fit shape this
    trainer will run under) to arm ``feed:cacheable-dataset``: a
    multi-epoch run whose encoded dataset fits the advisor's
    residual-HBM estimate but streams every epoch with the device
    cache off is flagged (``device_cache=True|False`` overrides the
    trainer-attribute detection).
    """
    enforce(trainer._step_fn is not None,
            "check_trainer: call Trainer.startup() first (the lint walks "
            "the built step function)")
    select = kwargs.pop("select", None)
    hlo = kwargs.pop("hlo", False) or (select is not None and "hlo" in select)
    hbm_budget_bytes = kwargs.pop("hbm_budget_bytes", None)
    replicated_optstate_bytes = kwargs.pop("replicated_optstate_bytes",
                                           64 << 20)
    # feed:cacheable-dataset inputs: the fit shape this trainer will
    # run under (unknown to startup-time lint unless the caller says)
    num_epochs = kwargs.pop("num_epochs", None)
    dataset_batches = kwargs.pop("dataset_batches", None)
    device_cache_on = kwargs.pop("device_cache", None)
    amp = kwargs.get("amp")
    want_coll = select is None or "collective" in select
    want_donation = select is None or "donation" in select
    want_dtype = select is None or "dtype" in select
    want_memory = select is None or "memory" in select
    # the collective, donation — and, when a step trace is possible,
    # dtype — families run over the STEP jaxpr below (the program jaxpr
    # is a subset of it — walking both would double-report; donation
    # needs the step's donate_argnums anyway; dtype over the step sees
    # the train path the forward program hides)
    step_dtype = want_dtype and sample_feed is not None
    inner_select = ({"sharding", "params", "retrace", "feed", "pipeline",
                     "moe"}
                    if select is None
                    else set(select) - {"collective", "donation"})
    if step_dtype:
        inner_select -= {"dtype"}
    elif select is None:
        inner_select |= {"dtype"}
    # the PRE-adaptation rule table: typo'd axes only exist there
    # (Trainer.__init__ adapts its working copy, stripping them)
    rules = getattr(trainer, "sharding_rules_raw", None) or trainer.sharding_rules
    # a ZeRO trainer's scope holds (N, k) shard rows; the program-level
    # rules (sharding audit, param stats, the dtype re-trace) reason
    # over LOGICAL shapes — _logical_params() is scope.params verbatim
    # otherwise
    logical_params = (trainer._logical_params()
                      if hasattr(trainer, "_logical_params")
                      else trainer.scope.params)
    report = check(
        trainer.program, sample_feed,
        params=logical_params, state=trainer.scope.state,
        mesh=trainer.mesh, rules=rules,
        strategy=trainer.strategy, loss_name=trainer.loss_name,
        select=inner_select,
        feed_wire=getattr(trainer, "feed_wire", None), **kwargs)
    report.subject = f"trainer({trainer.program.name})"
    # the ZeRO trigger: only the trainer door sees live optimizer state
    # (the program-level check has no opt_state to audit)
    if (select is None or "sharding" in select) \
            and trainer.mesh is not None \
            and trainer.scope.opt_state is not None:
        _rules.check_replicated_optstate(
            trainer.scope.params, trainer.scope.opt_state, trainer.mesh,
            rules, report,
            replicated_optstate_bytes=replicated_optstate_bytes,
            zero_sharding=getattr(trainer, "_zero", None) is not None)
    if want_coll or want_donation or step_dtype:
        _check_step_jaxpr(trainer, sample_feed, report, rules, amp,
                          want_coll, want_donation, step_dtype, kwargs)
    # feed:cacheable-dataset at the trainer door: the residual budget
    # comes from the advisor (device budget or hbm_budget_bytes minus
    # the step's estimated appetite) — the program-level door takes it
    # explicitly instead
    if (select is None or "feed" in select) and sample_feed is not None \
            and num_epochs and dataset_batches:
        try:
            from ..data.device_cache import residual_hbm_bytes
            residual = residual_hbm_bytes(
                trainer, sample_feed, hbm_budget_bytes=hbm_budget_bytes)
            cache_on = (device_cache_on
                        if device_cache_on is not None
                        else getattr(trainer, "device_cache", None)
                        is not None)
            _rules.check_cacheable_dataset(
                sample_feed, getattr(trainer, "feed_wire", None),
                num_epochs, dataset_batches, residual, report,
                cache_enabled=bool(cache_on))
        except Exception as e:
            report.add("feed:cacheable-dataset-failed", "info",
                       f"could not estimate the residual-HBM cache "
                       f"budget ({type(e).__name__}: {e})")
    # families that reach PAST the jaxpr — both need a sample feed to
    # trace/compile with, and both degrade to a finding on failure (the
    # lint surface must never crash the startup path it guards)
    if want_memory and sample_feed is not None:
        try:
            from ..profiling.advisor import advise
            advise(trainer, sample_feed, hbm_budget_bytes=hbm_budget_bytes,
                   report=report)
        except Exception as e:
            report.add("memory:advisor-failed", "info",
                       f"HBM advisor could not estimate the step "
                       f"({type(e).__name__}: {e})")
    if hlo and sample_feed is not None:
        try:
            from ..debugger import _lower_step
            from ..profiling.fusion import module_units, parse_hlo_module
            text = _lower_step(trainer, sample_feed).compile().as_text()
            _rules.check_hlo_collectives(
                module_units(parse_hlo_module(text)), report)
        except Exception as e:
            report.add("collective:hlo-walk-failed", "info",
                       f"could not compile/walk the optimized HLO "
                       f"({type(e).__name__}: {e})")
    return report


def _check_step_jaxpr(trainer, sample_feed, report, rules, amp,
                      want_coll, want_donation, step_dtype, kwargs) -> None:
    """The step-jaxpr families of ``check_trainer`` (collective,
    donation, train-path dtype)."""
    if want_coll:
        _rules.check_accum_exchange(trainer.strategy, trainer.mesh,
                                    trainer.scope.params, report)
        # advisory needs profile EVIDENCE of a link-bound run, so it
        # only applies once the trainer has dispatched steps
        profile = (trainer.profile_report()
                   if getattr(trainer.step_timer, "steps", 0) > 0 else None)
        _rules.check_quantized_exchange(trainer.strategy, trainer.mesh,
                                        trainer.scope.params, report,
                                        profile=profile)
    if sample_feed is None:
        return
    feed = _concrete_feed(sample_feed)
    ls = getattr(trainer.scope, "loss_scale_state", None) or {}
    args = (trainer.scope.params, trainer.scope.opt_state,
            trainer.scope.state, jax.random.PRNGKey(0), feed, ls)
    # the quantized-exchange error-feedback residual grows the step
    # signature by one trailing arg (executor._build_step)
    if getattr(trainer, "_quant_ef", False):
        args = args + (trainer.scope.quant_resid,)
    # ONE trace of the raw step body serves all three families: the same
    # collective eqns the jitted wrapper would show (minus the pjit
    # shell), the invar→outvar identity the donation rule needs (the
    # jitted wrapper hides passthrough aliasing), and — under amp_guard
    # — the train-path dtype flow (loss scaling included via the ls arg)
    from ..framework import amp_guard, compute_dtype
    import contextlib
    core = getattr(trainer, "_train_step_core", None) or trainer._step_fn
    cd = None
    trace_err = None
    with (amp_guard(amp) if amp else contextlib.nullcontext()):
        if amp:
            cd = compute_dtype()
        try:
            closed, out_shape = jax.make_jaxpr(core, return_shape=True)(*args)
        except Exception as e:
            trace_err = e
    if trace_err is not None:
        report.add("collective:step-trace-failed", "info",
                   f"could not trace the step for collective/donation/"
                   f"dtype rules ({type(trace_err).__name__}: {trace_err})")
        if step_dtype:
            # the dtype family was withheld from the program-level walk
            # in anticipation of the step trace — a step that won't
            # trace must not lose it entirely: fall back to the forward
            # program jaxpr (the pre-step_dtype coverage). This re-runs
            # init + the forward trace — acceptable on this rare
            # failure path; coverage beats the duplicate trace cost.
            fb = check(trainer.program, sample_feed,
                       params=trainer.scope.params, state=trainer.scope.state,
                       mesh=trainer.mesh, rules=rules,
                       strategy=trainer.strategy, loss_name=trainer.loss_name,
                       select={"dtype"},
                       feed_wire=getattr(trainer, "feed_wire", None), **kwargs)
            report.findings.extend(fb.findings)
        return
    if want_coll:
        _rules.check_collectives(closed, report, mesh=trainer.mesh)
    if step_dtype:
        _rules.check_dtypes(closed, report, compute_dtype=cd,
                            feed=sample_feed)
    if want_donation and getattr(trainer, "_train_step_core", None) is not None:
        _check_step_donation(trainer, args, closed, out_shape, report)


_STEP_ARGNAMES = ("params", "opt_state", "state", "rng", "feed",
                  "loss_scale", "quant_resid")


def _check_step_donation(trainer, args, closed, out_shape,
                         report: LintReport) -> None:
    """Donation lint over the traced RAW step body: map each donated
    argnum to its flat invar indices and the step's fetch dict to its
    flat outvar indices, then flag fetched outputs that ARE donated
    invars (rules.check_donation)."""
    donate = set(getattr(trainer, "_donate_argnums", ()) or ())
    if not donate:
        return
    donated = {}
    idx = 0
    for argnum, a in enumerate(args):
        for path, _leaf in jax.tree_util.tree_flatten_with_path(a)[0]:
            if argnum in donate:
                name = _STEP_ARGNAMES[argnum] + jax.tree_util.keystr(path)
                donated[idx] = name
            idx += 1
    # step outputs are (new_params, new_opt, new_state, out, new_ls):
    # only the fetch dict (index 3) is read by the caller after the
    # step — carry outputs aliasing donated inputs are the POINT of
    # donation, not a finding
    fetched = {}
    for i, (path, _leaf) in enumerate(
            jax.tree_util.tree_flatten_with_path(out_shape)[0]):
        if getattr(path[0], "idx", None) == 3:
            fetched[i] = "out" + jax.tree_util.keystr(path[1:])
    _rules.check_donation(closed, donated, fetched, report)
