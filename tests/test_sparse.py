"""SelectedRows / sparse-update / sharded-embedding tests
(test_selected_rows / test_lookup_table_op / dist lookup-table analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import sparse as sp


def test_selected_rows_to_dense_and_merge():
    sr = sp.SelectedRows(jnp.asarray([1, 3, 1], jnp.int32),
                         jnp.asarray([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]]), height=5)
    dense = np.asarray(sr.to_dense())
    np.testing.assert_allclose(dense[1], [4.0, 4.0])
    np.testing.assert_allclose(dense[3], [2.0, 2.0])
    merged = sp.merge_selected_rows(sr)
    d2 = np.asarray(merged.to_dense())
    np.testing.assert_allclose(d2, dense)
    # merged rows are unique (padding slots = height)
    rows = np.asarray(merged.rows)
    real = rows[rows < 5]
    assert len(np.unique(real)) == len(real)


def test_lookup_rowwise_grad_matches_dense_grad():
    vocab, dim = 10, 4
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    ids = jnp.asarray(np.array([[1, 2], [2, 5]], np.int64))
    w = jnp.asarray(rng.randn(2, 2, dim).astype(np.float32))

    def loss(t):
        return jnp.sum(jnp.take(t, ids, axis=0) * w)

    dense_grad = jax.grad(loss)(table)
    grad_out = w  # d loss / d lookup output
    sr = sp.lookup_rowwise_grad(ids, grad_out, vocab)
    np.testing.assert_allclose(np.asarray(sr.to_dense()), np.asarray(dense_grad),
                               rtol=1e-6)


def test_apply_sgd_sparse_rows_only():
    table = jnp.ones((6, 2))
    sr = sp.SelectedRows(jnp.asarray([0, 3], jnp.int32),
                         jnp.asarray([[1.0, 1.0], [2.0, 2.0]]), height=6)
    out = np.asarray(sp.apply_sgd(table, sr, lr=0.5))
    np.testing.assert_allclose(out[0], [0.5, 0.5])
    np.testing.assert_allclose(out[3], [0.0, 0.0])
    np.testing.assert_allclose(out[1], [1.0, 1.0])  # untouched


def test_apply_adagrad_and_adam_lazy_touch_only_rows():
    vocab, dim = 8, 3
    table = jnp.ones((vocab, dim))
    moment = jnp.zeros((vocab, dim))
    sr = sp.SelectedRows(jnp.asarray([2, 2, 5], jnp.int32),
                         jnp.ones((3, dim)), height=vocab)
    t2, m2 = sp.apply_adagrad(table, moment, sr, lr=0.1)
    assert not np.allclose(np.asarray(t2)[2], 1.0)
    assert not np.allclose(np.asarray(t2)[5], 1.0)
    np.testing.assert_allclose(np.asarray(t2)[0], 1.0)
    np.testing.assert_allclose(np.asarray(m2)[0], 0.0)

    m1 = jnp.zeros((vocab, dim))
    mm2 = jnp.zeros((vocab, dim))
    t3, nm1, nm2 = sp.apply_adam_lazy(table, m1, mm2, sr, lr=0.1, t=0)
    assert not np.allclose(np.asarray(t3)[2], 1.0)
    np.testing.assert_allclose(np.asarray(t3)[1], 1.0)
    # duplicate rows merged: row 2 got grad 2.0
    assert np.asarray(nm1)[2, 0] == pytest.approx(0.2, rel=1e-5)


def test_sharded_embedding_matches_dense():
    mesh = pt.make_mesh({"ep": 8})
    vocab, dim = 32, 4
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, vocab, (5, 7)).astype(np.int32))
    out = sp.sharded_embedding_lookup(table, ids, mesh, axis="ep", batch_axes=())
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.take(table, ids, axis=0)),
                               rtol=1e-6)


def test_sharded_embedding_with_dp():
    mesh = pt.make_mesh({"dp": 2, "ep": 4})
    vocab, dim = 16, 4
    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, vocab, (6, 3)).astype(np.int32))
    out = sp.sharded_embedding_lookup(table, ids, mesh, axis="ep")
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.take(table, ids, axis=0)),
                               rtol=1e-6)


def test_sharded_embedding_grad():
    mesh = pt.make_mesh({"ep": 4}, devices=jax.devices()[:4])
    vocab, dim = 16, 4
    rng = np.random.RandomState(2)
    table = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, vocab, (5,)).astype(np.int32))

    g1 = jax.grad(lambda t: jnp.sum(
        sp.sharded_embedding_lookup(t, ids, mesh, axis="ep", batch_axes=()) ** 2))(table)
    g2 = jax.grad(lambda t: jnp.sum(jnp.take(t, ids, axis=0) ** 2))(table)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5)
