"""Model zoo mirroring the reference's book/benchmark configs
(BASELINE.json: MNIST MLP, ResNet-50, Transformer-base, DeepFM,
BERT-base; plus VGG/LSTM from benchmark/fluid/models/)."""

from . import bert, deepfm, lstm, mnist, resnet, transformer, vgg, word2vec

__all__ = ["bert", "deepfm", "lstm", "mnist", "resnet", "transformer", "vgg", "word2vec"]
