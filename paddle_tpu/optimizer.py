"""Optimizers.

Analog of python/paddle/fluid/optimizer.py (optimizer.py:274-1313:
SGD/Momentum/LarsMomentum/Adagrad/Adam/Adamax/DecayedAdagrad/Adadelta/
RMSProp/Ftrl/ModelAverage). In the reference each optimizer emits
in-graph ops with accumulator variables per parameter
(_create_optimization_pass, optimizer.py:195); here each is a pure
pytree transform: ``init(params) -> opt_state`` builds the accumulators,
``update(grads, opt_state, params) -> (new_params, new_opt_state)`` is
the fused update XLA compiles into a handful of kernels (the reference's
per-param op-dispatch overhead disappears).

Regularization (global or per-ParamAttr), gradient clipping, and
per-param LR multipliers are applied inside ``update``, mirroring the
append_regularization_ops / append_gradient_clip_ops / param-lr flow of
Optimizer.minimize (optimizer.py:248).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .framework import ParamInfo

Params = Dict[str, jax.Array]
Grads = Dict[str, jax.Array]
OptState = Dict[str, Any]


class Optimizer:
    """Base optimizer (optimizer.py:41 Optimizer).

    ``state_dtype`` (set via :meth:`set_state_dtype` or
    ``DistStrategy.opt_state_dtype``) stores float accumulators (Adam
    moments etc.) in a reduced dtype — bfloat16 halves optimizer HBM,
    the big slice of training memory once params/grads are sharded.
    Update MATH always runs in float32: accumulators are upcast before
    ``_apply_dense`` and cast back after, so only storage precision
    changes.

    Opt-state layout contract: any PER-PARAMETER state must live under
    a dict keyed by the parameter's name — the built-ins use
    ``opt_state['accums'][param_name][slot]``, and subclasses adding
    state elsewhere must keep the name-keyed shape (e.g.
    ``opt_state['rows'][param_name]``). Machinery that re-layouts
    parameter rows (``Trainer._apply_row_perm``, the interleaved
    pipeline's checkpoint round-trip) walks opt_state for name-keyed
    subtrees and permutes arrays whose leading dim matches the param's
    row permutation; per-param state hidden under other keys would be
    checkpointed in the wrong row order silently. ``step``/``global``
    (not per-param) are exempt.

    Scan-carry contract (``Trainer.run_steps`` — the fused K-step
    dispatch threads opt_state through a ``lax.scan`` carry): ``update``
    must return an opt_state with the SAME pytree structure and leaf
    shapes/dtypes as its input — the built-ins already do (the
    ``_store_acc``/``_compute_acc`` round-trip keeps storage dtype
    invariant); a subclass that grows or retypes state per step would
    fail the scan's carry check loudly at trace time."""

    state_dtype = None  # class default: keep accumulators in float32

    def __init__(self, learning_rate, regularization=None, grad_clip=None, name=None):
        self._lr = learning_rate
        self.regularization = regularization
        self.grad_clip = grad_clip
        self.name = name

    def set_state_dtype(self, dtype) -> "Optimizer":
        """Store float accumulators as ``dtype`` (None restores f32)."""
        self.state_dtype = jnp.dtype(dtype) if dtype is not None else None
        return self

    def _store_acc(self, acc):
        if self.state_dtype is None:
            return acc
        return {k: (v.astype(self.state_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in acc.items()}

    def _compute_acc(self, acc):
        if self.state_dtype is None:
            return acc
        return {k: (v.astype(jnp.float32)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in acc.items()}

    # -- subclass interface -------------------------------------------------
    def _create_accumulators(self, param: jax.Array) -> Dict[str, jax.Array]:
        return {}

    def _apply_dense(self, lr, param, grad, acc: Dict[str, jax.Array], state: OptState
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    # -- global-state hooks (e.g. beta powers) ------------------------------
    def _init_global(self) -> Dict[str, jax.Array]:
        return {}

    def _update_global(self, g: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return g

    # -- public pytree API --------------------------------------------------
    def init(self, params: Params) -> OptState:
        return {
            "step": jnp.zeros((), jnp.int32),
            "global": self._init_global(),
            "accums": {k: self._store_acc(self._create_accumulators(v))
                       for k, v in params.items()},
        }

    def learning_rate(self, step) -> jax.Array:
        if callable(self._lr):
            return jnp.asarray(self._lr(step), jnp.float32)
        return jnp.asarray(self._lr, jnp.float32)

    def update(
        self,
        grads: Grads,
        opt_state: OptState,
        params: Params,
        param_info: Optional[Dict[str, ParamInfo]] = None,
    ) -> Tuple[Params, OptState]:
        param_info = param_info or {}
        step = opt_state["step"]
        lr = self.learning_rate(step)

        # 1. regularization (append_regularization_ops analog; per-param
        # attr wins over the optimizer-global setting).
        reg_grads: Grads = {}
        for k, g in grads.items():
            info = param_info.get(k)
            reg = (info.regularizer if info is not None and info.regularizer is not None
                   else self.regularization)
            if reg is not None and g is not None:
                g = reg.apply(params[k], g)
            reg_grads[k] = g

        # 2. clipping (append_gradient_clip_ops analog).
        if self.grad_clip is not None:
            reg_grads = self.grad_clip({k: g for k, g in reg_grads.items() if g is not None},
                                       params) | {k: g for k, g in reg_grads.items() if g is None}

        # 3. per-param updates.
        new_state: OptState = {"step": step + 1,
                               "global": self._update_global(opt_state["global"]),
                               "accums": {}}
        new_params: Params = {}
        for k, p in params.items():
            g = reg_grads.get(k)
            info = param_info.get(k)
            trainable = info.trainable if info is not None else True
            if g is None or not trainable:
                new_params[k] = p
                new_state["accums"][k] = opt_state["accums"][k]
                continue
            plr = lr * (info.learning_rate if info is not None else 1.0)
            state_for_param = {"step": step, "global": opt_state["global"]}
            np_, nacc = self._apply_dense(plr, p, g.astype(jnp.float32),
                                          self._compute_acc(opt_state["accums"][k]),
                                          state_for_param)
            new_params[k] = np_.astype(p.dtype)
            new_state["accums"][k] = self._store_acc(nacc)
        return new_params, new_state

    # convenience: apply to a (params, opt_state) pair
    def apply_gradients(self, params, grads, opt_state, param_info=None):
        return self.update(grads, opt_state, params, param_info)


# ---------------------------------------------------------------------------


class SGD(Optimizer):
    """SGDOptimizer (optimizer.py:274; sgd_op.cc)."""

    def _apply_dense(self, lr, p, g, acc, state):
        return p - lr * g, acc


class Momentum(Optimizer):
    """MomentumOptimizer (optimizer.py:325; momentum_op)."""

    def __init__(self, learning_rate, momentum: float = 0.9, use_nesterov: bool = False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _create_accumulators(self, p):
        return {"velocity": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        v = self.momentum * acc["velocity"] + g
        if self.use_nesterov:
            p = p - lr * (g + self.momentum * v)
        else:
            p = p - lr * v
        return p, {"velocity": v}


class LarsMomentum(Optimizer):
    """LarsMomentumOptimizer (optimizer.py:~400; lars_momentum_op):
    layer-adaptive rate scaling."""

    def __init__(self, learning_rate, momentum: float = 0.9, lars_coeff: float = 1e-3,
                 lars_weight_decay: float = 5e-4, epsilon: float = 0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay
        self.epsilon = epsilon

    def _create_accumulators(self, p):
        return {"velocity": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        p32 = p.astype(jnp.float32)
        pn = jnp.sqrt(jnp.sum(p32 * p32))
        gn = jnp.sqrt(jnp.sum(g * g))
        local_lr = jnp.where(
            (pn > 0) & (gn > 0),
            lr * self.lars_coeff * pn / (gn + self.lars_weight_decay * pn + self.epsilon),
            lr)
        v = self.momentum * acc["velocity"] + local_lr * (g + self.lars_weight_decay * p32)
        return p32 - v, {"velocity": v}


class Adagrad(Optimizer):
    """AdagradOptimizer (optimizer.py:~470; adagrad_op)."""

    def __init__(self, learning_rate, epsilon: float = 1e-6,
                 initial_accumulator_value: float = 0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon
        self.init_acc = initial_accumulator_value

    def _create_accumulators(self, p):
        return {"moment": jnp.full(p.shape, self.init_acc, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        m = acc["moment"] + g * g
        return p - lr * g / (jnp.sqrt(m) + self.epsilon), {"moment": m}


class Adam(Optimizer):
    """AdamOptimizer (optimizer.py:~520; adam_op.cc). Bias correction via
    global beta1^t/beta2^t accumulators, matching the reference."""

    def __init__(self, learning_rate=0.001, beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, lazy_mode: bool = False, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_global(self):
        return {"beta1_pow": jnp.ones((), jnp.float32) * self.beta1,
                "beta2_pow": jnp.ones((), jnp.float32) * self.beta2}

    def _update_global(self, g):
        return {"beta1_pow": g["beta1_pow"] * self.beta1,
                "beta2_pow": g["beta2_pow"] * self.beta2}

    def _create_accumulators(self, p):
        return {"moment1": jnp.zeros(p.shape, jnp.float32),
                "moment2": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        b1p = state["global"]["beta1_pow"]
        b2p = state["global"]["beta2_pow"]
        m1 = self.beta1 * acc["moment1"] + (1 - self.beta1) * g
        m2 = self.beta2 * acc["moment2"] + (1 - self.beta2) * g * g
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        p = p - lr_t * m1 / (jnp.sqrt(m2) + self.epsilon)
        return p, {"moment1": m1, "moment2": m2}


class AdamW(Adam):
    """Decoupled weight decay variant (modern addition; weight decay is
    applied directly to params, not through grads)."""

    def __init__(self, learning_rate=0.001, weight_decay: float = 0.01, **kw):
        super().__init__(learning_rate, **kw)
        self.weight_decay = weight_decay

    def _apply_dense(self, lr, p, g, acc, state):
        p2, nacc = super()._apply_dense(lr, p, g, acc, state)
        return p2 - lr * self.weight_decay * p.astype(jnp.float32), nacc


class Adamax(Optimizer):
    """AdamaxOptimizer (optimizer.py:~600; adamax_op)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_global(self):
        return {"beta1_pow": jnp.ones((), jnp.float32) * self.beta1}

    def _update_global(self, g):
        return {"beta1_pow": g["beta1_pow"] * self.beta1}

    def _create_accumulators(self, p):
        return {"moment": jnp.zeros(p.shape, jnp.float32),
                "inf_norm": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        b1p = state["global"]["beta1_pow"]
        m = self.beta1 * acc["moment"] + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * acc["inf_norm"], jnp.abs(g) + self.epsilon)
        p = p - (lr / (1 - b1p)) * m / u
        return p, {"moment": m, "inf_norm": u}


class DecayedAdagrad(Optimizer):
    """DecayedAdagradOptimizer (optimizer.py:~680; decayed_adagrad_op)."""

    def __init__(self, learning_rate, decay: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.epsilon = decay, epsilon

    def _create_accumulators(self, p):
        return {"moment": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        m = self.decay * acc["moment"] + (1 - self.decay) * g * g
        return p - lr * g / (jnp.sqrt(m) + self.epsilon), {"moment": m}


class Adadelta(Optimizer):
    """AdadeltaOptimizer (optimizer.py:~730; adadelta_op)."""

    def __init__(self, learning_rate=1.0, epsilon: float = 1e-6, rho: float = 0.95, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon, self.rho = epsilon, rho

    def _create_accumulators(self, p):
        return {"avg_squared_grad": jnp.zeros(p.shape, jnp.float32),
                "avg_squared_update": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        sg = self.rho * acc["avg_squared_grad"] + (1 - self.rho) * g * g
        upd = g * jnp.sqrt(acc["avg_squared_update"] + self.epsilon) / jnp.sqrt(sg + self.epsilon)
        su = self.rho * acc["avg_squared_update"] + (1 - self.rho) * upd * upd
        return p - lr * upd, {"avg_squared_grad": sg, "avg_squared_update": su}


class RMSProp(Optimizer):
    """RMSPropOptimizer (optimizer.py:~790; rmsprop_op) with momentum and
    centered variants, matching the reference's attrs."""

    def __init__(self, learning_rate, rho: float = 0.95, epsilon: float = 1e-6,
                 momentum: float = 0.0, centered: bool = False, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon, self.momentum, self.centered = rho, epsilon, momentum, centered

    def _create_accumulators(self, p):
        return {"mean_square": jnp.zeros(p.shape, jnp.float32),
                "mean_grad": jnp.zeros(p.shape, jnp.float32),
                "momentum": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        ms = self.rho * acc["mean_square"] + (1 - self.rho) * g * g
        if self.centered:
            mg = self.rho * acc["mean_grad"] + (1 - self.rho) * g
            denom = jnp.sqrt(ms - mg * mg + self.epsilon)
        else:
            mg = acc["mean_grad"]
            denom = jnp.sqrt(ms + self.epsilon)
        mom = self.momentum * acc["momentum"] + lr * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Ftrl(Optimizer):
    """FtrlOptimizer (optimizer.py:~870; ftrl_op)."""

    def __init__(self, learning_rate, l1: float = 0.0, l2: float = 0.0,
                 lr_power: float = -0.5, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def _create_accumulators(self, p):
        return {"squared": jnp.zeros(p.shape, jnp.float32),
                "linear": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        p32 = p.astype(jnp.float32)
        new_sq = acc["squared"] + g * g
        if self.lr_power == -0.5:
            sigma = (jnp.sqrt(new_sq) - jnp.sqrt(acc["squared"])) / lr
        else:
            sigma = (jnp.power(new_sq, -self.lr_power) - jnp.power(acc["squared"], -self.lr_power)) / lr
        lin = acc["linear"] + g - sigma * p32
        if self.lr_power == -0.5:
            x = self.l2 + jnp.sqrt(new_sq) / lr
        else:
            x = self.l2 + jnp.power(new_sq, -self.lr_power) / lr
        pre = jnp.clip(lin, -self.l1, self.l1) - lin
        new_p = jnp.where(jnp.abs(lin) > self.l1, pre / x, jnp.zeros_like(p32))
        return new_p, {"squared": new_sq, "linear": lin}


class Lamb(Optimizer):
    """LAMB (layerwise-adaptive Adam for large batch) — modern addition
    used for BERT-scale training."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay: float = 0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.wd, self.beta1, self.beta2, self.epsilon = lamb_weight_decay, beta1, beta2, epsilon

    def _create_accumulators(self, p):
        return {"moment1": jnp.zeros(p.shape, jnp.float32),
                "moment2": jnp.zeros(p.shape, jnp.float32)}

    def _apply_dense(self, lr, p, g, acc, state):
        t = state["step"].astype(jnp.float32) + 1.0
        p32 = p.astype(jnp.float32)
        m1 = self.beta1 * acc["moment1"] + (1 - self.beta1) * g
        m2 = self.beta2 * acc["moment2"] + (1 - self.beta2) * g * g
        m1h = m1 / (1 - jnp.power(self.beta1, t))
        m2h = m2 / (1 - jnp.power(self.beta2, t))
        r = m1h / (jnp.sqrt(m2h) + self.epsilon) + self.wd * p32
        pn = jnp.sqrt(jnp.sum(p32 * p32))
        rn = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((pn > 0) & (rn > 0), pn / rn, 1.0)
        return p32 - lr * trust * r, {"moment1": m1, "moment2": m2}


class ModelAverage:
    """ModelAverageOptimizer (optimizer.py:~1313): maintains a running
    average of parameters for evaluation. Functional version: feed every
    post-update params pytree to ``accumulate``; use ``average_params``
    for eval (apply_program analog) and keep the originals to restore."""

    def __init__(self, average_window_rate: float = 0.15,
                 min_average_window: int = 10000, max_average_window: int = 10000):
        self.rate = average_window_rate
        self.min_w, self.max_w = min_average_window, max_average_window

    def init(self, params: Params):
        return {"sum": jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
                "num": jnp.zeros((), jnp.float32)}

    def accumulate(self, avg_state, params: Params):
        num = avg_state["num"] + 1.0
        s = jax.tree.map(lambda a, p: a + p.astype(jnp.float32), avg_state["sum"], params)
        # window restart mirroring the reference's num_updates window logic
        restart = num > self.max_w
        s = jax.tree.map(lambda a, p: jnp.where(restart, p.astype(jnp.float32), a), s, params)
        num = jnp.where(restart, jnp.ones_like(num), num)
        return {"sum": s, "num": num}

    def average_params(self, avg_state, params: Params) -> Params:
        n = jnp.maximum(avg_state["num"], 1.0)
        return {k: (avg_state["sum"][k] / n).astype(v.dtype) for k, v in params.items()}


class ExponentialMovingAverage:
    """EMA of parameters (fluid ExponentialMovingAverage analog)."""

    def __init__(self, decay: float = 0.999):
        self.decay = decay

    def init(self, params: Params):
        return jax.tree.map(lambda p: p.astype(jnp.float32), params)

    def accumulate(self, ema, params: Params):
        return jax.tree.map(lambda e, p: self.decay * e + (1 - self.decay) * p.astype(jnp.float32),
                            ema, params)

    def average_params(self, ema, params: Params) -> Params:
        return {k: ema[k].astype(v.dtype) for k, v in params.items()}


# fluid-style aliases
SGDOptimizer = SGD
MomentumOptimizer = Momentum
LarsMomentumOptimizer = LarsMomentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
DecayedAdagradOptimizer = DecayedAdagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
