"""Reader combinators — the host-side data pipeline.

Analog of python/paddle/reader/decorator.py:36-338 (map_readers/
shuffle/chain/compose/buffered/firstn/xmap_readers/cache) and
fluid.layers.io batching. A *reader creator* is a zero-arg callable
returning an iterator of samples, exactly the reference's convention, so
user code ports 1:1. The device-feeding end (double-buffering, the
py_reader/buffered_reader analog) lives in paddle_tpu.data.feeder.
"""

from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading
from typing import Any, Callable, Iterable, Iterator, List, Sequence

Reader = Callable[[], Iterator[Any]]


def map_readers(func: Callable, *readers: Reader) -> Reader:
    """Apply func elementwise over zipped readers (decorator.py:36)."""

    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader: Reader, buf_size: int, seed: int = None) -> Reader:
    """Shuffle within a sliding buffer (decorator.py:~120)."""

    def new_reader():
        rnd = _random.Random(seed)
        buf: List[Any] = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rnd.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rnd.shuffle(buf)
            yield from buf

    return new_reader


def chain(*readers: Reader) -> Reader:
    """Concatenate readers (decorator.py chain)."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """Zip readers into tuple samples (decorator.py compose)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    _miss = object()

    def reader():
        its = [r() for r in readers]
        if not check_alignment:
            for items in zip(*its):
                yield sum((make_tuple(i) for i in items), ())
            return
        for items in itertools.zip_longest(*its, fillvalue=_miss):
            if any(i is _miss for i in items):
                raise ComposeNotAligned(
                    "compose: input readers yielded different lengths")
            yield sum((make_tuple(i) for i in items), ())

    return reader


def buffered(reader: Reader, size: int) -> Reader:
    """Read ahead in a daemon thread (decorator.py buffered) — overlaps
    host IO with device compute."""

    class _End:
        pass

    def new_reader():
        q: _queue.Queue = _queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return new_reader


def firstn(reader: Reader, n: int) -> Reader:
    def new_reader():
        yield from itertools.islice(reader(), n)

    return new_reader


def cache(reader: Reader) -> Reader:
    """Materialize once, replay from memory (decorator.py cache)."""
    data: List[Any] = []
    filled = [False]

    def new_reader():
        if not filled[0]:
            data.extend(reader())
            filled[0] = True
        yield from data

    return new_reader


def xmap_readers(mapper: Callable, reader: Reader, process_num: int,
                 buffer_size: int, order: bool = False) -> Reader:
    """Parallel map via worker threads (decorator.py:~250 xmap_readers).
    Threads (not processes) suffice here: host-side decode work releases
    the GIL in numpy, and device feeding is the bottleneck anyway."""

    class _End:
        pass

    def new_reader():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)

        def feed():
            for i, d in enumerate(reader()):
                in_q.put((i, d))
            for _ in range(process_num):
                in_q.put(_End)

        def work():
            while True:
                e = in_q.get()
                if e is _End:
                    out_q.put(_End)
                    break
                i, d = e
                out_q.put((i, mapper(d)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        done = 0
        if order:
            pending = {}
            nxt = 0
            while done < process_num:
                e = out_q.get()
                if e is _End:
                    done += 1
                    continue
                i, d = e
                pending[i] = d
                while nxt in pending:
                    yield pending.pop(nxt)
                    nxt += 1
            while nxt in pending:
                yield pending.pop(nxt)
                nxt += 1
        else:
            while done < process_num:
                e = out_q.get()
                if e is _End:
                    done += 1
                    continue
                yield e[1]

    return new_reader


def batch(reader: Reader, batch_size: int, drop_last: bool = True) -> Reader:
    """Group samples into lists (paddle.batch analog). drop_last defaults
    True because XLA wants static shapes (the design decision replacing
    the reference's dynamic final batch)."""

    def new_reader():
        b = []
        for s in reader():
            b.append(s)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return new_reader


class ComposeNotAligned(ValueError):
    """reader/decorator.py ComposeNotAligned: compose() inputs yielded
    different lengths."""


def fake(reader, n: int = 1):
    """decorator.py Fake: cache the first sample and replay it ``n``
    times — the input-pipeline-removal benchmark trick."""
    def _r():
        it = iter(reader())
        try:
            cached = next(it)
        except StopIteration:
            raise ValueError("fake(): source reader is empty") from None
        for _ in range(n):
            yield cached
    return _r


Fake = fake


class PipeReader:
    """decorator.py PipeReader: stream samples from a shell command's
    stdout (e.g. zcat / hadoop fs -cat), split on a delimiter."""

    def __init__(self, command: str, bufsize: int = 8192, file_type: str = "plain"):
        import subprocess
        if file_type not in ("plain", "gzip"):
            raise ValueError(f"PipeReader: unsupported file_type {file_type!r}")
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type
        self.process = subprocess.Popen(
            command.split(" "), bufsize=bufsize, stdout=subprocess.PIPE)

    def get_line(self, cut_lines: bool = True, line_break: str = "\n"):
        import zlib
        decomp = zlib.decompressobj(32 + zlib.MAX_WBITS) \
            if self.file_type == "gzip" else None
        remained = ""
        while True:
            buff = self.process.stdout.read(self.bufsize)
            if not buff:
                if decomp is not None:
                    tail = decomp.flush()
                    if tail:
                        remained += tail.decode("utf-8", errors="replace")
                break
            if decomp is not None:
                out = decomp.decompress(buff)
                # concatenated gzip members (cat a.gz b.gz): restart the
                # stream on each member boundary or data after the first
                # member is silently dropped
                while decomp.eof and decomp.unused_data:
                    rest = decomp.unused_data
                    decomp = zlib.decompressobj(32 + zlib.MAX_WBITS)
                    out += decomp.decompress(rest)
                buff = out
            buff = buff.decode("utf-8", errors="replace")
            if cut_lines:
                lines = (remained + buff).split(line_break)
                remained = lines.pop(-1)
                for line in lines:
                    yield line
            else:
                yield buff
        if remained:
            yield remained


def multiprocess_reader(readers, use_pipe: bool = True, queue_size: int = 1000):
    """decorator.py multiprocess_reader: run N readers in workers, merge
    into one stream. Thread-based on TPU hosts (workers are IO-bound;
    avoids fork-vs-XLA-runtime hazards; ``use_pipe`` is accepted for API
    parity — both reference transports map to the same queue here).
    Worker exceptions are re-raised in the consumer."""
    import queue as _q

    def _r():
        q: _q.Queue = _q.Queue(maxsize=queue_size)
        _sentinel = object()

        def work(r):
            try:
                for sample in r():
                    q.put(sample)
                q.put(_sentinel)
            except BaseException as e:  # propagate to the consumer
                q.put(_WorkerError(e))

        ts = [threading.Thread(target=work, args=(r,), daemon=True) for r in readers]
        for t in ts:
            t.start()
        done = 0
        while done < len(readers):
            item = q.get()
            if item is _sentinel:
                done += 1
            elif isinstance(item, _WorkerError):
                raise item.error
            else:
                yield item
    return _r


class _WorkerError:
    def __init__(self, error: BaseException):
        self.error = error
